"""Synthetic cluster snapshot generators (SURVEY.md §4 items 1/6).

Plays the role of upstream scheduler_perf's fake-node/fake-pod fixtures:
scale and property tests need thousands of nodes with no real cluster.
Each generator returns a (ClusterSnapshot, SnapshotMeta) pair via
SnapshotBuilder, so the synthetic data exercises the same interning and
padding paths as real input.
"""

from __future__ import annotations

import numpy as np

from tpusched.config import Buckets, EngineConfig
from tpusched.snapshot import (
    MatchExpression,
    NodeSelectorTerm,
    PodAffinityTerm,
    PreferredTerm,
    SnapshotBuilder,
    Toleration,
    TopologySpreadConstraint,
)

ZONES = ("zone-a", "zone-b", "zone-c", "zone-d")
NODE_CLASSES = (
    # (cpu millicores, memory bytes)
    (4000, 16 << 30),
    (8000, 32 << 30),
    (16000, 64 << 30),
    (32000, 128 << 30),
)


def make_cluster(
    rng: np.random.Generator,
    n_pods: int,
    n_nodes: int,
    config: EngineConfig | None = None,
    buckets: Buckets | None = None,
    initial_utilization: float = 0.3,
    n_running_per_node: int = 2,
    with_qos: bool = True,
    taint_frac: float = 0.0,
    toleration_frac: float = 0.0,
    selector_frac: float = 0.0,
    affinity_frac: float = 0.0,
    spread_frac: float = 0.0,
    interpod_frac: float = 0.0,
    run_anti_frac: float = 0.0,
    gang_frac: float = 0.0,
    gang_size: int = 4,
    keyless_node_frac: float = 0.0,
    namespace_count: int = 1,
    pdb_frac: float = 0.0,
    cordon_frac: float = 0.0,
    as_records: bool = False,
    tight_utilization: bool = False,
):
    """General-purpose random cluster. Fractions control what share of
    pods/nodes carry each constraint type, so the same generator covers
    BASELINE configs 1-5 (resource-only through gangs).

    as_records=True returns (node_records, pod_records, running_records)
    — builder-style dicts ready for rpc.codec.snapshot_to_proto — instead
    of building the array snapshot; the wire benches use this to drive
    the full gRPC cycle with the same synthetic clusters."""
    config = config or EngineConfig()
    b = SnapshotBuilder(config, buckets)

    zones = [ZONES[i % len(ZONES)] for i in range(n_nodes)]
    for i in range(n_nodes):
        cpu, mem = NODE_CLASSES[rng.integers(len(NODE_CLASSES))]
        labels = {
            "topology.kubernetes.io/zone": zones[i],
            "kubernetes.io/hostname": f"node-{i}",
            "disktype": "ssd" if rng.random() < 0.5 else "hdd",
            "tier": str(rng.integers(0, 4)),
        }
        if rng.random() < keyless_node_frac:
            # Node missing the topology key: exercises the upstream
            # "member on a key-less node" corner (spread DoNotSchedule
            # filters such nodes; affinity match-anywhere still counts).
            del labels["topology.kubernetes.io/zone"]
        taints = []
        if rng.random() < taint_frac:
            taints.append(("dedicated", "batch", "NoSchedule"))
        if rng.random() < taint_frac / 2:
            taints.append(("maintenance", "true", "PreferNoSchedule"))
        b.add_node(
            f"node-{i}",
            allocatable={"cpu": float(cpu), "memory": float(mem)},
            labels=labels,
            taints=taints,
            unschedulable=bool(rng.random() < cordon_frac),
        )

    # Background running pods establishing initial utilization + labels
    # for pairwise constraints. Requests draw from the node's REMAINING
    # capacity so the initial state is never request-overcommitted (a
    # real scheduler would have enforced that).
    apps = ("web", "db", "cache", "batch")
    remaining = {}  # node -> [cpu, mem] left
    node_caps = {}
    for nrec in b._nodes:
        node_caps[nrec["name"]] = (
            nrec["allocatable"]["cpu"], nrec["allocatable"]["memory"]
        )
        remaining[nrec["name"]] = [
            nrec["allocatable"]["cpu"], nrec["allocatable"]["memory"]
        ]
    for i in range(n_nodes):
        name = f"node-{i}"
        cap_cpu, cap_mem = node_caps[name]
        for j in range(n_running_per_node):
            rem = remaining[name]
            want_cpu = int(cap_cpu * initial_utilization / max(n_running_per_node, 1))
            want_mem = int(cap_mem * initial_utilization / max(n_running_per_node, 1))
            if tight_utilization:
                # Deterministic sizing AT the target fraction: the
                # random draw below averages half the target, which at
                # large node counts leaves so much headroom that the
                # preemption config never actually preempts.
                cpu_req, mem_req = float(max(100, want_cpu)), float(
                    max(1 << 28, want_mem)
                )
            else:
                cpu_req = float(rng.integers(100, max(101, want_cpu + 1)))
                mem_req = float(
                    rng.integers(1 << 28, max((1 << 28) + 1, want_mem + 1))
                )
            cpu_req = min(cpu_req, max(rem[0] - 100.0, 0.0))
            mem_req = min(mem_req, max(rem[1] - float(1 << 28), 0.0))
            if cpu_req <= 0 or mem_req <= 0:
                continue
            rem[0] -= cpu_req
            rem[1] -= mem_req
            run_kwargs: dict = {}
            if rng.random() < run_anti_frac:
                # A running pod whose required anti-affinity repels a
                # whole app from its zone (symmetric anti-affinity).
                run_kwargs["pod_affinity"] = [PodAffinityTerm(
                    topology_key="topology.kubernetes.io/zone",
                    selector=(MatchExpression(
                        "app", "In", (apps[int(rng.integers(len(apps)))],)
                    ),),
                    anti=True,
                    required=True,
                )]
            if rng.random() < pdb_frac:
                # PDB per (app-ish) group of running pods: a shared
                # budget of 0-2 remaining disruptions.
                g = int(rng.integers(8))
                run_kwargs["pdb_group"] = f"pdb-{g}"
                run_kwargs["pdb_disruptions_allowed"] = int(rng.integers(0, 3))
            b.add_running_pod(
                node=name,
                requests={"cpu": cpu_req, "memory": mem_req},
                priority=float(rng.integers(0, 100)),
                slack=float(rng.uniform(-0.2, 0.3)),
                labels={"app": apps[int(rng.integers(len(apps)))]},
                namespace=f"ns-{rng.integers(namespace_count)}",
                **run_kwargs,
            )

    for i in range(n_pods):
        app = apps[int(rng.integers(len(apps)))]
        kwargs: dict = {}
        if rng.random() < toleration_frac:
            kwargs["tolerations"] = [Toleration("dedicated", "Equal", "batch", "NoSchedule")]
        if rng.random() < selector_frac:
            kwargs["node_selector"] = {"disktype": "ssd"}
        if rng.random() < affinity_frac:
            kwargs["required_terms"] = [
                NodeSelectorTerm((MatchExpression("tier", "In", ("0", "1", "2")),))
            ]
            kwargs["preferred_terms"] = [
                PreferredTerm(
                    weight=float(rng.integers(1, 100)),
                    term=NodeSelectorTerm((MatchExpression("disktype", "In", ("ssd",)),)),
                )
            ]
        if rng.random() < spread_frac:
            kwargs["topology_spread"] = [
                TopologySpreadConstraint(
                    topology_key="topology.kubernetes.io/zone",
                    max_skew=2,
                    when_unsatisfiable=(
                        "DoNotSchedule" if rng.random() < 0.5 else "ScheduleAnyway"
                    ),
                    selector=(MatchExpression("app", "In", (app,)),),
                )
            ]
        if rng.random() < interpod_frac:
            anti = rng.random() < 0.5
            # Namespace scope variation (upstream podAffinityTerm
            # .namespaces): mostly own-namespace (default), sometimes an
            # explicit cross-namespace list or all-namespaces.
            ns_roll = rng.random()
            if namespace_count > 1 and ns_roll < 0.2:
                term_ns = ("*",)
            elif namespace_count > 1 and ns_roll < 0.5:
                term_ns = tuple(
                    f"ns-{k}" for k in rng.choice(
                        namespace_count,
                        size=int(rng.integers(1, min(namespace_count, 3) + 1)),
                        replace=False,
                    )
                )
            else:
                term_ns = ()
            kwargs["pod_affinity"] = [
                PodAffinityTerm(
                    topology_key="topology.kubernetes.io/zone",
                    selector=(MatchExpression("app", "In", ("db" if not anti else app,)),),
                    anti=anti,
                    required=bool(rng.random() < 0.3),
                    weight=float(rng.integers(1, 100)),
                    namespaces=term_ns,
                )
            ]
        if gang_frac > 0 and rng.random() < gang_frac:
            kwargs["pod_group"] = f"gang-{i // gang_size}"
            kwargs["pod_group_min_member"] = gang_size
        slo = float(rng.choice([0.0, 0.9, 0.95, 0.99])) if with_qos else 0.0
        b.add_pod(
            f"pod-{i}",
            requests={
                "cpu": float(rng.integers(100, 4000)),
                "memory": float(rng.integers(1 << 28, 8 << 30)),
            },
            priority=float(rng.integers(0, 1000)),
            slo_target=slo,
            observed_avail=float(rng.uniform(0.5, 1.0)),
            labels={"app": app},
            namespace=f"ns-{rng.integers(namespace_count)}",
            **kwargs,
        )
    if as_records:
        # Reshape builder-internal records into the wire-record dialect
        # snapshot_to_proto takes: gang min_member is builder-global,
        # running pods need unique names (delta-safety), and running
        # pdb_group is stored namespace-qualified as a tuple.
        pod_recs = []
        for p in b._pods:
            q = dict(p)
            if q.get("pod_group"):
                q["pod_group_min_member"] = b._groups[q["pod_group"]]
            pod_recs.append(q)
        run_recs = []
        for i, r in enumerate(b._running):
            q = dict(r)
            q["name"] = f"run-{i}"
            if q.get("pdb_group"):
                # The builder aggregates budgets in b._pdbs keyed by the
                # namespace-qualified tuple; the wire record carries the
                # bare name plus the aggregated budget.
                q["pdb_disruptions_allowed"] = b._pdbs[q["pdb_group"]]
                q["pdb_group"] = q["pdb_group"][1]
            run_recs.append(q)
        return b._nodes, pod_recs, run_recs
    return b.build()


# -- BASELINE.json config presets (SURVEY.md §6) ----------------------------


def config1_kind_like(rng: np.random.Generator, **kw):
    """QoS-weighted LeastRequested: 100 pods x 10 nodes
    (BASELINE.json:"configs"[0]; kind-cluster scale)."""
    return make_cluster(rng, 100, 10, with_qos=True, **kw)


def config2_scale(rng: np.random.Generator, n_pods: int = 10_000, n_nodes: int = 5_000, **kw):
    """NodeResourcesFit + BalancedAllocation at 10k x 5k
    (BASELINE.json:"configs"[1])."""
    return make_cluster(rng, n_pods, n_nodes, n_running_per_node=1, **kw)


def config3_pairwise(rng: np.random.Generator, n_pods: int = 2_000, n_nodes: int = 500, **kw):
    """PodTopologySpread + InterPodAffinity (BASELINE.json:"configs"[2])."""
    kw.setdefault("spread_frac", 0.5)
    kw.setdefault("interpod_frac", 0.5)
    return make_cluster(rng, n_pods, n_nodes, **kw)


def config4_gangs(rng: np.random.Generator, n_groups: int = 1_000, gang_size: int = 4,
                  n_nodes: int = 1_000, **kw):
    """Gang/coscheduling bin-pack: 1k pod-groups all-or-nothing
    (BASELINE.json:"configs"[3]). Enforcement: gang_rollback in
    kernels/assign.py (both modes) and the oracle's Permit-gate unwind."""
    return make_cluster(
        rng, n_groups * gang_size, n_nodes, gang_frac=1.0, gang_size=gang_size, **kw
    )


def config5_preemption(rng: np.random.Generator, n_pods: int = 1_000, n_nodes: int = 200, **kw):
    """Multi-tenant preemption pressure: cluster near-full so most pending
    pods need victims; a third of them PDB-covered so the victim search
    exercises the fewest-violations ranking (BASELINE.json:"configs"[4])."""
    kw.setdefault("initial_utilization", 0.9)
    kw.setdefault("n_running_per_node", 8)
    kw.setdefault("pdb_frac", 0.3)
    kw.setdefault("tight_utilization", True)
    return make_cluster(rng, n_pods, n_nodes, **kw)
