"""Double-buffered batch pipeline (SURVEY.md §2.3 "Pipeline parallel").

The schedule cycle has three phases with different engines:
  decode  — proto -> SnapshotBuilder -> padded arrays   (host CPU)
  H2D + solve — device compute                          (TPU)
  fetch   — packed result device->host                  (transport)

A sequential loop pays decode_k+1 strictly after fetch_k. Two transports
need two overlap mechanisms, and this module uses both:

  * Standard runtimes: jax dispatch is asynchronous, so dispatching
    batch k and then decoding batch k+1 on the same thread overlaps
    host decode with device compute.
  * The axon tunnel (this image): execution is DRIVEN BY THE FETCH —
    dispatch returns in <1 ms but the program only runs while a
    device->host read is in flight (measured: a 0.5 s sleep after
    dispatch does not shorten the subsequent fetch). The overlap
    therefore comes from fetching batch k on a background thread
    (np.asarray releases the GIL inside the transport wait) while the
    main thread does batch k+1's GIL-bound decode.

Wall-clock per batch approaches max(decode, solve + fetch) instead of
their sum — the "double-buffered" overlap SURVEY.md §7 hard part 6 asks
for.

This is for streams of INDEPENDENT snapshots (a sidecar serving many
schedulers, replay/bench pipelines). A single cluster's consecutive
cycles feed back (cycle k's binds change cycle k+1's snapshot), so they
cannot be pipelined — same limit as the reference's one-at-a-time
scheduleOne loop.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterable, Iterator

import numpy as np

from tpusched import ledger as ledgering
from tpusched.engine import Engine, SolveResult
from tpusched.snapshot import ClusterSnapshot


def solve_stream(
    engine: Engine,
    batches: Iterable[Any],
    decode: Callable[[Any], tuple[ClusterSnapshot, Any]] | None = None,
) -> Iterator[tuple[Any, SolveResult]]:
    """Pipeline a stream of batches through the engine.

    batches: an iterable of raw batch items. decode(item) must return
    (ClusterSnapshot, meta); None means items already ARE
    (snapshot, meta) pairs. Yields (meta, SolveResult) in order.

    The generator keeps exactly one batch in flight on the device while
    the host decodes the next (double buffering): dispatch(k) ->
    decode(k+1) -> fetch(k) -> dispatch(k+1) -> ...

    Round 6: the dispatch + background-fetch mechanics moved INTO the
    engine (Engine.solve_async, one shared ordered fetch worker), so
    this generator is now just the stream-shaped driver and the SAME
    overlap serves single requests in rpc/server.py's staged handlers.
    """
    decode = decode or (lambda item: item)

    in_flight = None  # (meta, PendingFetch)
    for item in batches:
        snap, meta = decode(item)  # overlaps the in-flight fetch
        if in_flight is not None:
            pmeta, pending = in_flight
            yield pmeta, pending.result()
        in_flight = (meta, engine.solve_async(engine.put(snap)))
    if in_flight is not None:
        pmeta, pending = in_flight
        yield pmeta, pending.result()


def warm_cycle_stream(
    engine: Engine,
    device,
    deltas: Iterable[dict],
    incremental: bool = False,
    ledger=None,
) -> Iterator[tuple[Any, SolveResult]]:
    """Pipeline consecutive DELTA CYCLES of one device-resident lineage
    through the warm-start path (ROADMAP item 3): `device` is a
    tpusched.device_state.DeviceSnapshot, each item of `deltas` is a
    dict of DeviceSnapshot.apply kwargs. Yields (ApplyStats,
    SolveResult) in order.

    Unlike solve_stream (independent snapshots), consecutive cycles here
    share one lineage and FEED FORWARD through the carried tableau —
    they cannot be reordered, but the host-side work of cycle k+1
    (apply(): record normalization, dirty-set accounting, scatter-index
    building) still overlaps cycle k's in-flight result fetch, because
    apply() mutates the host mirror and builds NEW device arrays
    functionally while the dispatched program holds the old ones.

    Contract note: the engine commits the refreshed warm handle at
    dispatch time; a caller that abandons the stream mid-flight after a
    fetch error should device.invalidate_warm("stream_error").

    incremental=True (ISSUE 12): route each cycle through the
    bounded-divergence warm path (Engine.solve_warm_async(incremental=
    True)). The assignment CARRY is committed at result-join time, so
    the stream joins cycle k BEFORE dispatching cycle k+1 — apply(k+1)
    (the host-side record work) still overlaps fetch(k), only the
    dispatch is deferred; dispatching early would seed k+1 from the
    k-1 carry and widen the divergence for no latency win (the device
    is serial across cycles of one lineage anyway).

    ledger (round 18, ISSUE 13): optional tpusched.ledger.CycleLedger;
    None falls back to the process default. Each cycle appends one
    CycleRecord (source="pipeline") at its result join — warm path
    taken, churn carried by the delta, commit rounds/frontier, and
    the XLA cache misses its dispatch paid."""
    lg = ledger or ledgering.DEFAULT

    def _join(entry):
        stats, pending, ctx = entry
        res = pending.result()
        if ctx is not None:
            evicted = 0
            if res.evicted is not None:
                evicted = int(res.evicted.sum())
            frontier = 0
            if res.inc_info:
                frontier = int(res.inc_info.get("frontier", 0))
            lg.observe(ledgering.CycleRecord(
                ts=time.time(), source="pipeline", pods=ctx["pods"],
                nodes=ctx["nodes"], running=ctx["running"],
                placed=int((res.assignment[: ctx["pods"]] >= 0).sum()),
                evicted=evicted, churn=ctx["churn"], frontier=frontier,
                rounds=int(res.rounds), warm_path=ctx["path"],
                solve_s=res.solve_seconds,
                stages=dict(solve=res.solve_seconds),
                compiles=ctx["compiles"],
                compile_s=round(ctx["compile_s"], 6),
            ))
        return stats, res

    in_flight = None  # (ApplyStats, PendingFetch, ledger ctx | None)
    for delta in deltas:
        stats = device.apply(**delta)
        marker = device.warm_marker()
        comp0 = ledgering.COMPILES.counters() if lg.enabled else (0, 0.0)
        if incremental:
            if in_flight is not None:
                yield _join(in_flight)
                in_flight = None
            pending = engine.solve_warm_async(device, incremental=True)
        else:
            pending = engine.solve_warm_async(device)
        ctx = None
        if lg.enabled:
            # Captured at dispatch: commit_warm stamped the path
            # counters, the jit wrapper recorded any compile this
            # dispatch paid, and meta still names THIS cycle's rows (a
            # concurrent next apply would shift them before the join).
            comp1 = ledgering.COMPILES.counters()
            meta = device.meta
            ctx = dict(path=device.warm_path_taken(marker),
                       pods=meta.n_pods, nodes=meta.n_nodes,
                       running=meta.n_running,
                       churn=stats.churn_records,
                       compiles=comp1[0] - comp0[0],
                       compile_s=comp1[1] - comp0[1])
        if in_flight is not None:
            yield _join(in_flight)
        in_flight = (stats, pending, ctx)
    if in_flight is not None:
        yield _join(in_flight)


def bench_overlap(
    engine: Engine,
    batches: list[Any],
    decode: Callable[[Any], tuple[ClusterSnapshot, Any]],
) -> dict:
    """Measure sequential vs pipelined wall-clock over the same batch
    list (first batch compiles and is excluded via a warmup pass).
    Returns {sequential_s, pipelined_s, speedup}."""
    # Warmup/compile on the first batch.
    snap, _ = decode(batches[0])
    np.asarray(engine._solve_packed_jit(engine.put(snap)))

    t0 = time.perf_counter()
    for item in batches:
        snap, _ = decode(item)
        np.asarray(engine._solve_packed_jit(engine.put(snap)))
    sequential = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in solve_stream(engine, batches, decode):
        pass
    pipelined = time.perf_counter() - t0
    return dict(
        sequential_s=sequential,
        pipelined_s=pipelined,
        speedup=sequential / pipelined if pipelined > 0 else float("inf"),
    )
