"""Stock-semantics oracle: a deliberately naive per-pod NumPy scheduler.

This is the parity reference demanded by SURVEY.md §4 item 2: it replays
the reference scheduler's one-pod-at-a-time cycle (SURVEY.md §3.1
`scheduleOne`) — pop highest dynamic-priority pod, Filter every node,
Score, NormalizeScore, weighted sum, pick the max, commit to the cache —
in plain NumPy with zero batching tricks. The batched TPU engine must
produce identical placements in parity mode (bit-identical, fuzz-tested).
Fast mode does NOT promise node-identical placements: every commit
couples later pods through load-balancing scores, so node agreement
collapses once commit order diverges (measured ~11% node-identical even
with no constraints; net placed-pod delta about -2% on the mixed preset
as of round 5 — run tpusched/divergence.py for current numbers). Fast
mode's contract is validity (audited) and near-equal placement COUNT,
not the same nodes.

Semantics notes (each mirrors an upstream plugin, SURVEY.md C2-C7):
  * NodeResourcesFit filter: forall r: used_r + req_r <= allocatable_r.
  * TaintToleration filter: every NoSchedule/NoExecute taint tolerated.
  * NodeAffinity filter: OR over nodeSelectorTerms, AND within a term;
    nodeSelector is ANDed into every term. Operators In/NotIn/Exists/
    DoesNotExist/Gt/Lt with apimachinery labels.Requirement semantics
    (NotIn/DoesNotExist match when the key is absent).
  * LeastRequested score: sum_r w_r * (alloc - used - req)*100/alloc / sum w.
  * BalancedAllocation score: (1 - stddev of utilisation fractions) * 100.
  * NodeAffinity score: sum of satisfied preferred-term weights,
    default-normalized to [0,100] per pod across nodes.
  * TaintToleration score: intolerable PreferNoSchedule taint count,
    inverse-normalized to [0,100].
  * PodTopologySpread: DoNotSchedule -> filter (count[dom]+1-min <= maxSkew);
    ScheduleAnyway -> inverse-normalized penalty score. Nodes missing the
    topology key are infeasible for DoNotSchedule constraints.
  * InterPodAffinity: required (anti-)affinity -> filter against running
    AND previously-assigned pending pods; preferred terms -> +-weight,
    upstream-normalized. Symmetric required anti-affinity: an *existing*
    member's (running pod's or earlier-assigned pending pod's) required
    anti-affinity term repels an incoming pod matching its selector
    (SURVEY.md C7).
  * Dynamic QoS priority (C10): effective = base + gain*pressure,
    pressure = clip(slo - observed_avail, 0, 1); pop order is stable
    descending.

Tie-break (SURVEY.md §7 hard part 2): EngineConfig.tie_break "first"
picks the lowest node index among score maxima; "seeded" reproduces
upstream's rand-among-max as a deterministic per-pod hash pick
(qos.tie_hash), implemented bit-identically here (Oracle.solve's
tie-set pick) and on device (kernels.assign.pick_node /
pick_node_batch), so parity holds for any seed in both engine modes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np

from tpusched.config import (
    DO_NOT_SCHEDULE,
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    EFFECT_PREFER_NO_SCHEDULE,
    EngineConfig,
    MAX_NODE_SCORE,
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
    SCHEDULE_ANYWAY,
)
from tpusched.qos import (
    effective_priority,
    effective_weights,
    evict_cost_raw,
    pressure_of,
    tie_hash,
    victim_effective_priority,
)
from tpusched.snapshot import ClusterSnapshot


@dataclasses.dataclass
class OracleResult:
    assignment: np.ndarray       # [P] int32 node index or -1
    order: np.ndarray            # [P] int32 pop order (indices into pods)
    chosen_score: np.ndarray     # [P] f32 score of the chosen node (-inf if none)
    final_used: np.ndarray       # [N, R] f32 node used after all commits
    evicted: np.ndarray | None = None  # [M] bool preemption victims


def _np(x: Any) -> np.ndarray:
    return np.asarray(x)


class Oracle:
    def __init__(self, snap: ClusterSnapshot,
                 config: EngineConfig) -> None:
        self.snap = snap
        self.cfg = config
        self.nodes = snap.nodes
        self.pods = snap.pods
        self._atom_sat_nodes: np.ndarray | None = None
        # Preemption state: evicted running pods stop counting as
        # members everywhere (capacity, pairwise counts, anti holders).
        self._evicted = np.zeros(_np(snap.running.valid).shape[0], bool)

    # -- atoms over node labels --------------------------------------------

    def atom_sat_nodes(self) -> np.ndarray:
        """[A, N] bool: does node n satisfy match-expression atom a."""
        if self._atom_sat_nodes is not None:
            return self._atom_sat_nodes
        at = self.snap.atoms
        key, op, pairs, num, avalid = map(_np, (at.key, at.op, at.pairs, at.num, at.valid))
        lp, lk, ln = map(_np, (self.nodes.label_pairs, self.nodes.label_keys,
                               self.nodes.label_nums))
        A, N = key.shape[0], lp.shape[0]
        sat = np.zeros((A, N), bool)
        for a in range(A):
            if not avalid[a]:
                continue
            sat[a] = _atom_sat_row(key[a], op[a], pairs[a], num[a], lp, lk, ln)
        self._atom_sat_nodes = sat
        return sat

    def atom_sat_over(self, lp: np.ndarray, lk: np.ndarray) -> np.ndarray:
        """[A, X] bool atom satisfaction over arbitrary label sets (pods)."""
        at = self.snap.atoms
        key, op, pairs, num, avalid = map(_np, (at.key, at.op, at.pairs, at.num, at.valid))
        A, X = key.shape[0], lp.shape[0]
        sat = np.zeros((A, X), bool)
        ln = np.full(lp.shape, np.nan, np.float32)
        for a in range(A):
            if avalid[a]:
                sat[a] = _atom_sat_row(key[a], op[a], pairs[a], num[a], lp, lk, ln)
        return sat

    # -- filters ------------------------------------------------------------

    def resource_fit(self, p: int, used: np.ndarray) -> np.ndarray:
        alloc = _np(self.nodes.allocatable)
        req = _np(self.pods.requests)[p]
        return np.all(used + req <= alloc, axis=1)

    def taints_ok(self, p: int) -> np.ndarray:
        tids = _np(self.nodes.taint_ids)          # [N, TN]
        effect = _np(self.snap.taint_effect)      # [VT]
        tol = _np(self.pods.tolerated)[p]         # [VT]
        N = tids.shape[0]
        ok = np.ones(N, bool)
        for n in range(N):
            for t in tids[n]:
                if t < 0:
                    continue
                if effect[t] in (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE) and not tol[t]:
                    ok[n] = False
        return ok

    def node_affinity_ok(self, p: int) -> np.ndarray:
        sat = self.atom_sat_nodes()                      # [A, N]
        atoms = _np(self.pods.req_term_atoms)[p]         # [T, AT]
        tvalid = _np(self.pods.req_term_valid)[p]        # [T]
        N = sat.shape[1]
        if not tvalid.any():
            return np.ones(N, bool)
        ok = np.zeros(N, bool)
        for t in range(atoms.shape[0]):
            if not tvalid[t]:
                continue
            term_ok = np.ones(N, bool)
            for a in atoms[t]:
                if a >= 0:
                    term_ok &= sat[a]
            ok |= term_ok
        return ok

    # -- scores (each returns [N] f32 in [0, 100]) --------------------------

    def score_least_requested(self, p: int, used: np.ndarray) -> np.ndarray:
        alloc = _np(self.nodes.allocatable)
        req = _np(self.pods.requests)[p]
        w = np.asarray(self.cfg.score_weights_vector(), np.float32)
        wsum = w.sum()
        with np.errstate(divide="ignore", invalid="ignore"):
            per_r = np.where(
                alloc > 0, (alloc - used - req) * MAX_NODE_SCORE / alloc, 0.0
            )
        per_r = np.where(per_r < 0, 0.0, per_r)  # over-requested -> 0 (upstream)
        return (per_r * w).sum(axis=1).astype(np.float32) / max(wsum, 1e-9)

    def score_balanced(self, p: int, used: np.ndarray) -> np.ndarray:
        alloc = _np(self.nodes.allocatable)
        req = _np(self.pods.requests)[p]
        # Masked-sum formulation identical to kernels/score.py
        # balanced_allocation so parity holds bitwise.
        sel = (np.asarray(self.cfg.score_weights_vector(), np.float32) > 0).astype(np.float32)
        k = max(sel.sum(), 1.0)
        with np.errstate(divide="ignore", invalid="ignore"):
            frac = np.where(alloc > 0, (used + req) / alloc, 1.0)
        frac = np.clip(frac, 0.0, 1.0)
        mean = (frac * sel).sum(axis=1, keepdims=True) / k
        var = (((frac - mean) ** 2) * sel).sum(axis=1) / k
        return ((1.0 - np.sqrt(var)) * MAX_NODE_SCORE).astype(np.float32)

    def score_node_affinity(self, p: int) -> np.ndarray:
        sat = self.atom_sat_nodes()
        atoms = _np(self.pods.pref_term_atoms)[p]
        tvalid = _np(self.pods.pref_term_valid)[p]
        weight = _np(self.pods.pref_weight)[p]
        N = sat.shape[1]
        raw = np.zeros(N, np.float32)
        for t in range(atoms.shape[0]):
            if not tvalid[t]:
                continue
            term_ok = np.ones(N, bool)
            for a in atoms[t]:
                if a >= 0:
                    term_ok &= sat[a]
            raw += weight[t] * term_ok
        return _default_normalize(raw, _np(self.nodes.valid))

    def score_taint_toleration(self, p: int) -> np.ndarray:
        tids = _np(self.nodes.taint_ids)
        effect = _np(self.snap.taint_effect)
        tol = _np(self.pods.tolerated)[p]
        N = tids.shape[0]
        count = np.zeros(N, np.float32)
        for n in range(N):
            for t in tids[n]:
                if t >= 0 and effect[t] == EFFECT_PREFER_NO_SCHEDULE and not tol[t]:
                    count[n] += 1
        nvalid = _np(self.nodes.valid)
        mx = count[nvalid].max() if nvalid.any() else 0.0
        if mx <= 0:
            return np.full(N, MAX_NODE_SCORE, np.float32)
        return ((mx - count) * MAX_NODE_SCORE / mx).astype(np.float32)

    # -- pairwise: topology spread + inter-pod affinity ---------------------

    def _ns_ok(self, sig: int, member_ns: np.ndarray) -> np.ndarray:
        """[X] bool: member namespaces within signature sig's scope
        (upstream podAffinityTerm.namespaces / same-namespace spread)."""
        sigs = self.snap.sigs
        if bool(_np(sigs.ns_all)[sig]):
            return np.ones(member_ns.shape[0], bool)
        allowed = _np(sigs.ns)[sig]
        allowed = allowed[allowed >= 0]
        return np.isin(member_ns, allowed)

    def _match_counts(self, sel_atoms: np.ndarray, sig: int,
                      assigned_pods: list[int]) -> np.ndarray:
        """[X] bool: which of running+assigned pods match the selector
        within the signature's namespace scope. A selector with zero
        atoms matches everything (upstream empty label selector)."""
        run = self.snap.running
        ap = list(assigned_pods)
        plp, plk = _np(self.pods.label_pairs), _np(self.pods.label_keys)
        pns = _np(self.pods.namespace)
        lp = np.concatenate([_np(run.label_pairs), plp[ap]], axis=0)
        lk = np.concatenate([_np(run.label_keys), plk[ap]], axis=0)
        mns = np.concatenate([_np(run.namespace), pns[ap]])
        valid = np.concatenate(
            [_np(run.valid) & ~self._evicted, np.ones(len(ap), bool)]
        )
        sat = self.atom_sat_over(lp, lk)
        match = valid & self._ns_ok(sig, mns)
        for a in sel_atoms:
            if a >= 0:
                match &= sat[a]
        return match

    def spread_ok_and_penalty(
        self, p: int, assigned_nodes: list[int], assigned_pods: list[int]
    ) -> tuple[np.ndarray, np.ndarray]:
        """Returns (feasible [N] bool, penalty [N] f32) for all spread
        constraints of pod p given already-committed pending pods."""
        nodes, pods = self.nodes, self.pods
        dom = _np(nodes.domain)                       # [N, TK]
        nvalid = _np(nodes.valid)
        N = dom.shape[0]
        ok = np.ones(N, bool)
        penalty = np.zeros(N, np.float32)
        tsk = _np(pods.ts_key)[p]
        tsv = _np(pods.ts_valid)[p]
        if not tsv.any():
            return ok, penalty
        run_nodes = _np(self.snap.running.node_idx)
        member_nodes = np.concatenate(
            [run_nodes, np.asarray(assigned_nodes, np.int32)]
        ) if assigned_pods else run_nodes
        # Eligible nodes for domain discovery: honor the pod's own node
        # affinity (upstream NodeAffinityPolicy: Honor default).
        eligible = nvalid & self.node_affinity_ok(p)
        for c in range(tsk.shape[0]):
            if not tsv[c]:
                continue
            key = tsk[c]
            has_key = dom[:, key] >= 0
            match = self._match_counts(
                _np(pods.ts_sel_atoms)[p, c], int(_np(pods.ts_sig)[p, c]),
                assigned_pods,
            )
            # count matching member pods per domain of this topo key
            member_dom = np.where(member_nodes >= 0, dom[member_nodes, key], -1)
            n_dom = int(dom[:, key].max()) + 1 if has_key.any() else 0
            counts = np.zeros(max(n_dom, 1), np.float32)
            for md, m in zip(member_dom, match):
                if m and md >= 0:
                    counts[md] += 1
            elig_doms = np.unique(dom[eligible & has_key, key]) if (eligible & has_key).any() else np.array([], np.int64)
            min_count = counts[elig_doms].min() if elig_doms.size else 0.0
            node_count = np.where(has_key, counts[np.clip(dom[:, key], 0, None)], np.inf)
            if _np(pods.ts_when)[p, c] == DO_NOT_SCHEDULE:
                ok &= has_key & (node_count + 1 - min_count <= _np(pods.ts_max_skew)[p, c])
            else:
                penalty += np.where(has_key, node_count, counts.max() if n_dom else 0.0)
        return ok, penalty

    def interpod_ok_and_raw(
        self, p: int, assigned_nodes: list[int], assigned_pods: list[int]
    ) -> tuple[np.ndarray, np.ndarray]:
        """(feasible [N] bool, preferred raw score [N] f32) over the pod's
        inter-pod (anti-)affinity terms."""
        nodes, pods = self.nodes, self.pods
        dom = _np(nodes.domain)
        N = dom.shape[0]
        ok = np.ones(N, bool)
        raw = np.zeros(N, np.float32)
        iav = _np(pods.ia_valid)[p]
        if not iav.any():
            return ok, raw
        plp, plk = _np(pods.label_pairs), _np(pods.label_keys)
        run_nodes = _np(self.snap.running.node_idx)
        member_nodes = np.concatenate(
            [run_nodes, np.asarray(assigned_nodes, np.int32)]
        ) if assigned_pods else run_nodes
        for t in range(iav.shape[0]):
            if not iav[t]:
                continue
            key = _np(pods.ia_key)[p, t]
            match = self._match_counts(
                _np(pods.ia_sel_atoms)[p, t], int(_np(pods.ia_sig)[p, t]),
                assigned_pods,
            )
            member_dom = np.where(member_nodes >= 0, dom[member_nodes, key], -1)
            # domain -> has matching pod?
            has_key = dom[:, key] >= 0
            n_dom = int(dom[:, key].max()) + 1 if has_key.any() else 0
            dom_has = np.zeros(max(n_dom, 1), bool)
            for md, m in zip(member_dom, match):
                if m and md >= 0:
                    dom_has[md] = True
            node_has = has_key & dom_has[np.clip(dom[:, key], 0, None)]
            anti = _np(pods.ia_anti)[p, t]
            if _np(pods.ia_required)[p, t]:
                # Required affinity: node's domain must contain a match
                # (nodes missing the key fail). Upstream special case: if
                # NO pod in the cluster matches the selector but the
                # incoming pod matches its own selector, the term is
                # satisfied on any node with the key (lets the first pod
                # of a self-affine group schedule). Required
                # anti-affinity: no match in the domain (missing key ok).
                if anti:
                    ok &= ~node_has
                else:
                    self_sat = self.atom_sat_over(
                        plp[p : p + 1], plk[p : p + 1]
                    )[:, 0]
                    self_match = bool(_np(pods.valid)[p]) and bool(
                        self._ns_ok(
                            int(_np(pods.ia_sig)[p, t]),
                            _np(pods.namespace)[p : p + 1],
                        )[0]
                    )
                    for a in _np(pods.ia_sel_atoms)[p, t]:
                        if a >= 0:
                            self_match = self_match and bool(self_sat[a])
                    all_zero = not match.any()
                    ok &= node_has | (all_zero & self_match & has_key)
            else:
                w = _np(pods.ia_weight)[p, t]
                raw += np.where(node_has, -w if anti else w, 0.0)
        return ok, raw

    def symmetric_anti_ok(
        self, p: int, assigned_nodes: list[int], assigned_pods: list[int]
    ) -> np.ndarray:
        """[N] bool: no member (running pod or already-assigned pending
        pod) holds a required anti-affinity term whose selector matches
        pod p with node n inside the holder's topology domain (upstream
        symmetric anti-affinity)."""
        snap, nodes, pods = self.snap, self.nodes, self.pods
        dom = _np(nodes.domain)
        N = dom.shape[0]
        ok = np.ones(N, bool)
        sig_key = _np(snap.sigs.key)
        sig_atoms = _np(snap.sigs.atoms)
        if not _np(snap.sigs.valid).any():
            return ok
        plp = _np(pods.label_pairs)[p : p + 1]
        plk = _np(pods.label_keys)[p : p + 1]
        sat_p = self.atom_sat_over(plp, plk)[:, 0]           # [A]

        holders: list[tuple[int, int]] = []                  # (sig, node)
        run = self.snap.running
        ranti, rnode, rvalid = map(_np, (run.anti_sig, run.node_idx, run.valid))
        for m in range(ranti.shape[0]):
            if not rvalid[m] or rnode[m] < 0 or self._evicted[m]:
                continue
            for s in ranti[m]:
                if s >= 0:
                    holders.append((int(s), int(rnode[m])))
        ia_sig, ia_anti, ia_req, ia_valid = map(
            _np, (pods.ia_sig, pods.ia_anti, pods.ia_required, pods.ia_valid)
        )
        for q, nq in zip(assigned_pods, assigned_nodes):
            for t in range(ia_sig.shape[1]):
                if ia_valid[q, t] and ia_anti[q, t] and ia_req[q, t]:
                    holders.append((int(ia_sig[q, t]), int(nq)))
        for s, hn in holders:
            match = bool(_np(pods.valid)[p]) and bool(
                self._ns_ok(int(s), _np(pods.namespace)[p : p + 1])[0]
            )
            for a in sig_atoms[s]:
                if a >= 0:
                    match = match and bool(sat_p[a])
            if not match:
                continue
            key = sig_key[s]
            hd = dom[hn, key]
            if hd < 0:
                continue  # holder's node lacks the key: no domain to poison
            ok &= dom[:, key] != hd
        return ok

    # -- the per-pod cycle ---------------------------------------------------

    def feasible_and_score(
        self, p: int, used: np.ndarray,
        assigned_nodes: list[int] | None = None,
        assigned_pods: list[int] | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """One scheduling cycle's Filter + Score for pod p: returns
        (feasible [N] bool, total weighted score [N] f32)."""
        assigned_nodes = assigned_nodes or []
        assigned_pods = assigned_pods or []
        nvalid = _np(self.nodes.valid)
        spread_ok, spread_penalty = self.spread_ok_and_penalty(
            p, assigned_nodes, assigned_pods
        )
        ia_ok, ia_raw = self.interpod_ok_and_raw(p, assigned_nodes, assigned_pods)
        feasible = (
            nvalid
            # Cordon filter with the NodeUnschedulable toleration escape.
            & (_np(self.nodes.schedulable)
               | bool(_np(self.pods.tolerates_unsched)[p]))
            & self.resource_fit(p, used)
            & self.taints_ok(p)
            & self.node_affinity_ok(p)
            & spread_ok
            & ia_ok
            & self.symmetric_anti_ok(p, assigned_nodes, assigned_pods)
        )
        w = effective_weights(
            self.cfg,
            pressure_of(_np(self.pods.slo_target)[p], _np(self.pods.observed_avail)[p]),
        )
        # Grouping mirrors kernels/assign.py pod_cycle (static NodeAffinity
        # + TaintToleration term parenthesised together) for f32 parity.
        static = (
            w["node_affinity"] * self.score_node_affinity(p)
            + w["taint_toleration"] * self.score_taint_toleration(p)
        ).astype(np.float32)
        score = (
            w["least_requested"] * self.score_least_requested(p, used)
            + w["balanced_allocation"] * self.score_balanced(p, used)
            + static
            + w["topology_spread"] * _inverse_normalize(spread_penalty, nvalid)
            + w["interpod_affinity"] * _upstream_normalize(ia_raw, nvalid)
        ).astype(np.float32)
        return feasible, score

    def try_preempt(
        self, p: int, p_prio: float, used: np.ndarray,
        assigned_nodes: list[int], assigned_pods: list[int],
    ) -> tuple[int, list[int]]:
        """PostFilter (SURVEY.md C9): find the minimum-cost eligible
        victim prefix per allowed node, pick the cheapest node. Mirrors
        kernels/preempt.py exactly (same cost shift, same stable
        cost-sort, same first-feasible-prefix rule). Returns
        (node or -1, victim running-pod indices)."""
        cfg = self.cfg
        run = self.snap.running
        rvalid, rnode = _np(run.valid), _np(run.node_idx)
        M = rvalid.shape[0]
        if not cfg.preemption or M == 0:
            return -1, []
        if _np(self.pods.group)[p] >= 0:
            # Gang members never preempt: their placement is provisional
            # until quorum, and evicting for a provisional placement
            # would strand the victims (mirrors kernels/assign.py).
            return -1, []
        spread_ok, _ = self.spread_ok_and_penalty(p, assigned_nodes, assigned_pods)
        ia_ok, _ = self.interpod_ok_and_raw(p, assigned_nodes, assigned_pods)
        allowed = (
            _np(self.nodes.valid)
            & (_np(self.nodes.schedulable)
               | bool(_np(self.pods.tolerates_unsched)[p]))
            & self.taints_ok(p)
            & self.node_affinity_ok(p)
            & spread_ok
            & ia_ok
            & self.symmetric_anti_ok(p, assigned_nodes, assigned_pods)
        )
        N = allowed.shape[0]
        vprio = np.asarray(
            victim_effective_priority(cfg, _np(run.priority), _np(run.slack)),
            np.float32,
        )
        raw = np.asarray(
            evict_cost_raw(cfg, _np(run.priority), _np(run.slack)), np.float32
        )
        mn = raw[rvalid].min() if rvalid.any() else np.float32(0.0)
        cost = (raw - mn + 1.0).astype(np.float32)
        elig = (
            rvalid & ~self._evicted & (rnode >= 0)
            & (vprio + cfg.qos.preemption_margin < p_prio)
        )
        alloc = _np(self.nodes.allocatable)
        req_p = _np(self.pods.requests)[p]
        rreq = _np(run.requests)
        # Victim-prefix search, arithmetic mirroring kernels/preempt.py
        # step for step (global f32 cumsum minus segment offset over the
        # same (node, cost) sort) so fit/cost decisions agree with the
        # device to the last ULP the scan association allows.
        node_m = np.where(rvalid & (rnode >= 0), rnode, N)
        perm = np.lexsort((cost, node_m))
        node_s = node_m[perm]
        idx = np.arange(M)
        boundary = np.concatenate([[True], node_s[1:] != node_s[:-1]]) if M else np.zeros(0, bool)
        seg_start = np.maximum.accumulate(np.where(boundary, idx, 0))
        elig_s = elig[perm]
        # PDB violation flags, mirroring kernels/preempt.py: a victim
        # violates its budget when the same-budget count within its
        # node-segment prefix (incl. itself) plus earlier preemptors'
        # evictions exceeds the remaining allowance. Prefixes are ranked
        # lexicographically by (violations, cost) — never summed into
        # one penalty channel, so f32 parity with the device holds.
        pdb_allowed = _np(self.snap.pdb_allowed)
        GP = pdb_allowed.shape[0]
        if GP:
            run_pdb = _np(run.pdb_group)
            pdb_s = run_pdb[perm]
            consumed = np.zeros(GP, np.float32)
            for m in range(M):
                if self._evicted[m] and run_pdb[m] >= 0 and rvalid[m]:
                    consumed[run_pdb[m]] += 1.0
            remaining = pdb_allowed - consumed
            pdb_clip = np.clip(pdb_s, 0, None)
            gsel = (
                (np.arange(GP)[:, None] == pdb_clip[None, :])
                & (elig_s & (pdb_s >= 0))[None, :]
            )
            cum_g = np.cumsum(gsel.astype(np.float32), axis=1)
            my_cum = cum_g[pdb_clip, idx]
            off_g = np.where(
                seg_start > 0,
                cum_g[pdb_clip, np.maximum(seg_start - 1, 0)], 0.0,
            )
            within_cnt = my_cum - off_g
            viol = elig_s & (pdb_s >= 0) & (within_cnt > remaining[pdb_clip])
        else:
            viol = np.zeros(M, bool)
        req_s = np.where(elig_s[:, None], rreq[perm], 0.0).astype(np.float32)
        cum_req = np.cumsum(req_s, axis=0, dtype=np.float32)
        cum_cost = np.cumsum(
            np.where(elig_s, cost[perm], 0.0), dtype=np.float32
        )
        cum_viol = np.cumsum(viol.astype(np.float32), dtype=np.float32)
        off_req = np.where(
            (seg_start > 0)[:, None], cum_req[np.maximum(seg_start - 1, 0)], 0.0
        )
        off_cost = np.where(
            seg_start > 0, cum_cost[np.maximum(seg_start - 1, 0)], 0.0
        )
        off_viol = np.where(
            seg_start > 0, cum_viol[np.maximum(seg_start - 1, 0)], 0.0
        )
        within_req = cum_req - off_req
        within_cost = cum_cost - off_cost
        within_viol = cum_viol - off_viol
        cap_node = np.minimum(node_s, N - 1)
        fits = elig_s & np.all(
            used[cap_node] - within_req + req_p[None, :] <= alloc[cap_node],
            axis=-1,
        )
        # Lexicographic (violations, cost) MIN feasible prefix per node
        # (ties -> first position), mirroring the kernel's two-stage
        # scatter-min + argmin selection exactly.
        node_viol = np.full(N + 1, np.inf, np.float32)
        node_cost = np.full(N + 1, np.inf, np.float32)
        for i in range(M):
            if fits[i]:
                n_i = node_s[i]
                if within_viol[i] < node_viol[n_i]:
                    node_viol[n_i] = within_viol[i]
        for i in range(M):
            if fits[i] and within_viol[i] == node_viol[node_s[i]]:
                n_i = node_s[i]
                if within_cost[i] < node_cost[n_i]:
                    node_cost[n_i] = within_cost[i]
        nvalid = _np(self.nodes.valid)
        ok_node = allowed & nvalid
        viol_total = np.where(ok_node, node_viol[:N], np.inf)
        min_viol = viol_total.min() if N else np.inf
        total = np.where(
            ok_node & (viol_total == min_viol), node_cost[:N], np.inf
        )
        best_n = int(np.argmin(total))
        if not np.isfinite(total[best_n]):
            return -1, []
        cand = fits & (node_s == best_n) & (within_viol == min_viol)
        masked = np.where(cand, within_cost, np.inf)
        fp = int(np.argmin(masked))
        sel_s = (node_s == best_n) & elig_s & (idx <= fp)
        return best_n, [int(perm[i]) for i in range(M) if sel_s[i]]

    def solve(self) -> OracleResult:
        pods, nodes = self.pods, self.nodes
        pvalid = _np(pods.valid)
        P = pvalid.shape[0]
        used = _np(nodes.used).copy()
        prio = effective_priority(
            self.cfg, _np(pods.base_priority), _np(pods.slo_target),
            _np(pods.observed_avail),
        )
        # Stable descending pop order over valid pods (SURVEY.md §3.1
        # queue.Pop of max dynamic priority; ties by submission order =
        # pod index).
        order = np.argsort(-np.where(pvalid, prio, -np.inf), kind="stable")
        order = order[pvalid[order]]
        assignment = np.full(P, -1, np.int32)
        chosen_score = np.full(P, -np.inf, np.float32)
        assigned_nodes: list[int] = []
        assigned_pods: list[int] = []
        self._evicted[:] = False
        rreq = _np(self.snap.running.requests)
        for p in order:
            feasible, score = self.feasible_and_score(
                int(p), used, assigned_nodes, assigned_pods
            )
            if not feasible.any():
                n, victims = self.try_preempt(
                    int(p), float(prio[p]), used, assigned_nodes, assigned_pods
                )
                if n >= 0:
                    for m in victims:
                        used[n] -= rreq[m]
                        self._evicted[m] = True
                    assignment[p] = n  # chosen_score stays -inf (no rescore)
                    used[n] += _np(pods.requests)[p]
                    assigned_nodes.append(n)
                    assigned_pods.append(int(p))
                continue
            masked = np.where(feasible, score, -np.inf)
            if self.cfg.tie_break == "seeded":
                mx = masked.max()
                ties = np.where(masked == mx)[0]
                n = int(ties[tie_hash(self.cfg.tie_seed, int(p)) % len(ties)])
            else:
                n = int(np.argmax(masked))  # first max = tie_break "first"
            assignment[p] = n
            chosen_score[p] = masked[n]
            used[n] += _np(pods.requests)[p]
            assigned_nodes.append(n)
            assigned_pods.append(int(p))
        # Gang all-or-nothing Permit gate (SURVEY.md C8): groups below
        # their minMember quorum unwind entirely (assignments, capacity).
        group = _np(pods.group)
        gmin = _np(self.snap.group_min_member)
        if gmin.shape[0]:
            cnt = np.zeros(gmin.shape[0], np.int64)
            for p in range(P):
                if assignment[p] >= 0 and group[p] >= 0:
                    cnt[group[p]] += 1
            for p in range(P):
                gp = group[p]
                if assignment[p] >= 0 and gp >= 0 and cnt[gp] < gmin[gp]:
                    used[assignment[p]] -= _np(pods.requests)[p]
                    assignment[p] = -1
                    chosen_score[p] = -np.inf
        return OracleResult(
            assignment=assignment,
            order=order.astype(np.int32),
            chosen_score=chosen_score,
            final_used=used,
            evicted=self._evicted.copy(),
        )


def validate_assignment(snap: ClusterSnapshot, cfg: EngineConfig,
                        assignment: np.ndarray,
                        commit_key: np.ndarray | None = None,
                        evicted: np.ndarray | None = None,
                        hard_only: bool = True) -> list[str]:
    """Independent validity audit of any assignment (used to check the
    fast mode's guarantees): capacity respected, static predicates hold,
    and every placed pod's DoNotSchedule-spread / required inter-pod
    constraints hold against its commit-time state.

    commit_key [P]: pods with a strictly smaller key committed earlier.
    A pod is checked against members committed at key <= its own
    (excluding itself) — upstream semantics check only the incoming pod,
    so later commits may legally raise an earlier pod's skew; the fast
    mode additionally guarantees validity against same-key (same-round)
    commits, which this reproduces. With commit_key=None the check is
    against the FINAL state (strictly stronger; holds for parity mode
    only in the absence of retroactive skew).

    GANG-ROLLBACK CAVEAT: a pod whose required affinity (or spread
    headroom) was satisfied at commit time by gang members that the
    all-or-nothing gate later rolled back can be reported as violating
    here, in BOTH modes and in the oracle itself — the audit only sees
    the final placed set. This mirrors upstream optimism: a pod that
    passed Filter using an assumed gang member binds even if that gang
    later un-reserves; nothing re-schedules the dependent. Audits of
    gang-bearing snapshots should treat such reports as the documented
    optimistic-assume edge, not an engine defect (see
    tests/test_gangs.py::test_gang_rollback_audit_caveat).

    Violations consistent with that caveat carry a machine-readable
    " [gang-optimism]" suffix: the constraint flips to satisfied when
    the snapshot's UNPLACED gang members are hypothetically restored to
    the placed set (the audit cannot know their rolled-back provisional
    nodes, so it tries a small greedy family of candidate placements —
    each member alone at each domain-representative node, all members
    at one node, and members round-robin across domains). A flip under
    any tried restoration applies the tag; exotic multi-member cases
    may stay untagged, erring toward reporting a hard violation — the
    tag is never spurious, and gang-free snapshots are never tagged
    (there is nothing to restore).

    hard_only (default True): drop tagged gang-optimism caveats from
    the returned list, so every consumer audits the HARD-violation set
    by default. Pass hard_only=False to also see the tagged caveats
    (opt-in diagnostics; see
    tests/test_gangs.py::test_gang_rollback_audit_caveat).

    EVICTION-TIMING CAVEAT: with `evicted` given, the audit cannot
    know WHEN each eviction happened relative to each commit, so
    pairwise violations are reported only when they hold with the
    evictions applied AND ignored (see the inline note) — faithful
    engine output never yields a false report; a placement valid only
    under a strict subset of the evictions may go unreported.

    Returns human-readable violation strings (empty = valid)."""
    ora = Oracle(snap, cfg)
    pods, nodes = snap.pods, snap.nodes
    assignment = np.asarray(assignment)
    placed = [
        (p, int(n)) for p, n in enumerate(assignment)
        if n >= 0 and _np(pods.valid)[p]
    ]
    out = []
    used = _np(nodes.used).copy()
    if evicted is not None:
        evicted = np.asarray(evicted)
        ora._evicted[:] = evicted  # members stop counting in pairwise checks
        run = snap.running
        rnode, rreq = _np(run.node_idx), _np(run.requests)
        for m in np.argwhere(evicted).ravel():
            if rnode[m] >= 0:
                used[rnode[m]] -= rreq[m]
    for p, n in placed:
        used[n] += _np(pods.requests)[p]
    over = used > _np(nodes.allocatable) + 1e-3
    for n in np.argwhere(over.any(axis=1)).ravel():
        if _np(nodes.valid)[n]:
            out.append(f"node {n}: capacity exceeded {used[n]}")
    # Gang-optimism tagging support (see docstring): the unplaced valid
    # gang members a rollback could have removed, and restoration
    # candidates (one representative node per topology domain). Both
    # lists are CAPPED — the search is a diagnostic aid, and each
    # family costs a full oracle re-check over the placed set; beyond
    # the caps a report simply stays untagged (conservative direction).
    _TAG_MEMBER_CAP, _TAG_CAND_CAP = 32, 16
    group = _np(pods.group)
    gmin = _np(snap.group_min_member)
    pods_valid = _np(pods.valid)
    restorable = (
        [int(q) for q in range(assignment.shape[0])
         if group[q] >= 0 and assignment[q] < 0 and pods_valid[q]]
        [:_TAG_MEMBER_CAP]
        if gmin.shape[0] else []
    )

    def _restore_candidates(n: int) -> list[int]:
        dom = _np(nodes.domain)
        nvalid = _np(nodes.valid)
        cands = {int(n)}
        for k in range(dom.shape[1]):
            seen: set[int] = set()
            for m in np.argwhere(nvalid).ravel():
                d = int(dom[m, k])
                if d >= 0 and d not in seen:
                    seen.add(d)
                    cands.add(int(m))
        return sorted(cands)[:_TAG_CAND_CAP]

    def _gang_tag(p: int, n: int, others: "list[tuple[int, int]]",
                  check: "Callable[[list[int], list[int]], Any]") -> str:
        """' [gang-optimism]' iff some tried hypothetical restoration
        of the unplaced gang members satisfies the constraint."""
        if not restorable:
            return ""
        cands = _restore_candidates(n)
        families = [[(u, c)] for u in restorable for c in cands]
        families += [[(u, c) for u in restorable] for c in cands]
        families.append(
            [(u, cands[i % len(cands)]) for i, u in enumerate(restorable)]
        )
        for fam in families:
            aug = others + fam
            if check([m for _, m in aug], [q for q, _ in aug]):
                return " [gang-optimism]"
        return ""

    # Retroactive-eviction ambiguity (round 5): the audit applies ALL
    # evictions up front, but a pod committed BEFORE a later preemptor's
    # eviction legitimately counted the evicted member in its own
    # check (upstream checks the incoming pod against the cache of its
    # cycle). The audit has no per-eviction timing, so a pairwise
    # violation is reported only if it holds under BOTH timing extremes
    # — evictions applied AND evictions ignored. One-sided: no false
    # reports on faithful engine output; an exotic placement valid only
    # under a strict SUBSET of the evictions could go unreported.
    ora_noev = None
    if evicted is not None and evicted.any() and snap.sigs.key.shape[0]:
        ora_noev = Oracle(snap, cfg)

    def _both(check_fn: "Callable[..., np.ndarray]", p: int,
              on: "list[int]", op: "list[int]", n: int) -> bool:
        """True iff the check FAILS under both eviction timings."""
        if check_fn(ora, p, on, op)[n]:
            return False
        return ora_noev is None or not check_fn(ora_noev, p, on, op)[n]

    sp_fn = lambda o, p, on, op: o.spread_ok_and_penalty(p, on, op)[0]
    ia_fn = lambda o, p, on, op: o.interpod_ok_and_raw(p, on, op)[0]
    sym_fn = lambda o, p, on, op: o.symmetric_anti_ok(p, on, op)

    for p, n in placed:
        if not _np(nodes.valid)[n]:
            out.append(f"pod {p}: placed on invalid node {n}")
            continue
        if not _np(nodes.schedulable)[n] and not _np(
            pods.tolerates_unsched
        )[p]:
            out.append(f"pod {p}: placed on cordoned node {n}")
        if not ora.taints_ok(p)[n]:
            out.append(f"pod {p}: node {n} has untolerated taint")
        if not ora.node_affinity_ok(p)[n]:
            out.append(f"pod {p}: node {n} fails required node affinity")
        if commit_key is None:
            others = [(q, m) for q, m in placed if q != p]
        else:
            others = [
                (q, m) for q, m in placed
                if q != p and commit_key[q] <= commit_key[p]
            ]
        others_n = [m for _, m in others]
        others_p = [q for q, _ in others]
        if _both(sp_fn, p, others_n, others_p, n):
            tag = _gang_tag(
                p, n, others,
                lambda on, op: ora.spread_ok_and_penalty(p, on, op)[0][n],
            )
            out.append(
                f"pod {p}: node {n} violates DoNotSchedule spread{tag}"
            )
        if _both(ia_fn, p, others_n, others_p, n):
            tag = _gang_tag(
                p, n, others,
                lambda on, op: ora.interpod_ok_and_raw(p, on, op)[0][n],
            )
            out.append(
                f"pod {p}: node {n} violates required pod affinity{tag}"
            )
        if _both(sym_fn, p, others_n, others_p, n):
            # Restoring members can only ADD anti holders, never remove
            # them, so a symmetric-anti violation cannot be
            # gang-optimism: always untagged.
            out.append(
                f"pod {p}: node {n} violates a member's symmetric anti-affinity"
            )
    # Gang all-or-nothing: a group with ANY placed member must have at
    # least minMember placed (SURVEY.md C8).
    if gmin.shape[0]:
        cnt: dict[int, int] = {}
        for p, n in placed:
            if group[p] >= 0:
                cnt[int(group[p])] = cnt.get(int(group[p]), 0) + 1
        for g, c in sorted(cnt.items()):
            if c < gmin[g]:
                out.append(
                    f"group {g}: {c} placed < minMember {gmin[g]} "
                    "(partial gang placement)"
                )
    if hard_only:
        out = [v for v in out if "[gang-optimism]" not in v]
    return out


# ---------------------------------------------------------------------------


def _atom_sat_row(key: int, op: int, pairs: np.ndarray, num: float,
                  lp: np.ndarray, lk: np.ndarray,
                  ln: np.ndarray) -> np.ndarray:
    """Satisfaction of one atom over label arrays lp/lk/ln of shape [X, L]."""
    pair_set = pairs[pairs >= 0]
    any_pair = np.isin(lp, pair_set).any(axis=1) if pair_set.size else np.zeros(lp.shape[0], bool)
    exists = (lk == key).any(axis=1)
    if op == OP_IN:
        return any_pair
    if op == OP_NOT_IN:
        return ~any_pair
    if op == OP_EXISTS:
        return exists
    if op == OP_DOES_NOT_EXIST:
        return ~exists
    # Gt / Lt: numeric value of the matching key; absent or unparsable
    # (NaN) labels never satisfy. Formulation mirrors kernels/atoms.py
    # exactly so oracle and device agree bitwise.
    matched = (lk == key) & np.isfinite(ln)
    has = matched.any(axis=1)
    val = np.where(matched, ln, 0.0).sum(axis=1)
    if op == OP_GT:
        return has & (val > num)
    if op == OP_LT:
        return has & (val < num)
    raise ValueError(f"bad op {op}")


def _default_normalize(raw: np.ndarray, nvalid: np.ndarray) -> np.ndarray:
    """Upstream DefaultNormalizeScore: scale so max becomes 100."""
    mx = raw[nvalid].max() if nvalid.any() else 0.0
    if mx <= 0:
        return np.zeros_like(raw)
    return (raw * MAX_NODE_SCORE / mx).astype(np.float32)


def _inverse_normalize(penalty: np.ndarray, nvalid: np.ndarray) -> np.ndarray:
    """Lower penalty -> higher score; all-equal -> 100."""
    if not nvalid.any():
        return np.zeros_like(penalty)
    mx = penalty[nvalid].max()
    mn = penalty[nvalid].min()
    if mx <= mn:
        return np.full_like(penalty, MAX_NODE_SCORE)
    return ((mx - penalty) * MAX_NODE_SCORE / (mx - mn)).astype(np.float32)


def _upstream_normalize(raw: np.ndarray, nvalid: np.ndarray) -> np.ndarray:
    """Upstream InterPodAffinity normalize: (raw-min)/(max-min)*100,
    all-zero -> 0."""
    if not nvalid.any():
        return np.zeros_like(raw)
    mx = raw[nvalid].max()
    mn = raw[nvalid].min()
    if mx == mn:
        return np.zeros_like(raw)
    return ((raw - mn) * MAX_NODE_SCORE / (mx - mn)).astype(np.float32)
