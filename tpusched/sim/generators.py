"""Borg/Azure-shaped workload generators (ISSUE 9 tentpole part 2).

The methodology lineage of this scheduler family is TRACE-DRIVEN
evaluation — Borg ("Large-scale cluster management at Google with
Borg", EuroSys'15) and Azure's Resource Central trace analysis
(SOSP'17) — not hand-picked synthetic corners. This module shapes
tpusched.sim.workloads.Scenario values after the published
characteristics of those traces:

  * long-tail LOGNORMAL durations (most jobs short, a heavy tail of
    long-runners that outlive several arrival cycles);
  * DIURNAL arrival cycles (events.diurnal_times thinning — the
    day/night swing every production trace shows);
  * Zipf TENANT skew (tenants.zipf_weights, the one shared
    definition: a few subscriptions/users dominate submission volume);
  * a prefill/decode-flavored CLASS MIX for the serving-shaped preset:
    short interactive bursts (prefill-like) next to long-lived
    SLO-carrying servers (decode-like) over batch filler;
  * GANG arrivals (Borg jobs are sets of identical tasks; the sim's
    gang members carry pod_group/minMember with test_gangs.py
    all-or-nothing semantics);
  * AUTOSCALE + heterogeneous pools (clusters are not static: node
    pools grow and shrink mid-horizon, which on the gRPC path drives
    the device-resident state's real bucket-growth / taint-vocab
    rebuild machinery in device_state.py);
  * a long-horizon SOAK composing node flaps, autoscale, gangs, and a
    seeded tpusched.faults plan with the virtual clock.

Everything here EMITS TRACES in the one-code-path sense: a preset is
an ordinary Scenario fed through workloads.generate(), and
generate_trace()/write_trace serialize that SimSetup with
tpusched.sim.traces — so a Borg-shaped workload, a hand-written trace,
and a replayed file all drive SimDriver identically.

This module is imported by workloads.py at its BOTTOM (after Scenario
and generate are defined) to merge SCENARIOS into the one registry;
import workloads, not this module, to enumerate scenarios.
"""

from __future__ import annotations

import dataclasses

from tpusched.faults import FaultPlan
from tpusched.sim import traces
from tpusched.sim.workloads import Scenario, generate

# A PreferNoSchedule taint on the scale-out pool: it never filters a
# pod (the cluster stays schedulable for tolerance-less sim pods) but
# its FIRST appearance mid-horizon is a brand-new taint vocabulary
# entry — the [P, VT] tolerated-matrix growth that forces the
# device-resident state's "new_taint" full rebuild (device_state.py),
# exactly the path an autoscale scenario exists to exercise.
SCALEOUT_TAINT = ("tpusched.io/scaleout", "true", "PreferNoSchedule")


SCENARIOS: dict[str, Scenario] = {
    # Borg-shaped: lognormal long-tail durations, heavy batch tier at
    # HIGH base priority (Borg's production/batch split inverted into
    # the adversarial pressure-skew form), Zipf'd tenants, a slice of
    # gang jobs. The long tail is the point: a handful of prefilled
    # long-runners pin capacity while the short majority churns.
    "borg_longtail": Scenario(
        name="borg_longtail", n_nodes=8, horizon_s=150.0,
        description="Borg-shaped: lognormal long-tail durations, "
                    "Zipf tenants, gang jobs, batch tier at high "
                    "priority over low-priority SLO servers",
        arrival="poisson", rate=1.0, prefill=20,
        prefill_duration_s=(20.0, 200.0),
        duration_dist="lognormal",
        mix=(
            # batch filler: no SLO, HIGH priority, median 20s, p99 ~5min
            (0.35, 0.0, (20.0, 300.0), (60, 100), (1700.0, 2300.0)),
            # prod serving: SLO 0.8, LOW base priority
            (0.40, 0.8, (15.0, 120.0), (0, 30), (1700.0, 2300.0)),
            # prod tier-2: tight SLO
            (0.25, 0.95, (10.0, 60.0), (0, 30), (1700.0, 2300.0)),
        ),
        gang_frac=0.15, gang_size=3,
        tenants=8, tenant_skew=1.2,
    ),
    # Azure-shaped: diurnal arrival cycle, VM-like duration mix — many
    # short interactive instances (prefill-like), long-lived SLO
    # servers (decode-like), and long batch VMs — with strong
    # subscription (tenant) skew.
    "azure_diurnal": Scenario(
        name="azure_diurnal", n_nodes=6, horizon_s=180.0,
        description="Azure-shaped: diurnal arrivals, prefill/decode "
                    "class mix (short interactive vs long-lived SLO "
                    "servers), strong subscription skew",
        arrival="diurnal", rate=0.75,
        diurnal_period_s=120.0, diurnal_amplitude=0.9,
        prefill=16, prefill_duration_s=(30.0, 150.0),
        duration_dist="lognormal",
        mix=(
            # batch VMs: no SLO, high priority, very long tail
            (0.30, 0.0, (30.0, 600.0), (50, 100), (1800.0, 2400.0)),
            # interactive (prefill-like): short-lived, SLO-carrying
            (0.40, 0.75, (8.0, 40.0), (0, 40), (1500.0, 2000.0)),
            # servers (decode-like): long-lived, tight SLO
            (0.30, 0.9, (20.0, 90.0), (0, 40), (1800.0, 2400.0)),
        ),
        tenants=8, tenant_skew=1.4,
    ),
    # Cluster dynamics: a tight 6-node pool rides out an overload wave
    # by growing a TAINTED heterogeneous scale-out pool (first grow =
    # new taint vocab; second grow bursts the 8-node row bucket), then
    # shrinks back — scale-down interrupts running pods, which requeue
    # with lifecycle history. On the gRPC path the two grows force both
    # device-resident rebuild flavors (new_taint, row_bucket).
    "autoscale_stress": Scenario(
        name="autoscale_stress", horizon_s=140.0,
        description="mid-horizon autoscale: tainted heterogeneous "
                    "pool grows past the node bucket (drives "
                    "device-state rebuilds), then shrinks back",
        pools=((6, 1), (0, 2, SCALEOUT_TAINT)),
        autoscale=(
            (40.0, "grow", 1, 2),    # within the 8-row bucket: new_taint
            # Staged grow: +1 bursts the 8-row node bucket as a SMALL
            # delta (the row_bucket rebuild path, not a pipeline
            # full-send), then the rest of the wave lands.
            (60.0, "grow", 0, 1),    # 9 > 8 rows: row_bucket growth
            (62.0, "grow", 0, 3),    # -> 12 nodes at the grown bucket
            (100.0, "shrink", 0, 4),  # scale-down evicts + requeues
        ),
        arrival="poisson", rate=0.6, prefill=18,
        prefill_duration_s=(15.0, 100.0),
        mix=(
            (0.5, 0.0, (40.0, 90.0), (60, 100), (1800.0, 2400.0)),
            (0.5, 0.85, (20.0, 45.0), (0, 20), (1800.0, 2400.0)),
        ),
        tenants=4, tenant_skew=1.0,
    ),
    # Gang arrivals under pressure: gangs of 4 near-node-sized members
    # compete with a standing filler backlog. A gang that cannot fully
    # place is HELD (all-or-nothing rollback, test_gangs.py semantics)
    # — never partially bound — and its held members show up in
    # report.miss_attribution as gang_held.
    "gang_pressure": Scenario(
        name="gang_pressure", n_nodes=6, horizon_s=150.0,
        description="gang arrivals under filler pressure: sub-quorum "
                    "gangs hold all-or-nothing instead of partially "
                    "binding",
        arrival="poisson", rate=0.28, prefill=16,
        prefill_duration_s=(15.0, 110.0),
        gang_frac=0.35, gang_size=4,
        mix=(
            (0.5, 0.0, (40.0, 80.0), (60, 100), (1800.0, 2400.0)),
            (0.5, 0.85, (20.0, 40.0), (0, 20), (1700.0, 2100.0)),
        ),
        tenants=4, tenant_skew=1.0,
    ),
    # Long-horizon soak: diurnal load + node flaps + autoscale + gangs
    # + lognormal tails over 600 virtual seconds, normally composed
    # with soak_fault_plan() so injected engine faults land mid-run
    # (the driver tolerates and logs them as cycle_failed events).
    # Full horizon is marked slow in tests; the tier-1 smoke runs a
    # shortened horizon (see soak_smoke()).
    "soak_storm": Scenario(
        name="soak_storm", horizon_s=600.0,
        description="long-horizon soak: diurnal load + node flaps + "
                    "autoscale + gangs + injected faults (slow; "
                    "tier-1 runs the bounded smoke)",
        pools=((8, 1), (0, 2, SCALEOUT_TAINT)),
        autoscale=(
            (150.0, "grow", 1, 3),
            (300.0, "shrink", 1, 2),
            (450.0, "grow", 0, 2),
        ),
        arrival="diurnal", rate=0.30,
        diurnal_period_s=200.0, diurnal_amplitude=0.8,
        prefill=20, prefill_duration_s=(20.0, 180.0),
        duration_dist="lognormal",
        gang_frac=0.10, gang_size=3,
        mix=(
            (0.35, 0.0, (25.0, 400.0), (50, 100), (1700.0, 2300.0)),
            (0.40, 0.8, (15.0, 90.0), (0, 30), (1700.0, 2300.0)),
            (0.25, 0.9, (15.0, 60.0), (0, 30), (1700.0, 2300.0)),
        ),
        tenants=8, tenant_skew=1.2,
        node_mtbf_s=150.0, node_mttr_s=20.0,
    ),
}


def soak_fault_plan(seed: int, cycles: int = 300):
    """The soak scenario's fault composition: a fresh, seeded
    tpusched.faults.FaultPlan whose engine.fetch error shots land at
    deterministic solve indices spread over roughly `cycles` scheduling
    cycles. The sim driver tolerates these the way the host's
    run_until_idle tolerates a flaky sidecar — the cycle is dropped,
    counted (SimResult.failed_cycles), and noted in the event log
    ("cycle_failed"), so the fault schedule is part of the pinned
    deterministic timeline. Build a FRESH plan per run: plans carry
    invocation counters.

    The shot window is cycles//4: idle ticks (empty pending queue) run
    no solve, so actual engine.fetch invocations trail the tick count —
    a window at the full cycle count could land every shot past the end
    of the run (a silent no-op soak)."""
    return FaultPlan.seeded(seed, {
        "engine.fetch": dict(kind="error", n=3,
                             window=max(cycles // 4, 6)),
    })


def soak_smoke(horizon_s: float = 60.0) -> Scenario:
    """The bounded tier-1 form of soak_storm: same composition, short
    horizon, autoscale/flap times rescaled into the window."""
    base = SCENARIOS["soak_storm"]
    scale = horizon_s / base.horizon_s
    return dataclasses.replace(
        base,
        name="soak_smoke",
        description="bounded tier-1 soak smoke (rescaled soak_storm)",
        horizon_s=horizon_s,
        diurnal_period_s=base.diurnal_period_s * scale,
        prefill_duration_s=(5.0, 40.0),
        node_mtbf_s=base.node_mtbf_s * scale,
        node_mttr_s=base.node_mttr_s * scale,
        autoscale=tuple(
            (round(t * scale, 6), op, pi, count)
            for (t, op, pi, count) in base.autoscale
        ),
        mix=tuple(
            (w, slo, (d_lo * scale, d_hi * scale), prio, cpu)
            for (w, slo, (d_lo, d_hi), prio, cpu) in base.mix
        ),
    )


def generate_trace(scenario: Scenario, seed: int, path: str) -> str:
    """Generate a workload and write it as an on-disk trace: the
    generate -> write half of the trace round trip (load_trace +
    SimDriver(setup=...) is the other half). Returns `path`."""
    return traces.write_trace(generate(scenario, seed), path)


# Merge these presets into THE scenario registry. Down here (after
# SCENARIOS exists) the merge is safe in either import order: importing
# workloads first runs this module to completion from workloads'
# bottom bare-import; importing this module first pulls workloads in
# fully via the top-of-module Scenario import before reaching here.
from tpusched.sim import workloads as _workloads  # noqa: E402

_workloads.SCENARIOS.update(SCENARIOS)
