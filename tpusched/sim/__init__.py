"""Virtual-time cluster simulator (ISSUE 5): the evaluation subsystem
that closes the QoS availability loop and measures SLO attainment
end-to-end.

Submodules (import what you need; this package root stays light so
host.py can import `lifecycle` without dragging in the driver stack):

  clock      VirtualClock — zero-real-sleep virtual time
  lifecycle  per-pod availability accounting (the closed loop's state)
  events     seeded event queue + arrival/failure/autoscale processes
  workloads  Scenario + generate(): THE workload-synthesis path and the
             scenario registry (presets + the Borg/Azure shapes)
  generators Borg/Azure-shaped presets, soak composition, trace
             emission (ISSUE 9)
  traces     versioned seed-free on-disk trace format: validate /
             write_trace / load_trace / replay (ISSUE 9)
  driver     SimDriver + run_scenario + twin_run + matrix_run
  report     SLO-attainment summaries, CDFs, matrix/text rendering
"""

from tpusched.sim.clock import VirtualClock  # noqa: F401
from tpusched.sim.lifecycle import (  # noqa: F401
    LifecycleTracker,
    observed_availability,
)


def __getattr__(name):
    # Lazy: driver/report import host/engine/rpc layers; workloads pulls
    # synth. Loading them only on demand keeps `import tpusched.sim`
    # cheap for the host's lifecycle import.
    if name in ("SimDriver", "run_scenario", "twin_run", "matrix_run"):
        from tpusched.sim import driver  # tpl: disable=TPL001(lazy public API: `import tpusched.sim` must not pull the engine/rpc stack)

        return getattr(driver, name)
    if name in ("Scenario", "SCENARIOS", "MATRIX_SCENARIOS", "generate"):
        from tpusched.sim import workloads  # tpl: disable=TPL001(lazy public API: `import tpusched.sim` must not pull the synth vocabulary)

        return getattr(workloads, name)
    if name in ("write_trace", "load_trace", "replay"):
        from tpusched.sim import traces  # tpl: disable=TPL001(lazy public API: `import tpusched.sim` stays cheap for the host lifecycle import)

        return getattr(traces, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
