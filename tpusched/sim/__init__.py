"""Virtual-time cluster simulator (ISSUE 5): the evaluation subsystem
that closes the QoS availability loop and measures SLO attainment
end-to-end.

Submodules (import what you need; this package root stays light so
host.py can import `lifecycle` without dragging in the driver stack):

  clock      VirtualClock — zero-real-sleep virtual time
  lifecycle  per-pod availability accounting (the closed loop's state)
  events     seeded event queue + arrival/failure processes
  workloads  scenario library (steady_state / burst / pressure_skew /
             failure_storm)
  driver     SimDriver + run_scenario + twin_run (QoS vs static)
  report     SLO-attainment summaries, CDFs, text rendering
"""

from tpusched.sim.clock import VirtualClock  # noqa: F401
from tpusched.sim.lifecycle import (  # noqa: F401
    LifecycleTracker,
    observed_availability,
)


def __getattr__(name):
    # Lazy: driver/report import host/engine/rpc layers; workloads pulls
    # synth. Loading them only on demand keeps `import tpusched.sim`
    # cheap for the host's lifecycle import.
    if name in ("SimDriver", "run_scenario", "twin_run"):
        from tpusched.sim import driver

        return getattr(driver, name)
    if name in ("Scenario", "SCENARIOS", "generate"):
        from tpusched.sim import workloads

        return getattr(workloads, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
