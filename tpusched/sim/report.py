"""SLO-attainment reporting for sim runs (ISSUE 5).

Turns a SimResult into the numbers the paper's evaluation methodology
is built on: the fraction of SLO-carrying pods whose final observed
availability met their target (long-horizon SLO attainment, the
Borg-style trace-sim metric), availability CDFs, wait/run percentiles,
pressure summaries, and goodput. Everything here is pure numpy over
the recorded outcomes — no scheduling state, so reports are cheap to
recompute and stable to compare across twin runs.
"""

from __future__ import annotations

import numpy as np

from tpusched.explain import (_NO_FEASIBLE, OUTCOME_GANG_HELD,
                              OUTCOME_PENDING, OUTCOMES, _pending_reason)


def _pct(xs, q) -> float:
    return round(float(np.percentile(np.asarray(xs, np.float64), q)), 6) \
        if len(xs) else 0.0


def attainment_cdf(pods, points: int = 11) -> list:
    """CDF of final availability over SLO-carrying pods: points evenly
    spaced availability thresholds in [0, 1] with the fraction of pods
    at or below each — the distribution behind the single attainment
    number (two policies with equal attainment can still have very
    different tails)."""
    avails = sorted(p.final_avail for p in pods if p.slo > 0)
    if not avails:
        return []
    n = len(avails)
    out = []
    for i in range(points):
        x = i / (points - 1)
        frac = sum(1 for a in avails if a <= x + 1e-12) / n
        out.append((round(x, 4), round(frac, 6)))
    return out


def summarize(res) -> dict:
    """One sim run -> flat report dict (json-friendly)."""
    pods = res.pods
    slo_pods = [p for p in pods if p.slo > 0]
    attained = [p for p in slo_pods if p.attained]
    waits = [p.waited_s for p in pods]
    runs = [p.ran_s for p in pods]
    press_mean = [s[2] for s in res.pressure_samples]
    press_max = [s[3] for s in res.pressure_samples]
    by_slo: dict[float, list] = {}
    for p in slo_pods:
        by_slo.setdefault(p.slo, []).append(p)
    return dict(
        scenario=res.scenario, seed=res.seed, backend=res.backend,
        qos_gain=res.qos_gain, horizon_s=res.horizon_s,
        ticks=res.ticks, cycles=res.cycles,
        events_applied=res.events_applied,
        pods_submitted=len(pods),
        completions=res.completions,
        placed=res.placed, evicted=res.evicted,
        requeues=res.requeues, node_failures=res.node_failures,
        autoscale_events=getattr(res, "autoscale_events", 0),
        failed_cycles=getattr(res, "failed_cycles", 0),
        # Preemption churn (ISSUE 9 matrix metric): evictions per
        # placement — the fraction of placements the policy later
        # undid. Lower is better; a policy can buy attainment with
        # churn, and the matrix reports both so the trade is visible.
        preemption_churn=round(res.evicted / max(res.placed, 1), 6),
        slo_pods=len(slo_pods),
        slo_attained=len(attained),
        slo_attainment_frac=(
            round(len(attained) / len(slo_pods), 6) if slo_pods else 1.0
        ),
        attainment_by_slo={
            str(slo): round(
                sum(1 for p in ps if p.attained) / len(ps), 6
            )
            for slo, ps in sorted(by_slo.items())
        },
        attainment_cdf=attainment_cdf(pods),
        wait_p50_s=_pct(waits, 50), wait_p99_s=_pct(waits, 99),
        run_p50_s=_pct(runs, 50),
        goodput_run_s=round(float(np.sum(runs)), 3) if runs else 0.0,
        completed_frac=(
            round(res.completions / len(pods), 6) if pods else 1.0
        ),
        pressure_mean=_pct(press_mean, 50),
        pressure_peak=_pct(press_max, 100),
        event_log_hash=res.event_log_hash,
        wall_seconds=round(res.wall_seconds, 3),
    )


def render_text(summary: dict) -> str:
    """Human-readable block for the CLI."""
    lines = [
        f"scenario={summary['scenario']} seed={summary['seed']} "
        f"backend={summary['backend']} qos_gain={summary['qos_gain']}",
        f"  horizon={summary['horizon_s']}s ticks={summary['ticks']} "
        f"cycles={summary['cycles']} events={summary['events_applied']} "
        f"wall={summary['wall_seconds']}s",
        f"  pods={summary['pods_submitted']} "
        f"completed={summary['completions']} "
        f"placed={summary['placed']} evicted={summary['evicted']} "
        f"requeues={summary['requeues']} "
        f"node_failures={summary['node_failures']}",
        f"  SLO attainment: {summary['slo_attained']}/"
        f"{summary['slo_pods']} = {summary['slo_attainment_frac']}"
        f"   by target: {summary['attainment_by_slo']}",
        f"  wait p50/p99: {summary['wait_p50_s']}/"
        f"{summary['wait_p99_s']}s   pressure mean/peak: "
        f"{summary['pressure_mean']}/{summary['pressure_peak']}",
        f"  event-log hash: {summary['event_log_hash']}",
    ]
    return "\n".join(lines)


def render_twin(twin: dict) -> str:
    """Twin-run comparison block (+ per-arm miss attribution when the
    twin ran explained)."""
    q, s = twin["qos"], twin["static"]
    lines = [
        f"twin-run scenario={twin['scenario']} seed={twin['seed']} "
        f"backend={twin['backend']}",
        f"  qos-driven : attainment={q['slo_attainment_frac']} "
        f"(evictions={q['evicted']}, wait_p99={q['wait_p99_s']}s)",
        f"  static     : attainment={s['slo_attainment_frac']} "
        f"(evictions={s['evicted']}, wait_p99={s['wait_p99_s']}s)",
        f"  attainment_gain_vs_static = "
        f"{twin['attainment_gain_vs_static']}",
    ]
    for arm in ("qos", "static"):
        att = twin[arm].get("miss_attribution")
        if att:
            lines.append(render_attribution(att, label=arm))
    return "\n".join(lines)


def render_matrix(matrix: dict) -> str:
    """The scenario-matrix table (driver.matrix_run output): one row
    per scenario, QoS vs static attainment + preemption churn, gain,
    and both arms' hash prefixes (the determinism pin)."""
    head = (f"{'scenario':<18} {'qos':>7} {'static':>7} {'gain':>8} "
            f"{'churn_q':>8} {'churn_s':>8} {'slo_pods':>8}  hashes")
    lines = [f"scenario matrix: seed={matrix['seed']} "
             f"backend={matrix['backend']}", head, "-" * len(head)]
    for r in matrix["rows"]:
        lines.append(
            f"{r['scenario']:<18} {r['slo_attainment_frac']:>7.3f} "
            f"{r['slo_attainment_frac_static']:>7.3f} "
            f"{r['attainment_gain_vs_static']:>+8.3f} "
            f"{r['preemption_churn']:>8.3f} "
            f"{r['preemption_churn_static']:>8.3f} "
            f"{r['slo_pods']:>8} "
            f" {r['hash_qos'][:8]}/{r['hash_static'][:8]}"
        )
        for arm in ("miss_causes", "miss_causes_static"):
            if r.get(arm):
                tag = "static" if arm.endswith("static") else "qos"
                causes = ", ".join(f"{k}={v}" for k, v in
                                   sorted(r[arm].items(),
                                          key=lambda kv: -kv[1]))
                lines.append(f"{'':<18}   misses ({tag}): {causes}")
    gains = [r["attainment_gain_vs_static"] for r in matrix["rows"]]
    if gains:
        lines.append(
            f"mean attainment_gain_vs_static over {len(gains)} "
            f"scenarios: {sum(gains) / len(gains):+.3f}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Miss attribution (round 12, ISSUE 8): join missed-SLO pods to their
# recorded decision chains.
# ---------------------------------------------------------------------------

# Cause labels, most to least actionable. A pod can match several over
# its lifetime (evicted AND later unschedulable); the FIRST matching
# cause in this order wins — eviction explains a miss better than the
# requeue-era pending states it produces. gang_held ranks ABOVE
# outranked and is GROUP-propagated (ISSUE 9): in a held gang only the
# members that placed-then-rolled-back carry the gang_held outcome
# code, while quorum-missing members read as ordinary pending — but
# their "outranked" cycles are an artifact of the hold, so any member's
# hold classifies the whole group.
CAUSE_PREEMPTED = "preempted"
CAUSE_UNSCHED = "unschedulable"      # rendered with dominant reason
CAUSE_OUTRANKED = "outranked"        # feasible nodes existed; capacity
#                                      went to higher-priority pods
CAUSE_GANG_HELD = "gang_held"
CAUSE_PLACED_LATE = "placed_below_slo"  # placed whenever seen; the SLO
#                                      was lost to queueing before/after
#                                      the recorded window
CAUSE_NO_RECORD = "no_decision_recorded"


def miss_attribution(res, records) -> dict:
    """Join every missed-SLO pod of a SimResult to its decision chain
    across the run's DecisionRecords (tpusched.explain) and roll the
    per-pod causes into a "top miss causes" table.

    Per missed pod, the recorded evidence is summarized as:
      * preempted    — it shows up as an eviction victim (the record
                       names the evictor and auction round);
      * unschedulable:<reason> — some cycle left it pending with ZERO
                       feasible nodes; <reason> is the dominant
                       filter-elimination reason at the LAST such cycle;
      * outranked    — pending cycles always had feasible nodes; the
                       capacity went to higher-priority pods;
      * gang_held    — held below gang quorum;
      * placed_below_slo — every recorded sighting was a placement; the
                       availability was lost outside scheduling;
      * no_decision_recorded — never in an explained batch (ring
                       overflow or arrival after the last cycle).

    Returns {"misses": n, "causes": {label: count}, "pods": {name:
    {cause, evidence...}}} — json-friendly; render_attribution prints
    the table. Consistency contract (test-pinned): every "preempted"
    pod IS an eviction victim in some record; every "unschedulable"
    pod has a recorded zero-feasible pending cycle."""
    pend_code = OUTCOMES.index(OUTCOME_PENDING)
    gang_code = OUTCOMES.index(OUTCOME_GANG_HELD)
    # Pod -> accumulated evidence over the record stream (records are
    # oldest-first; later sightings overwrite "last_*" fields).
    seen: dict[str, dict] = {}
    for rec in records:
        for i, name in enumerate(rec.pod_names):
            ev = seen.setdefault(name, {})
            code = int(rec.outcome[i])
            if code == pend_code:
                if int(rec.feasible_nodes[i]) == 0:
                    ev["unsched_reason"] = _pending_reason(rec, i)
                    ev["unsched_cycle"] = rec.cycle
                else:
                    ev["outranked_cycles"] = ev.get("outranked_cycles", 0) + 1
            elif code == gang_code:
                ev["gang_held"] = True
            else:
                ev["placed_cycles"] = ev.get("placed_cycles", 0) + 1
        for m, vname in enumerate(rec.running_names):
            if rec.evicted[m]:
                evictor = int(rec.evictor[m])
                seen.setdefault(vname, {})["evicted"] = dict(
                    cycle=rec.cycle,
                    by=(rec.pod_names[evictor]
                        if 0 <= evictor < len(rec.pod_names) else None),
                    round=int(rec.evict_round[m]),
                )
    # Gangs with a recorded hold: any member's gang_held outcome marks
    # the GROUP held (see the cause-order comment above).
    held_groups = {
        p.gang for p in res.pods
        if getattr(p, "gang", None) and seen.get(p.name, {}).get("gang_held")
    }
    causes: dict[str, int] = {}
    pods: dict[str, dict] = {}
    n_miss = 0
    for p in res.pods:
        if p.attained is not False:
            continue  # attained, or SLO-less (None)
        n_miss += 1
        ev = seen.get(p.name, {})
        gang = getattr(p, "gang", None)
        if "evicted" in ev or p.evictions > 0:
            cause = CAUSE_PREEMPTED
            detail = ev.get("evicted", {})
        elif "unsched_reason" in ev:
            reason = ev["unsched_reason"]
            if reason.startswith(_NO_FEASIBLE):
                reason = reason[len(_NO_FEASIBLE):]
            cause = f"{CAUSE_UNSCHED}:{reason}"
            detail = dict(last_cycle=ev.get("unsched_cycle"))
        elif ev.get("gang_held") or (gang and gang in held_groups):
            cause = CAUSE_GANG_HELD
            detail = dict(gang=gang) if gang else {}
        elif ev.get("outranked_cycles"):
            cause = CAUSE_OUTRANKED
            detail = dict(pending_cycles=ev["outranked_cycles"])
        elif ev.get("placed_cycles"):
            cause = CAUSE_PLACED_LATE
            detail = dict(placed_cycles=ev["placed_cycles"])
        else:
            cause = CAUSE_NO_RECORD
            detail = {}
        causes[cause] = causes.get(cause, 0) + 1
        pods[p.name] = dict(cause=cause, final_avail=p.final_avail,
                            slo=p.slo, **detail)
    return dict(misses=n_miss, causes=causes, pods=pods)


def render_attribution(att: dict, label: str = "") -> str:
    """The "top miss causes" table, most frequent first, with one
    example pod per cause."""
    tag = f" ({label})" if label else ""
    lines = [f"  top miss causes{tag}: {att['misses']} missed-SLO pods"]
    by_cause: dict[str, list] = {}
    for name, d in att["pods"].items():
        by_cause.setdefault(d["cause"], []).append((name, d))
    for cause, n in sorted(att["causes"].items(),
                           key=lambda kv: (-kv[1], kv[0])):
        ex_name, ex = by_cause[cause][0]
        extra = ""
        if cause == CAUSE_PREEMPTED and ex.get("by"):
            extra = f" (e.g. {ex_name} evicted by {ex['by']})"
        elif ex:
            extra = f" (e.g. {ex_name})"
        lines.append(f"    {cause:<34} {n:>5}{extra}")
    return "\n".join(lines)
