"""SLO-attainment reporting for sim runs (ISSUE 5).

Turns a SimResult into the numbers the paper's evaluation methodology
is built on: the fraction of SLO-carrying pods whose final observed
availability met their target (long-horizon SLO attainment, the
Borg-style trace-sim metric), availability CDFs, wait/run percentiles,
pressure summaries, and goodput. Everything here is pure numpy over
the recorded outcomes — no scheduling state, so reports are cheap to
recompute and stable to compare across twin runs.
"""

from __future__ import annotations

import numpy as np


def _pct(xs, q) -> float:
    return round(float(np.percentile(np.asarray(xs, np.float64), q)), 6) \
        if len(xs) else 0.0


def attainment_cdf(pods, points: int = 11) -> list:
    """CDF of final availability over SLO-carrying pods: points evenly
    spaced availability thresholds in [0, 1] with the fraction of pods
    at or below each — the distribution behind the single attainment
    number (two policies with equal attainment can still have very
    different tails)."""
    avails = sorted(p.final_avail for p in pods if p.slo > 0)
    if not avails:
        return []
    n = len(avails)
    out = []
    for i in range(points):
        x = i / (points - 1)
        frac = sum(1 for a in avails if a <= x + 1e-12) / n
        out.append((round(x, 4), round(frac, 6)))
    return out


def summarize(res) -> dict:
    """One sim run -> flat report dict (json-friendly)."""
    pods = res.pods
    slo_pods = [p for p in pods if p.slo > 0]
    attained = [p for p in slo_pods if p.attained]
    waits = [p.waited_s for p in pods]
    runs = [p.ran_s for p in pods]
    press_mean = [s[2] for s in res.pressure_samples]
    press_max = [s[3] for s in res.pressure_samples]
    by_slo: dict[float, list] = {}
    for p in slo_pods:
        by_slo.setdefault(p.slo, []).append(p)
    return dict(
        scenario=res.scenario, seed=res.seed, backend=res.backend,
        qos_gain=res.qos_gain, horizon_s=res.horizon_s,
        ticks=res.ticks, cycles=res.cycles,
        events_applied=res.events_applied,
        pods_submitted=len(pods),
        completions=res.completions,
        placed=res.placed, evicted=res.evicted,
        requeues=res.requeues, node_failures=res.node_failures,
        slo_pods=len(slo_pods),
        slo_attained=len(attained),
        slo_attainment_frac=(
            round(len(attained) / len(slo_pods), 6) if slo_pods else 1.0
        ),
        attainment_by_slo={
            str(slo): round(
                sum(1 for p in ps if p.attained) / len(ps), 6
            )
            for slo, ps in sorted(by_slo.items())
        },
        attainment_cdf=attainment_cdf(pods),
        wait_p50_s=_pct(waits, 50), wait_p99_s=_pct(waits, 99),
        run_p50_s=_pct(runs, 50),
        goodput_run_s=round(float(np.sum(runs)), 3) if runs else 0.0,
        completed_frac=(
            round(res.completions / len(pods), 6) if pods else 1.0
        ),
        pressure_mean=_pct(press_mean, 50),
        pressure_peak=_pct(press_max, 100),
        event_log_hash=res.event_log_hash,
        wall_seconds=round(res.wall_seconds, 3),
    )


def render_text(summary: dict) -> str:
    """Human-readable block for the CLI."""
    lines = [
        f"scenario={summary['scenario']} seed={summary['seed']} "
        f"backend={summary['backend']} qos_gain={summary['qos_gain']}",
        f"  horizon={summary['horizon_s']}s ticks={summary['ticks']} "
        f"cycles={summary['cycles']} events={summary['events_applied']} "
        f"wall={summary['wall_seconds']}s",
        f"  pods={summary['pods_submitted']} "
        f"completed={summary['completions']} "
        f"placed={summary['placed']} evicted={summary['evicted']} "
        f"requeues={summary['requeues']} "
        f"node_failures={summary['node_failures']}",
        f"  SLO attainment: {summary['slo_attained']}/"
        f"{summary['slo_pods']} = {summary['slo_attainment_frac']}"
        f"   by target: {summary['attainment_by_slo']}",
        f"  wait p50/p99: {summary['wait_p50_s']}/"
        f"{summary['wait_p99_s']}s   pressure mean/peak: "
        f"{summary['pressure_mean']}/{summary['pressure_peak']}",
        f"  event-log hash: {summary['event_log_hash']}",
    ]
    return "\n".join(lines)


def render_twin(twin: dict) -> str:
    """Twin-run comparison block."""
    q, s = twin["qos"], twin["static"]
    lines = [
        f"twin-run scenario={twin['scenario']} seed={twin['seed']} "
        f"backend={twin['backend']}",
        f"  qos-driven : attainment={q['slo_attainment_frac']} "
        f"(evictions={q['evicted']}, wait_p99={q['wait_p99_s']}s)",
        f"  static     : attainment={s['slo_attainment_frac']} "
        f"(evictions={s['evicted']}, wait_p99={s['wait_p99_s']}s)",
        f"  attainment_gain_vs_static = "
        f"{twin['attainment_gain_vs_static']}",
    ]
    return "\n".join(lines)
