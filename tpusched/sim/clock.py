"""Virtual time for the cluster simulator (ISSUE 5 tentpole).

One rule makes long-horizon SLO evaluation tractable: NOTHING in a sim
run sleeps on the wall clock. The clock is a number that only moves when
the driver advances it, so a 2-hour diurnal scenario runs in seconds and
two runs with the same seed see byte-identical timelines — the property
the event-log-hash determinism test pins. The same instance is injected
everywhere host-side code would otherwise reach for time.time /
time.monotonic: FakeApiServer pod timestamps (lifecycle accounting) and
HostScheduler's backoff book (a pod's retry window expires in VIRTUAL
seconds, so backoff interacts with queue pressure the way it would on a
live cluster, just faster).
"""

from __future__ import annotations


class VirtualClock:
    """A manually-advanced monotone clock. Callable so it drops into
    any `clock=` injection point that expects a time.monotonic-like
    zero-arg callable."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def __call__(self) -> float:
        return self._now

    def advance(self, dt: float) -> float:
        """Move time forward by dt (>= 0) virtual seconds."""
        if dt < 0:
            raise ValueError(f"advance({dt}): virtual time is monotone")
        self._now += float(dt)
        return self._now

    def advance_to(self, t: float) -> float:
        """Jump to absolute time t; never moves backwards (a target in
        the past is a no-op, matching monotone-clock semantics)."""
        if t > self._now:
            self._now = float(t)
        return self._now

    def sleep(self, dt: float) -> None:
        """Drop-in for time.sleep under simulation: advances virtual
        time instantly, zero real blocking."""
        self.advance(max(dt, 0.0))
