"""Sim driver: tick loop, event application, and the twin run (ISSUE 5).

SimDriver marries the event timeline (events/workloads) to the REAL
scheduling stack — FakeApiServer + HostScheduler + Engine, or the full
host -> gRPC sidecar path — under a virtual clock. Nothing is mocked
below the API-server boundary: batches build wire snapshots through the
C12 codec, solves run the jitted kernels, binds/evictions go through
the same idempotent-bind machinery live hosts use. The gRPC mode rides
HostScheduler's AssignPipeline transport, so a simulated week of
cluster time also exercises the pinned-base delta + resync path.

Per tick:
  1. apply due events (arrivals, completions, node fail/recover);
  2. every `resolve_every` ticks, run one scheduling cycle — the
     snapshot it builds reads lifecycle-accounted observed_avail, so
     QoS pressure is DYNAMIC: this cycle's decisions move next cycle's
     availability, the loop the reference system is named for;
  3. account outcomes: newly-bound pods get completion events at
     now + remaining_duration; pods evicted by preemption are re-queued
     with their lifecycle history (availability keeps decaying);
  4. sample the pressure distribution, advance the clock.

The headline entry is twin_run(): the same scenario and seed under the
QoS-driven config and under a static-priority baseline (qos_gain=0,
urgency_reweight off) — attainment_gain_vs_static is the paper's
central claim as one repeatable number.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from tpusched import metrics as pm
from tpusched import qos
from tpusched import trace as tracing
from tpusched.engine import Engine
from tpusched.explain import ExplainCollector
from tpusched.config import (DEFAULT_OBSERVED_AVAIL, DEFAULT_SLO_TARGET,
                             EngineConfig, QoSConfig, SimConfig)
from tpusched.faults import FaultError
from tpusched.host import FakeApiServer, HostScheduler
from tpusched.sim import report
from tpusched.sim.clock import VirtualClock
from tpusched.sim.lifecycle import LifecycleTracker
from tpusched.sim.workloads import (MATRIX_SCENARIOS, SCENARIOS, Scenario,
                                    SimSetup, generate)

# Sim-run counters in the process-default registry: sim runs export
# through the same Prometheus surface as serving (ISSUE 5 "sim runs
# emit the same spans/counters").
_M_EVENTS = pm.Counter(
    "tpusched_sim_events_total",
    "virtual-time simulator events applied", ("kind",))
_M_COMPLETIONS = pm.Counter(
    "tpusched_sim_completions_total",
    "simulated pods that ran to completion")
_M_REQUEUES = pm.Counter(
    "tpusched_sim_requeues_total",
    "simulated pods returned to pending", ("reason",))
_M_AVAIL = pm.Histogram(
    "tpusched_sim_final_availability",
    "per-pod final observed availability at completion/horizon",
    buckets=tuple(round(i / 10, 1) for i in range(11)))

# Floor on a pod's remaining duration after an interruption: an evicted
# pod always needs at least one more tick of service.
_MIN_REMAINING_S = 1e-3


def effective_config(sc: Scenario, config: "EngineConfig | None") -> EngineConfig:
    """Scenario knobs that live on EngineConfig (preemption) merged
    into the caller's config — shared by SimDriver and the gRPC-mode
    server construction so both sides run the same program."""
    cfg = config or EngineConfig(mode="fast")
    if sc.preemption and not cfg.preemption:
        cfg = dataclasses.replace(cfg, preemption=True)
    return cfg


@dataclasses.dataclass
class PodOutcome:
    name: str
    tenant: int
    slo: float
    priority: float
    submitted: float
    completed: bool
    end_time: float
    ran_s: float
    waited_s: float
    evictions: int
    final_avail: float
    attained: "bool | None"    # None for SLO-less pods (slo == 0)
    gang: "str | None" = None  # pod_group id for gang members


@dataclasses.dataclass
class SimResult:
    scenario: str
    seed: int
    backend: str
    qos_gain: float
    horizon_s: float
    ticks: int
    cycles: int
    events_applied: int
    placed: int
    evicted: int
    completions: int
    requeues: int
    node_failures: int
    autoscale_events: int
    failed_cycles: int
    pods: list          # [PodOutcome]
    pressure_samples: list   # (t, n_pending, mean_pressure, max_pressure)
    event_log_hash: str
    wall_seconds: float


class SimDriver:
    def __init__(
        self,
        scenario: "Scenario | None" = None,
        seed: int = 0,
        config: "EngineConfig | None" = None,
        sim: "SimConfig | None" = None,
        client=None,
        engine=None,
        faults=None,
        tracer=None,
        explain=None,
        setup: "SimSetup | None" = None,
        ledger=None,
        device_queue: bool = False,
        ingest=None,
    ):
        """explain (round 12): optional ExplainCollector threaded into
        the in-process HostScheduler — every cycle records a
        DecisionRecord on VIRTUAL time, the input report.py's
        miss-attribution join consumes. gRPC runs record server-side
        instead (run_scenario wires the collector into make_server).

        setup (round 13, ISSUE 9): a prebuilt SimSetup — the trace
        REPLAY input (traces.load_trace) or a generate() result the
        caller wants to inspect/serialize first. When given, no
        generation happens here; the scenario rides in on the setup
        (pass scenario=None). Note a setup's event queue is consumed
        by the run — build/load a fresh one per run.

        ledger (round 18, ISSUE 13): optional
        tpusched.ledger.CycleLedger threaded into the HostScheduler —
        virtual-time replays then emit the SAME CycleRecord schema as
        live serving (tests/test_ledger.py pins the twin), with
        source="sim" and ts on the virtual clock, so a recorded
        workload's flight ledger is directly comparable to the
        production one it replays.

        device_queue (ISSUE 20): thread the device-resident pending
        queue into the HostScheduler — batch membership comes from the
        in-kernel availability-decay ranking instead of the per-cycle
        host re-read. Whenever every eligible pod fits the batch the
        run is event-for-event identical to the host-sorted path
        (tests pin the pressure_skew twin hash).

        ingest (ISSUE 20): optional tpusched.ingest.IngestGate —
        arrivals pass through token-bucket admission before reaching
        the api server; shed pods are re-offered every tick until
        admitted (the sim twin of the rpc client's
        RESOURCE_EXHAUSTED retry loop), so a run under admission
        pressure still converges to the same end state."""
        if setup is not None:
            if scenario is not None and scenario is not setup.scenario:
                raise ValueError(
                    "pass scenario OR setup, not a conflicting pair"
                )
            scenario = setup.scenario
            seed = setup.seed
        elif scenario is None:
            raise ValueError("SimDriver needs a scenario or a setup")
        self.sc = scenario
        self.seed = int(seed)
        self.cfg = effective_config(scenario, config)
        self.sim = sim or SimConfig()
        self.tracer = tracer
        self.clock = VirtualClock()
        self.api = FakeApiServer(clock=self.clock)
        self.setup: SimSetup = (setup if setup is not None
                                else generate(scenario, self.seed))
        for n in self.setup.nodes:
            self.api.add_node(**n)
        self._node_specs = {n["name"]: n for n in self.setup.nodes}
        self._down: set[str] = set()

        self._owns_engine = False
        if client is None and engine is None:
            engine = Engine(self.cfg, faults=faults)
            self._owns_engine = True
        self.engine = engine
        self.host = HostScheduler(
            self.api, self.cfg, client=client, engine=engine,
            clock=self.clock, batch_size=self.sim.batch_size,
            backoff_initial=self.sim.backoff_initial_s,
            backoff_max=self.sim.backoff_max_s,
            transport="pipeline" if client is not None else "delta",
            explain=explain,
            refresh_frac=self.sim.pipeline_refresh_frac,
            ledger=ledger,
            device_queue=device_queue,
        )
        self.ingest = ingest
        # The gate sheds into this retry buffer; _ingest_tick re-offers
        # each tick (deliveries stay exactly-once: admission dedups by
        # name). Always present so callers may attach a gate post-init.
        self._shed_retry: list[str] = []
        # Re-tag the host's ledger records: a virtual-time replay's
        # cycles must be distinguishable from live host cycles while
        # keeping the identical schema (the twin contract).
        self.host.ledger_source = "sim"
        self.backend = "grpc" if client is not None else "inprocess"

        self.life = LifecycleTracker()
        self.q = self.setup.queue
        self._remaining: dict[str, float] = {}
        self._gen: dict[str, int] = {}
        self._arrived: list[str] = []
        self.events_applied = 0
        self.completions = 0
        self.requeues = 0
        self.node_failures = 0
        self.autoscale_events = 0
        self.failed_cycles = 0
        self.pressure_samples: list[tuple] = []

    # -- event application --------------------------------------------------

    def _apply(self, ev) -> None:
        now = self.clock.now()
        _M_EVENTS.labels(ev.kind).inc()
        if ev.kind == "arrival":
            name = ev.data["pod"]
            spec = self.setup.specs[name]
            meta = self.setup.meta[name]
            self.life.on_submit(name, now, slo_target=meta["slo"])
            self._remaining[name] = meta["duration_s"]
            self._gen[name] = 0
            self._arrived.append(name)
            self.q.note(ev.time, "arrival", pod=name)
            if self.ingest is None:
                self.api.add_pod(name, **spec)
            else:
                # Admission-gated arrival (ISSUE 20): the pod reaches
                # the api server only when the gate drains it
                # (_ingest_tick); sheds go to the retry buffer.
                self._offer_pod(name, now)
        elif ev.kind == "complete":
            name = ev.data["pod"]
            if ev.data["gen"] != self._gen.get(name):
                return  # stale: the pod was interrupted after scheduling
            pod = self.api.get_pod(name)
            if pod is None or pod.get("phase") != "Bound":
                return
            avail = self.life.on_complete(name, now)
            self.api.delete_pod(name)
            self.completions += 1
            _M_COMPLETIONS.inc()
            _M_AVAIL.observe(avail)
            self.q.note(now, "complete", pod=name,
                        avail=round(avail, 6))
        elif ev.kind == "node_fail":
            node = ev.data["node"]
            if node in self._down or node not in self._node_specs:
                return
            self._down.add(node)
            self.node_failures += 1
            victims = sorted(
                p["name"] for p in self.api.bound_pods()
                if p.get("node") == node
            )
            for name in victims:
                self._interrupt(name, now, reason="node_fail")
            self.api.delete_node(node)
            self.q.note(ev.time, "node_fail", node=node,
                        victims=victims)
        elif ev.kind == "node_recover":
            node = ev.data["node"]
            if node not in self._down or node not in self._node_specs:
                return
            self._down.discard(node)
            self.api.add_node(**self._node_specs[node])
            self.q.note(ev.time, "node_recover", node=node)
        elif ev.kind == "node_add":
            # Autoscale-up: the node's full spec rides in the event
            # (generate/_schedule_autoscale put it there; a trace
            # serializes it with the timeline), so the driver needs no
            # side channel to learn grown shapes.
            node = ev.data["node"]
            if node in self._node_specs and node not in self._down:
                return
            self._node_specs[node] = ev.data["spec"]
            self._down.discard(node)
            self.api.add_node(**ev.data["spec"])
            self.autoscale_events += 1
            self.q.note(ev.time, "node_add", node=node)
        elif ev.kind == "node_remove":
            # Autoscale-down: permanent removal (unlike node_fail there
            # is no pending recovery). Running pods are interrupted and
            # re-queued with lifecycle history — a real scale-down
            # eviction, and the availability hit is attributed to it.
            node = ev.data["node"]
            if node not in self._node_specs:
                return
            victims = sorted(
                p["name"] for p in self.api.bound_pods()
                if p.get("node") == node
            )
            for name in victims:
                self._interrupt(name, now, reason="autoscale_down")
            self.api.delete_node(node)
            del self._node_specs[node]
            self._down.discard(node)
            self.autoscale_events += 1
            self.q.note(ev.time, "node_remove", node=node,
                        victims=victims)
        else:
            raise ValueError(f"unknown sim event kind {ev.kind!r}")
        self.events_applied += 1

    def _interrupt(self, name: str, now: float, reason: str) -> None:
        """A running pod loses its node (preemption or node failure):
        bank its run credit, shorten the remaining duration by what it
        already ran, bump its completion generation (pending completion
        events become stale), and re-queue it with lifecycle history so
        availability keeps decaying from where it was.

        GANG members propagate (ISSUE 9): the solver's minMember
        quorum is batch-local — running members do not count toward
        it — so a lone requeued member could NEVER re-place (held
        below quorum forever, silently dragging attainment). All-or-
        nothing semantics cut the other way too: losing any member
        interrupts the whole gang, and the group re-forms quorum in
        one pending batch.

        Idempotent per instant: gang propagation can race the caller's
        victims snapshot (co-located siblings get re-queued by the
        first victim's propagation before the loop reaches them) — a
        pod that is already back to Pending with no live run was
        interrupted this instant and must not bank a second eviction.
        The host-preempted path (api record already deleted) still has
        bound_at set and passes through."""
        pod = self.api.get_pod(name)
        if (self.life.pods[name].bound_at is None and pod is not None
                and pod.get("phase") == "Pending"):
            return
        ran = self.life.on_unbind(name, now, evicted=True)
        self._remaining[name] = max(
            self._remaining.get(name, 0.0) - ran, _MIN_REMAINING_S
        )
        self._gen[name] = self._gen.get(name, 0) + 1
        life = self.life.pods[name]
        self.api.delete_pod(name)
        self.api.add_pod(
            name, **self.setup.specs[name],
            submitted=life.submitted, run_seconds=life.run_seconds,
        )
        self.requeues += 1
        _M_REQUEUES.labels(reason).inc()
        gang = self.setup.meta[name].get("gang")
        if gang and reason != "gang_reform":
            siblings = sorted(
                p["name"] for p in self.api.bound_pods()
                if self.setup.meta.get(p["name"], {}).get("gang") == gang
            )
            for member in siblings:
                self._interrupt(member, now, reason="gang_reform")
            if siblings:
                self.q.note(now, "gang_reform", gang=gang,
                            members=siblings)

    # -- scheduling cycle ---------------------------------------------------

    def _cycle(self, now: float) -> None:
        bound_prev = {p["name"] for p in self.api.bound_pods()}
        try:
            self.host.cycle()
        except BaseException as e:
            # Soak composition (ISSUE 9): an injected engine fault
            # (FaultError via engine.fetch) or a transient sidecar rpc
            # failure drops THIS cycle the way the host's
            # run_until_idle tolerates a flaky scheduler backend — the
            # failed cycle mutated nothing (binds happen after a
            # successful solve; cycle()'s unwind restored the change
            # hints), so the next tick re-reads truth. Counted AND
            # noted in the event log: the fault schedule is part of
            # the deterministic timeline the hash pins.
            if not (isinstance(e, FaultError)
                    or HostScheduler._transient_rpc_error(e)):
                raise
            self.failed_cycles += 1
            self.q.note(now, "cycle_failed", n=self.failed_cycles)
            return
        bound_now = {p["name"]: p.get("node") for p in self.api.bound_pods()}

        for name in sorted(set(bound_now) - bound_prev):
            self.life.on_bind(name, now)
            gen = self._gen.get(name, 0)
            self.q.push(now + self._remaining[name], "complete",
                        pod=name, gen=gen)
            self.q.note(now, "bind", pod=name, node=bound_now[name])

        # Bound before the cycle, gone after it, and not re-added:
        # evicted by the scheduler's preemption path (the host already
        # issued the delete). Re-queue with history.
        for name in sorted(bound_prev - set(bound_now)):
            if self.api.get_pod(name) is not None:
                continue
            self._interrupt(name, now, reason="preempted")
            self.q.note(now, "evict", pod=name)

    def _offer_pod(self, name: str, now: float) -> None:
        """One pod through the ingest gate. An injected enqueue fault
        (ingest.enqueue error-rule) behaves exactly like a shed here —
        the sim IS the retrying client — and lands in the event log so
        the fault schedule stays part of the hashed timeline."""
        spec = self.setup.specs[name]
        meta = self.setup.meta[name]
        life = self.life.pods[name]
        rec = dict(name=name, priority=spec.get("priority", 0.0),
                   slo_target=meta["slo"], submitted=life.submitted,
                   run_seconds=life.run_seconds)
        try:
            res = self.ingest.offer([rec], tenant=meta.get("tenant", 0),
                                    now=now)
        except FaultError:
            self._shed_retry.append(name)
            self.q.note(now, "ingest_fault", pod=name)
            return
        if res["shed"]:
            self._shed_retry.extend(res["shed"])
            self.q.note(now, "ingest_shed", pod=name)

    def _ingest_tick(self, now: float) -> None:
        """Per-tick front-door pump: re-offer everything shed (the
        RESOURCE_EXHAUSTED retry loop, virtual-time edition), then
        drain the gate's admitted window into the api server with
        lifecycle history preserved — convergence to the ungated end
        state is what the chaos arm pins."""
        retry, self._shed_retry = self._shed_retry, []
        for name in retry:
            self._offer_pod(name, now)
        for name in self.ingest.take_window(now, w=self.sim.batch_size):
            life = self.life.pods[name]
            self.api.add_pod(
                name, **self.setup.specs[name],
                submitted=life.submitted, run_seconds=life.run_seconds,
            )

    def _sample_pressure(self, now: float) -> None:
        pend = self.api.pending_pods()
        if not pend:
            self.pressure_samples.append((now, 0, 0.0, 0.0))
            return
        slo = np.asarray(
            [p.get("slo_target", DEFAULT_SLO_TARGET) for p in pend])
        avail = np.asarray(
            [p.get("observed_avail", DEFAULT_OBSERVED_AVAIL) for p in pend])
        pressure = qos.pressure_of(slo, avail)
        self.pressure_samples.append((
            now, len(pend),
            round(float(pressure.mean()), 6),
            round(float(pressure.max()), 6),
        ))

    # -- main loop ----------------------------------------------------------

    def run(self) -> SimResult:
        tr = self.tracer or tracing.DEFAULT
        sc, sim = self.sc, self.sim
        wall0 = time.perf_counter()
        ticks = 0
        try:
            while self.clock.now() < sc.horizon_s - 1e-9:
                now = self.clock.now()
                t0 = time.perf_counter()
                due = self.q.pop_until(now)
                for event in due:
                    self._apply(event)
                if self.ingest is not None:
                    self._ingest_tick(now)
                if ticks % sim.resolve_every == 0:
                    self._cycle(now)
                self._sample_pressure(now)
                tr.record(
                    "sim.tick", dur_s=time.perf_counter() - t0, cat="sim",
                    t=now, events=len(due),
                    pending=self.pressure_samples[-1][1],
                )
                self.clock.advance(sim.tick_s)
                ticks += 1
            # Final drain: the loop's last pop ran one tick before the
            # horizon, so events due in the closing window — completions
            # of pods bound on the final tick among them — would be
            # silently dropped and systematically undercount attainment.
            for event in self.q.pop_until(self.clock.now()):
                self._apply(event)
        finally:
            self.host.close()
            if self._owns_engine and self.engine is not None:
                self.engine.close()
        return self._result(ticks, time.perf_counter() - wall0)

    def _result(self, ticks: int, wall_s: float) -> SimResult:
        horizon = self.clock.now()
        outcomes = []
        for name in self._arrived:
            life = self.life.pods[name]
            meta = self.setup.meta[name]
            completed = life.completed_at is not None
            end = life.completed_at if completed else horizon
            avail = life.availability(end)
            ran = life.run_seconds + (
                max(end - life.bound_at, 0.0)
                if life.bound_at is not None else 0.0
            )
            slo = meta["slo"]
            outcomes.append(PodOutcome(
                name=name, tenant=meta["tenant"], slo=slo,
                priority=meta["priority"], submitted=life.submitted,
                completed=completed, end_time=end, ran_s=ran,
                waited_s=max(end - life.submitted - ran, 0.0),
                evictions=life.evictions, final_avail=avail,
                attained=(avail + 1e-9 >= slo) if slo > 0 else None,
                gang=meta.get("gang"),
            ))
        placed = sum(c.placed for c in self.host.cycles)
        evicted = sum(c.evicted for c in self.host.cycles)
        return SimResult(
            scenario=self.sc.name, seed=self.seed, backend=self.backend,
            qos_gain=self.cfg.qos.qos_gain, horizon_s=horizon,
            ticks=ticks, cycles=len(self.host.cycles),
            events_applied=self.events_applied, placed=placed,
            evicted=evicted, completions=self.completions,
            requeues=self.requeues, node_failures=self.node_failures,
            autoscale_events=self.autoscale_events,
            failed_cycles=self.failed_cycles,
            pods=outcomes, pressure_samples=self.pressure_samples,
            event_log_hash=self.q.log_hash(), wall_seconds=wall_s,
        )


# ---------------------------------------------------------------------------
# Entry points.
# ---------------------------------------------------------------------------


def run_scenario(
    scenario: "Scenario | None" = None,
    seed: int = 0,
    config: "EngineConfig | None" = None,
    sim: "SimConfig | None" = None,
    backend: str = "inprocess",
    engine=None,
    faults=None,
    tracer=None,
    replicas: int = 1,
    explain=None,
    setup: "SimSetup | None" = None,
    ledger=None,
    device_queue: bool = False,
) -> SimResult:
    """One sim run. backend="grpc" spins an in-process sidecar and
    drives the full host -> gRPC path (AssignPipeline transport);
    "inprocess" solves through a local Engine (pass `engine` to share
    one jit cache across runs of the SAME config). replicas > 1 (grpc
    only) serves from a tpusched.replicate.ReplicaSet — warm-standby
    replication behind the same pipeline transport, so long simulated
    horizons ride the failover machinery the chaos harness pins.
    explain: optional ExplainCollector — in-process it rides the host,
    on grpc it is handed to make_server so the sidecar records every
    Assign (same collector object either way; replicas > 1 records on
    the initial leader only).
    setup (ISSUE 9): a prebuilt SimSetup (trace replay via
    traces.load_trace, or a pre-generated workload) instead of
    `scenario` — generated and ingested workloads ride this one path.
    ledger (round 18): optional CycleLedger for the in-process host's
    CycleRecord emission (grpc runs record server-side instead)."""
    if setup is not None:
        scenario = setup.scenario
        seed = setup.seed
    if backend == "inprocess":
        if replicas != 1:
            raise ValueError("replicas > 1 needs backend='grpc'")
        return SimDriver(scenario, seed, config=config, sim=sim,
                         engine=engine, faults=faults, tracer=tracer,
                         explain=explain, setup=setup,
                         ledger=ledger, device_queue=device_queue).run()
    if backend != "grpc":
        raise ValueError(f"backend={backend!r}: want inprocess|grpc")
    from tpusched.rpc.client import SchedulerClient  # tpl: disable=TPL001(grpc backend is optional; the in-process sim must import without grpc)
    from tpusched.rpc.server import make_server  # tpl: disable=TPL001(grpc backend is optional; the in-process sim must import without grpc)

    cfg = effective_config(scenario, config)
    if replicas > 1:
        from tpusched.replicate import ReplicaSet  # tpl: disable=TPL001(grpc backend is optional; the in-process sim must import without grpc)

        fleet = ReplicaSet(replicas, config=cfg, faults=faults,
                           explain=explain)
        client = SchedulerClient(fleet.addresses())
        try:
            return SimDriver(scenario, seed, config=cfg, sim=sim,
                             client=client, tracer=tracer,
                             setup=setup).run()
        finally:
            client.close()
            fleet.close()
    server, port, svc = make_server("127.0.0.1:0", config=cfg,
                                    faults=faults, explain=explain)
    server.start()
    client = SchedulerClient(f"127.0.0.1:{port}")
    try:
        return SimDriver(scenario, seed, config=cfg, sim=sim,
                         client=client, tracer=tracer, setup=setup).run()
    finally:
        client.close()
        server.stop(0)
        svc.close()


def static_baseline(config: "EngineConfig | None" = None) -> EngineConfig:
    """The twin run's control arm: identical config with the QoS loop
    severed — qos_gain 0 (priority is the static pod.spec.priority
    again) and urgency_reweight off (no pressure-driven plugin-weight
    interpolation). Preemption/eviction-cost machinery stays as
    configured, so the ONLY difference is the dynamic-priority signal."""
    cfg = config or EngineConfig(mode="fast")
    return dataclasses.replace(
        cfg,
        qos=dataclasses.replace(cfg.qos, qos_gain=0.0,
                                urgency_reweight=False),
    )


def twin_run(
    scenario: "Scenario | None" = None,
    seed: int = 0,
    config: "EngineConfig | None" = None,
    sim: "SimConfig | None" = None,
    backend: str = "inprocess",
    log=None,
    explain: bool = False,
    setup_factory=None,
    faults_factory=None,
    device_queue: bool = False,
) -> dict:
    """The headline experiment: same scenario, same seed, QoS-driven vs
    static-priority baseline. Returns both summaries plus
    attainment_gain_vs_static (fraction of SLO-carrying pods attaining
    their target, QoS minus static) — the reference paper's central
    claim as a repeatable bench number.

    explain=True (round 12) runs each arm with a per-arm
    ExplainCollector and attaches `miss_attribution` to its summary:
    every missed-SLO pod joined to its recorded decision chain, rolled
    up into a "top miss causes" table (report.miss_attribution) — the
    twin then says not just THAT static lost but WHY its misses
    happened (preempted vs unschedulable vs outranked).

    setup_factory (ISSUE 9): zero-arg callable returning a FRESH
    SimSetup per arm (a run consumes its event queue) — the trace-twin
    entry: `lambda: traces.load_trace(path)` twins an INGESTED
    workload; scenario may then be None. faults_factory likewise
    builds a fresh FaultPlan per arm (plans carry invocation counters),
    so soak compositions twin deterministically."""
    # When the scenario rides in on the factory (trace twins), keep the
    # setup we peeked at for the FIRST arm — a large ingested trace
    # should parse once per arm, not an extra time for the header.
    pending_setup = None
    if setup_factory is not None and scenario is None:
        pending_setup = setup_factory()
        scenario = pending_setup.scenario
    cfg = effective_config(scenario, config)
    if cfg.qos.qos_gain <= 0:
        raise ValueError(
            "twin_run wants a QoS-driven config (qos_gain > 0) as the "
            "treatment arm; got qos_gain="
            f"{cfg.qos.qos_gain}"
        )
    results = {}
    for arm, arm_cfg in (("qos", cfg), ("static", static_baseline(cfg))):
        if log:
            log(f"[sim] twin-run arm={arm} scenario={scenario.name} "
                f"seed={seed} qos_gain={arm_cfg.qos.qos_gain}")
        col = None
        if explain:
            # Capacity covers a full horizon of per-tick cycles, so the
            # attribution join sees every decision, not a recent window.
            col = ExplainCollector(capacity=65536, enabled=True)
        if pending_setup is not None:
            arm_setup, pending_setup = pending_setup, None
        elif setup_factory is not None:
            arm_setup = setup_factory()
        else:
            arm_setup = None
        res = run_scenario(
            scenario, seed, config=arm_cfg, sim=sim, backend=backend,
            explain=col, setup=arm_setup,
            faults=(faults_factory() if faults_factory is not None
                    else None),
            device_queue=device_queue,
        )
        results[arm] = report.summarize(res)
        if col is not None:
            results[arm]["miss_attribution"] = report.miss_attribution(
                res, col.records())
        if log:
            s = results[arm]
            log(f"[sim]   attainment={s['slo_attainment_frac']} "
                f"completions={s['completions']} evictions={s['evicted']} "
                f"hash={s['event_log_hash'][:12]}")
    gain = (results["qos"]["slo_attainment_frac"]
            - results["static"]["slo_attainment_frac"])
    return dict(
        scenario=scenario.name, seed=seed, backend=backend,
        qos=results["qos"], static=results["static"],
        slo_attainment_frac=results["qos"]["slo_attainment_frac"],
        attainment_gain_vs_static=round(gain, 6),
    )


def matrix_run(
    scenario_names=None,
    seed: int = 0,
    config: "EngineConfig | None" = None,
    sim: "SimConfig | None" = None,
    backend: str = "inprocess",
    horizon_s: "float | None" = None,
    log=None,
    explain: bool = False,
) -> dict:
    """The scenario-matrix bench (ISSUE 9): twin_run every scenario in
    `scenario_names` (default workloads.MATRIX_SCENARIOS, >= 6
    Borg/Azure-shaped shapes) and tabulate slo_attainment_frac +
    preemption churn per scenario x {QoS, static}, with both arms'
    event-log hashes — so every future PR's QoS-vs-static gain is
    judged across the matrix instead of one hand-picked corner.
    horizon_s caps (never extends) each scenario's virtual horizon —
    the bench-budget knob."""
    names = list(scenario_names if scenario_names is not None
                 else MATRIX_SCENARIOS)
    rows = []
    for name in names:
        sc = SCENARIOS[name]
        if horizon_s is not None:
            sc = dataclasses.replace(
                sc, horizon_s=min(sc.horizon_s, float(horizon_s))
            )
        twin = twin_run(sc, seed=seed, config=config, sim=sim,
                        backend=backend, log=log, explain=explain)
        q, s = twin["qos"], twin["static"]
        extra = {}
        if explain:
            extra = dict(
                miss_causes=q.get("miss_attribution", {}).get("causes"),
                miss_causes_static=s.get("miss_attribution",
                                         {}).get("causes"),
            )
        rows.append(dict(
            **extra,
            scenario=name,
            slo_attainment_frac=q["slo_attainment_frac"],
            slo_attainment_frac_static=s["slo_attainment_frac"],
            attainment_gain_vs_static=twin["attainment_gain_vs_static"],
            preemption_churn=q["preemption_churn"],
            preemption_churn_static=s["preemption_churn"],
            slo_pods=q["slo_pods"],
            evictions=q["evicted"], evictions_static=s["evicted"],
            autoscale_events=q["autoscale_events"],
            hash_qos=q["event_log_hash"], hash_static=s["event_log_hash"],
        ))
        if log:
            r = rows[-1]
            log(f"[sim] matrix {name}: qos={r['slo_attainment_frac']} "
                f"static={r['slo_attainment_frac_static']} "
                f"gain={r['attainment_gain_vs_static']}")
    return dict(seed=seed, backend=backend, scenarios=names, rows=rows)
