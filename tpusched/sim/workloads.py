"""Scenario library + the ONE workload-synthesis path (ISSUE 5, 9).

Layered on tpusched/synth.py's cluster vocabulary (the same node
classes, zone labels, and app names the snapshot-level generators use)
but producing API-SERVER records plus an event timeline instead of a
prebuilt array snapshot: the simulator exercises the full host path —
watch, batch, solve, bind — not just the kernels.

`generate()` is the single synthesis code path (ISSUE 9): the
Borg/Azure-shaped presets in tpusched/sim/generators.py are plain
Scenario values fed through it, and tpusched/sim/traces.py serializes
its output (a SimSetup) to the on-disk trace format — so a generated
workload and an ingested trace drive SimDriver through identical
machinery and replay to byte-identical event-log hashes.

Scenario axes:

  * arrival process (poisson / burst / diurnal) and rate;
  * workload mix: per-class SLO target, base priority, duration, and
    resource shape, with tenant skew (tenants.zipf_weights — the one
    shared Zipf definition) for multi-tenant pressure;
  * duration distribution: uniform over the mix range, or lognormal
    long-tail (Borg-shaped: the range is read as (median, ~p99));
  * gang arrivals: a fraction of arrivals submit `gang_size` identical
    members under one pod_group with all-or-nothing minMember
    semantics (test_gangs.py is the kernel-level contract);
  * heterogeneous node pools (>= 2 shapes per cluster) and autoscale
    events: pools grow/shrink mid-horizon, which on the gRPC path
    drives the device-resident state's real bucket-growth and
    taint-vocab rebuild paths (device_state.py);
  * node failures (MTBF/MTTR flaps);
  * the pressure-skew twist, expressed in the mix itself: SLO-carrying
    classes get LOW base-priority ranges, SLO-less filler classes get
    HIGH ones — the adversarial mix where static priority starves
    exactly the pods with availability targets, and QoS-driven dynamic
    priority is the only thing that can rescue them. This is the
    twin-run headline scenario: attainment(qos_gain>0) -
    attainment(qos_gain=0) is the paper's central claim as one number.

Everything is drawn from one seeded Generator in generate(): same
(scenario, seed) -> identical specs and timeline. Scenarios that do not
use a new axis (gang_frac=0, uniform durations, no pools) draw the
EXACT same RNG stream as before the axis existed, so preset timelines
are stable across versions.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from tpusched.synth import NODE_CLASSES, ZONES
from tpusched.tenants import zipf_weights

from tpusched.sim import events as ev

APPS = ("web", "db", "cache", "batch")


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    description: str = ""          # one-liner for --list / the matrix
    # cluster (legacy single-pool form; `pools` overrides when set)
    n_nodes: int = 8
    node_class: int = 1            # index into synth.NODE_CLASSES
    # Heterogeneous pools: ((count, node_class[, (taint_k, v, effect)]),
    # ...). Pool p's nodes are named "p{p}-node-{i}" and labeled
    # tpusched.io/pool=p{p}; a pool may start at count 0 and only exist
    # through autoscale growth.
    pools: tuple = ()
    # Autoscale events: ((t, "grow"|"shrink", pool_idx, count), ...).
    # grow appends `count` nodes to the pool at virtual time t; shrink
    # removes the pool's highest-numbered nodes (running pods are
    # interrupted and re-queued with lifecycle history, like a real
    # scale-down eviction).
    autoscale: tuple = ()
    # time
    horizon_s: float = 150.0
    # arrivals
    arrival: str = "poisson"       # poisson | burst | diurnal
    rate: float = 0.3              # pods per virtual second
    burst_every_s: float = 40.0
    burst_size: int = 12
    diurnal_period_s: float = 120.0
    diurnal_amplitude: float = 0.8
    prefill: int = 0               # pods submitted at t=0
    # Prefill pods draw from ONE mix class (index; the filler class by
    # convention) with an optional widened duration range: staggered
    # durations make the warm cluster release slots continuously from
    # early in the run instead of in one cliff at min(duration).
    prefill_class: int = 0
    prefill_duration_s: "tuple | None" = None
    # workload mix: (weight, slo_target, duration range, priority range,
    # cpu range). Weights are normalized. slo_target 0 = no SLO.
    mix: tuple = (
        (0.5, 0.0, (40.0, 80.0), (50, 100), (1500.0, 2500.0)),
        (0.3, 0.7, (20.0, 40.0), (0, 50), (1500.0, 2500.0)),
        (0.2, 0.9, (20.0, 40.0), (0, 50), (1500.0, 2500.0)),
    )
    # Duration distribution over each class's (d_lo, d_hi) range:
    # "uniform", or "lognormal" long-tail where d_lo is the MEDIAN and
    # d_hi sits near the 99th percentile (Borg-style job durations:
    # most short, a heavy tail of long-runners).
    duration_dist: str = "uniform"
    # gang arrivals (coscheduling): fraction of non-prefill arrivals
    # that submit `gang_size` identical members under one pod_group.
    # gang_min_member 0 means all-or-nothing (minMember = gang_size).
    gang_frac: float = 0.0
    gang_size: int = 4
    gang_min_member: int = 0
    # multi-tenancy
    tenants: int = 4
    tenant_skew: float = 0.0       # 0 = uniform; higher = heavier head
    # failures
    node_mtbf_s: float = 0.0       # 0 = no failures
    node_mttr_s: float = 10.0
    # solver
    preemption: bool = False


@dataclasses.dataclass
class SimSetup:
    """generate()'s output: the initial cluster, per-pod specs/meta,
    and the fully-populated event queue. traces.write_trace serializes
    exactly these four members; traces.load_trace rebuilds them."""

    scenario: Scenario
    seed: int
    nodes: list            # api.add_node kwargs, keyed by ["name"]
    specs: dict            # pod name -> api.add_pod spec (wire fields)
    meta: dict             # pod name -> dict(duration_s, slo, tenant, ...)
    queue: ev.EventQueue


def _sample_duration(rng: np.random.Generator, dist: str,
                     d_lo: float, d_hi: float) -> float:
    if dist == "uniform":
        return float(rng.uniform(d_lo, d_hi))
    if dist == "lognormal":
        # d_lo = median, d_hi ~ p99 (z=2.326). The tail is deliberately
        # uncapped above d_hi — the long-runners that outlive several
        # diurnal periods are the point of a Borg-shaped trace.
        sigma = math.log(max(d_hi / max(d_lo, 1e-9), 1.0 + 1e-9)) / 2.326
        return float(max(d_lo * math.exp(sigma * rng.standard_normal()),
                         1e-3))
    raise ValueError(f"unknown duration_dist {dist!r}")


def _effective_pools(sc: Scenario) -> list[tuple]:
    """Pool list as (count, class_idx, taint-or-None); the legacy
    n_nodes/node_class form is one unnamed pool."""
    if not sc.pools:
        return [(int(sc.n_nodes), int(sc.node_class), None)]
    out = []
    for entry in sc.pools:
        if len(entry) == 2:
            count, cls = entry
            taint = None
        elif len(entry) == 3:
            count, cls, taint = entry
        else:
            raise ValueError(
                f"pool entry {entry!r}: want (count, node_class"
                "[, (taint_key, value, effect)])"
            )
        out.append((int(count), int(cls), taint))
    return out


def _node_record(sc: Scenario, pools: list[tuple], pi: int, i: int,
                 global_idx: int) -> dict:
    """Node record for pool pi's i-th node. Legacy single-pool
    scenarios keep the historical 'node-{i}' names (stable preset
    timelines); pooled clusters name 'p{pi}-node-{i}' and carry a pool
    label (and the pool's taint, if any)."""
    _, cls, taint = pools[pi]
    cpu, mem = NODE_CLASSES[cls % len(NODE_CLASSES)]
    if not sc.pools:
        name = f"node-{i}"
        labels = {
            "kubernetes.io/hostname": name,
            "topology.kubernetes.io/zone": ZONES[i % len(ZONES)],
        }
    else:
        name = f"p{pi}-node-{i}"
        labels = {
            "kubernetes.io/hostname": name,
            "topology.kubernetes.io/zone": ZONES[global_idx % len(ZONES)],
            "tpusched.io/pool": f"p{pi}",
        }
    rec = dict(
        name=name,
        allocatable={"cpu": float(cpu), "memory": float(mem)},
        labels=labels,
    )
    if taint is not None:
        rec["taints"] = [tuple(taint)]
    return rec


def _schedule_autoscale(sc: Scenario, pools: list[tuple],
                        counts: list[int], q: ev.EventQueue) -> None:
    """Turn sc.autoscale into node_add / node_remove events. Processed
    in time order so a later shrink sees earlier growth; node specs for
    grown nodes ride IN the event (the driver learns them at apply
    time, and the trace serializes them with the timeline)."""
    global_idx = sum(counts)
    for entry in sorted(sc.autoscale, key=lambda e: (e[0],)):
        t, op, pi, count = entry
        t, pi, count = float(t), int(pi), int(count)
        if not 0 <= pi < len(pools):
            raise ValueError(f"autoscale {entry!r}: no pool {pi}")
        if op == "grow":
            for _ in range(count):
                rec = _node_record(sc, pools, pi, counts[pi], global_idx)
                counts[pi] += 1
                global_idx += 1
                q.push(t, "node_add", node=rec["name"], spec=rec)
        elif op == "shrink":
            if counts[pi] < count:
                raise ValueError(
                    f"autoscale {entry!r}: pool {pi} has only "
                    f"{counts[pi]} nodes at t={t}"
                )
            for _ in range(count):
                counts[pi] -= 1
                rec = _node_record(sc, pools, pi, counts[pi], global_idx)
                q.push(t, "node_remove", node=rec["name"])
        else:
            raise ValueError(
                f"autoscale {entry!r}: op must be grow|shrink"
            )


def generate(sc: Scenario, seed: int) -> SimSetup:
    rng = np.random.default_rng(seed)
    pools = _effective_pools(sc)
    nodes = []
    global_idx = 0
    for pi, (count, _, _) in enumerate(pools):
        for i in range(count):
            nodes.append(_node_record(sc, pools, pi, i, global_idx))
            global_idx += 1

    if sc.arrival == "burst":
        times = ev.bursty_times(rng, sc.rate, sc.horizon_s,
                                sc.burst_every_s, sc.burst_size)
    elif sc.arrival == "diurnal":
        times = ev.diurnal_times(rng, sc.rate, sc.horizon_s,
                                 sc.diurnal_period_s, sc.diurnal_amplitude)
    elif sc.arrival == "poisson":
        times = ev.poisson_times(rng, sc.rate, sc.horizon_s)
    else:
        raise ValueError(f"unknown arrival process {sc.arrival!r}")
    times = [0.0] * sc.prefill + list(times)

    weights = np.asarray([m[0] for m in sc.mix], np.float64)
    weights = weights / weights.sum()
    tenant_p = zipf_weights(sc.tenants, sc.tenant_skew)

    specs: dict[str, dict] = {}
    meta: dict[str, dict] = {}
    q = ev.EventQueue()
    for i, t in enumerate(times):
        is_prefill = i < sc.prefill
        # The gang gate draw only happens when the axis is in use, so
        # gang-less scenarios keep their historical RNG stream.
        is_gang = (sc.gang_frac > 0.0 and not is_prefill
                   and rng.uniform() < sc.gang_frac)
        cls = (sc.prefill_class if is_prefill
               else int(rng.choice(len(sc.mix), p=weights)))
        _, slo, (d_lo, d_hi), (p_lo, p_hi), (c_lo, c_hi) = sc.mix[cls]
        if is_prefill and sc.prefill_duration_s is not None:
            d_lo, d_hi = sc.prefill_duration_s
        duration = _sample_duration(rng, sc.duration_dist, d_lo, d_hi)
        priority = float(rng.integers(p_lo, max(p_hi, p_lo + 1)))
        tenant = int(rng.choice(sc.tenants, p=tenant_p))
        cpu_req = float(rng.uniform(c_lo, c_hi))
        mem_req = float(rng.integers(1 << 28, 1 << 30))
        app = APPS[int(rng.integers(len(APPS)))]
        base = dict(
            requests={"cpu": cpu_req, "memory": mem_req},
            priority=priority,
            slo_target=float(slo),
            labels={"app": app, "tenant": f"tenant-{tenant}"},
            namespace=f"ns-{tenant}",
        )
        if is_gang:
            # One gang = gang_size IDENTICAL members (one Borg job's
            # homogeneous tasks) under one pod_group; one duration, so
            # a placed gang completes together. Members arrive at the
            # same instant and share the host's gang backoff key.
            gname = f"gang-sim-{i}"
            minm = sc.gang_min_member or sc.gang_size
            for j in range(sc.gang_size):
                name = f"sim-{i}g{j}"
                member = dict(base)
                member["labels"] = dict(base["labels"])
                member["pod_group"] = gname
                member["pod_group_min_member"] = minm
                specs[name] = member
                meta[name] = dict(duration_s=duration, slo=float(slo),
                                  tenant=tenant, priority=priority,
                                  gang=gname)
                q.push(t, "arrival", pod=name)
        else:
            name = f"sim-{i}"
            specs[name] = base
            meta[name] = dict(duration_s=duration, slo=float(slo),
                              tenant=tenant, priority=priority)
            q.push(t, "arrival", pod=name)

    for t, kind, node in ev.failure_times(
        rng, [n["name"] for n in nodes], sc.node_mtbf_s, sc.node_mttr_s,
        sc.horizon_s,
    ):
        q.push(t, kind, node=node)

    counts = [count for count, _, _ in pools]
    _schedule_autoscale(sc, pools, counts, q)

    return SimSetup(scenario=sc, seed=seed, nodes=nodes, specs=specs,
                    meta=meta, queue=q)


# ---------------------------------------------------------------------------
# Presets. Capacity intuition (node_class=1: 8000 cpu): each pod asks
# ~2000 cpu, so a node runs ~4 pods; slots = 4 * n_nodes. Service rate
# ~ slots / mean_duration; rates above it build the queues that make
# SLO attainment a real contest.
#
# The Borg/Azure-shaped presets live in tpusched/sim/generators.py and
# are merged into this registry at the bottom of this module; matrix
# consumers (bench.py --sim-scenario all, tools/simulate.py --scenario
# all) iterate MATRIX_SCENARIOS.
# ---------------------------------------------------------------------------


SCENARIOS: dict[str, Scenario] = {
    # Comfortable load, no failures: the sanity scenario where both
    # static and QoS-driven scheduling should attain nearly everything.
    "steady_state": Scenario(
        name="steady_state", n_nodes=6, horizon_s=120.0,
        description="comfortable Poisson load, no failures: both "
                    "policies should attain nearly everything",
        arrival="poisson", rate=0.25,
        mix=(
            (0.5, 0.0, (20.0, 40.0), (0, 100), (1500.0, 2500.0)),
            (0.5, 0.8, (20.0, 40.0), (0, 100), (1500.0, 2500.0)),
        ),
    ),
    # Periodic submission spikes over a modest base: queues form during
    # bursts and drain between them.
    "burst": Scenario(
        name="burst", n_nodes=6, horizon_s=180.0,
        description="periodic submission spikes over a modest base: "
                    "queues form during bursts and drain between",
        arrival="burst", rate=0.15, burst_every_s=45.0, burst_size=16,
        mix=(
            (0.5, 0.0, (25.0, 50.0), (20, 100), (1500.0, 2500.0)),
            (0.5, 0.85, (15.0, 30.0), (0, 20), (1500.0, 2500.0)),
        ),
    ),
    # The headline twin-run scenario: a warm, permanently-overloaded
    # cluster of SLO-less fillers with HIGH base priority, plus a
    # stream of SLO pods with LOW base priority whose demand alone
    # would fit comfortably. Static priority hands every released slot
    # to the standing filler backlog and starves the SLO class; QoS
    # pressure lifts waiting SLO pods over the fillers. Preemption is
    # deliberately OFF here: under permanent overload the preemption
    # path equalizes availability across pods (pending pressured pods
    # evict just-recovered runners whose slack crossed zero), which
    # SPREADS the misses instead of concentrating them — a real effect
    # worth measuring, but it muddies the single-number queue-ordering
    # claim this scenario exists to pin.
    "pressure_skew": Scenario(
        name="pressure_skew", n_nodes=6, horizon_s=150.0,
        description="adversarial headline: high-priority SLO-less "
                    "fillers starve low-priority SLO pods unless QoS "
                    "pressure reorders the queue",
        arrival="poisson", rate=0.32, prefill=30,
        prefill_duration_s=(10.0, 90.0),
        mix=(
            # fillers: no SLO, high base priority, long-running
            (0.60, 0.0, (60.0, 90.0), (60, 100), (1800.0, 2400.0)),
            # SLO classes: tight availability targets, LOW base priority
            (0.20, 0.7, (25.0, 40.0), (0, 10), (1800.0, 2400.0)),
            (0.20, 0.9, (30.0, 45.0), (0, 10), (1800.0, 2400.0)),
        ),
        tenants=4, tenant_skew=1.0,
        preemption=False,
    ),
    # Node flaps mid-run: interrupted pods lose availability through no
    # queueing fault; measures how scheduling policy recovers them.
    "failure_storm": Scenario(
        name="failure_storm", n_nodes=8, horizon_s=180.0,
        description="node MTBF/MTTR flaps interrupt running pods; "
                    "measures how policy recovers their availability",
        arrival="poisson", rate=0.25,
        mix=(
            (0.4, 0.0, (30.0, 60.0), (20, 100), (1500.0, 2500.0)),
            (0.6, 0.8, (20.0, 40.0), (0, 40), (1500.0, 2500.0)),
        ),
        node_mtbf_s=60.0, node_mttr_s=15.0,
    ),
}


# Borg/Azure-shaped presets (ISSUE 9): generators.py builds them from
# the Scenario machinery above and MERGES them into SCENARIOS at its
# own import bottom — a bare import here is safe in either import
# order (no attribute access on a possibly-partially-initialized
# module), and either entry module leaves the registry complete.
import tpusched.sim.generators  # noqa: E402,F401  (side effect: merge)

# The bench.py --sim / simulate.py matrix: every scenario cheap enough
# to twin-run in one bench invocation (the long-horizon soak is
# deliberately excluded — run it alone, or via its bounded smoke).
MATRIX_SCENARIOS: tuple = (
    "steady_state",
    "burst",
    "pressure_skew",
    "failure_storm",
    "borg_longtail",
    "azure_diurnal",
    "autoscale_stress",
    "gang_pressure",
)
