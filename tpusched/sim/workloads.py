"""Scenario library for the virtual-time simulator (ISSUE 5).

Layered on tpusched/synth.py's cluster vocabulary (the same node
classes, zone labels, and app names the snapshot-level generators use)
but producing API-SERVER records plus an event timeline instead of a
prebuilt array snapshot: the simulator exercises the full host path —
watch, batch, solve, bind — not just the kernels.

Scenario axes:

  * arrival process (poisson / burst / diurnal) and rate;
  * workload mix: per-class SLO target, base priority, duration, and
    resource shape, with tenant skew (Zipf-ish weights) for
    multi-tenant pressure;
  * node failures (MTBF/MTTR flaps);
  * the pressure-skew twist, expressed in the mix itself: SLO-carrying
    classes get LOW base-priority ranges, SLO-less filler classes get
    HIGH ones — the adversarial mix where static priority starves
    exactly the pods with availability targets, and QoS-driven dynamic
    priority is the only thing that can rescue them. This is the
    twin-run headline scenario: attainment(qos_gain>0) -
    attainment(qos_gain=0) is the paper's central claim as one number.

Everything is drawn from one seeded Generator in generate(): same
(scenario, seed) -> identical specs and timeline.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tpusched.synth import NODE_CLASSES, ZONES

from tpusched.sim import events as ev

APPS = ("web", "db", "cache", "batch")


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    # cluster
    n_nodes: int = 8
    node_class: int = 1            # index into synth.NODE_CLASSES
    # time
    horizon_s: float = 150.0
    # arrivals
    arrival: str = "poisson"       # poisson | burst | diurnal
    rate: float = 0.3              # pods per virtual second
    burst_every_s: float = 40.0
    burst_size: int = 12
    diurnal_period_s: float = 120.0
    diurnal_amplitude: float = 0.8
    prefill: int = 0               # pods submitted at t=0
    # Prefill pods draw from ONE mix class (index; the filler class by
    # convention) with an optional widened duration range: staggered
    # durations make the warm cluster release slots continuously from
    # early in the run instead of in one cliff at min(duration).
    prefill_class: int = 0
    prefill_duration_s: "tuple | None" = None
    # workload mix: (weight, slo_target, duration range, priority range,
    # cpu range). Weights are normalized. slo_target 0 = no SLO.
    mix: tuple = (
        (0.5, 0.0, (40.0, 80.0), (50, 100), (1500.0, 2500.0)),
        (0.3, 0.7, (20.0, 40.0), (0, 50), (1500.0, 2500.0)),
        (0.2, 0.9, (20.0, 40.0), (0, 50), (1500.0, 2500.0)),
    )
    # multi-tenancy
    tenants: int = 4
    tenant_skew: float = 0.0       # 0 = uniform; higher = heavier head
    # failures
    node_mtbf_s: float = 0.0       # 0 = no failures
    node_mttr_s: float = 10.0
    # solver
    preemption: bool = False


@dataclasses.dataclass
class SimSetup:
    """generate()'s output: the initial cluster, per-pod specs/meta,
    and the fully-populated event queue."""

    scenario: Scenario
    seed: int
    nodes: list            # api.add_node kwargs, keyed by ["name"]
    specs: dict            # pod name -> api.add_pod spec (wire fields)
    meta: dict             # pod name -> dict(duration_s, slo, tenant, ...)
    queue: ev.EventQueue


def _tenant_weights(n: int, skew: float) -> np.ndarray:
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), max(skew, 0.0))
    return w / w.sum()


def generate(sc: Scenario, seed: int) -> SimSetup:
    rng = np.random.default_rng(seed)
    cpu, mem = NODE_CLASSES[sc.node_class % len(NODE_CLASSES)]
    nodes = [
        dict(
            name=f"node-{i}",
            allocatable={"cpu": float(cpu), "memory": float(mem)},
            labels={
                "kubernetes.io/hostname": f"node-{i}",
                "topology.kubernetes.io/zone": ZONES[i % len(ZONES)],
            },
        )
        for i in range(sc.n_nodes)
    ]

    if sc.arrival == "burst":
        times = ev.bursty_times(rng, sc.rate, sc.horizon_s,
                                sc.burst_every_s, sc.burst_size)
    elif sc.arrival == "diurnal":
        times = ev.diurnal_times(rng, sc.rate, sc.horizon_s,
                                 sc.diurnal_period_s, sc.diurnal_amplitude)
    elif sc.arrival == "poisson":
        times = ev.poisson_times(rng, sc.rate, sc.horizon_s)
    else:
        raise ValueError(f"unknown arrival process {sc.arrival!r}")
    times = [0.0] * sc.prefill + list(times)

    weights = np.asarray([m[0] for m in sc.mix], np.float64)
    weights = weights / weights.sum()
    tenant_p = _tenant_weights(sc.tenants, sc.tenant_skew)

    specs: dict[str, dict] = {}
    meta: dict[str, dict] = {}
    q = ev.EventQueue()
    for i, t in enumerate(times):
        name = f"sim-{i}"
        is_prefill = i < sc.prefill
        cls = (sc.prefill_class if is_prefill
               else int(rng.choice(len(sc.mix), p=weights)))
        _, slo, (d_lo, d_hi), (p_lo, p_hi), (c_lo, c_hi) = sc.mix[cls]
        if is_prefill and sc.prefill_duration_s is not None:
            d_lo, d_hi = sc.prefill_duration_s
        duration = float(rng.uniform(d_lo, d_hi))
        priority = float(rng.integers(p_lo, max(p_hi, p_lo + 1)))
        tenant = int(rng.choice(sc.tenants, p=tenant_p))
        cpu_req = float(rng.uniform(c_lo, c_hi))
        specs[name] = dict(
            requests={"cpu": cpu_req,
                      "memory": float(rng.integers(1 << 28, 1 << 30))},
            priority=priority,
            slo_target=float(slo),
            labels={"app": APPS[int(rng.integers(len(APPS)))],
                    "tenant": f"tenant-{tenant}"},
            namespace=f"ns-{tenant}",
        )
        meta[name] = dict(duration_s=duration, slo=float(slo),
                          tenant=tenant, priority=priority)
        q.push(t, "arrival", pod=name)

    for t, kind, node in ev.failure_times(
        rng, [n["name"] for n in nodes], sc.node_mtbf_s, sc.node_mttr_s,
        sc.horizon_s,
    ):
        q.push(t, kind, node=node)

    return SimSetup(scenario=sc, seed=seed, nodes=nodes, specs=specs,
                    meta=meta, queue=q)


# ---------------------------------------------------------------------------
# Presets. Capacity intuition (node_class=1: 8000 cpu): each pod asks
# ~2000 cpu, so a node runs ~4 pods; slots = 4 * n_nodes. Service rate
# ~ slots / mean_duration; rates above it build the queues that make
# SLO attainment a real contest.
# ---------------------------------------------------------------------------


SCENARIOS: dict[str, Scenario] = {
    # Comfortable load, no failures: the sanity scenario where both
    # static and QoS-driven scheduling should attain nearly everything.
    "steady_state": Scenario(
        name="steady_state", n_nodes=6, horizon_s=120.0,
        arrival="poisson", rate=0.25,
        mix=(
            (0.5, 0.0, (20.0, 40.0), (0, 100), (1500.0, 2500.0)),
            (0.5, 0.8, (20.0, 40.0), (0, 100), (1500.0, 2500.0)),
        ),
    ),
    # Periodic submission spikes over a modest base: queues form during
    # bursts and drain between them.
    "burst": Scenario(
        name="burst", n_nodes=6, horizon_s=180.0,
        arrival="burst", rate=0.15, burst_every_s=45.0, burst_size=16,
        mix=(
            (0.5, 0.0, (25.0, 50.0), (20, 100), (1500.0, 2500.0)),
            (0.5, 0.85, (15.0, 30.0), (0, 20), (1500.0, 2500.0)),
        ),
    ),
    # The headline twin-run scenario: a warm, permanently-overloaded
    # cluster of SLO-less fillers with HIGH base priority, plus a
    # stream of SLO pods with LOW base priority whose demand alone
    # would fit comfortably. Static priority hands every released slot
    # to the standing filler backlog and starves the SLO class; QoS
    # pressure lifts waiting SLO pods over the fillers. Preemption is
    # deliberately OFF here: under permanent overload the preemption
    # path equalizes availability across pods (pending pressured pods
    # evict just-recovered runners whose slack crossed zero), which
    # SPREADS the misses instead of concentrating them — a real effect
    # worth measuring, but it muddies the single-number queue-ordering
    # claim this scenario exists to pin.
    "pressure_skew": Scenario(
        name="pressure_skew", n_nodes=6, horizon_s=150.0,
        arrival="poisson", rate=0.32, prefill=30,
        prefill_duration_s=(10.0, 90.0),
        mix=(
            # fillers: no SLO, high base priority, long-running
            (0.60, 0.0, (60.0, 90.0), (60, 100), (1800.0, 2400.0)),
            # SLO classes: tight availability targets, LOW base priority
            (0.20, 0.7, (25.0, 40.0), (0, 10), (1800.0, 2400.0)),
            (0.20, 0.9, (30.0, 45.0), (0, 10), (1800.0, 2400.0)),
        ),
        tenants=4, tenant_skew=1.0,
        preemption=False,
    ),
    # Node flaps mid-run: interrupted pods lose availability through no
    # queueing fault; measures how scheduling policy recovers them.
    "failure_storm": Scenario(
        name="failure_storm", n_nodes=8, horizon_s=180.0,
        arrival="poisson", rate=0.25,
        mix=(
            (0.4, 0.0, (30.0, 60.0), (20, 100), (1500.0, 2500.0)),
            (0.6, 0.8, (20.0, 40.0), (0, 40), (1500.0, 2500.0)),
        ),
        node_mtbf_s=60.0, node_mttr_s=15.0,
    ),
}
