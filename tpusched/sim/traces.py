"""On-disk workload traces (ISSUE 9 tentpole part 1).

A versioned, SEED-FREE trace format: everything a sim run consumes —
the initial cluster, every pod's spec + accounting meta (submit time,
duration, resource shape, SLO target, tenant, optional gang id), and
the full pre-drawn event timeline (arrivals, node fail/recover flaps,
autoscale node add/remove, each add carrying its node shape) — written
out as JSON lines. Replaying a trace needs NO generator and NO rng:
`load_trace` rebuilds the exact SimSetup `workloads.generate` produced,
and a SimDriver run over it yields a BYTE-IDENTICAL event-log hash to
the in-memory run that wrote it (the tier-1 round-trip lint pins this).
Python floats survive the trip exactly: json emits repr-quality
decimal strings and parses them back to the same IEEE-754 value.

File layout (one JSON object per line):

    {"schema": "tpusched-sim-trace", "version": 1, "scenario": {...},
     "seed": 0, "counts": {"nodes": N, "pods": P, "events": E}}
    {"kind": "node", "spec": {...}}            x N  (initial cluster)
    {"kind": "pod", "name": ..., "spec": {...}, "meta": {...}}   x P
    {"kind": "event", "t": ..., "etype": ..., "data": {...}}     x E

The header's `scenario` carries only what REPLAY reads (name,
horizon_s, preemption) plus free-form `generator` provenance — a trace
is self-contained, not a recipe: ingesting someone else's trace works
without their generator config. `validate()` runs on every load and
fails loudly on version or field mismatches (the CI lint surface).
"""

from __future__ import annotations

import json

from tpusched.sim import events as ev
from tpusched.sim.workloads import Scenario, SimSetup

SCHEMA = "tpusched-sim-trace"
VERSION = 1

# Event kinds the driver understands; anything else in a file is a
# version-skew error, not a silent skip.
EVENT_KINDS = ("arrival", "node_fail", "node_recover",
               "node_add", "node_remove")

_POD_SPEC_REQUIRED = ("requests", "priority", "slo_target")
_META_REQUIRED = ("duration_s", "slo", "tenant", "priority")


class TraceError(ValueError):
    """A malformed/incompatible trace file; the message says which
    line and what is wrong."""


def _err(lineno: "int | None", msg: str) -> TraceError:
    where = f"line {lineno}: " if lineno is not None else ""
    return TraceError(f"trace: {where}{msg}")


def _require(rec: dict, keys, lineno: int, what: str) -> None:
    missing = [k for k in keys if k not in rec]
    if missing:
        raise _err(lineno, f"{what} record missing fields {missing} "
                           f"(have {sorted(rec)})")


def validate(records: "list[tuple[int, dict]]") -> dict:
    """Validate a parsed trace ((lineno, record) pairs, header first).
    Returns the header. Raises TraceError with the offending line on
    any schema/version/field mismatch — wired into load_trace so a bad
    file cannot half-load into a run."""
    if not records:
        raise _err(None, "empty file (want a header line first)")
    ln0, header = records[0]
    if header.get("schema") != SCHEMA:
        raise _err(ln0, f"schema {header.get('schema')!r} is not "
                        f"{SCHEMA!r} (is this a trace file?)")
    version = header.get("version")
    if version != VERSION:
        raise _err(ln0, f"version {version!r} unsupported (this build "
                        f"reads version {VERSION})")
    _require(header, ("scenario", "counts"), ln0, "header")
    _require(header["scenario"], ("name", "horizon_s", "preemption"),
             ln0, "header scenario")
    counts = header["counts"]
    _require(counts, ("nodes", "pods", "events"), ln0, "header counts")

    n_nodes = n_pods = n_events = 0
    node_names: set = set()
    pod_names: set = set()
    for lineno, rec in records[1:]:
        kind = rec.get("kind")
        if kind == "node":
            _require(rec, ("spec",), lineno, "node")
            spec = rec["spec"]
            _require(spec, ("name", "allocatable"), lineno, "node spec")
            if spec["name"] in node_names:
                raise _err(lineno, f"duplicate node {spec['name']!r}")
            node_names.add(spec["name"])
            n_nodes += 1
        elif kind == "pod":
            _require(rec, ("name", "spec", "meta"), lineno, "pod")
            _require(rec["spec"], _POD_SPEC_REQUIRED, lineno, "pod spec")
            _require(rec["meta"], _META_REQUIRED, lineno, "pod meta")
            if rec["name"] in pod_names:
                raise _err(lineno, f"duplicate pod {rec['name']!r}")
            pod_names.add(rec["name"])
            n_pods += 1
        elif kind == "event":
            _require(rec, ("t", "etype", "data"), lineno, "event")
            etype = rec["etype"]
            if etype not in EVENT_KINDS:
                raise _err(lineno, f"unknown event kind {etype!r} "
                                   f"(this build knows {EVENT_KINDS})")
            data = rec["data"]
            if etype == "arrival":
                if data.get("pod") not in pod_names:
                    raise _err(lineno, "arrival references undefined "
                                       f"pod {data.get('pod')!r} (pods "
                                       "must precede events)")
            elif etype == "node_add":
                _require(data, ("node", "spec"), lineno, "node_add")
            elif "node" not in data:
                raise _err(lineno, f"{etype} record missing 'node'")
            n_events += 1
        else:
            raise _err(lineno, f"unknown record kind {kind!r}")
    got = dict(nodes=n_nodes, pods=n_pods, events=n_events)
    if {k: counts[k] for k in got} != got:
        raise _err(None, f"header counts {counts} != body {got} "
                         "(truncated or spliced file)")
    return header


def write_trace(setup: SimSetup, path: str) -> str:
    """Serialize a SimSetup (workloads.generate output) to `path`.
    Non-destructive: the setup's event queue is listed, not drained,
    so the same object can still be run. Returns `path`."""
    sc = setup.scenario
    events = setup.queue.events()
    header = dict(
        schema=SCHEMA, version=VERSION,
        scenario=dict(name=sc.name, horizon_s=sc.horizon_s,
                      preemption=sc.preemption),
        seed=setup.seed,
        generator=dict(description=sc.description,
                       arrival=sc.arrival,
                       duration_dist=sc.duration_dist),
        counts=dict(nodes=len(setup.nodes), pods=len(setup.specs),
                    events=len(events)),
    )
    with open(path, "w") as f:
        f.write(json.dumps(header) + "\n")
        for spec in setup.nodes:
            f.write(json.dumps(dict(kind="node", spec=spec)) + "\n")
        for name, spec in setup.specs.items():
            f.write(json.dumps(dict(kind="pod", name=name, spec=spec,
                                    meta=setup.meta[name])) + "\n")
        for e in events:
            f.write(json.dumps(dict(kind="event", t=e.time, etype=e.kind,
                                    data=e.data)) + "\n")
    return path


def _detuple_taints(spec: dict) -> dict:
    """JSON turned taint tuples into lists; restore tuples so loaded
    node specs compare equal to generated ones (and hash the same way
    through the snapshot builder)."""
    if spec.get("taints"):
        spec = dict(spec, taints=[tuple(t) for t in spec["taints"]])
    return spec


def load_trace(path: str) -> SimSetup:
    """Parse + validate a trace file into a runnable SimSetup.

    The reconstructed Scenario carries only the replay-relevant fields
    (name, horizon_s, preemption); the timeline and every spec come
    from the file, so SimDriver(setup=load_trace(p)) replays the
    recorded run — byte-identical event-log hash to the in-memory run
    that produced the file."""
    records: list[tuple[int, dict]] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append((lineno, json.loads(line)))
            except json.JSONDecodeError as e:
                raise _err(lineno, f"not JSON: {e}") from None
    header = validate(records)
    hs = header["scenario"]
    scenario = Scenario(
        name=str(hs["name"]),
        description="ingested trace",
        horizon_s=float(hs["horizon_s"]),
        preemption=bool(hs["preemption"]),
    )
    nodes: list = []
    specs: dict = {}
    meta: dict = {}
    q = ev.EventQueue()
    for _, rec in records[1:]:
        kind = rec["kind"]
        if kind == "node":
            nodes.append(_detuple_taints(rec["spec"]))
        elif kind == "pod":
            specs[rec["name"]] = rec["spec"]
            meta[rec["name"]] = rec["meta"]
        else:
            data = rec["data"]
            if rec["etype"] == "node_add":
                data = dict(data, spec=_detuple_taints(data["spec"]))
            q.push(rec["t"], rec["etype"], **data)
    return SimSetup(scenario=scenario, seed=int(header.get("seed", 0)),
                    nodes=nodes, specs=specs, meta=meta, queue=q)


def replay(path: str, **run_kwargs):
    """Load a trace and run it through the real stack: load_trace +
    driver.run_scenario(setup=...). run_kwargs pass through (config,
    sim, backend, engine, faults, explain, ...)."""
    from tpusched.sim.driver import run_scenario  # tpl: disable=TPL001(trace I/O stays importable without the driver's engine stack; replay reaches the driver only when called)

    return run_scenario(setup=load_trace(path), **run_kwargs)
