"""Per-pod availability accounting (ISSUE 5: close the QoS loop).

The reference system's defining feedback loop is

    observed availability -> pressure -> scheduling decision
                 ^                              |
                 +------- time running <--------+

and before this module `observed_avail` was a dead input (a kube
annotation default, or a uniform draw in the demo cluster). Lifecycle
accounting makes it real: availability is the fraction of a pod's life
it actually spent running,

    avail(t) = run_seconds(t) / (t - submitted)        clipped to [0, 1]

the same running-time-over-wall-time ratio the QoS paper scores SLOs
against (and Borg-style trace simulation measures). A pod that has
never been OBSERVED (age zero — just submitted this instant) falls back
to optimistic compliance (config.DEFAULT_OBSERVED_AVAIL = 1.0): with no
history there is no evidence of SLO violation, so a fresh pod carries
no pressure and cannot jump the queue the tick it arrives. From its
first tick of waiting, avail decays toward 0 and pressure climbs toward
`slo_target` — the dynamic-priority signal qos.py turns into queue
position and preemption appetite.

Two consumers:

  * host.FakeApiServer computes availability inline from the fields
    this module reads (`submitted`, `run_seconds`, `bound_at`) for any
    pod record that does not PIN an explicit `observed_avail` — so the
    whole closed loop works for plain host runs, not only under the
    simulator;
  * the sim driver keeps a LifecycleTracker as the cross-requeue
    authority: evictions and node failures DELETE the api record, so
    accumulated run credit must survive outside the api and ride back
    in on resubmission.
"""

from __future__ import annotations

import dataclasses

from tpusched.config import DEFAULT_OBSERVED_AVAIL

# The availability formula itself lives in tpusched.qos next to the
# pressure/slack math it feeds (host.py reads it from there too — sim
# must not be a dependency of core host); re-exported here because
# this module is the accounting authority that documents it.
from tpusched.qos import MIN_OBSERVED_AGE_S, observed_availability

__all__ = [
    "MIN_OBSERVED_AGE_S",
    "observed_availability",
    "PodLife",
    "LifecycleTracker",
]


@dataclasses.dataclass
class PodLife:
    """One pod's accounting state, from submission to completion."""

    name: str
    submitted: float
    slo_target: float = 0.0
    run_seconds: float = 0.0     # banked (completed) run intervals
    bound_at: "float | None" = None   # start of the current run, if any
    evictions: int = 0
    completed_at: "float | None" = None

    def availability(self, now: float) -> float:
        end = self.completed_at if self.completed_at is not None else now
        return observed_availability(
            self.submitted, self.run_seconds, self.bound_at, end
        )


class LifecycleTracker:
    """The sim driver's authority on pod history. api records are
    transient (evictions delete them); this book is not."""

    def __init__(self):
        self.pods: dict[str, PodLife] = {}

    def on_submit(self, name: str, now: float, slo_target: float = 0.0):
        if name not in self.pods:
            self.pods[name] = PodLife(
                name=name, submitted=now, slo_target=float(slo_target)
            )
        return self.pods[name]

    def on_bind(self, name: str, now: float) -> None:
        life = self.pods[name]
        life.bound_at = now

    def on_unbind(self, name: str, now: float, evicted: bool = True) -> float:
        """End the current run (eviction / node failure), banking its
        credit; returns the seconds this run lasted."""
        life = self.pods[name]
        ran = 0.0
        if life.bound_at is not None:
            ran = max(now - life.bound_at, 0.0)
            life.run_seconds += ran
            life.bound_at = None
        if evicted:
            life.evictions += 1
        return ran

    def on_complete(self, name: str, now: float) -> float:
        """Terminal: bank the final run and freeze availability at the
        completion instant. Returns final availability."""
        self.on_unbind(name, now, evicted=False)
        life = self.pods[name]
        life.completed_at = now
        return life.availability(now)

    def availability(self, name: str, now: float) -> float:
        life = self.pods.get(name)
        if life is None:
            return DEFAULT_OBSERVED_AVAIL
        return life.availability(now)
