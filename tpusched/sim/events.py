"""Seeded discrete-event queue + arrival/failure processes (ISSUE 5).

Everything stochastic in a sim run is drawn HERE, once, from one seeded
numpy Generator — the event timeline is fully determined before the
first tick executes, so two runs with the same (scenario, seed) apply
byte-identical event sequences and the log hash pins it. Scheduling
OUTCOMES (binds, evictions, completions) are appended to the same log
as they happen, so the hash covers the whole causal chain: a solver
nondeterminism would show up as a hash mismatch, not just a metric
wobble.

Processes offered (the trace-driven-simulation staples Borg/k8s
evaluations lean on):

  * Poisson arrivals — exponential inter-arrival gaps at a fixed rate;
  * bursty — a Poisson base load plus periodic arrival spikes (the
    batch-submission pattern that builds queues);
  * diurnal — a sinusoidally modulated rate via thinning (day/night
    load swing over the horizon);
  * node failure/flap — per-node exponential MTBF/MTTR fail->recover
    pairs (the availability threat SLOs exist to measure).
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
import json

import numpy as np

from tpusched.config import clamp01


@dataclasses.dataclass(frozen=True)
class Event:
    time: float
    seq: int          # tie-break: push order, so equal times stay stable
    kind: str
    data: dict


class EventQueue:
    """Min-heap of events plus the applied-event log the determinism
    hash is computed over. The driver pops due events each tick and
    `note()`s outcomes (binds/evictions/completions) into the log."""

    def __init__(self):
        self._heap: list = []
        self._seq = 0
        self.log: list[dict] = []

    def push(self, time: float, kind: str, **data) -> Event:
        ev = Event(float(time), self._seq, kind, data)
        heapq.heappush(self._heap, (ev.time, ev.seq, ev))
        self._seq += 1
        return ev

    def pop_until(self, t: float) -> list[Event]:
        """All events due at or before t, in (time, push-order)."""
        out = []
        while self._heap and self._heap[0][0] <= t + 1e-9:
            out.append(heapq.heappop(self._heap)[2])
        return out

    def next_time(self) -> "float | None":
        return self._heap[0][0] if self._heap else None

    def events(self) -> list[Event]:
        """Non-destructive (time, push-order) listing of every PENDING
        event — the trace writer's view of the full timeline. The heap
        is untouched, so a setup can be serialized and then run."""
        return [entry[2] for entry in
                sorted(self._heap, key=lambda entry: (entry[0], entry[1]))]

    def __len__(self) -> int:
        return len(self._heap)

    def note(self, time: float, kind: str, **data) -> None:
        """Append one applied-event/outcome record to the log."""
        self.log.append(dict(t=round(float(time), 9), kind=kind, **data))

    def log_hash(self) -> str:
        """Canonical digest of the applied log: sorted-key JSON lines.
        Floats go through repr via json — identical arithmetic yields
        identical text, which is exactly the determinism being pinned
        (virtual time makes the arithmetic reproducible)."""
        h = hashlib.sha256()
        for entry in self.log:
            h.update(json.dumps(entry, sort_keys=True).encode())
            h.update(b"\n")
        return h.hexdigest()


# ---------------------------------------------------------------------------
# Arrival processes: each returns a sorted list of arrival times in
# [t0, horizon). All randomness comes from the caller's Generator.
# ---------------------------------------------------------------------------


def poisson_times(rng: np.random.Generator, rate: float, horizon: float,
                  t0: float = 0.0) -> list[float]:
    if rate <= 0:
        return []
    out = []
    t = t0
    while True:
        t += float(rng.exponential(1.0 / rate))
        if t >= horizon:
            return out
        out.append(t)


def bursty_times(rng: np.random.Generator, base_rate: float, horizon: float,
                 burst_every_s: float, burst_size: int,
                 burst_span_s: float = 2.0, t0: float = 0.0) -> list[float]:
    """Poisson base load plus `burst_size` arrivals packed into a
    `burst_span_s` window every `burst_every_s` (first burst one full
    period in, so the queue starts from the base load)."""
    out = poisson_times(rng, base_rate, horizon, t0)
    t = t0 + burst_every_s
    while t < horizon:
        out.extend(
            float(t + x) for x in rng.uniform(0.0, burst_span_s, burst_size)
            if t + x < horizon
        )
        t += burst_every_s
    return sorted(out)


def diurnal_times(rng: np.random.Generator, base_rate: float, horizon: float,
                  period_s: float, amplitude: float = 0.8,
                  t0: float = 0.0) -> list[float]:
    """Thinning (Lewis-Shedler): candidates at the peak rate
    base*(1+amplitude), kept with probability lambda(t)/peak where
    lambda(t) = base * (1 + amplitude * sin(2 pi t / period))."""
    amplitude = clamp01(amplitude)
    peak = base_rate * (1.0 + amplitude)
    out = []
    for t in poisson_times(rng, peak, horizon, t0):
        lam = base_rate * (1.0 + amplitude * np.sin(2.0 * np.pi * t / period_s))
        if rng.uniform() * peak < lam:
            out.append(t)
    return out


def failure_times(rng: np.random.Generator, node_names: list[str],
                  mtbf_s: float, mttr_s: float,
                  horizon: float) -> list[tuple[float, str, str]]:
    """Per-node alternating fail/recover epochs: exponential up-time
    (mean mtbf_s) then exponential down-time (mean mttr_s), repeated to
    the horizon. Returns (time, "node_fail"|"node_recover", node)
    sorted by time. A recovery beyond the horizon is dropped — the node
    simply stays down for the rest of the run."""
    out: list[tuple[float, str, str]] = []
    if mtbf_s <= 0:
        return out
    for name in node_names:
        t = 0.0
        while True:
            t += float(rng.exponential(mtbf_s))
            if t >= horizon:
                break
            out.append((t, "node_fail", name))
            t += float(rng.exponential(max(mttr_s, 1e-6)))
            if t >= horizon:
                break
            out.append((t, "node_recover", name))
    return sorted(out)
