"""Engine configuration: resource axes, static shape buckets, plugin weights.

Mirrors the role of KubeSchedulerConfiguration in the reference ecosystem
(SURVEY.md §5 "Config / flag system"): which plugins are enabled, their
weights, QoS parameters, plus the TPU-specific knobs (bucket sizes, mesh
shape) that have no upstream equivalent.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Mapping

# ---------------------------------------------------------------------------
# Resource axes.
#
# The device-side resource dimension R is a fixed, configured list of
# resource names. The first three are always present and always in this
# order; extended resources (gpus, custom devices) append after.
# "pods" is modelled as an ordinary resource with request == 1 for every
# pod, which turns the node pod-count cap into the same <= comparison as
# cpu/memory (upstream NodeResourcesFit semantics, SURVEY.md C2).
# ---------------------------------------------------------------------------

RESOURCE_CPU = "cpu"          # millicores
RESOURCE_MEMORY = "memory"    # bytes
RESOURCE_PODS = "pods"        # count; every pod requests exactly 1

DEFAULT_RESOURCES: tuple[str, ...] = (RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_PODS)

# Default per-resource weights for the LeastRequested score, matching the
# upstream NodeResourcesFit default of cpu:1 memory:1 (the "pods" axis does
# not participate in scoring upstream, weight 0).
DEFAULT_SCORE_RESOURCE_WEIGHTS: Mapping[str, float] = {
    RESOURCE_CPU: 1.0,
    RESOURCE_MEMORY: 1.0,
    RESOURCE_PODS: 0.0,
}

MAX_NODE_SCORE = 100.0  # upstream framework.MaxNodeScore

# Taint effects (int8 codes on device).
EFFECT_NO_SCHEDULE = 0
EFFECT_PREFER_NO_SCHEDULE = 1
EFFECT_NO_EXECUTE = 2
TAINT_EFFECTS = ("NoSchedule", "PreferNoSchedule", "NoExecute")

# Match-expression operators (int8 codes on device).
OP_IN = 0
OP_NOT_IN = 1
OP_EXISTS = 2
OP_DOES_NOT_EXIST = 3
OP_GT = 4
OP_LT = 5
OPERATORS = ("In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt")

# whenUnsatisfiable codes for topology spread.
DO_NOT_SCHEDULE = 0
SCHEDULE_ANYWAY = 1

# QoS defaults, threaded through every layer that parses pod records
# (kube annotations, host records, the wire codec): slo_target 0 means
# "no availability SLO" (pressure is always 0), and a pod with no
# observed-availability history is OPTIMISTICALLY compliant (1.0) until
# lifecycle accounting produces a real number — the never-scheduled
# fallback the sim's closed loop and the kube annotation default share.
DEFAULT_SLO_TARGET = 0.0
DEFAULT_OBSERVED_AVAIL = 1.0


def clamp01(v: float, default: float = 0.0) -> float:
    """Clamp to the unit interval. The ONE clamp both ends of the QoS
    availability path share (annotation parse, write-back, lifecycle
    accounting, FakeApiServer pinning) so the domain contract cannot
    drift between them. Non-finite input (NaN/inf from a hostile or
    garbage annotation) collapses to `default` — Python's min/max would
    propagate NaN straight through a naive clamp and poison the
    pressure math downstream."""
    v = float(v)
    if not math.isfinite(v):
        return float(default)
    return min(max(v, 0.0), 1.0)  # tpl: disable=TPL004(this IS clamp01 — the non-finite guard above makes the naive clamp safe here)


def _next_pow2(x: int) -> int:
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


def _next_bucket(x: int) -> int:
    """Bucket size policy: powers of two up to 2048, then multiples of
    1024. Pure pow2 pads a 10k x 5k problem to 16384 x 8192 — 2.7x the
    arithmetic and HBM traffic for nothing. Multiples of 1024 keep the
    distinct-shape count (recompiles) bounded while capping padding
    overhead at ~10% for large axes; 1024-alignment also keeps the lane
    dimension a multiple of the TPU tile (8x128)."""
    if x <= 2048:
        return _next_pow2(x)
    return ((x + 1023) // 1024) * 1024


@dataclasses.dataclass(frozen=True)
class Buckets:
    """Static device-side array sizes.

    XLA compiles one program per distinct shape tuple, so all host-side
    builders pad every axis up to these bucket sizes (SURVEY.md §7 hard
    part 5: "bucket to powers of two and mask padding everywhere").
    Padding rows/cols are masked so they can never win an argmax.
    """

    pods: int = 128            # P: pending pods
    nodes: int = 128           # N: candidate nodes
    running_pods: int = 256    # M: bound pods (preemption victims, affinity)
    node_labels: int = 16      # LN: label (key,value) pairs per node
    pod_labels: int = 8        # LP: label pairs per pod
    node_taints: int = 4       # TN: taints per node
    atoms: int = 64            # A: distinct match-expression atoms
    atom_values: int = 8       # VA: values per In/NotIn atom
    terms: int = 4             # T: nodeSelectorTerms per pod (OR)
    term_atoms: int = 4        # AT: expressions per term (AND)
    pref_terms: int = 4        # PT: preferred affinity terms per pod
    topo_keys: int = 4         # TK: distinct topology keys in play
    spread_constraints: int = 2  # C: topology-spread constraints per pod
    affinity_terms: int = 2    # IT: inter-pod (anti)affinity terms per pod
    pod_groups: int = 64       # G: distinct gangs (pod groups)
    taint_vocab: int = 16      # VT: distinct taints across the cluster
    signatures: int = 8        # S: distinct (topo key, ns, selector) signatures
    sig_namespaces: int = 2    # NSV: explicit namespace ids per signature
    pdb_groups: int = 8        # GP: distinct PodDisruptionBudgets

    @staticmethod
    def fit(
        n_pods: int,
        n_nodes: int,
        n_running: int = 0,
        min_pods: int = 8,
        min_nodes: int = 8,
        **overrides: int,
    ) -> "Buckets":
        """Smallest bucket set covering the given counts (pow2 up to
        2048, multiples of 1024 above — see _next_bucket)."""
        base = Buckets(
            pods=max(min_pods, _next_bucket(n_pods)),
            nodes=max(min_nodes, _next_bucket(n_nodes)),
            running_pods=max(8, _next_bucket(max(1, n_running))),
        )
        return dataclasses.replace(base, **overrides) if overrides else base

    @staticmethod
    def minimal(n_pods: int, n_nodes: int, n_running: int = 0) -> "Buckets":
        """Like fit(), but every feature dimension starts at ZERO and only
        grows to what the snapshot actually uses (SnapshotBuilder grows
        them from observed need). Unused features then have 0-sized axes,
        and the traced program drops their kernels entirely (loops over
        `range(0)` vanish, empty gathers fold away) — at 10k x 5k the
        difference between milliseconds and tens of seconds."""
        return dataclasses.replace(
            Buckets.fit(n_pods, n_nodes, n_running),
            node_labels=0, pod_labels=0, node_taints=0, atoms=0,
            atom_values=0, terms=0, term_atoms=0, pref_terms=0,
            topo_keys=0, spread_constraints=0, affinity_terms=0,
            pod_groups=0, taint_vocab=0, signatures=0, sig_namespaces=0,
            pdb_groups=0,
        )

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "Buckets":
        """Inverse of dataclasses.asdict for serialized bucket sets (the
        shape-class registry round-trips buckets through JSON). Unknown
        keys are rejected loudly: a registry written by a build with more
        axes must not silently deserialize into smaller shapes."""
        fields = {f.name for f in dataclasses.fields(Buckets)}
        extra = set(d) - fields
        if extra:
            raise ValueError(
                f"Buckets.from_dict: unknown bucket axes {sorted(extra)}"
            )
        return Buckets(**{k: int(v) for k, v in d.items()})


@dataclasses.dataclass(frozen=True)
class PluginWeights:
    """Score-plugin weights, the analogue of the `weight` field on each
    entry of a scheduler-framework plugin profile (SURVEY.md C5).

    A weight of 0 disables the plugin's score contribution; filter
    plugins are structural and always on (as upstream defaults them).
    """

    least_requested: float = 1.0        # NodeResourcesFit/LeastAllocated (C3)
    balanced_allocation: float = 1.0    # NodeResourcesBalancedAllocation (C4)
    node_affinity: float = 1.0          # preferred node affinity terms
    taint_toleration: float = 1.0       # PreferNoSchedule taint counting
    topology_spread: float = 2.0        # upstream default weight is 2
    interpod_affinity: float = 1.0      # preferred pod (anti)affinity


@dataclasses.dataclass(frozen=True)
class QoSConfig:
    """Parameters of the QoS-driven dynamic priority (SURVEY.md C10).

    priority(pod, t) = base_priority + qos_gain * pressure where
    pressure = clip(slo_target - observed_availability, 0, 1): how far the
    pod is *below* its availability SLO. Pods further below their SLO pop
    first and may preempt pods with positive slack (above their SLO).
    """

    qos_gain: float = 1000.0
    # Pressure also interpolates per-pod plugin weights between the
    # configured ("balanced") profile and a pure least-requested
    # ("place me fast") profile: effective_w = (1-p)*w + p*w_urgent.
    urgency_reweight: bool = True
    # A preemptor's effective priority must exceed a victim's effective
    # priority (victim: priority + qos_gain * clip(-slack, 0, 1), i.e. a
    # victim below its SLO is boosted) by this margin to evict it.
    preemption_margin: float = 0.0
    # Eviction cost (SURVEY.md C9: "eviction cost = victim's QoS slack"):
    #   cost(victim) = eff_priority(victim) - evict_slack_weight
    #                  * clip(slack, 0, 1)
    # so among equal-priority victims, the one furthest ABOVE its SLO is
    # cheapest. Costs are shifted positive per snapshot (+1 per victim),
    # which also encodes the upstream "fewer victims" preference.
    evict_slack_weight: float = 100.0


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Virtual-time cluster simulator knobs (tpusched/sim).

    The simulator advances a virtual clock in fixed ticks; events
    (arrivals, completions, node failures) apply at tick boundaries and
    the scheduler re-solves on a tick-driven cadence — `resolve_every`
    ticks between cycles models a batching scheduler that lets pressure
    accumulate, the analogue of kube-scheduler's percentage-based
    batching intervals. All durations are VIRTUAL seconds: a run's wall
    time is dominated by solve latency, not the simulated horizon.
    """

    tick_s: float = 1.0        # virtual seconds per tick
    resolve_every: int = 1     # scheduling cycles every N ticks
    batch_size: int = 256      # host batch cap per cycle
    # Host backoff under simulation. The reference's QoS queue re-sorts
    # EVERY cycle (priority is dynamic, so yesterday's unschedulable
    # pod may be today's most-pressured) — kube-style exponential
    # backoff would exclude exactly the pods whose pressure just rose
    # from the batch, hiding the priority signal the sim exists to
    # measure. Default 0: the full pending queue is reconsidered every
    # cycle. Set >0 to model backoff-queue semantics instead.
    backoff_initial_s: float = 0.0
    backoff_max_s: float = 0.0
    # gRPC-mode transport: AssignPipeline's pin-refresh threshold
    # (fraction of records whose cumulative churn triggers a full-send
    # pin refresh). None = the client default (0.25). Raising it >= 1
    # keeps a drifting sim workload on the DELTA path — the knob the
    # autoscale rebuild tests use to pin the device-resident
    # bucket-growth path instead of a churn-triggered reseed.
    pipeline_refresh_frac: "float | None" = None

    def __post_init__(self) -> None:
        if self.tick_s <= 0:
            raise ValueError(f"tick_s={self.tick_s}: must be > 0")
        if self.resolve_every < 1:
            raise ValueError(
                f"resolve_every={self.resolve_every}: must be >= 1"
            )


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    resources: tuple[str, ...] = DEFAULT_RESOURCES
    score_resource_weights: Mapping[str, float] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_SCORE_RESOURCE_WEIGHTS)
    )
    weights: PluginWeights = dataclasses.field(default_factory=PluginWeights)
    qos: QoSConfig = dataclasses.field(default_factory=QoSConfig)
    # "parity" = exactly-sequential lax.scan commit (stock semantics);
    # "fast" = round-based batched commit (same placements for
    # non-contended snapshots, bounded rounds otherwise). SURVEY.md C11.
    mode: str = "parity"
    # Cap on fast-mode commit rounds; 0 = auto (2*P+8, enough for the
    # worst case of one conservative commit per round). A positive cap
    # trades completeness for bounded latency: pods still pending at the
    # cap stay unassigned for the batch. In the no-signature tranche
    # path (large P) a positive value caps each tranche's INNER rounds
    # (every selected pod's view gets up to that many rounds) rather
    # than the cumulative total, which would starve later-ranked
    # tranches of any examination at all.
    max_rounds: int = 0
    # PostFilter preemption (SURVEY.md C9): pods with no feasible node
    # evict the cheapest eligible victim set (QoS-slack cost) on the
    # best node. Off by default: enabling it changes SolveResult
    # semantics (evicted victims) and the host must issue deletes.
    preemption: bool = False
    # Tie-break among equal-score maxima (SURVEY.md §7 hard part 2):
    #   "first"  — lowest node index (deterministic default);
    #   "seeded" — uniform pick via a per-pod hash of tie_seed, the
    #              deterministic analogue of upstream's rand-among-max
    #              (identical in oracle and device, so parity holds for
    #              any seed). Parity mode + oracle only; fast mode's
    #              dealing commit always uses "first".
    tie_break: str = "first"
    tie_seed: int = 0
    # Mesh shape for multi-device runs: (pods-axis, nodes-axis). (1,1)
    # means single device. Consumed by the gRPC sidecar
    # (rpc.server.SchedulerService): a non-(1,1) shape — or
    # ring_counts=True — makes the server build a jax Mesh of this
    # shape (mesh.make_mesh) and run its Engine on it, so a deployed
    # sidecar reaches the sharded/ring paths from YAML alone.
    # Library users pass Engine(mesh=...) directly.
    mesh_shape: tuple[int, int] = (1, 1)
    # Route the initial pairwise domain counts through the blockwise
    # ring kernel (tpusched.ring): signature blocks rotate around the
    # 'p' mesh axis via lax.ppermute, so the [S, members] match matrix
    # never materializes on one device (SURVEY.md §2.3 SP/CP row, §5
    # long-context analogue). Requires Engine(mesh=...) with a
    # multi-device mesh; counts are bit-identical to the dense path.
    ring_counts: bool = False
    # Frontier compaction of the fast-mode SIGNATURE-path commit rounds
    # (ISSUE 12): once the pending frontier fits this many pods, rounds
    # run on a gathered [cap, N] view instead of full-width [P, N] —
    # bitwise-identical placements (kernels.assign._solve_rounds_sig
    # documents the width-invariance construction; pinned by
    # tests/test_frontier.py). -1 = auto (the residual-compaction cap,
    # kernels.assign._RESIDUAL_CAP, skipped when P is not meaningfully
    # larger); 0 = off, every round full-width (the twin-test reference
    # and a conservative escape hatch); > 0 = explicit cap (tests use a
    # tiny cap to exercise the compacted program on small clusters).
    compact_cap: int = -1

    def resource_index(self, name: str) -> int:
        return self.resources.index(name)

    def score_weights_vector(self) -> list[float]:
        return [float(self.score_resource_weights.get(r, 0.0)) for r in self.resources]

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "EngineConfig":
        """Build from a YAML/JSON-decoded mapping (KubeSchedulerConfiguration
        profile analogue); unknown keys rejected to catch typos."""
        kw: dict[str, Any] = {}
        if "resources" in d:
            kw["resources"] = tuple(d["resources"])
        if "score_resource_weights" in d:
            kw["score_resource_weights"] = dict(d["score_resource_weights"])
        if "weights" in d:
            kw["weights"] = PluginWeights(**d["weights"])
        if "qos" in d:
            kw["qos"] = QoSConfig(**d["qos"])
        for k in ("mode", "max_rounds", "tie_break", "tie_seed",
                  "preemption", "ring_counts", "compact_cap"):
            if k in d:
                kw[k] = d[k]
        if "mesh_shape" in d:
            kw["mesh_shape"] = tuple(d["mesh_shape"])
        extra = set(d) - {
            "resources", "score_resource_weights", "weights", "qos",
            "mode", "max_rounds", "tie_break", "tie_seed", "mesh_shape",
            "preemption", "ring_counts", "compact_cap",
        }
        if extra:
            raise ValueError(f"unknown EngineConfig keys: {sorted(extra)}")
        return EngineConfig(**kw)


def load_config(path: str) -> EngineConfig:
    import yaml  # noqa: allowlisted optional dep (TPL001)

    with open(path) as f:
        return EngineConfig.from_dict(yaml.safe_load(f) or {})
