"""Multi-tenant batched solving (SURVEY.md §2.3 "EP" row).

The expert-parallel analogue in this domain is routing INDEPENDENT
scheduling problems to solver shards. A sidecar serving many clusters
(or many isolated tenants of one control plane) holds B snapshots with
no cross-tenant interaction — exactly a batch dimension:

  * stack_snapshots: B bucket-aligned ClusterSnapshots -> one pytree
    with a leading tenant axis;
  * solve_many: jax.vmap of the SAME solve kernels over that axis —
    one compiled program schedules every tenant simultaneously,
    saturating a chip that a single small cluster would leave idle;
  * the tenant axis shards over the mesh's 'p' axis (tenant_sharding),
    routing whole tenants to devices — no cross-device communication at
    all, the cheapest collective there is.

Alignment requirement: all tenants must share identical bucket shapes —
build them with one explicit `Buckets` floor (the same discipline the
serving sidecar already uses to pin compile shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tpusched.config import EngineConfig
from tpusched.engine import _sat_tables
from tpusched.kernels.assign import solve_rounds, solve_sequential
from tpusched.snapshot import ClusterSnapshot


def stack_snapshots(snaps: list[ClusterSnapshot]) -> ClusterSnapshot:
    """Stack bucket-aligned snapshots along a new leading tenant axis.
    Raises if any leaf shapes disagree (different buckets)."""
    if not snaps:
        raise ValueError("no snapshots to stack")
    first = jax.tree.leaves(snaps[0])
    for i, s in enumerate(snaps[1:], 1):
        for a, b in zip(first, jax.tree.leaves(s)):
            if np.shape(a) != np.shape(b):
                raise ValueError(
                    f"tenant {i} bucket shapes differ: {np.shape(b)} vs "
                    f"{np.shape(a)} — build all tenants with one explicit "
                    "Buckets floor"
                )
    return jax.tree.map(lambda *xs: jnp.stack(xs), *snaps)


def _solve_one(cfg: EngineConfig, snap: ClusterSnapshot):
    node_sat_t, member_sat_t = _sat_tables(snap)
    if cfg.mode == "fast":
        a, c, u, o, _, rounds, ev = solve_rounds(
            cfg, snap, node_sat_t, member_sat_t
        )
        return a, c, u, o, rounds, ev
    a, c, u, o, ev = solve_sequential(cfg, snap, node_sat_t, member_sat_t)
    P = a.shape[0]
    return a, c, u, o, jnp.int32(P), ev


def solve_many(cfg: EngineConfig, stacked: ClusterSnapshot):
    """Solve B independent tenants at once: returns per-tenant
    (assignment [B, P], chosen [B, P], used [B, N, R], order [B, P],
    rounds [B], evicted [B, M]). jit/vmap-compiled; call through
    jax.jit for caching (solve_many_jit does)."""
    return jax.vmap(lambda s: _solve_one(cfg, s))(stacked)


def solve_many_jit(cfg: EngineConfig):
    """Jitted entry closed over the config (compile-time constants)."""
    return jax.jit(lambda stacked: solve_many(cfg, stacked))


def tenant_sharding(mesh, stacked: ClusterSnapshot):
    """NamedShardings putting the TENANT axis on the mesh's 'p' axis:
    whole problems route to devices, zero cross-device collectives."""
    from jax.sharding import NamedSharding, PartitionSpec as PS

    from tpusched.mesh import POD_AXIS

    return jax.tree.map(
        lambda _: NamedSharding(mesh, PS(POD_AXIS)), stacked
    )
