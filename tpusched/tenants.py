"""Multi-tenant batched solving (SURVEY.md §2.3 "EP" row).

The expert-parallel analogue in this domain is routing INDEPENDENT
scheduling problems to solver shards. A sidecar serving many clusters
(or many isolated tenants of one control plane) holds B snapshots with
no cross-tenant interaction — exactly a batch dimension:

  * stack_snapshots: B bucket-aligned ClusterSnapshots -> one pytree
    with a leading tenant axis;
  * solve_many: jax.vmap of the SAME solve kernels over that axis —
    one compiled program schedules every tenant simultaneously,
    saturating a chip that a single small cluster would leave idle;
  * the tenant axis shards over the mesh's 'p' axis (tenant_sharding),
    routing whole tenants to devices — no cross-device communication at
    all, the cheapest collective there is.

Alignment requirement: all tenants must share identical bucket shapes —
build them with one explicit `Buckets` floor (the same discipline the
serving sidecar already uses to pin compile shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as PS

from tpusched.config import EngineConfig
from tpusched.mesh import POD_AXIS
from tpusched.engine import solve_core
from tpusched.snapshot import ClusterSnapshot


def zipf_weights(n: int, skew: float) -> np.ndarray:
    """Normalized Zipf weights over n tenants: w_r ∝ 1 / rank^skew.

    THE tenant-skew definition shared across the codebase — the sim's
    workload generators (tpusched/sim/workloads.py draws each pod's
    tenant from these weights) and any serving-path tenant-fairness
    weighting must read it from here, so "tenant 0 gets X% of traffic"
    means the same thing in a trace-driven sim run and on the serving
    path. skew=0 is uniform; the Borg/Azure trace analyses this
    reproduces (Resource Central, SOSP'17) put subscription skew around
    1.0-1.4."""
    if n < 1:
        raise ValueError(f"zipf_weights: n={n} must be >= 1")
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64),
                       max(float(skew), 0.0))
    return w / w.sum()


def stack_snapshots(snaps: list[ClusterSnapshot]) -> ClusterSnapshot:
    """Stack bucket-aligned snapshots along a new leading tenant axis.
    Raises if any leaf shapes disagree (different buckets)."""
    if not snaps:
        raise ValueError("no snapshots to stack")
    first = jax.tree.leaves(snaps[0])
    for i, s in enumerate(snaps[1:], 1):
        for a, b in zip(first, jax.tree.leaves(s)):
            if np.shape(a) != np.shape(b):
                raise ValueError(
                    f"tenant {i} bucket shapes differ: {np.shape(b)} vs "
                    f"{np.shape(a)} — build all tenants with one explicit "
                    "Buckets floor"
                )
    return jax.tree.map(lambda *xs: jnp.stack(xs), *snaps)


def _solve_one(cfg: EngineConfig, snap: ClusterSnapshot):
    a, c, u, o, _, rounds, ev = solve_core(cfg, snap)
    return a, c, u, o, rounds, ev


def solve_many(cfg: EngineConfig, stacked: ClusterSnapshot):
    """Solve B independent tenants at once: returns per-tenant
    (assignment [B, P], chosen [B, P], used [B, N, R], order [B, P],
    rounds [B], evicted [B, M]). jit/vmap-compiled; call through
    solve_many_jit for compile caching."""
    return jax.vmap(lambda s: _solve_one(cfg, s))(stacked)


_JIT_CACHE: dict[str, object] = {}
#: Distinct configs the memo holds before OLDEST-FIRST eviction kicks
#: in (TPL104, ISSUE 14): repr-keyed means config churn would
#: otherwise grow one compiled program per variant forever.
_JIT_CACHE_CAP = 8


def solve_many_jit(cfg: EngineConfig):
    """Jitted entry closed over the config (compile-time constants);
    memoized so repeated calls share one jit/compile cache. Keyed by
    repr (EngineConfig is frozen but holds a dict field, so it is not
    hashable; its repr is deterministic and value-complete)."""
    key = repr(cfg)
    fn = _JIT_CACHE.get(key)
    if fn is None:
        while len(_JIT_CACHE) >= _JIT_CACHE_CAP:
            # Evict oldest-first: a wholesale clear() would turn
            # steady-state config diversity just past the cap into a
            # periodic full-recompile storm. Race-tolerant: a
            # concurrent miss may drain the dict between the len
            # check and the pop (default-pop swallows the lost key;
            # StopIteration/RuntimeError mean someone else evicted).
            try:
                _JIT_CACHE.pop(next(iter(_JIT_CACHE)), None)
            except (StopIteration, RuntimeError):
                break
        fn = jax.jit(lambda stacked: solve_many(cfg, stacked))
        _JIT_CACHE[key] = fn
    return fn


def tenant_sharding(mesh, stacked: ClusterSnapshot):
    """NamedShardings putting the TENANT axis on the mesh's 'p' axis:
    whole problems route to devices, zero cross-device collectives."""
    return jax.tree.map(
        lambda _: NamedSharding(mesh, PS(POD_AXIS)), stacked
    )
