"""Snapshot dump/replay (SURVEY.md §5 "Checkpoint / resume").

Scheduler state is soft — the cluster is the source of truth — so the
engine checkpoints nothing. What IS worth persisting: the exact padded
ClusterSnapshot of a batch, for bench reproducibility and offline
debugging of a production decision ("replay the batch that made this
placement"). One .npz per snapshot: leaves in deterministic pytree
order + a JSON meta record.
"""

from __future__ import annotations

import dataclasses
import json

import jax
import numpy as np

from tpusched.config import Buckets
from tpusched.snapshot import (AtomTable, ClusterSnapshot, NodeArrays,
                               PodArrays, RunningPodArrays, SigTable,
                               SnapshotMeta)


def _norm(path: str) -> str:
    # np.savez appends .npz to bare paths but np.load does not; keep the
    # two symmetric so dump/replay accept the same string.
    return path if path.endswith(".npz") else path + ".npz"


def save_snapshot(path: str, snap: ClusterSnapshot,
                  meta: SnapshotMeta | None = None) -> None:
    path = _norm(path)
    leaves = jax.tree.leaves(snap)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    if meta is not None:
        md = dataclasses.asdict(meta)
        md["buckets"] = dataclasses.asdict(meta.buckets)
        arrays["meta_json"] = np.frombuffer(
            json.dumps(md).encode(), dtype=np.uint8
        )
    np.savez_compressed(path, **arrays)


def load_snapshot(path: str) -> tuple[ClusterSnapshot, SnapshotMeta | None]:
    data = np.load(_norm(path))
    treedef = jax.tree.structure(snap_skeleton())
    n = treedef.num_leaves
    snap = jax.tree.unflatten(
        treedef, [data[f"leaf_{i}"] for i in range(n)]
    )
    meta = None
    if "meta_json" in data:
        md = json.loads(bytes(data["meta_json"]).decode())
        md["buckets"] = Buckets(**md["buckets"])
        meta = SnapshotMeta(**md)
    return snap, meta


def snap_skeleton() -> ClusterSnapshot:
    """A ClusterSnapshot whose every field is a (distinct) scalar leaf:
    defines the canonical leaf order for save/load. Structure is fixed
    by the dataclass definitions, so any snapshot flattens to the same
    treedef."""

    def fill(cls):
        return cls(**{f.name: 0 for f in dataclasses.fields(cls)})

    return ClusterSnapshot(
        nodes=fill(NodeArrays),
        pods=fill(PodArrays),
        running=fill(RunningPodArrays),
        atoms=fill(AtomTable),
        sigs=fill(SigTable),
        taint_effect=0,
        group_min_member=0,
        pdb_allowed=0,
    )
