"""Feasibility predicates as boolean masks (SURVEY.md C2).

The reference's Filter extension point runs per (pod, node) in Go
(SURVEY.md §3.1); here each predicate is one broadcasted array op over
the full [P, N] matrix. All functions take pre-broadcast snapshot arrays
and return [P, N] bool (or [N] bool for the single-pod variants used by
the sequential parity scan).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

# jax ships no stubs on this image (mypy.ini: ignore_missing_imports),
# so traced arrays type as Any; the alias keeps signatures legible and
# becomes jax.Array the day stubs exist.
Array = Any

from tpusched.config import (
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
)
from tpusched.kernels.atoms import gather_term_sat
from tpusched.snapshot import ClusterSnapshot


def resource_fit(alloc: Array, used: Array, requests: Array) -> Array:
    """NodeResourcesFit: forall r: used + req <= alloc.
    alloc/used: [N, R]; requests: [P, R] -> [P, N] (or [R] -> [N])."""
    if requests.ndim == 1:
        return jnp.all(used + requests[None, :] <= alloc, axis=-1)
    return jnp.all(
        used[None, :, :] + requests[:, None, :] <= alloc[None, :, :], axis=-1
    )


def taint_mask(node_taint_ids: Array, taint_effect: Array,
               tolerated: Array) -> Array:
    """TaintToleration filter: every NoSchedule/NoExecute taint tolerated.
    node_taint_ids: [N, TN] (-1 pad); taint_effect: [VT];
    tolerated: [P, VT] -> [P, N]  (or [VT] -> [N])."""
    tid = jnp.clip(node_taint_ids, 0, None)
    eff = taint_effect[tid]                              # [N, TN]
    hard = (node_taint_ids >= 0) & (
        (eff == EFFECT_NO_SCHEDULE) | (eff == EFFECT_NO_EXECUTE)
    )
    if tolerated.ndim == 1:
        tol = tolerated[tid]                             # [N, TN]
        return jnp.all(~hard | tol, axis=-1)
    tol = tolerated[:, tid]                              # [P, N, TN]
    return jnp.all(~hard[None] | tol, axis=-1)


def node_affinity_mask(node_sat_t: Array, req_term_atoms: Array,
                       req_term_valid: Array) -> Array:
    """Required node affinity + nodeSelector: OR over terms, AND within.
    node_sat_t: [A, N]; req_term_atoms: [P, T, AT] or [T, AT];
    returns [P, N] or [N]. A pod with zero valid terms matches all."""
    term_ok = gather_term_sat(node_sat_t, req_term_atoms)     # [..., T, N]
    term_ok &= req_term_valid[..., None]
    has_req = jnp.any(req_term_valid, axis=-1)                # [...]
    any_term = jnp.any(term_ok, axis=-2)                      # [..., N]
    return jnp.where(has_req[..., None], any_term, True)


def full_static_mask(snap: ClusterSnapshot, node_sat_t: Array) -> Array:
    """All non-pairwise, state-independent predicates for all pods:
    taints & node affinity & node validity -> [P, N]. Resource fit is
    state-dependent (used changes as pods commit) and pairwise terms are
    handled in kernels/pairwise.py."""
    m = taint_mask(snap.nodes.taint_ids, snap.taint_effect, snap.pods.tolerated)
    m &= node_affinity_mask(
        node_sat_t, snap.pods.req_term_atoms, snap.pods.req_term_valid
    )
    m &= snap.nodes.valid[None, :]
    m &= snap.pods.valid[:, None]
    return m
