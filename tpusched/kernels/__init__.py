"""Device-side kernels: the batched Filter/Score/Commit compute path.

Each module batches one group of scheduler-framework plugins
(SURVEY.md §1.3 "Kernels" layer):
  atoms    — match-expression satisfaction tables (shared by everything)
  filter   — feasibility predicates -> boolean masks (C2)
  score    — scoring plugins -> [P, N] float matrices (C3-C5)
  pairwise — topology spread + inter-pod affinity (C6, C7)
  assign   — commit loops: sequential parity scan + batched rounds (C11)
"""
