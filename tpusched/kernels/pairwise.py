"""PodTopologySpread + InterPodAffinity kernels (SURVEY.md C6, C7).

Pairwise constraints: where pod p may land depends on where *other* pods
(running + already-committed pending) sit. The scalable formulation works
per SIGNATURE, not per pod: SnapshotBuilder interns every distinct
(topology key, pod-label selector) pair into a SigTable entry, and the
kernels maintain

    counts[s, d] = number of matching member pods in domain d of
                   signature s's topology key

as an [S, N] matrix (domain ids are < number of nodes by construction).
Counting is ONE scatter over members per evaluation — independent of P —
and per-pod constraint checks are gathers from counts. Commit loops
update counts incrementally as pods place (counts_commit_pods /
counts_add_pod) instead of recounting members.

Members are the concatenation [running | pending]; a pending pod's
member column activates when it commits. Self-exclusion: a pod's own
contribution must not count toward its own constraint check (upstream
checks before adding the pod) — `exclude_self_node` handles that for
post-commit validation.
"""

from __future__ import annotations

import jax.numpy as jnp

from tpusched.config import DO_NOT_SCHEDULE
from tpusched.kernels.atoms import gather_term_sat
from tpusched.snapshot import ClusterSnapshot


def member_label_sat_t(snap: ClusterSnapshot, sat_fn):
    """[A, M+P] atom satisfaction over member pod labels; static across a
    solve (labels never change), so computed once and closed over."""
    lp = jnp.concatenate([snap.running.label_pairs, snap.pods.label_pairs])
    lk = jnp.concatenate([snap.running.label_keys, snap.pods.label_keys])
    return sat_fn(lp, lk).T


def sig_member_match(snap: ClusterSnapshot, member_sat_t):
    """[S, M+P] bool: does member x's label set match signature s's
    selector. Label-only (validity applied at count time). A signature
    with zero atoms matches everything (upstream empty label selector)."""
    match = gather_term_sat(member_sat_t, snap.sigs.atoms)   # [S, M+P]
    return match & snap.sigs.valid[:, None]


def sig_domains(snap: ClusterSnapshot):
    """[S, N] int32: domain id of node n under signature s's topology
    key; -1 where the node lacks the key (or the sig slot is padding)."""
    dom = snap.nodes.domain                                  # [N, TK]
    key = jnp.clip(snap.sigs.key, 0, None)
    dom_s = dom[:, key].T if dom.shape[1] else jnp.full(
        (snap.sigs.key.shape[0], dom.shape[0]), -1, jnp.int32
    )
    return jnp.where(snap.sigs.valid[:, None], dom_s, -1)


def sig_counts(snap: ClusterSnapshot, sig_match, assigned):
    """[S, N] f32 domain counts from scratch for the given assignment
    state (used at loop init and in tests; loops update incrementally)."""
    node = jnp.concatenate([snap.running.node_idx, assigned])
    valid = jnp.concatenate([snap.running.valid, assigned >= 0])
    dom_s = sig_domains(snap)                                # [S, N]
    S, N = dom_s.shape
    mdom = jnp.where(
        valid[None, :], dom_s[:, jnp.clip(node, 0, None)], -1
    )                                                        # [S, M+P]
    contrib = (sig_match & valid[None, :] & (mdom >= 0)).astype(jnp.float32)
    rows = jnp.broadcast_to(jnp.arange(S)[:, None], mdom.shape)
    return jnp.zeros((S, N), jnp.float32).at[
        rows, jnp.clip(mdom, 0, None)
    ].add(contrib)


def counts_commit_pods(snap: ClusterSnapshot, counts, sig_match, choice,
                       commit_mask, sign=1.0):
    """Add (sign=+1) or roll back (sign=-1) the contribution of pending
    pods committed to choice[p] where commit_mask[p]."""
    M = snap.running.valid.shape[0]
    dom_s = sig_domains(snap)                                # [S, N]
    pod_dom = dom_s[:, jnp.clip(choice, 0, None)]            # [S, P]
    contrib = (
        sig_match[:, M:] & commit_mask[None, :] & (pod_dom >= 0)
    ).astype(jnp.float32) * sign
    S = dom_s.shape[0]
    rows = jnp.broadcast_to(jnp.arange(S)[:, None], pod_dom.shape)
    return counts.at[rows, jnp.clip(pod_dom, 0, None)].add(contrib)


def counts_add_pod(snap: ClusterSnapshot, counts, sig_match, p, n, on):
    """Incremental update for one pod p committing to node n (traced
    scalars); `on` gates the add (False -> no-op). Used by the
    sequential scan."""
    M = snap.running.valid.shape[0]
    dom_s = sig_domains(snap)                                # [S, N]
    S = dom_s.shape[0]
    dom_n = dom_s[:, n]                                      # [S]
    col = sig_match[:, M + p]                                # [S]
    contrib = (col & (dom_n >= 0) & on).astype(jnp.float32)
    return counts.at[jnp.arange(S), jnp.clip(dom_n, 0, None)].add(contrib)


# ---------------------------------------------------------------------------
# Constraint evaluation from counts.
# ---------------------------------------------------------------------------


def _self_adj(snap, sig_match, dom_s, s, exclude_self_node, pod_idx):
    """Count adjustments removing each pod's own contribution when it is
    assumed placed on exclude_self_node[p] (post-commit validation:
    upstream checks a pod's constraints BEFORE adding the pod itself).
    Returns (adj [P, N] f32, active [P] f32) — per-node and total."""
    if exclude_self_node is None:
        return 0.0, 0.0
    M = snap.running.valid.shape[0]
    esn = exclude_self_node                                   # [P]
    own_dom = dom_s[s, jnp.clip(esn, 0, None)]                # [P]
    self_match = sig_match[s, M + pod_idx]                    # [P]
    active = (self_match & (esn >= 0) & (own_dom >= 0))       # [P]
    adj = (
        active[:, None] & (dom_s[s] == own_dom[:, None])
    ).astype(jnp.float32)
    return adj, active.astype(jnp.float32)


def pairwise_from_counts(snap: ClusterSnapshot, counts, aff_ok,
                         sig_match=None, exclude_self_node=None):
    """Batched [P, N] evaluation of all spread/inter-pod constraints from
    the current domain counts.

    aff_ok: [P, N] required-node-affinity mask (spread domain-discovery
    honors it: upstream NodeAffinityPolicy Honor).
    exclude_self_node: optional [P] int32 — for post-commit validation,
    remove pod p's own contribution assuming it sits on that node
    (requires sig_match).

    Returns (spread_ok, spread_penalty, ia_ok, ia_raw), each [P, N].
    """
    if exclude_self_node is not None and sig_match is None:
        raise ValueError("exclude_self_node requires sig_match")
    nodes, pods = snap.nodes, snap.pods
    dom_s = sig_domains(snap)                                # [S, N]
    node_count_sig = jnp.take_along_axis(
        counts, jnp.clip(dom_s, 0, None), axis=1
    )                                                        # [S, N]
    has_key_sig = dom_s >= 0
    max_count_sig = jnp.max(
        jnp.where(has_key_sig, node_count_sig, 0.0), axis=1
    )                                                        # [S]
    P = pods.valid.shape[0]
    N = nodes.valid.shape[0]
    pod_idx = jnp.arange(P)

    spread_ok = jnp.ones((P, N), bool)
    spread_pen = jnp.zeros((P, N), jnp.float32)
    C = pods.ts_key.shape[1]
    for c in range(C):  # static unroll; C is a small bucket
        s = jnp.clip(pods.ts_sig[:, c], 0, None)             # [P]
        valid_c = pods.ts_valid[:, c]
        adj, _ = _self_adj(snap, sig_match, dom_s, s, exclude_self_node, pod_idx)
        nc = node_count_sig[s] - adj                         # [P, N]
        hk = has_key_sig[s]
        eligible = nodes.valid[None, :] & aff_ok & hk
        min_c = jnp.min(jnp.where(eligible, nc, jnp.inf), axis=1)
        min_c = jnp.where(jnp.any(eligible, axis=1), min_c, 0.0)
        dns = pods.ts_when[:, c] == DO_NOT_SCHEDULE
        ok_c = hk & (
            nc + 1.0 - min_c[:, None] <= pods.ts_max_skew[:, c][:, None]
        )
        spread_ok &= jnp.where((valid_c & dns)[:, None], ok_c, True)
        mx = jnp.where(
            hk, nc, max_count_sig[s][:, None]
        )
        spread_pen += jnp.where((valid_c & ~dns)[:, None], mx, 0.0)

    ia_ok = jnp.ones((P, N), bool)
    ia_raw = jnp.zeros((P, N), jnp.float32)
    IT = pods.ia_key.shape[1]
    M = snap.running.valid.shape[0]
    total_sig = counts.sum(axis=1)                           # [S]
    for t in range(IT):
        s = jnp.clip(pods.ia_sig[:, t], 0, None)
        valid_t = pods.ia_valid[:, t]
        adj, active = _self_adj(snap, sig_match, dom_s, s,
                                exclude_self_node, pod_idx)
        nc = node_count_sig[s] - adj
        hk = has_key_sig[s]
        node_has = hk & (nc > 0)
        anti = pods.ia_anti[:, t]
        req = pods.ia_required[:, t]
        # Upstream special case for required positive affinity: if no
        # pod anywhere matches the selector but the incoming pod matches
        # its own selector, any node with the topology key satisfies.
        if sig_match is not None:
            self_match = sig_match[s, M + pod_idx]           # [P]
            all_zero = (total_sig[s] - active) <= 0          # [P]
            pos_ok = node_has | ((all_zero & self_match)[:, None] & hk)
        else:
            pos_ok = node_has
        ok_t = jnp.where(anti[:, None], ~node_has, pos_ok)
        ia_ok &= jnp.where((valid_t & req)[:, None], ok_t, True)
        w = jnp.where(anti, -pods.ia_weight[:, t], pods.ia_weight[:, t])
        ia_raw += jnp.where(
            (valid_t & ~req)[:, None] & node_has, w[:, None], 0.0
        )
    return spread_ok, spread_pen, ia_ok, ia_raw


def pairwise_row(snap: ClusterSnapshot, counts, sig_match, p, aff_ok_p):
    """Single-pod [N] variant for the sequential scan: same math as
    pairwise_from_counts restricted to traced pod index p (no
    self-exclusion needed: the scan checks before committing)."""
    nodes, pods = snap.nodes, snap.pods
    dom_s = sig_domains(snap)                                # [S, N]
    node_count_sig = jnp.take_along_axis(
        counts, jnp.clip(dom_s, 0, None), axis=1
    )
    has_key_sig = dom_s >= 0
    max_count_sig = jnp.max(
        jnp.where(has_key_sig, node_count_sig, 0.0), axis=1
    )
    N = nodes.valid.shape[0]

    spread_ok = jnp.ones(N, bool)
    spread_pen = jnp.zeros(N, jnp.float32)
    C = pods.ts_key.shape[1]
    for c in range(C):
        s = jnp.clip(pods.ts_sig[p, c], 0, None)
        valid_c = pods.ts_valid[p, c]
        nc = node_count_sig[s]                               # [N]
        hk = has_key_sig[s]
        eligible = nodes.valid & aff_ok_p & hk
        min_c = jnp.min(jnp.where(eligible, nc, jnp.inf))
        min_c = jnp.where(jnp.any(eligible), min_c, 0.0)
        dns = pods.ts_when[p, c] == DO_NOT_SCHEDULE
        ok_c = hk & (nc + 1.0 - min_c <= pods.ts_max_skew[p, c])
        spread_ok &= jnp.where(valid_c & dns, ok_c, True)
        pen_c = jnp.where(hk, nc, max_count_sig[s])
        spread_pen += jnp.where(valid_c & ~dns, pen_c, 0.0)

    ia_ok = jnp.ones(N, bool)
    ia_raw = jnp.zeros(N, jnp.float32)
    IT = pods.ia_key.shape[1]
    M = snap.running.valid.shape[0]
    for t in range(IT):
        s = jnp.clip(pods.ia_sig[p, t], 0, None)
        valid_t = pods.ia_valid[p, t]
        nc = node_count_sig[s]
        hk = has_key_sig[s]
        node_has = hk & (nc > 0)
        anti = pods.ia_anti[p, t]
        req = pods.ia_required[p, t]
        # Same required-positive-affinity self-match special case as
        # pairwise_from_counts.
        all_zero = counts[s].sum() <= 0
        self_match = sig_match[s, M + p]
        pos_ok = node_has | (all_zero & self_match & hk)
        ok_t = jnp.where(anti, ~node_has, pos_ok)
        ia_ok &= jnp.where(valid_t & req, ok_t, True)
        w = jnp.where(anti, -pods.ia_weight[p, t], pods.ia_weight[p, t])
        ia_raw += jnp.where(valid_t & ~req & node_has, w, 0.0)
    return spread_ok, spread_pen, ia_ok, ia_raw
