"""PodTopologySpread + InterPodAffinity kernels (SURVEY.md C6, C7).

Pairwise constraints: where pod p may land depends on where *other* pods
(running + already-committed pending) sit. The scalable formulation works
per SIGNATURE, not per pod: SnapshotBuilder interns every distinct
(topology key, pod-label selector) pair into a SigTable entry, and the
kernels maintain a PairState of three arrays:

    counts[s, d]    = matching member pods in domain d of signature s's
                      topology key (spread counts / affinity presence)
    anti[s, d]      = members HOLDING a required anti-affinity term with
                      signature s in domain d (symmetric anti-affinity:
                      an existing pod's required anti term repels
                      incoming pods matching its selector)
    match_tot[s]    = members matching s's selector ANYWHERE, including
                      nodes that lack the topology key (drives the
                      upstream "no pod matches the selector" special
                      case for required positive affinity)

Counting is a handful of scatters over members per evaluation —
independent of P — and per-pod constraint checks are gathers from the
state. The symmetric-anti check for all pods at once is a single
[P, S] x [S, N] matmul (MXU-friendly). Commit loops update the state
incrementally as pods place (pair_state_commit / pair_state_add_pod)
instead of recounting members.

Members are the concatenation [running | pending]; a pending pod's
member column activates when it commits. Self-exclusion: a pod's own
contribution must not count toward its own constraint check (upstream
checks before adding the pod) — `exclude_self_node` handles that for
post-commit validation.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import struct

from tpusched.config import DO_NOT_SCHEDULE
from tpusched.kernels.atoms import gather_term_sat
from tpusched.shardctx import constrain_replicated
from tpusched.snapshot import ClusterSnapshot


def merge_members(run_arr, pod_arr, mesh=None):
    """[M+P] (or [M+P, ...]) member-axis merge of a replicated running
    array with a 'p'-sharded pending array, pinned REPLICATED under
    `mesh` (shardctx module docstring: the 2D-mesh partitioner
    mis-routes mixed-sharding concatenates; every device needs every
    member column for the [S, M+P] signature contraction anyway)."""
    return constrain_replicated(jnp.concatenate([run_arr, pod_arr]), mesh)


@struct.dataclass
class PairState:
    counts: Any     # [S, N] f32 selector-match counts per domain
    anti: Any       # [S, N] f32 required-anti-term HOLDER counts per domain
    match_tot: Any  # [S] f32 selector-match counts over all members


def member_label_sat_t(snap: ClusterSnapshot, sat_fn, mesh=None):
    """[A, M+P] atom satisfaction over member pod labels; static across a
    solve (labels never change), so computed once and closed over."""
    lp = merge_members(snap.running.label_pairs, snap.pods.label_pairs, mesh)
    lk = merge_members(snap.running.label_keys, snap.pods.label_keys, mesh)
    return constrain_replicated(sat_fn(lp, lk).T, mesh)


def ns_scope_ok(sigs_ns, sigs_ns_all, member_ns):
    """[S, X] bool: member namespace within each signature's scope
    (explicit ns-id list, or ns_all). Shared by sig_member_match and the
    ring/blockwise path (tpusched.ring) so scope semantics live once."""
    if sigs_ns.shape[1]:
        ok = jnp.any(
            sigs_ns[:, :, None] == member_ns[None, None, :], axis=1
        )
        return ok | sigs_ns_all[:, None]
    return jnp.broadcast_to(
        sigs_ns_all[:, None], (sigs_ns.shape[0], member_ns.shape[0])
    )


def sig_member_match(snap: ClusterSnapshot, member_sat_t, mesh=None):
    """[S, M+P] bool: does member x match signature s — label selector
    satisfied AND member namespace in the sig's scope (upstream
    podAffinityTerm.namespaces / same-namespace spread counting). A
    signature with zero atoms matches every namespace-eligible member
    (upstream empty label selector)."""
    match = gather_term_sat(member_sat_t, snap.sigs.atoms)   # [S, M+P]
    member_ns = merge_members(
        snap.running.namespace, snap.pods.namespace, mesh
    )                                                        # [M+P]
    ns_ok = ns_scope_ok(snap.sigs.ns, snap.sigs.ns_all, member_ns)
    return match & ns_ok & snap.sigs.valid[:, None]


def sig_domains(snap: ClusterSnapshot):
    """[S, N] int32: domain id of node n under signature s's topology
    key; -1 where the node lacks the key (or the sig slot is padding)."""
    dom = snap.nodes.domain                                  # [N, TK]
    key = jnp.clip(snap.sigs.key, 0, None)
    dom_s = dom[:, key].T if dom.shape[1] else jnp.full(
        (snap.sigs.key.shape[0], dom.shape[0]), -1, jnp.int32
    )
    return jnp.where(snap.sigs.valid[:, None], dom_s, -1)


def sig_counts(snap: ClusterSnapshot, sig_match, assigned, mesh=None):
    """[S, N] f32 domain counts from scratch for the given assignment
    state (used at loop init and in tests; loops update incrementally)."""
    node = merge_members(snap.running.node_idx, assigned, mesh)
    valid = merge_members(snap.running.valid, assigned >= 0, mesh)
    dom_s = sig_domains(snap)                                # [S, N]
    S, N = dom_s.shape
    mdom = jnp.where(
        valid[None, :], dom_s[:, jnp.clip(node, 0, None)], -1
    )                                                        # [S, M+P]
    contrib = (sig_match & valid[None, :] & (mdom >= 0)).astype(jnp.float32)
    rows = jnp.broadcast_to(jnp.arange(S)[:, None], mdom.shape)
    return jnp.zeros((S, N), jnp.float32).at[
        rows, jnp.clip(mdom, 0, None)
    ].add(contrib)


def _anti_counts_running(snap: ClusterSnapshot, dom_s):
    """[S, N] f32: required-anti-term holders among RUNNING pods per
    domain of each term's signature."""
    S, N = dom_s.shape
    asig = snap.running.anti_sig                             # [M, J]
    out = jnp.zeros((S, N), jnp.float32)
    if asig.shape[1] == 0 or S == 0:
        return out
    node = snap.running.node_idx                             # [M]
    sclip = jnp.clip(asig, 0, None)
    dom_m = dom_s[sclip, jnp.clip(node, 0, None)[:, None]]   # [M, J]
    ok = (
        (asig >= 0) & (node >= 0)[:, None]
        & snap.running.valid[:, None] & (dom_m >= 0)
    )
    return out.at[sclip, jnp.clip(dom_m, 0, None)].add(ok.astype(jnp.float32))


def pair_state_init(snap: ClusterSnapshot, sig_match,
                    counts=None, mesh=None) -> PairState:
    """State with no pending pods committed: counts from running pods.
    `counts`: optional precomputed [S, N] initial domain counts (the
    ring path, tpusched.ring.ring_sig_counts, is bit-identical to the
    dense sig_counts and is routed here via EngineConfig.ring_counts)."""
    P = snap.pods.valid.shape[0]
    dom_s = sig_domains(snap)
    M = snap.running.valid.shape[0]
    match_tot = jnp.sum(
        (sig_match[:, :M] & snap.running.valid[None, :]).astype(jnp.float32),
        axis=1,
    )
    if counts is None:
        counts = sig_counts(snap, sig_match, jnp.full(P, -1, jnp.int32),
                            mesh)
    return PairState(
        counts=counts,
        anti=_anti_counts_running(snap, dom_s),
        match_tot=match_tot,
    )


def _pod_anti_holds(snap: ClusterSnapshot, t: int):
    """[P] bool: pod holds a live required anti term in ia slot t."""
    pods = snap.pods
    return pods.ia_valid[:, t] & pods.ia_anti[:, t] & pods.ia_required[:, t]


def pair_state_commit(snap: ClusterSnapshot, st: PairState, sig_match,
                      choice, commit_mask, sign=1.0) -> PairState:
    """Add (sign=+1) or roll back (sign=-1) the contribution of pending
    pods committed to choice[p] where commit_mask[p]."""
    M = snap.running.valid.shape[0]
    dom_s = sig_domains(snap)                                # [S, N]
    pod_dom = dom_s[:, jnp.clip(choice, 0, None)]            # [S, P]
    contrib = (
        sig_match[:, M:] & commit_mask[None, :] & (pod_dom >= 0)
    ).astype(jnp.float32) * sign
    S = dom_s.shape[0]
    rows = jnp.broadcast_to(jnp.arange(S)[:, None], pod_dom.shape)
    counts = st.counts.at[rows, jnp.clip(pod_dom, 0, None)].add(contrib)
    match_tot = st.match_tot + sign * jnp.sum(
        (sig_match[:, M:] & commit_mask[None, :]).astype(jnp.float32), axis=1
    )
    anti = st.anti
    for t in range(snap.pods.ia_key.shape[1]):
        s = jnp.clip(snap.pods.ia_sig[:, t], 0, None)        # [P]
        dom_p = dom_s[s, jnp.clip(choice, 0, None)]          # [P]
        on = _pod_anti_holds(snap, t) & commit_mask & (dom_p >= 0)
        anti = anti.at[s, jnp.clip(dom_p, 0, None)].add(
            on.astype(jnp.float32) * sign
        )
    return PairState(counts=counts, anti=anti, match_tot=match_tot)


def pair_state_add_pod(snap: ClusterSnapshot, st: PairState, sig_match,
                       p, n, on) -> PairState:
    """Incremental update for one pod p committing to node n (traced
    scalars); `on` gates the add (False -> no-op). Used by the
    sequential scan."""
    M = snap.running.valid.shape[0]
    dom_s = sig_domains(snap)                                # [S, N]
    S = dom_s.shape[0]
    dom_n = dom_s[:, n]                                      # [S]
    col = sig_match[:, M + p]                                # [S]
    contrib = (col & (dom_n >= 0) & on).astype(jnp.float32)
    counts = st.counts.at[jnp.arange(S), jnp.clip(dom_n, 0, None)].add(contrib)
    match_tot = st.match_tot + (col & on).astype(jnp.float32)
    anti = st.anti
    for t in range(snap.pods.ia_key.shape[1]):
        s = jnp.clip(snap.pods.ia_sig[p, t], 0, None)        # scalar
        dom_pn = dom_s[s, n]
        hold = _pod_anti_holds(snap, t)[p] & on & (dom_pn >= 0)
        anti = anti.at[s, jnp.clip(dom_pn, 0, None)].add(
            hold.astype(jnp.float32)
        )
    return PairState(counts=counts, anti=anti, match_tot=match_tot)


def pair_state_seed(snap: ClusterSnapshot, sig_match, choice, mask,
                    counts=None, mesh=None) -> PairState:
    """State with a PRE-COMMITTED pending assignment: running members
    plus every pending pod p with mask[p] counted at choice[p]. The
    incremental warm path (ISSUE 12) seeds its round loop with this —
    carried placements enter the counts exactly as if the rounds had
    just committed them, so frontier commits validate against the same
    state a cold solve would have reached — and its in-kernel audit
    recounts the final carried set through the same helper."""
    st = pair_state_init(snap, sig_match, counts=counts, mesh=mesh)
    if snap.sigs.key.shape[0] == 0:
        return st
    return pair_state_commit(snap, st, sig_match, choice, mask)


def pair_state_evict(snap: ClusterSnapshot, st: PairState, sig_match,
                     evict_m) -> PairState:
    """Remove evicted RUNNING members' contributions (preemption,
    SURVEY.md C9): their selector matches leave counts/match_tot and
    their required anti terms stop poisoning domains."""
    dom_s = sig_domains(snap)                                # [S, N]
    S = dom_s.shape[0]
    node = snap.running.node_idx                             # [M]
    M = node.shape[0]
    mdom = dom_s[:, jnp.clip(node, 0, None)]                 # [S, M]
    ok = (
        sig_match[:, :M] & evict_m[None, :]
        & (mdom >= 0) & (node >= 0)[None, :]
    )
    rows = jnp.broadcast_to(jnp.arange(S)[:, None], mdom.shape)
    counts = st.counts.at[rows, jnp.clip(mdom, 0, None)].add(
        -ok.astype(jnp.float32)
    )
    match_tot = st.match_tot - jnp.sum(
        (sig_match[:, :M] & evict_m[None, :]).astype(jnp.float32), axis=1
    )
    anti = st.anti
    asig = snap.running.anti_sig                             # [M, J]
    if asig.shape[1]:
        sclip = jnp.clip(asig, 0, None)
        dom_mj = dom_s[sclip, jnp.clip(node, 0, None)[:, None]]  # [M, J]
        okj = (
            (asig >= 0) & evict_m[:, None]
            & (node >= 0)[:, None] & (dom_mj >= 0)
        )
        anti = anti.at[sclip, jnp.clip(dom_mj, 0, None)].add(
            -okj.astype(jnp.float32)
        )
    return PairState(counts=counts, anti=anti, match_tot=match_tot)


# ---------------------------------------------------------------------------
# Constraint evaluation from the state.
# ---------------------------------------------------------------------------


def _self_adj(snap, sig_match, dom_s, s, exclude_self_node, pod_idx):
    """Count adjustments removing each pod's own contribution when it is
    assumed placed on exclude_self_node[p] (post-commit validation:
    upstream checks a pod's constraints BEFORE adding the pod itself).
    Returns (adj [P, N] f32, active [P] f32, active_tot [P] f32) — the
    per-node domain-count adjustment, its row-mask, and the match_tot
    adjustment (which ignores domains: match_tot counts key-less members
    too)."""
    if exclude_self_node is None:
        return 0.0, 0.0, 0.0
    M = snap.running.valid.shape[0]
    esn = exclude_self_node                                   # [P]
    own_dom = dom_s[s, jnp.clip(esn, 0, None)]                # [P]
    self_match = sig_match[s, M + pod_idx]                    # [P]
    committed = self_match & (esn >= 0)
    active = committed & (own_dom >= 0)                       # [P]
    adj = (
        active[:, None] & (dom_s[s] == own_dom[:, None])
    ).astype(jnp.float32)
    return adj, active.astype(jnp.float32), committed.astype(jnp.float32)


def symmetric_anti_block(snap: ClusterSnapshot, st: PairState, sig_match,
                         exclude_self_node=None):
    """[P, N] bool: node n is in a domain containing a holder of a
    required anti-affinity term whose selector matches pod p (upstream
    symmetric anti-affinity). One [P, S] x [S, N] matmul.

    The contraction runs in int32 (round 20, ISSUE 15 / TPL201): the
    holder counts are integers, and an f32 matmul over the S axis is
    tree-order-sensitive once partial sums leave the exact range —
    integer adds are associativity-exact in any tree, which is what
    sharding this contraction over the mesh requires. Bitwise-identical
    verdicts to the f32 form on every existing suite (counts are far
    below 2**24 there); pinned by
    tests/test_kernelflow.py::test_symmetric_anti_int32_matches_f32."""
    dom_s = sig_domains(snap)                                # [S, N]
    M = snap.running.valid.shape[0]
    anti_at = jnp.take_along_axis(
        st.anti, jnp.clip(dom_s, 0, None), axis=1
    )                                                        # [S, N]
    anti_i = jnp.where(dom_s >= 0, anti_at, 0.0).astype(jnp.int32)
    matchers = sig_match[:, M:].astype(jnp.int32)            # [S, P]
    blocked_cnt = matchers.T @ anti_i                        # [P, N] int32
    if exclude_self_node is not None:
        pods = snap.pods
        esn = exclude_self_node
        pod_idx = jnp.arange(pods.valid.shape[0])
        for t in range(pods.ia_key.shape[1]):
            s = jnp.clip(pods.ia_sig[:, t], 0, None)         # [P]
            own_dom = dom_s[s, jnp.clip(esn, 0, None)]       # [P]
            self_match = sig_match[s, M + pod_idx]           # [P]
            active = (
                _pod_anti_holds(snap, t) & self_match
                & (esn >= 0) & (own_dom >= 0)
            )
            sub = active[:, None] & (dom_s[s] == own_dom[:, None])
            blocked_cnt = blocked_cnt - sub.astype(jnp.int32)
    return blocked_cnt > 0


def pairwise_from_counts(snap: ClusterSnapshot, st: PairState, aff_ok,
                         sig_match=None, exclude_self_node=None):
    """Batched [P, N] evaluation of all spread/inter-pod constraints from
    the current pair state.

    aff_ok: [P, N] required-node-affinity mask (spread domain-discovery
    honors it: upstream NodeAffinityPolicy Honor).
    exclude_self_node: optional [P] int32 — for post-commit validation,
    remove pod p's own contribution assuming it sits on that node
    (requires sig_match).

    Returns (spread_ok, spread_penalty, ia_ok, ia_raw), each [P, N].
    """
    if exclude_self_node is not None and sig_match is None:
        raise ValueError("exclude_self_node requires sig_match")
    nodes, pods = snap.nodes, snap.pods
    counts = st.counts
    dom_s = sig_domains(snap)                                # [S, N]
    node_count_sig = jnp.take_along_axis(
        counts, jnp.clip(dom_s, 0, None), axis=1
    )                                                        # [S, N]
    has_key_sig = dom_s >= 0
    max_count_sig = jnp.max(
        jnp.where(has_key_sig, node_count_sig, 0.0), axis=1
    )                                                        # [S]
    P = pods.valid.shape[0]
    N = nodes.valid.shape[0]
    pod_idx = jnp.arange(P)

    spread_ok = jnp.ones((P, N), bool)
    spread_pen = jnp.zeros((P, N), jnp.float32)
    C = pods.ts_key.shape[1]
    for c in range(C):  # static unroll; C is a small bucket
        s = jnp.clip(pods.ts_sig[:, c], 0, None)             # [P]
        valid_c = pods.ts_valid[:, c]
        adj, _, _ = _self_adj(snap, sig_match, dom_s, s, exclude_self_node,
                              pod_idx)
        nc = node_count_sig[s] - adj                         # [P, N]
        hk = has_key_sig[s]
        eligible = nodes.valid[None, :] & aff_ok & hk
        min_c = jnp.min(jnp.where(eligible, nc, jnp.inf), axis=1)
        min_c = jnp.where(jnp.any(eligible, axis=1), min_c, 0.0)
        dns = pods.ts_when[:, c] == DO_NOT_SCHEDULE
        ok_c = hk & (
            nc + 1.0 - min_c[:, None] <= pods.ts_max_skew[:, c][:, None]
        )
        spread_ok &= jnp.where((valid_c & dns)[:, None], ok_c, True)
        mx = jnp.where(
            hk, nc, max_count_sig[s][:, None]
        )
        spread_pen += jnp.where((valid_c & ~dns)[:, None], mx, 0.0)

    ia_ok = jnp.ones((P, N), bool)
    ia_raw = jnp.zeros((P, N), jnp.float32)
    IT = pods.ia_key.shape[1]
    M = snap.running.valid.shape[0]
    for t in range(IT):
        s = jnp.clip(pods.ia_sig[:, t], 0, None)
        valid_t = pods.ia_valid[:, t]
        adj, _, active_tot = _self_adj(snap, sig_match, dom_s, s,
                                       exclude_self_node, pod_idx)
        nc = node_count_sig[s] - adj
        hk = has_key_sig[s]
        node_has = hk & (nc > 0)
        anti = pods.ia_anti[:, t]
        req = pods.ia_required[:, t]
        # Upstream special case for required positive affinity: if no
        # pod anywhere matches the selector (including on nodes lacking
        # the topology key — hence match_tot, not domain counts) but the
        # incoming pod matches its own selector, any node with the
        # topology key satisfies.
        if sig_match is not None:
            self_match = sig_match[s, M + pod_idx]           # [P]
            all_zero = (st.match_tot[s] - active_tot) <= 0   # [P]
            pos_ok = node_has | ((all_zero & self_match)[:, None] & hk)
        else:
            pos_ok = node_has
        ok_t = jnp.where(anti[:, None], ~node_has, pos_ok)
        ia_ok &= jnp.where((valid_t & req)[:, None], ok_t, True)
        w = jnp.where(anti, -pods.ia_weight[:, t], pods.ia_weight[:, t])
        ia_raw += jnp.where(
            (valid_t & ~req)[:, None] & node_has, w[:, None], 0.0
        )

    # Symmetric required anti-affinity: other members' anti terms repel
    # matching incoming pods — applies to every pod, even ones with no
    # constraints of their own.
    if sig_match is not None:
        ia_ok &= ~symmetric_anti_block(snap, st, sig_match, exclude_self_node)
    return spread_ok, spread_pen, ia_ok, ia_raw


def ia_ok_at_choice(snap: ClusterSnapshot, st: PairState, sig_match,
                    choice, esn):
    """[P] bool: the required inter-pod-affinity + symmetric-anti
    verdict of `pairwise_from_counts(..., exclude_self_node=esn)`
    gathered at each pod's chosen node — O(S*P) gathers instead of the
    full [P, N] matrices (the commit-validation fixpoint only ever
    reads the chosen-node column, which at 10k x 5k made each
    validation pass as expensive as a whole scoring round).

    choice: [P] node of each committed pod (rows with choice < 0 are
    evaluated at node 0 and must be masked by the caller).
    esn: [P] exclude-self-node (-1 = no exclusion), exactly the
    exclude_self_node contract. Kept bit-equivalent to the full path;
    tests/test_fast.py pins the equality on fuzz snapshots."""
    pods = snap.pods
    dom_s = sig_domains(snap)                                # [S, N]
    S = dom_s.shape[0]
    M = snap.running.valid.shape[0]
    P = pods.valid.shape[0]
    pod_idx = jnp.arange(P)
    ch = jnp.clip(choice, 0, None)
    ok = jnp.ones(P, bool)
    for t in range(pods.ia_key.shape[1]):
        s = jnp.clip(pods.ia_sig[:, t], 0, None)             # [P]
        valid_t = pods.ia_valid[:, t]
        d = dom_s[s, ch]                                     # [P]
        self_match = sig_match[s, M + pod_idx]
        committed = self_match & (esn >= 0)
        own_dom = dom_s[s, jnp.clip(esn, 0, None)]
        # _self_adj at n = choice: the pod's own contribution counts
        # only where the evaluated node's domain equals its own-node
        # domain.
        active = committed & (own_dom >= 0) & (d == own_dom)
        nc = st.counts[s, jnp.clip(d, 0, None)] - active.astype(
            jnp.float32
        )
        hk = d >= 0
        node_has = hk & (nc > 0)
        anti = pods.ia_anti[:, t]
        req = pods.ia_required[:, t]
        all_zero = (
            st.match_tot[s] - committed.astype(jnp.float32)
        ) <= 0
        pos_ok = node_has | (all_zero & self_match & hk)
        ok_t = jnp.where(anti, ~node_has, pos_ok)
        ok &= jnp.where(valid_t & req, ok_t, True)
    # Symmetric anti at the chosen node (symmetric_anti_block column),
    # contracted in int32 like symmetric_anti_block itself (TPL201:
    # integer adds are tree-order-exact; the f32 sum was not once
    # counts leave the exact range).
    d_all = dom_s[:, ch]                                     # [S, P]
    anti_at = st.anti[
        jnp.arange(S)[:, None], jnp.clip(d_all, 0, None)
    ]
    anti_i = jnp.where(d_all >= 0, anti_at, 0.0).astype(jnp.int32)
    match = sig_match[:, M:].astype(jnp.int32)               # [S, P]
    blocked = jnp.sum(match * anti_i, axis=0)                # [P] int32
    for t in range(pods.ia_key.shape[1]):
        s = jnp.clip(pods.ia_sig[:, t], 0, None)
        d = dom_s[s, ch]
        own_dom = dom_s[s, jnp.clip(esn, 0, None)]
        self_match = sig_match[s, M + pod_idx]
        active = (
            _pod_anti_holds(snap, t) & self_match
            & (esn >= 0) & (own_dom >= 0) & (d == own_dom)
        )
        blocked = blocked - active.astype(jnp.int32)
    return ok & ~(blocked > 0)


def pairwise_row(snap: ClusterSnapshot, st: PairState, sig_match, p, aff_ok_p):
    """Single-pod [N] variant for the sequential scan: same math as
    pairwise_from_counts restricted to traced pod index p (no
    self-exclusion needed: the scan checks before committing)."""
    nodes, pods = snap.nodes, snap.pods
    counts = st.counts
    dom_s = sig_domains(snap)                                # [S, N]
    node_count_sig = jnp.take_along_axis(
        counts, jnp.clip(dom_s, 0, None), axis=1
    )
    has_key_sig = dom_s >= 0
    max_count_sig = jnp.max(
        jnp.where(has_key_sig, node_count_sig, 0.0), axis=1
    )
    N = nodes.valid.shape[0]

    spread_ok = jnp.ones(N, bool)
    spread_pen = jnp.zeros(N, jnp.float32)
    C = pods.ts_key.shape[1]
    for c in range(C):
        s = jnp.clip(pods.ts_sig[p, c], 0, None)
        valid_c = pods.ts_valid[p, c]
        nc = node_count_sig[s]                               # [N]
        hk = has_key_sig[s]
        eligible = nodes.valid & aff_ok_p & hk
        min_c = jnp.min(jnp.where(eligible, nc, jnp.inf))
        min_c = jnp.where(jnp.any(eligible), min_c, 0.0)
        dns = pods.ts_when[p, c] == DO_NOT_SCHEDULE
        ok_c = hk & (nc + 1.0 - min_c <= pods.ts_max_skew[p, c])
        spread_ok &= jnp.where(valid_c & dns, ok_c, True)
        pen_c = jnp.where(hk, nc, max_count_sig[s])
        spread_pen += jnp.where(valid_c & ~dns, pen_c, 0.0)

    ia_ok = jnp.ones(N, bool)
    ia_raw = jnp.zeros(N, jnp.float32)
    IT = pods.ia_key.shape[1]
    M = snap.running.valid.shape[0]
    for t in range(IT):
        s = jnp.clip(pods.ia_sig[p, t], 0, None)
        valid_t = pods.ia_valid[p, t]
        nc = node_count_sig[s]
        hk = has_key_sig[s]
        node_has = hk & (nc > 0)
        anti = pods.ia_anti[p, t]
        req = pods.ia_required[p, t]
        # Same required-positive-affinity self-match special case as
        # pairwise_from_counts; match_tot counts members on key-less
        # nodes too, matching the oracle's match.any().
        all_zero = st.match_tot[s] <= 0
        self_match = sig_match[s, M + p]
        pos_ok = node_has | (all_zero & self_match & hk)
        ok_t = jnp.where(anti, ~node_has, pos_ok)
        ia_ok &= jnp.where(valid_t & req, ok_t, True)
        w = jnp.where(anti, -pods.ia_weight[p, t], pods.ia_weight[p, t])
        ia_raw += jnp.where(valid_t & ~req & node_has, w, 0.0)

    # Symmetric anti: [S] match vector x [S, N] holder counts, in
    # int32 (tree-order-exact; see symmetric_anti_block).
    anti_at = jnp.take_along_axis(
        st.anti, jnp.clip(dom_s, 0, None), axis=1
    )
    anti_i = jnp.where(dom_s >= 0, anti_at, 0.0).astype(jnp.int32)
    match_vec = sig_match[:, M + p].astype(jnp.int32)        # [S]
    sym_blocked = (match_vec[:, None] * anti_i).sum(axis=0) > 0
    ia_ok &= ~sym_blocked
    return spread_ok, spread_pen, ia_ok, ia_raw
