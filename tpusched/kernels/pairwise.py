"""PodTopologySpread + InterPodAffinity kernels (SURVEY.md C6, C7).

These are the pairwise constraints: where pod p may land depends on where
*other* pods (running + already-committed pending) sit. Members are the
concatenation [running | pending], with pending membership switched on as
pods commit — so the same kernel serves both the sequential parity scan
(assigned grows step by step) and one-shot ScoreBatch (assigned = none).

Domain counting uses scatter-adds into an [N]-sized domain-count buffer
(domain ids are interned per topology key by SnapshotBuilder and are
always < number of nodes), which keeps every shape static.

`pod_pairwise` evaluates ONE pod p (traced index) against all nodes; the
batched/ring variant for large P lands in phase 4 (SURVEY.md §2.3 SP/CP
row: block the [P, P] matrix and rotate pod blocks with lax.ppermute).
"""

from __future__ import annotations

import jax.numpy as jnp

from tpusched.config import DO_NOT_SCHEDULE
from tpusched.kernels.atoms import gather_selector_match
from tpusched.snapshot import ClusterSnapshot


def member_arrays(snap: ClusterSnapshot, assigned):
    """Member (running + pending) node index and validity.
    assigned: [P] int32 node or -1. Returns ([M+P] int32, [M+P] bool)."""
    node = jnp.concatenate([snap.running.node_idx, assigned])
    valid = jnp.concatenate([snap.running.valid, assigned >= 0])
    return node, valid


def member_label_sat_t(snap: ClusterSnapshot, sat_fn):
    """[A, M+P] atom satisfaction over member pod labels; static across a
    solve (labels never change), so computed once and closed over."""
    lp = jnp.concatenate([snap.running.label_pairs, snap.pods.label_pairs])
    lk = jnp.concatenate([snap.running.label_keys, snap.pods.label_keys])
    return sat_fn(lp, lk).T


def _domain_counts(member_dom_ok, match, n_buckets):
    """Scatter-count matching members into their domains: [N] f32."""
    dom = jnp.clip(member_dom_ok, 0, None)
    contrib = (match & (member_dom_ok >= 0)).astype(jnp.float32)
    return jnp.zeros(n_buckets, jnp.float32).at[dom].add(contrib)


def pod_pairwise(
    snap: ClusterSnapshot,
    member_sat_t,          # [A, M+P]
    p,                     # traced pod index
    assigned,              # [P] int32
    node_affinity_ok_p,    # [N] bool — pod p's required-affinity mask
):
    """Returns (spread_ok [N], spread_penalty [N], ia_ok [N], ia_raw [N])
    for pod p given currently-committed members."""
    nodes, pods = snap.nodes, snap.pods
    dom = nodes.domain                                   # [N, TK]
    N = dom.shape[0]
    member_node, member_valid = member_arrays(snap, assigned)
    # Member's domain per topology key: [M+P, TK] (-1 when member or its
    # node lacks the key).
    mdom = jnp.where(
        (member_node >= 0)[:, None],
        dom[jnp.clip(member_node, 0, None)],
        -1,
    )

    spread_ok = jnp.ones(N, bool)
    spread_penalty = jnp.zeros(N, jnp.float32)
    C = pods.ts_key.shape[1]
    for c in range(C):  # static unroll; C is a small bucket
        valid_c = pods.ts_valid[p, c]
        key = jnp.clip(pods.ts_key[p, c], 0, None)
        match = gather_selector_match(
            member_sat_t, pods.ts_sel_atoms[p, c], member_valid
        )
        counts = _domain_counts(mdom[:, key], match, N)
        has_key = dom[:, key] >= 0
        node_count = counts[jnp.clip(dom[:, key], 0, None)]
        eligible = nodes.valid & node_affinity_ok_p & has_key
        min_count = jnp.min(jnp.where(eligible, node_count, jnp.inf))
        min_count = jnp.where(jnp.any(eligible), min_count, 0.0)
        max_count = jnp.max(jnp.where(has_key, node_count, 0.0))
        dns = pods.ts_when[p, c] == DO_NOT_SCHEDULE
        ok_c = has_key & (node_count + 1.0 - min_count <= pods.ts_max_skew[p, c])
        spread_ok &= jnp.where(valid_c & dns, ok_c, True)
        pen_c = jnp.where(has_key, node_count, max_count)
        spread_penalty += jnp.where(valid_c & ~dns, pen_c, 0.0)

    ia_ok = jnp.ones(N, bool)
    ia_raw = jnp.zeros(N, jnp.float32)
    IT = pods.ia_key.shape[1]
    for t in range(IT):
        valid_t = pods.ia_valid[p, t]
        key = jnp.clip(pods.ia_key[p, t], 0, None)
        match = gather_selector_match(
            member_sat_t, pods.ia_sel_atoms[p, t], member_valid
        )
        counts = _domain_counts(mdom[:, key], match, N)
        has_key = dom[:, key] >= 0
        node_has = has_key & (counts[jnp.clip(dom[:, key], 0, None)] > 0)
        anti = pods.ia_anti[p, t]
        req = pods.ia_required[p, t]
        ok_t = jnp.where(anti, ~node_has, node_has)
        ia_ok &= jnp.where(valid_t & req, ok_t, True)
        w = jnp.where(anti, -pods.ia_weight[p, t], pods.ia_weight[p, t])
        ia_raw += jnp.where(
            valid_t & ~req & node_has, w, 0.0
        )
    return spread_ok, spread_penalty, ia_ok, ia_raw
