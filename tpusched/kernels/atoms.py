"""Match-expression atom satisfaction (device side of SURVEY.md C2's
label machinery).

`SnapshotBuilder` interns every distinct matchExpression into an atom;
this kernel evaluates all atoms against all label sets at once:

    sat[x, a] = does label-set x satisfy atom a

computed as pure broadcast-compare-reduce, which XLA fuses into a single
pass — no per-atom Python, no dynamic shapes. The same kernel serves
node labels (node affinity) and pod labels (spread / inter-pod selectors).
"""

from __future__ import annotations

import jax.numpy as jnp

from tpusched.config import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
)
from tpusched.snapshot import AtomTable


def atom_sat(atoms: AtomTable, label_pairs, label_keys, label_nums=None):
    """Returns [X, A] bool for label arrays of shape [X, L].

    label_nums may be None for label sets that never face Gt/Lt atoms
    (pod labels) — saves the numeric branch entirely.
    """
    lp = label_pairs[:, :, None]                     # [X, L, 1]
    lk = label_keys[:, :, None]                      # [X, L, 1]
    # In/NotIn: does any node pair id appear in the atom's value set?
    pair_hit = (lp[:, :, :, None] == atoms.pairs[None, None, :, :])  # [X,L,A,V]
    pair_hit &= (atoms.pairs >= 0)[None, None, :, :]
    any_pair = jnp.any(pair_hit, axis=(1, 3))        # [X, A]
    exists = jnp.any((lk == atoms.key[None, None, :]) & (lk >= 0), axis=1)  # [X, A]

    if label_nums is not None:
        matched = (lk == atoms.key[None, None, :]) & jnp.isfinite(label_nums)[:, :, None]
        has_num = jnp.any(matched, axis=1)           # [X, A]
        val = jnp.sum(jnp.where(matched, label_nums[:, :, None], 0.0), axis=1)  # tpl: disable=TPL201(at most ONE label row matches a key per label set, so this sum is a masked select over the small fixed label axis — never padded or sharded)
        gt = has_num & (val > atoms.num[None, :])
        lt = has_num & (val < atoms.num[None, :])
    else:
        gt = jnp.zeros(exists.shape, bool)
        lt = jnp.zeros(exists.shape, bool)

    op = atoms.op[None, :]
    sat = jnp.select(
        [op == OP_IN, op == OP_NOT_IN, op == OP_EXISTS,
         op == OP_DOES_NOT_EXIST, op == OP_GT, op == OP_LT],
        [any_pair, ~any_pair, exists, ~exists, gt, lt],
        default=False,
    )
    return sat & atoms.valid[None, :]


def gather_term_sat(sat_t, term_atoms):
    """AND-combine atom satisfaction over a term's atom list.

    sat_t: [A, X] (transposed atom table, X = nodes or pods)
    term_atoms: [..., AT] int32 atom ids, -1 padded.
    Returns [..., X] bool: every listed atom satisfied. Padded slots are
    the AND identity (True); a term with zero atoms yields all-True and
    must be masked by the caller's term-valid flag (empty terms match no
    objects upstream — snapshot.py drops them at build)."""
    gathered = sat_t[jnp.clip(term_atoms, 0, None)]          # [..., AT, X]
    gathered = gathered | (term_atoms < 0)[..., None]
    return jnp.all(gathered, axis=-2)


def gather_selector_match(sat_t, sel_atoms, subject_valid):
    """AND-combine selector atoms over pod label sets; a selector with
    zero atoms matches ALL valid subjects (upstream empty label
    selector). sel_atoms: [..., AT]; returns [..., X] bool."""
    return gather_term_sat(sat_t, sel_atoms) & subject_valid
