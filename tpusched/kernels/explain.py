"""Decision-provenance probe (round 12, ISSUE 8 tentpole).

The solve kernels answer "WHAT was decided"; this module answers "WHY"
for one snapshot, evaluated against SNAPSHOT-START state:

  * per-pod filter-elimination tallies by reason — every (valid pod,
    valid node) pair is attributed to its FIRST failing predicate in
    the fixed FILTER_REASONS order, so for every valid pod
    ``feasible_nodes + sum(filter_counts) == number of valid nodes``
    is an exact partition (test-pinned);
  * the top-k candidate nodes by total score with the score DECOMPOSED
    into its plugin terms (SCORE_TERMS order, urgency-reweighted per
    pod exactly like the solve's StaticCtx weights) — the per-term
    columns sum to the reported candidate total (f32: same terms,
    different summation grouping than batched_cycle, so use allclose,
    not bit equality, against the solve's chosen score);
  * the QoS inputs the paper's loop runs on: per-pod pressure and
    effective priority, per-victim effective priority / slack /
    shifted-positive eviction cost (the same cost_s the preemption
    auction ranks by, kernels/preempt.precompute).

Everything is packed into ONE flat f32 buffer (one D2H fetch through
the engine's ordered worker) and is computed ONLY for explained cycles
— the serving hot path never traces this program (Engine lazily jits
it on first solve_explained call).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from tpusched.config import EngineConfig
from tpusched.kernels import filter as kfilter
from tpusched.kernels import pairwise as kpair
from tpusched.kernels import score as kscore
from tpusched.kernels.assign import precompute_static
from tpusched.qos import (
    effective_weights,
    evict_cost_raw,
    pressure_of,
    priority_terms,
    victim_effective_priority,
)
from tpusched.snapshot import ClusterSnapshot

# First-failing-predicate attribution order (the order the serving
# filters conceptually run): cordon, taints, node affinity, resources,
# then the pairwise constraints. Invalid (bucket-padding) node slots
# are excluded from the universe, so for every valid pod
# feasible + sum(tallies) == number of VALID nodes.
FILTER_REASONS = (
    "cordoned",
    "taint",
    "node_affinity",
    "resources",
    "spread",
    "interpod_affinity",
)

# Score decomposition columns; matches qos._PLUGINS order.
SCORE_TERMS = (
    "least_requested",
    "balanced_allocation",
    "node_affinity",
    "taint_toleration",
    "topology_spread",
    "interpod_affinity",
)


@dataclasses.dataclass
class ScoreExplain:
    """Host-side decode of one explain probe (arrays carry the full
    bucketed axes; tpusched.explain.build_record slices to the real
    record counts via SnapshotMeta)."""

    k: int
    topk_idx: np.ndarray       # [P, k] int32 node index, -1 = no candidate
    topk_score: np.ndarray     # [P, k] f32 total score (0 at -1 slots)
    topk_terms: np.ndarray     # [P, k, T] f32 per-term contributions
    filter_counts: np.ndarray  # [P, NR] int32 eliminated nodes by reason
    feasible_nodes: np.ndarray  # [P] int32
    pressure: np.ndarray       # [P] f32 QoS pressure
    priority: np.ndarray       # [P] f32 effective (dynamic) priority
    victim_priority: np.ndarray  # [M] f32 victim effective priority
    victim_slack: np.ndarray   # [M] f32
    evict_cost: np.ndarray     # [M] f32 shifted-positive auction cost


def explain_probe(cfg: EngineConfig, snap: ClusterSnapshot, node_sat_t,
                  member_sat_t, k: int, init_counts=None, mesh=None):
    """One flat f32 buffer of the provenance arrays (module docstring).
    `k` is a trace-time constant clipped to [1, N] by the caller."""
    static = precompute_static(cfg, snap, node_sat_t, member_sat_t, mesh)
    st0 = kpair.pair_state_init(snap, static.sig_match, counts=init_counts,
                                mesh=mesh)
    nodes, pods = snap.nodes, snap.pods
    P = pods.valid.shape[0]
    N = nodes.valid.shape[0]
    S = snap.sigs.key.shape[0]

    cordon_ok = nodes.schedulable[None, :] | pods.tolerates_unsched[:, None]
    taint_ok = kfilter.taint_mask(
        nodes.taint_ids, snap.taint_effect, pods.tolerated
    )
    res_ok = kfilter.resource_fit(
        nodes.allocatable, nodes.used, pods.requests
    )
    if S:
        spread_ok, spread_pen, ia_ok, ia_raw = kpair.pairwise_from_counts(
            snap, st0, static.aff_ok, static.sig_match, None
        )
    else:
        spread_ok = ia_ok = jnp.ones((P, N), bool)
        spread_pen = ia_raw = None

    # Hierarchical tallies: `alive` shrinks predicate by predicate, so
    # each pair lands in exactly one reason column and what survives is
    # EXACTLY batched_cycle's feasibility (same predicate set; the
    # valid-node/valid-pod pre-mask is the universe, not a reason).
    alive = pods.valid[:, None] & nodes.valid[None, :]
    fails = (
        ~cordon_ok,
        ~taint_ok,
        ~static.aff_ok,
        ~res_ok,
        ~spread_ok,
        ~ia_ok,
    )
    tallies = []
    for fail in fails:
        hit = alive & fail
        tallies.append(jnp.sum(hit, axis=1).astype(jnp.float32))
        alive = alive & ~hit
    feasible = alive

    # Per-term score columns with the solve's effective (urgency-
    # reweighted) weights — static.w_* ARE these weights; node-affinity
    # and taint-toleration are recomputed unsummed (StaticCtx folds
    # them into one static score).
    w = effective_weights(
        cfg, pressure_of(pods.slo_target, pods.observed_avail)
    )
    lr = static.w_lr[:, None] * kscore.least_requested(
        nodes.allocatable, nodes.used, pods.requests, static.rw
    )
    ba = static.w_ba[:, None] * kscore.balanced_allocation(
        nodes.allocatable, nodes.used, pods.requests, static.rw
    )
    na = w["node_affinity"][:, None] * kscore.node_affinity_score(
        node_sat_t, pods.pref_term_atoms, pods.pref_term_valid,
        pods.pref_weight, nodes.valid,
    )
    tt = w["taint_toleration"][:, None] * kscore.taint_toleration_score(
        nodes.taint_ids, snap.taint_effect, pods.tolerated, nodes.valid
    )
    if S:
        ts = static.w_ts[:, None] * kscore.inverse_normalize(
            spread_pen, nodes.valid
        )
        ia = static.w_ia[:, None] * kscore.minmax_normalize(
            ia_raw, nodes.valid
        )
    else:
        # No pairwise constraints: spread score is the constant 100
        # (batched_cycle's trace-time shortcut), inter-pod raw is 0 and
        # minmax-normalizes to 0.
        ts = jnp.broadcast_to(static.w_ts[:, None] * 100.0, (P, N))
        ia = jnp.zeros((P, N), jnp.float32)
    terms = jnp.stack([lr, ba, na, tt, ts, ia], axis=-1).astype(jnp.float32)
    total = jnp.sum(terms, axis=-1)
    masked = jnp.where(feasible, total, -jnp.inf)
    v, i = jax.lax.top_k(masked, k)
    okk = jnp.isfinite(v)
    idx = jnp.where(okk, i, -1)
    val = jnp.where(okk, v, 0.0)
    term_k = jnp.take_along_axis(
        terms, jnp.clip(i, 0, N - 1)[..., None], axis=1
    )
    term_k = jnp.where(okk[..., None], term_k, 0.0)

    pt = priority_terms(
        cfg, pods.base_priority, pods.slo_target, pods.observed_avail
    )
    press = pt["pressure"]
    prio = pt["effective"]
    run = snap.running
    vprio = victim_effective_priority(cfg, run.priority, run.slack)
    raw = evict_cost_raw(cfg, run.priority, run.slack).astype(jnp.float32)
    # Same positive shift as kernels/preempt.precompute, so reported
    # costs are the very numbers the auction's prefix sums rank by.
    mn = jnp.min(jnp.where(run.valid, raw, jnp.inf))
    mn = jnp.where(jnp.isfinite(mn), mn, 0.0)
    cost = raw - mn + 1.0

    f32 = jnp.float32
    return jnp.concatenate([
        idx.astype(f32).ravel(),
        val.astype(f32).ravel(),
        term_k.reshape(-1),
        jnp.stack(tallies, axis=1).ravel(),
        jnp.sum(feasible, axis=1).astype(f32),
        press.astype(f32),
        prio.astype(f32),
        vprio.astype(f32),
        run.slack.astype(f32),
        cost.astype(f32),
    ])


def unpack_probe(snap: ClusterSnapshot, buf, k: int) -> ScoreExplain:
    """Decode explain_probe's flat buffer (the single layout authority
    — Engine fetches through here)."""
    buf = np.asarray(buf)
    P = snap.pods.valid.shape[0]
    M = snap.running.valid.shape[0]
    T = len(SCORE_TERMS)
    NR = len(FILTER_REASONS)
    off = 0

    def take(n, shape=None):
        nonlocal off
        out = buf[off:off + n]
        off += n
        return out.reshape(shape) if shape is not None else out

    return ScoreExplain(
        k=k,
        topk_idx=take(P * k, (P, k)).astype(np.int32),
        topk_score=take(P * k, (P, k)).astype(np.float32),
        topk_terms=take(P * k * T, (P, k, T)).astype(np.float32),
        filter_counts=take(P * NR, (P, NR)).astype(np.int32),
        feasible_nodes=take(P).astype(np.int32),
        pressure=take(P).astype(np.float32),
        priority=take(P).astype(np.float32),
        victim_priority=take(M).astype(np.float32),
        victim_slack=take(M).astype(np.float32),
        evict_cost=take(M).astype(np.float32),
    )
