"""Preemption (PostFilter) kernel (SURVEY.md C9, §3.4).

The reference scheduler's signature behavior: a pod with no feasible
node searches for nodes where evicting lower-priority victims makes it
fit, choosing the minimum-cost victim set, with eviction cost driven by
the victims' QoS slack (pods running above their SLO are cheap to evict;
see qos.evict_cost_raw and QoSConfig).

TPU formulation: victims are sorted ONCE per snapshot by (node, cost)
(PreemptCtx). A preemptor's step is then a masked segment-prefix scan —
eligible victims' cumulative requests within each node's segment — and
the cheapest feasible prefix per node falls out of the FIRST position
where the preemptor fits (costs ascend within a segment, so the first
feasible prefix is the min-cost one). A scatter-min over segments yields
per-node best costs; argmin picks the node. Everything is fixed-shape
[M]/[N] arithmetic — no Hungarian augmenting paths, no data-dependent
loops (the auction-style "bid per node, pick globally best" recommended
over classical Hungarian by SURVEY.md §7 hard part 4).

PodDisruptionBudgets (SURVEY.md C9 "fewest PDB violations"): each
running pod may belong to a budget (running.pdb_group) with a remaining
disruptions_allowed (snapshot.pdb_allowed). A victim whose eviction
would exceed its budget's remaining allowance — counting earlier
preemptors' evictions AND same-prefix co-victims — is a VIOLATION.
Candidate prefixes are ranked lexicographically by (violation count,
cost), exactly upstream's ordering: any non-violating set beats any
violating one, and violation stays available as the last resort
(upstream evicts PDB-protected pods when nothing else fits). Violation
counts are small integers (exact in f32 under any summation order), so
oracle/device parity survives; a cost PENALTY of ~1e8 would instead
poison the f32 prefix sums, whose rounding depends on the backend's
scan association. With violations in play, costs within a segment no
longer rank prefixes, so the chosen prefix is the lexicographic MIN
over all feasible prefix positions, not the first feasible.

Scope notes (mirrored exactly by the oracle so parity is testable):
  * Only RESOURCE infeasibility is repaired: the preemptor's static
    predicates (taints/affinity) and pairwise constraints must already
    hold on the target node, evaluated against pre-eviction state.
  * The preemptor is assigned immediately (the host shim issues deletes
    then binds; upstream nominates and re-queues instead).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from tpusched.config import EngineConfig
from tpusched.kernels import pairwise as kpair
from tpusched.qos import evict_cost_raw, victim_effective_priority
from tpusched.snapshot import ClusterSnapshot


@struct.dataclass
class PreemptCtx:
    """Snapshot-static victim ordering and costs."""

    perm: Any        # [M] int32: running pods sorted by (node, cost)
    node_s: Any      # [M] int32 node of sorted victim (N = invalid sentinel)
    seg_start: Any   # [M] int32 index where this node's segment begins
    cost_s: Any      # [M] f32 shifted-positive eviction cost, sorted
    vprio_s: Any     # [M] f32 victim effective priority, sorted
    req_s: Any       # [M, R] f32 victim requests, sorted
    pdb_s: Any       # [M] int32 PDB id of sorted victim (-1 none)


def precompute(cfg: EngineConfig, snap: ClusterSnapshot) -> PreemptCtx:
    run = snap.running
    M = run.valid.shape[0]
    N = snap.nodes.valid.shape[0]
    vprio = victim_effective_priority(cfg, run.priority, run.slack)
    raw = evict_cost_raw(cfg, run.priority, run.slack).astype(jnp.float32)
    # Shift costs positive (+1 per victim): prefix sums then strictly
    # increase, making "first feasible prefix = cheapest" hold and
    # encoding the fewer-victims preference (upstream tie-break).
    mn = jnp.min(jnp.where(run.valid, raw, jnp.inf))
    mn = jnp.where(jnp.isfinite(mn), mn, 0.0)
    cost = raw - mn + 1.0
    node_m = jnp.where(run.valid & (run.node_idx >= 0), run.node_idx, N)
    perm = jnp.lexsort((cost, node_m))
    node_s = node_m[perm]
    idx = jnp.arange(M, dtype=jnp.int32)
    if M:
        boundary = jnp.concatenate(
            [jnp.ones(1, bool), node_s[1:] != node_s[:-1]]
        )
        seg_start = jax.lax.cummax(jnp.where(boundary, idx, 0))
    else:
        seg_start = idx
    return PreemptCtx(
        perm=perm, node_s=node_s, seg_start=seg_start,
        cost_s=cost[perm], vprio_s=vprio[perm].astype(jnp.float32),
        req_s=run.requests[perm],
        pdb_s=run.pdb_group[perm],
    )


@struct.dataclass
class PreemptCtxNV:
    """Node-major victim table for the fast auction (round 5): per node,
    up to V victims in ascending-cost order (the same within-segment
    order as PreemptCtx's global (node, cost) sort). The [C, M] global
    prefix sums of the sorted layout cost ~25 ms/round at 10k x 5k
    (log-depth cumsums over M=40960); in node-major layout every prefix
    is a V-length cumsum and the PDB same-budget counts become one
    [V, V] triangular contraction — MXU work instead of scan passes.
    Victims beyond the per-node cap V are unreachable for fast-mode
    preemption (a documented approximation: a prefix needing > V
    evictions on one node falls back to other nodes or stays pending;
    the sequential/parity path has no cap)."""

    vreq: Any    # [N, V, R] f32 victim requests
    vcost: Any   # [N, V] f32 shifted-positive eviction cost, ascending
    vprio: Any   # [N, V] f32 victim effective priority
    vpdb: Any    # [N, V] int32 PDB id (-1 none/pad)
    vvalid: Any  # [N, V] bool
    vidx: Any    # [N, V] int32 index into running arrays (M = pad)


def precompute_nv(cfg: EngineConfig, snap: ClusterSnapshot,
                  cap: int) -> PreemptCtxNV:
    """Build the node-major victim table (fast-auction counterpart of
    precompute; same sort keys, so victim order within a node matches
    the sequential tableau exactly)."""
    run = snap.running
    M = run.valid.shape[0]
    N = snap.nodes.valid.shape[0]
    V = max(1, min(cap, M))
    vprio = victim_effective_priority(cfg, run.priority, run.slack)
    raw = evict_cost_raw(cfg, run.priority, run.slack).astype(jnp.float32)
    mn = jnp.min(jnp.where(run.valid, raw, jnp.inf))
    mn = jnp.where(jnp.isfinite(mn), mn, 0.0)
    cost = raw - mn + 1.0
    node_m = jnp.where(run.valid & (run.node_idx >= 0), run.node_idx, N)
    perm = jnp.lexsort((cost, node_m))
    node_s = node_m[perm]
    idx = jnp.arange(M, dtype=jnp.int32)
    boundary = jnp.concatenate(
        [jnp.ones(1, bool), node_s[1:] != node_s[:-1]]
    )
    seg_start = jax.lax.cummax(jnp.where(boundary, idx, 0))
    pos = idx - seg_start
    ok = (node_s < N) & (pos < V)
    tn = jnp.where(ok, node_s, N)   # sentinel row N for drops/pads
    tv = jnp.where(ok, pos, 0)

    def scat(vals, fill, dtype):
        shape = (N + 1, V) + vals.shape[1:]
        out = jnp.full(shape, fill, dtype)
        src = jnp.where(
            ok.reshape((M,) + (1,) * (vals.ndim - 1)), vals, fill
        )
        return out.at[tn, tv].set(src.astype(dtype))[:N]

    return PreemptCtxNV(
        vreq=scat(run.requests[perm], 0.0, jnp.float32),
        vcost=scat(cost[perm], 0.0, jnp.float32),
        vprio=scat(vprio[perm].astype(jnp.float32), jnp.inf, jnp.float32),
        vpdb=scat(run.pdb_group[perm], -1, jnp.int32),
        vvalid=jnp.zeros((N + 1, V), bool).at[tn, tv].set(ok)[:N],
        vidx=scat(perm, M, jnp.int32),
    )


def _tableau_nv(cfg: EngineConfig, snap: ClusterSnapshot,
                ctx: PreemptCtxNV, p_prio, p_req, used, evicted):
    """All C bidders' victim-prefix tableaus at once on the node-major
    table: [C, N, V] arrays, V-length prefix sums, PDB counts as one
    triangular [V, V] contraction. Ranking semantics identical to
    _tableau (lexicographic (violations, cost) min over feasible
    prefixes per node). Returns (elig, wcost, wviol, fits,
    node_viol [C, N], node_cost [C, N]) with [C, N, V] leading four.

    Round 6: RETAINED FOR PROFILING/REFERENCE ONLY
    (tools/prof_components.py slopes it) — preempt_auction no longer
    materializes it; see its docstring for the [N, V]-table + [C, V]
    validation restructure that replaced the ~0.5 GB/round of f32
    cumsums this form costs at 10k x 5k."""
    nodes = snap.nodes
    N, V = ctx.vvalid.shape
    M = evicted.shape[0]
    ev_nv = evicted[jnp.clip(ctx.vidx, 0, M - 1)] & ctx.vvalid
    base_elig = ctx.vvalid & ~ev_nv                          # [N, V]
    elig = base_elig[None] & (
        ctx.vprio[None] + cfg.qos.preemption_margin
        < p_prio[:, None, None]
    )                                                        # [C, N, V]
    gr = jnp.where(elig[..., None], ctx.vreq[None], 0.0)
    wreq = jnp.cumsum(gr, axis=2)                            # [C, N, V, R]  # tpl: disable=TPL201(_tableau_nv is retained for profiling/reference only — prof_components slopes it; no product path calls it)
    fits = elig & jnp.all(
        used[None, :, None, :] - wreq + p_req[:, None, None, :]
        <= nodes.allocatable[None, :, None, :],
        axis=-1,
    )
    wcost = jnp.cumsum(jnp.where(elig, ctx.vcost[None], 0.0), axis=2)
    GP = snap.pdb_allowed.shape[0]
    if GP:
        run_pdb = snap.running.pdb_group
        consumed = jnp.zeros(GP, jnp.float32).at[
            jnp.clip(run_pdb, 0, None)
        ].add(
            (evicted & (run_pdb >= 0) & snap.running.valid).astype(
                jnp.float32
            )
        )
        remaining = snap.pdb_allowed - consumed              # [GP]
        has_pdb = ctx.vpdb >= 0                              # [N, V]
        tri = (
            jnp.arange(V)[:, None] >= jnp.arange(V)[None, :]
        )                                                    # [V(v), V(w)]
        same_g = (
            (ctx.vpdb[:, :, None] == ctx.vpdb[:, None, :])
            & has_pdb[:, :, None] & tri[None]
        ).astype(jnp.float32)                                # [N, V, V]
        eligp = (elig & has_pdb[None]).astype(jnp.float32)
        wcnt = jnp.einsum("nvw,cnw->cnv", same_g, eligp)
        rem_nv = remaining[jnp.clip(ctx.vpdb, 0, None)]      # [N, V]
        viol = elig & has_pdb[None] & (wcnt > rem_nv[None])
    else:
        viol = jnp.zeros_like(elig)
    wviol = jnp.cumsum(viol.astype(jnp.float32), axis=2)
    node_viol = jnp.min(jnp.where(fits, wviol, jnp.inf), axis=2)
    fits_v = fits & (wviol == node_viol[..., None])
    node_cost = jnp.min(jnp.where(fits_v, wcost, jnp.inf), axis=2)
    return elig, wcost, wviol, fits, node_viol, node_cost


def _tableau(cfg: EngineConfig, snap: ClusterSnapshot, ctx: PreemptCtx,
             p_prio, p_req, used, evicted):
    """One preemptor's victim-prefix tableau: everything preempt_step
    derives before node selection. Shared verbatim by the sequential
    step and the batched auction (preempt_auction) so their per-node
    rankings agree exactly. Returns
    (elig [M], within_cost [M], within_viol [M], fits [M],
    node_viol [N], node_cost [N])."""
    nodes = snap.nodes
    M = ctx.perm.shape[0]
    N = nodes.valid.shape[0]
    idx = jnp.arange(M, dtype=jnp.int32)

    elig = (
        (ctx.node_s < N)
        & ~evicted[ctx.perm]
        & (ctx.vprio_s + cfg.qos.preemption_margin < p_prio)
    )
    # PDB violations (see module docstring): a victim violates if the
    # same-budget count within its node-segment prefix (including
    # itself) plus earlier preemptors' evictions exceeds the budget.
    GP = snap.pdb_allowed.shape[0]
    if GP:
        pdb_clip = jnp.clip(ctx.pdb_s, 0, None)
        has_pdb = ctx.pdb_s >= 0
        run_pdb = snap.running.pdb_group
        consumed = jnp.zeros(GP, jnp.float32).at[
            jnp.clip(run_pdb, 0, None)
        ].add((evicted & (run_pdb >= 0) & snap.running.valid).astype(
            jnp.float32
        ))
        remaining = snap.pdb_allowed - consumed              # [GP]
        gsel = (
            (jnp.arange(GP)[:, None] == pdb_clip[None, :])
            & (elig & has_pdb)[None, :]
        )                                                    # [GP, M]
        cum_g = jnp.cumsum(gsel.astype(jnp.float32), axis=1)
        my_cum = cum_g[pdb_clip, idx]                        # [M] incl. self
        off_g = jnp.where(
            ctx.seg_start > 0,
            cum_g[pdb_clip, jnp.clip(ctx.seg_start - 1, 0, None)], 0.0,
        )
        within_cnt = my_cum - off_g
        viol = elig & has_pdb & (within_cnt > remaining[pdb_clip])
    else:
        viol = jnp.zeros(M, bool)
    req_m = jnp.where(elig[:, None], ctx.req_s, 0.0)
    cum_req = jnp.cumsum(req_m, axis=0)                      # [M, R] inclusive  # tpl: disable=TPL201(victim-prefix sums at the snapshot's fixed [M] width, mirrored op-for-op by oracle.py — parity suites pin the verdicts bitwise; the victim axis must stay unsharded, recorded in the ledger sharding column)
    cum_cost = jnp.cumsum(jnp.where(elig, ctx.cost_s, 0.0))  # [M]
    # Violation count per prefix: 0/1 sums are exact in f32 under any
    # summation order (<= M < 2^24), unlike penalty-inflated cost sums.
    cum_viol = jnp.cumsum(viol.astype(jnp.float32))          # [M]
    off_req = jnp.where(
        (ctx.seg_start > 0)[:, None],
        cum_req[jnp.clip(ctx.seg_start - 1, 0, None)], 0.0,
    )
    off_cost = jnp.where(
        ctx.seg_start > 0, cum_cost[jnp.clip(ctx.seg_start - 1, 0, None)], 0.0
    )
    off_viol = jnp.where(
        ctx.seg_start > 0, cum_viol[jnp.clip(ctx.seg_start - 1, 0, None)], 0.0
    )
    within_req = cum_req - off_req                           # [M, R]
    within_cost = cum_cost - off_cost                        # [M]
    within_viol = cum_viol - off_viol                        # [M]
    cap_node = jnp.clip(ctx.node_s, 0, N - 1)
    fits = elig & jnp.all(
        used[cap_node] - within_req + p_req[None, :]
        <= nodes.allocatable[cap_node],
        axis=-1,
    )
    # Lexicographic (violations, cost) MIN over feasible prefixes, in
    # exact two-stage comparisons (never summing the two channels):
    # per node, fewest violations first; among those prefixes, min cost.
    # N index = sentinel bucket.
    node_viol = jnp.full(N + 1, jnp.inf).at[ctx.node_s].min(
        jnp.where(fits, within_viol, jnp.inf)
    )[:N]
    fits_v = fits & (within_viol == node_viol[cap_node])
    node_cost = jnp.full(N + 1, jnp.inf).at[ctx.node_s].min(
        jnp.where(fits_v, within_cost, jnp.inf)
    )[:N]
    return elig, within_cost, within_viol, fits, node_viol, node_cost


def preempt_step(cfg: EngineConfig, snap: ClusterSnapshot, ctx: PreemptCtx,
                 p_prio, p_req, allowed_row, used, evicted):
    """One preemptor's victim search. Returns
    (best_n, can, evict_m, freed) — chosen node (int32), whether
    preemption succeeds (bool), the [M] eviction mask, and the [N, R]
    capacity freed on the chosen node (zeros elsewhere)."""
    nodes = snap.nodes
    M = ctx.perm.shape[0]
    idx = jnp.arange(M, dtype=jnp.int32)
    elig, within_cost, within_viol, fits, node_viol, node_cost = _tableau(
        cfg, snap, ctx, p_prio, p_req, used, evicted
    )
    # Across nodes: global fewest violations, then cheapest. (inf ==
    # inf is True, so the allowed mask must gate `total` as well —
    # otherwise a disallowed node's finite prefix wins when NO allowed
    # node is feasible.)
    ok_node = allowed_row & nodes.valid
    viol_total = jnp.where(ok_node, node_viol, jnp.inf)
    min_viol = jnp.min(viol_total)
    total = jnp.where(ok_node & (viol_total == min_viol), node_cost, jnp.inf)
    best_n = jnp.argmin(total).astype(jnp.int32)
    can = jnp.isfinite(total[best_n])
    best_pos = jnp.argmin(
        jnp.where(
            fits & (ctx.node_s == best_n) & (within_viol == min_viol),
            within_cost, jnp.inf,
        )
    ).astype(jnp.int32)
    sel_s = can & (ctx.node_s == best_n) & elig & (idx <= best_pos)
    evict_m = jnp.zeros(M, bool).at[ctx.perm].set(sel_s)
    freed_on_best = jnp.sum(
        jnp.where(sel_s[:, None], ctx.req_s, 0.0), axis=0
    )                                                        # [R]
    freed = jnp.zeros_like(used).at[best_n].add(
        jnp.where(can, freed_on_best, 0.0)
    )
    return best_n, can, evict_m, freed


# Quantile buckets of active-bidder priority for the candidate tables
# (see preempt_auction). 2: each bucket's table is traced/compiled as
# its own [N, V] subgraph, and 2 buckets + the optimistic lane already
# give the common case (victims below every bidder) exact tables while
# keeping the auction's compile time inside the tier-1 wall budget on
# CPU hosts; boundary bidders fall through to the optimistic lane +
# exact [C, V] validation either way.
_PRIO_BUCKETS = 2


def preempt_auction(cfg: EngineConfig, snap: ClusterSnapshot,
                    ctx: PreemptCtxNV, p_prio, p_req, allowed,
                    used, evicted, can_plain, n_plain,
                    k_cand: int = 256, rank=None, claim_iters: int = 6):
    """Batched bidding for C preemptors at once (the fast mode's
    auction round; SURVEY.md §7 hard part 4 — parallel bids, global
    resolution), restructured (round 6) so the EXACT per-bidder work is
    a [C, V] tableau on the claimed node only, never [C, N, V]:

      1. CANDIDATE RANKING from bidder-independent [N, V] prefix
         tables. Within a node, victims sit in ascending-cost slots,
         prefix-freed capacity / cost / violation count are all
         nondecreasing in prefix length, and feasibility is monotone
         (a longer prefix frees more) — so a bidder's best prefix on a
         node is always the FIRST feasible slot, and ranking nodes
         needs only "where does my demand cross this node's cumulative
         freed capacity" (a searchsorted-style compare+reduce per
         resource, [C, N] out) plus two [C, N] gathers into the
         node-major cumulative cost/violation tables. Eligibility is
         approximated by _PRIO_BUCKETS quantile buckets of the ACTIVE
         bidders' priorities: each bucket's table masks victims
         eligible at the bucket's LOWER bound, a conservative subset
         of every member bidder's true eligible set — so a node the
         bucket table calls feasible is feasible for the bidder (more
         eligibility only frees more), while cost is an upper
         estimate. A single active bidder (the small-cluster unit-test
         shape) gets thresholds equal to its own priority: exact.
         The old path materialized the exact [C, N, V(, R)] tableau —
         ~0.5 GB of f32 cumsums per round at 10k x 5k, the measured
         ~16 ms/round floor of the preemption drain.
      2. An OPTIMISTIC (priority-unaware) table answers "could this
         bidder EVER preempt anywhere": bidders with no bucket-feasible
         node but an optimistic-feasible one bid that node as a single
         candidate (exact validation decides), and could_bid/spent
         marking uses the optimistic answer so no pod is falsely
         retired by the bucket approximation.
      3. PARALLEL claim iterations (unchanged) deal bidders distinct
         still-unclaimed candidate nodes: each iteration every
         unclaimed bidder bids its (active-rank mod available)-th
         cheapest untaken candidate and the lowest-rank bidder per
         node wins (scatter-min) — one claimant per node, so
         same-round victim sets never overlap (victims are
         node-local). Losers re-deal next iteration; bidders still
         unclaimed after claim_iters defer to the next auction round.
         Plain placements (can_plain, from the caller's feasibility
         re-check) claim their scored node through the same iterations
         as single-candidate bidders.
      4. EXACT [C, V] VALIDATION on each bidder's claimed node: true
         priority eligibility, V-length prefix sums, first-feasible
         prefix selection — the same selection rule as preempt_step
         restricted to one node (per-prefix violation counts need no
         separate pass: the first feasible prefix is the lexicographic
         minimum). A claim whose exact check fails (possible only via
         the optimistic fallback lane) is released; the bidder is
         marked tried until the next keep changes the state.

    p_prio/p_req/allowed/can_plain/n_plain: [C]/[C,R]/[C,N]/[C]/[C] in
    descending rank order; inactive bidders must arrive with allowed
    all-False and can_plain False. rank: [C] distinct claim-priority
    keys (defaults to 0..C-1, the descending-rank slot order). Returns
    (target [C] int32 (-1 = no claim), claimed [C] bool,
    takes_evict [C] bool, vidx_t [C, V] int32 — running-pod indices of
    each bidder's victim prefix, M at non-victim slots,
    freed_req [C, R] f32 — capacity the prefix frees,
    usage [C, GP] f32 — prefix evictions per PDB budget,
    could_bid [C] bool — False means the pod has NO placement or
    victim prefix at all (spent), as opposed to losing this round's
    node race (retry))."""
    nodes = snap.nodes
    N = nodes.valid.shape[0]
    M = evicted.shape[0]
    C = p_prio.shape[0]
    V = ctx.vvalid.shape[1]
    R = p_req.shape[1]
    BIG = jnp.int32(2**31 - 1)
    if rank is None:
        rank = jnp.arange(C, dtype=jnp.int32)
    ok_node = allowed & nodes.valid[None, :]

    # -- stage 1/2: bidder-independent tables + [C, N] node ranking ---------
    ev_nv = evicted[jnp.clip(ctx.vidx, 0, M - 1)] & ctx.vvalid
    base_elig = ctx.vvalid & ~ev_nv                          # [N, V]
    active = jnp.any(ok_node, axis=1) & ~can_plain
    # Bucket thresholds: quantiles of the ACTIVE bidders' priorities
    # (lower bounds, so each bidder's bucket is conservative for it).
    # No active bidder -> NaN thresholds -> empty tables -> the
    # optimistic lane (whose threshold is +inf) carries nothing either
    # since ok_node is all-False then.
    qs = jnp.linspace(0.0, 1.0, _PRIO_BUCKETS, endpoint=False)
    thr = jnp.nanquantile(jnp.where(active, p_prio, jnp.nan), qs)
    # Bidder -> bucket: largest b with thr[b] <= p_prio (NaN compares
    # False -> bucket 0, harmless: its table is empty too).
    bk = jnp.clip(
        jnp.sum((thr[None, :] <= p_prio[:, None]).astype(jnp.int32), axis=1)
        - 1, 0, _PRIO_BUCKETS - 1,
    )                                                        # [C]
    GP = snap.pdb_allowed.shape[0]
    if GP:
        run_pdb = snap.running.pdb_group
        consumed = jnp.zeros(GP, jnp.float32).at[
            jnp.clip(run_pdb, 0, None)
        ].add(
            (evicted & (run_pdb >= 0) & snap.running.valid).astype(
                jnp.float32
            )
        )
        remaining = snap.pdb_allowed - consumed              # [GP]
        has_pdb = ctx.vpdb >= 0                              # [N, V]
        tri = (
            jnp.arange(V)[:, None] >= jnp.arange(V)[None, :]
        )
        same_g = (
            (ctx.vpdb[:, :, None] == ctx.vpdb[:, None, :])
            & has_pdb[:, :, None] & tri[None]
        ).astype(jnp.float32)                                # [N, V, V]
        rem_nv = remaining[jnp.clip(ctx.vpdb, 0, None)]      # [N, V]
    else:
        remaining = jnp.zeros(0, jnp.float32)
    # Demand each node must free for each bidder (<= 0 in every
    # resource cannot happen on an allowed node of a non-plain bidder).
    need = used[None] + p_req[:, None, :] - nodes.allocatable[None]

    def node_rank(thr_b):
        """[C, N] (feasible, first-feasible cost, viols) against the
        victim subset eligible at priority threshold thr_b."""
        elig_b = base_elig & (
            ctx.vprio + cfg.qos.preemption_margin < thr_b
        )                                                    # [N, V]
        cum_req = jnp.cumsum(  # tpl: disable=TPL201(bucket-table node RANKING only: every claim gets the exact [C, V] validation below before it commits, so a rounding flip here costs a re-deal, never a bad placement)
            jnp.where(elig_b[..., None], ctx.vreq, 0.0), axis=1
        )                                                    # [N, V, R]
        cum_cost = jnp.cumsum(  # tpl: disable=TPL202(same ranking-only role as cum_req above — cost upper estimates ordering candidates; exact validation arbitrates)
            jnp.where(elig_b, ctx.vcost, 0.0), axis=1
        )                                                    # [N, V]
        if GP:
            eligp = (elig_b & has_pdb).astype(jnp.float32)
            wcnt = jnp.einsum("nvw,nw->nv", same_g, eligp)
            viol_b = elig_b & has_pdb & (wcnt > rem_nv)
        else:
            viol_b = jnp.zeros_like(elig_b)
        cum_viol = jnp.cumsum(viol_b.astype(jnp.float32), axis=1)
        # First-feasible slot: the compare+reduce form of a per-(c, n)
        # searchsorted; [C, N, V] compares fuse into the [C, N] sum
        # without materializing the old [C, N, V, R] f32 tableau.
        pos = jnp.zeros((C, N), jnp.int32)
        for r in range(R):
            pos = jnp.maximum(
                pos,
                jnp.sum(
                    (cum_req[None, :, :, r] < need[:, :, None, r]
                     ).astype(jnp.int32),
                    axis=2,
                ),
            )
        feas = jnp.all(
            need <= cum_req[None, :, V - 1, :], axis=-1
        )                                                    # [C, N]
        posc = jnp.clip(pos, 0, V - 1)
        cost = cum_cost[jnp.arange(N)[None, :], posc]        # [C, N]
        viol = cum_viol[jnp.arange(N)[None, :], posc]        # [C, N]
        return feas, cost, viol

    feas_t, cost_t, viol_t = [], [], []
    for b in range(_PRIO_BUCKETS):
        f, c_, v_ = node_rank(thr[b])
        feas_t.append(f)
        cost_t.append(c_)
        viol_t.append(v_)
    # Optimistic (priority-unaware) lane: thr = +inf admits every
    # victim; used for spent-marking and the fallback candidate.
    feas_opt, cost_opt, viol_opt = node_rank(jnp.float32(jnp.inf))

    def pick_bucket(stacked):
        return jnp.take_along_axis(
            jnp.stack(stacked), bk[None, :, None], axis=0
        )[0]

    feas = pick_bucket(feas_t)
    cost = pick_bucket(cost_t)
    viol = pick_bucket(viol_t)
    # Fallback: bucket tables see no feasible node but the optimistic
    # one does (a bidder whose margin sits between its bucket's lower
    # bound and its own priority) — rank by the optimistic tables and
    # let exact validation arbitrate.
    use_fb = (
        ~jnp.any(ok_node & feas, axis=1)
        & jnp.any(ok_node & feas_opt, axis=1)
    )[:, None]
    feas = jnp.where(use_fb, feas_opt, feas)
    cost = jnp.where(use_fb, cost_opt, cost)
    viol = jnp.where(use_fb, viol_opt, viol)
    viol_total = jnp.where(ok_node & feas, viol, jnp.inf)
    min_viol = jnp.min(viol_total, axis=1, keepdims=True)    # [C, 1]
    total = jnp.where(
        ok_node & feas & (viol_total == min_viol), cost, jnp.inf
    )
    K = min(k_cand, N)
    neg_v, cand_i = jax.lax.top_k(-total, K)                 # [C, K]
    cand_finite = jnp.isfinite(neg_v)
    # Plain bidders carry exactly one candidate: their scored node.
    first_col = (jnp.arange(K) == 0)[None, :]                # [1, K]
    cand_i = jnp.where(
        can_plain[:, None],
        jnp.where(first_col, n_plain[:, None], 0), cand_i,
    )
    cand_finite = jnp.where(can_plain[:, None], first_col, cand_finite)

    # Each iteration DEALS bidders across their candidate lists: the
    # bidder with active-rank r (its position, in rank order, among
    # bidders still unclaimed) bids its (r mod #available)-th cheapest
    # untaken candidate, and the lowest-rank bidder per node wins
    # (scatter-min). When candidate lists coincide — the load-balanced
    # cluster's common case, every bidder pricing the same cheap
    # victim prefixes — the deal hands out DISTINCT nodes and one
    # iteration claims min(C, K) nodes at once, reproducing the old
    # rank-ordered scan's assignment without its C sequential steps
    # (greedy per-iteration variants herded onto the shared-cheapest
    # node and claimed ~one node per iteration). Diverging lists cause
    # collisions; losers re-deal next iteration over the remaining
    # nodes.
    cand_c = jnp.clip(cand_i, 0, N - 1)

    def claim_it(state, _):
        taken, target, claimed = state
        avail = (
            cand_finite & ~taken[cand_c] & ~claimed[:, None]
        )                                                    # [C, K]
        csum = jnp.cumsum(avail.astype(jnp.int32), axis=1)
        navail = csum[:, -1]
        has = ~claimed & (navail > 0)
        r_active = jnp.cumsum(has.astype(jnp.int32)) - 1     # [C]
        tgt_cnt = jnp.mod(r_active, jnp.maximum(navail, 1)) + 1
        # Position of the tgt_cnt-th available candidate: csum is a
        # monotone int prefix count, so the index is just how many
        # prefix counts fall short — a [C, K] compare+reduce (a vmapped
        # searchsorted lowered to 512 tiny serial searches and cost
        # ~1 ms/iteration here).
        j = jnp.sum((csum < tgt_cnt[:, None]).astype(jnp.int32), axis=1)
        j = jnp.clip(j, 0, K - 1)
        want = cand_i[jnp.arange(C), j]
        want_c = jnp.clip(want, 0, N - 1)
        key = jnp.where(has, rank, BIG)
        best = jnp.full(N, BIG, jnp.int32).at[want_c].min(key)
        winner = has & (best[want_c] == rank)
        target = jnp.where(winner, want, target).astype(jnp.int32)
        claimed = claimed | winner
        taken = taken.at[want_c].max(winner)
        return (taken, target, claimed), None

    (_, target, claimed), _ = jax.lax.scan(
        claim_it,
        (jnp.zeros(N, bool), jnp.full(C, -1, jnp.int32),
         jnp.zeros(C, bool)),
        None, length=claim_iters,
    )
    # -- stage 4: EXACT [C, V] validation on the claimed node ---------------
    # True-priority eligibility, V-length prefix sums, first-feasible
    # prefix selection — preempt_step's selection rule restricted to
    # one node per bidder (prefix cost/viol/freed are nondecreasing and
    # fits is monotone in prefix length, so first-feasible IS the
    # lexicographic (viol, cost) minimum and no per-prefix violation
    # pass is needed). Everything downstream is [C, V]-sized off the
    # node-major table — no [C, N, V] or [C, M] materialization.
    tgt = jnp.clip(target, 0, N - 1)
    vvalid_x = ctx.vvalid[tgt]                               # [C, V]
    ev_x = evicted[jnp.clip(ctx.vidx[tgt], 0, M - 1)] & vvalid_x
    elig_x = vvalid_x & ~ev_x & (
        ctx.vprio[tgt] + cfg.qos.preemption_margin < p_prio[:, None]
    )                                                        # [C, V]
    wreq_x = jnp.cumsum(  # tpl: disable=TPL201(exact validation prefix at the FIXED V=16 victim cap, same op order as the sequential _tableau the oracle mirrors — parity-pinned; V is a compile-time constant, never padded)
        jnp.where(elig_x[..., None], ctx.vreq[tgt], 0.0), axis=1
    )                                                        # [C, V, R]
    fits_x = elig_x & jnp.all(
        used[tgt][:, None, :] - wreq_x + p_req[:, None, :]
        <= nodes.allocatable[tgt][:, None, :],
        axis=-1,
    )                                                        # [C, V]
    feas_x = jnp.any(fits_x, axis=1)
    # A claim whose exact check fails (reachable only through the
    # optimistic fallback lane — bucket-table feasibility is a sound
    # subset) is RELEASED.
    released = claimed & ~can_plain & ~feas_x
    claimed = claimed & (can_plain | feas_x)
    target = jnp.where(claimed, target, -1)
    takes_evict = claimed & ~can_plain
    best_pos = jnp.argmax(fits_x, axis=1).astype(jnp.int32)  # first feasible
    sel_v = (
        takes_evict[:, None] & elig_x
        & (jnp.arange(V, dtype=jnp.int32)[None, :] <= best_pos[:, None])
    )
    vidx_t = jnp.where(sel_v, ctx.vidx[tgt], M)              # [C, V]
    freed_req = jnp.sum(  # tpl: disable=TPL202(sum over the fixed V=16 victim cap — a compile-time constant axis, not the compacted pod axis; matches the capacity math of the sequential path)
        jnp.where(sel_v[..., None], ctx.vreq[tgt], 0.0), axis=1
    )                                                        # [C, R]
    if GP:
        vpdb_t = ctx.vpdb[tgt]                               # [C, V]
        usage = jnp.zeros((C, GP), jnp.float32).at[
            jnp.arange(C)[:, None], jnp.clip(vpdb_t, 0, None)
        ].add((sel_v & (vpdb_t >= 0)).astype(jnp.float32))
    else:
        usage = jnp.zeros((C, 0), jnp.float32)
    # Spent-marking uses the OPTIMISTIC answer — a pod the bucket
    # approximation under-serves is a deferral, not a retirement —
    # EXCEPT for a released fallback claim: that bidder's best
    # optimistic node just failed the exact check, and keeping it
    # could_bid would let phantom bidders occupy the C slots round
    # after round (claiming and releasing a node each time) while pods
    # ranked beyond C are never examined. Marking it tried retires it
    # for now; any later keep resets `tried` in _preempt_rounds, so it
    # re-bids as soon as evictions actually change the state.
    could_bid = can_plain | (
        jnp.any(ok_node & feas_opt, axis=1) & ~released
    )
    return target, claimed, takes_evict, vidx_t, freed_req, usage, could_bid
