"""Preemption (PostFilter) kernel (SURVEY.md C9, §3.4).

The reference scheduler's signature behavior: a pod with no feasible
node searches for nodes where evicting lower-priority victims makes it
fit, choosing the minimum-cost victim set, with eviction cost driven by
the victims' QoS slack (pods running above their SLO are cheap to evict;
see qos.evict_cost_raw and QoSConfig).

TPU formulation: victims are sorted ONCE per snapshot by (node, cost)
(PreemptCtx). A preemptor's step is then a masked segment-prefix scan —
eligible victims' cumulative requests within each node's segment — and
the cheapest feasible prefix per node falls out of the FIRST position
where the preemptor fits (costs ascend within a segment, so the first
feasible prefix is the min-cost one). A scatter-min over segments yields
per-node best costs; argmin picks the node. Everything is fixed-shape
[M]/[N] arithmetic — no Hungarian augmenting paths, no data-dependent
loops (the auction-style "bid per node, pick globally best" recommended
over classical Hungarian by SURVEY.md §7 hard part 4).

PodDisruptionBudgets (SURVEY.md C9 "fewest PDB violations"): each
running pod may belong to a budget (running.pdb_group) with a remaining
disruptions_allowed (snapshot.pdb_allowed). A victim whose eviction
would exceed its budget's remaining allowance — counting earlier
preemptors' evictions AND same-prefix co-victims — is a VIOLATION.
Candidate prefixes are ranked lexicographically by (violation count,
cost), exactly upstream's ordering: any non-violating set beats any
violating one, and violation stays available as the last resort
(upstream evicts PDB-protected pods when nothing else fits). Violation
counts are small integers (exact in f32 under any summation order), so
oracle/device parity survives; a cost PENALTY of ~1e8 would instead
poison the f32 prefix sums, whose rounding depends on the backend's
scan association. With violations in play, costs within a segment no
longer rank prefixes, so the chosen prefix is the lexicographic MIN
over all feasible prefix positions, not the first feasible.

Scope notes (mirrored exactly by the oracle so parity is testable):
  * Only RESOURCE infeasibility is repaired: the preemptor's static
    predicates (taints/affinity) and pairwise constraints must already
    hold on the target node, evaluated against pre-eviction state.
  * The preemptor is assigned immediately (the host shim issues deletes
    then binds; upstream nominates and re-queues instead).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from tpusched.config import EngineConfig
from tpusched.kernels import pairwise as kpair
from tpusched.qos import evict_cost_raw, victim_effective_priority
from tpusched.snapshot import ClusterSnapshot


@struct.dataclass
class PreemptCtx:
    """Snapshot-static victim ordering and costs."""

    perm: Any        # [M] int32: running pods sorted by (node, cost)
    node_s: Any      # [M] int32 node of sorted victim (N = invalid sentinel)
    seg_start: Any   # [M] int32 index where this node's segment begins
    cost_s: Any      # [M] f32 shifted-positive eviction cost, sorted
    vprio_s: Any     # [M] f32 victim effective priority, sorted
    req_s: Any       # [M, R] f32 victim requests, sorted
    pdb_s: Any       # [M] int32 PDB id of sorted victim (-1 none)


def precompute(cfg: EngineConfig, snap: ClusterSnapshot) -> PreemptCtx:
    run = snap.running
    M = run.valid.shape[0]
    N = snap.nodes.valid.shape[0]
    vprio = victim_effective_priority(cfg, run.priority, run.slack)
    raw = evict_cost_raw(cfg, run.priority, run.slack).astype(jnp.float32)
    # Shift costs positive (+1 per victim): prefix sums then strictly
    # increase, making "first feasible prefix = cheapest" hold and
    # encoding the fewer-victims preference (upstream tie-break).
    mn = jnp.min(jnp.where(run.valid, raw, jnp.inf))
    mn = jnp.where(jnp.isfinite(mn), mn, 0.0)
    cost = raw - mn + 1.0
    node_m = jnp.where(run.valid & (run.node_idx >= 0), run.node_idx, N)
    perm = jnp.lexsort((cost, node_m))
    node_s = node_m[perm]
    idx = jnp.arange(M, dtype=jnp.int32)
    if M:
        boundary = jnp.concatenate(
            [jnp.ones(1, bool), node_s[1:] != node_s[:-1]]
        )
        seg_start = jax.lax.cummax(jnp.where(boundary, idx, 0))
    else:
        seg_start = idx
    return PreemptCtx(
        perm=perm, node_s=node_s, seg_start=seg_start,
        cost_s=cost[perm], vprio_s=vprio[perm].astype(jnp.float32),
        req_s=run.requests[perm],
        pdb_s=run.pdb_group[perm],
    )


def _tableau(cfg: EngineConfig, snap: ClusterSnapshot, ctx: PreemptCtx,
             p_prio, p_req, used, evicted):
    """One preemptor's victim-prefix tableau: everything preempt_step
    derives before node selection. Shared verbatim by the sequential
    step and the batched auction (preempt_auction) so their per-node
    rankings agree exactly. Returns
    (elig [M], within_cost [M], within_viol [M], fits [M],
    node_viol [N], node_cost [N])."""
    nodes = snap.nodes
    M = ctx.perm.shape[0]
    N = nodes.valid.shape[0]
    idx = jnp.arange(M, dtype=jnp.int32)

    elig = (
        (ctx.node_s < N)
        & ~evicted[ctx.perm]
        & (ctx.vprio_s + cfg.qos.preemption_margin < p_prio)
    )
    # PDB violations (see module docstring): a victim violates if the
    # same-budget count within its node-segment prefix (including
    # itself) plus earlier preemptors' evictions exceeds the budget.
    GP = snap.pdb_allowed.shape[0]
    if GP:
        pdb_clip = jnp.clip(ctx.pdb_s, 0, None)
        has_pdb = ctx.pdb_s >= 0
        run_pdb = snap.running.pdb_group
        consumed = jnp.zeros(GP, jnp.float32).at[
            jnp.clip(run_pdb, 0, None)
        ].add((evicted & (run_pdb >= 0) & snap.running.valid).astype(
            jnp.float32
        ))
        remaining = snap.pdb_allowed - consumed              # [GP]
        gsel = (
            (jnp.arange(GP)[:, None] == pdb_clip[None, :])
            & (elig & has_pdb)[None, :]
        )                                                    # [GP, M]
        cum_g = jnp.cumsum(gsel.astype(jnp.float32), axis=1)
        my_cum = cum_g[pdb_clip, idx]                        # [M] incl. self
        off_g = jnp.where(
            ctx.seg_start > 0,
            cum_g[pdb_clip, jnp.clip(ctx.seg_start - 1, 0, None)], 0.0,
        )
        within_cnt = my_cum - off_g
        viol = elig & has_pdb & (within_cnt > remaining[pdb_clip])
    else:
        viol = jnp.zeros(M, bool)
    req_m = jnp.where(elig[:, None], ctx.req_s, 0.0)
    cum_req = jnp.cumsum(req_m, axis=0)                      # [M, R] inclusive
    cum_cost = jnp.cumsum(jnp.where(elig, ctx.cost_s, 0.0))  # [M]
    # Violation count per prefix: 0/1 sums are exact in f32 under any
    # summation order (<= M < 2^24), unlike penalty-inflated cost sums.
    cum_viol = jnp.cumsum(viol.astype(jnp.float32))          # [M]
    off_req = jnp.where(
        (ctx.seg_start > 0)[:, None],
        cum_req[jnp.clip(ctx.seg_start - 1, 0, None)], 0.0,
    )
    off_cost = jnp.where(
        ctx.seg_start > 0, cum_cost[jnp.clip(ctx.seg_start - 1, 0, None)], 0.0
    )
    off_viol = jnp.where(
        ctx.seg_start > 0, cum_viol[jnp.clip(ctx.seg_start - 1, 0, None)], 0.0
    )
    within_req = cum_req - off_req                           # [M, R]
    within_cost = cum_cost - off_cost                        # [M]
    within_viol = cum_viol - off_viol                        # [M]
    cap_node = jnp.clip(ctx.node_s, 0, N - 1)
    fits = elig & jnp.all(
        used[cap_node] - within_req + p_req[None, :]
        <= nodes.allocatable[cap_node],
        axis=-1,
    )
    # Lexicographic (violations, cost) MIN over feasible prefixes, in
    # exact two-stage comparisons (never summing the two channels):
    # per node, fewest violations first; among those prefixes, min cost.
    # N index = sentinel bucket.
    node_viol = jnp.full(N + 1, jnp.inf).at[ctx.node_s].min(
        jnp.where(fits, within_viol, jnp.inf)
    )[:N]
    fits_v = fits & (within_viol == node_viol[cap_node])
    node_cost = jnp.full(N + 1, jnp.inf).at[ctx.node_s].min(
        jnp.where(fits_v, within_cost, jnp.inf)
    )[:N]
    return elig, within_cost, within_viol, fits, node_viol, node_cost


def preempt_step(cfg: EngineConfig, snap: ClusterSnapshot, ctx: PreemptCtx,
                 p_prio, p_req, allowed_row, used, evicted):
    """One preemptor's victim search. Returns
    (best_n, can, evict_m, freed) — chosen node (int32), whether
    preemption succeeds (bool), the [M] eviction mask, and the [N, R]
    capacity freed on the chosen node (zeros elsewhere)."""
    nodes = snap.nodes
    M = ctx.perm.shape[0]
    idx = jnp.arange(M, dtype=jnp.int32)
    elig, within_cost, within_viol, fits, node_viol, node_cost = _tableau(
        cfg, snap, ctx, p_prio, p_req, used, evicted
    )
    # Across nodes: global fewest violations, then cheapest. (inf ==
    # inf is True, so the allowed mask must gate `total` as well —
    # otherwise a disallowed node's finite prefix wins when NO allowed
    # node is feasible.)
    ok_node = allowed_row & nodes.valid
    viol_total = jnp.where(ok_node, node_viol, jnp.inf)
    min_viol = jnp.min(viol_total)
    total = jnp.where(ok_node & (viol_total == min_viol), node_cost, jnp.inf)
    best_n = jnp.argmin(total).astype(jnp.int32)
    can = jnp.isfinite(total[best_n])
    best_pos = jnp.argmin(
        jnp.where(
            fits & (ctx.node_s == best_n) & (within_viol == min_viol),
            within_cost, jnp.inf,
        )
    ).astype(jnp.int32)
    sel_s = can & (ctx.node_s == best_n) & elig & (idx <= best_pos)
    evict_m = jnp.zeros(M, bool).at[ctx.perm].set(sel_s)
    freed_on_best = jnp.sum(
        jnp.where(sel_s[:, None], ctx.req_s, 0.0), axis=0
    )                                                        # [R]
    freed = jnp.zeros_like(used).at[best_n].add(
        jnp.where(can, freed_on_best, 0.0)
    )
    return best_n, can, evict_m, freed


def preempt_auction(cfg: EngineConfig, snap: ClusterSnapshot,
                    ctx: PreemptCtx, p_prio, p_req, allowed,
                    used, evicted, can_plain, n_plain,
                    k_cand: int = 64):
    """Batched bidding for C preemptors at once (the fast mode's
    auction round; SURVEY.md §7 hard part 4 — parallel bids, global
    resolution). Every bidder computes its full per-node tableau
    (vmapped _tableau — the prefix sums batch into [C, M] matrix work),
    then a rank-ordered scan with an O(N) carry assigns each bidder its
    cheapest still-unclaimed candidate node: one claimant per node, no
    two same-round victim sets can overlap (victims are node-local).
    The sequential scan would give every bidder the GLOBALLY cheapest
    node — and one keep per round; taking the i-th bidder's best
    still-free node instead trades a slightly costlier victim set for
    ~C-way parallelism, the same deal the capacity dealer makes for
    placement. Plain placements (can_plain, from the caller's
    feasibility re-check) claim their scored node through the same
    scan.

    p_prio/p_req/allowed/can_plain/n_plain: [C]/[C,R]/[C,N]/[C]/[C] in
    descending rank order; inactive bidders must arrive with allowed
    all-False and can_plain False. Returns (target [C] int32 (-1 =
    no claim), claimed [C] bool, takes_evict [C] bool,
    evict_m [C, M] bool, could_bid [C] bool — False means the pod has
    NO placement or victim prefix at all (spent), as opposed to losing
    this round's node race (retry))."""
    nodes = snap.nodes
    N = nodes.valid.shape[0]
    M = ctx.perm.shape[0]
    C = p_prio.shape[0]
    elig, within_cost, within_viol, fits, node_viol, node_cost = jax.vmap(
        lambda pp, pr: _tableau(cfg, snap, ctx, pp, pr, used, evicted)
    )(p_prio, p_req)                                         # [C, ...]
    ok_node = allowed & nodes.valid[None, :]
    viol_total = jnp.where(ok_node, node_viol, jnp.inf)
    min_viol = jnp.min(viol_total, axis=1, keepdims=True)    # [C, 1]
    total = jnp.where(
        ok_node & (viol_total == min_viol), node_cost, jnp.inf
    )
    K = min(k_cand, N)
    neg_v, cand_i = jax.lax.top_k(-total, K)                 # [C, K]
    cand_finite = jnp.isfinite(neg_v)

    def nstep(taken, i):
        pl = can_plain[i]
        cands = cand_i[i]
        cok = cand_finite[i] & ~taken[cands]
        j = jnp.argmax(cok)
        pre_ok = jnp.any(cok) & ~pl
        t = jnp.where(pl, n_plain[i], cands[j]).astype(jnp.int32)
        ok = jnp.where(pl, ~taken[jnp.clip(n_plain[i], 0, N - 1)], pre_ok)
        taken = taken.at[jnp.clip(t, 0, N - 1)].set(
            taken[jnp.clip(t, 0, N - 1)] | ok
        )
        return taken, (t, ok)

    _, (target, claimed) = jax.lax.scan(
        nstep, jnp.zeros(N, bool), jnp.arange(C)
    )
    takes_evict = claimed & ~can_plain
    # Victim prefix of each bidder's CLAIMED node (same lexicographic
    # rule as preempt_step: min-viol prefixes, then cheapest; the
    # claimed node's viol equals the bidder's min_viol by construction).
    tgt = jnp.clip(target, 0, N - 1)
    in_node = ctx.node_s[None, :] == tgt[:, None]            # [C, M]
    best_pos = jnp.argmin(
        jnp.where(
            fits & in_node & (within_viol == min_viol),
            within_cost, jnp.inf,
        ),
        axis=1,
    ).astype(jnp.int32)                                      # [C]
    idx = jnp.arange(M, dtype=jnp.int32)
    sel_s = (
        takes_evict[:, None] & in_node & elig
        & (idx[None, :] <= best_pos[:, None])
    )
    evict_m = jnp.zeros((C, M), bool).at[:, ctx.perm].set(sel_s)
    could_bid = can_plain | jnp.any(jnp.isfinite(total), axis=1)
    return target, claimed, takes_evict, evict_m, could_bid
