"""Device-resident pending-queue kernels (ISSUE 20 tentpole part 1).

The paper's dynamic-priority queue — priority = distance to the
availability SLO, recomputed as observed availability decays — used to
live on the host: every cycle re-read the whole pending set and the
selection window rode dict order. These kernels relocate that loop onto
the device: a persistent [Q] pending table (struct-of-arrays, pow2
capacity) holds each waiting pod's QoS terms, and `rank_window` re-
derives every slot's availability / pressure / effective priority
in-kernel each cycle and extracts the top-W solve window with ONE
lexicographic device sort — so per-cycle host work is O(arrivals), not
O(pending).

Ordering contract (pinned bit-for-bit by tests/test_devqueue.py against
`rank_reference`, the numpy host oracle below):

    (eligible first,  effective_priority DESC,  arrival seq ASC)

Floats don't lexicographic-sort as bits, so the priority key is the
classic monotone float32 -> uint32 embedding (`sortable_u32`: flip all
bits of negatives, set the sign bit of non-negatives), inverted for the
descending leg. The arrival sequence is a uint32 the api server stamps
at submission — the deterministic tie-break (same role as
qos.tie_hash for pop order), so two pods at identical pressure pop in
arrival order on every backend.

The availability/pressure math is qos.observed_availability /
qos.pressure_of relocated verbatim (same clip bounds, same
MIN_OBSERVED_AGE_S grace, same never-observed fallback); pending slots
have no live bind, so the `bound_at` leg is structurally zero.

Shape discipline: the table capacity Q and the window bucket kb are
both pow2 (config.Buckets style), so the jit cache stays bounded the
same way the engine's `_k_bucket` top-k does.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusched.config import DEFAULT_OBSERVED_AVAIL
from tpusched.qos import MIN_OBSERVED_AGE_S


class QueueTable(NamedTuple):
    """The [Q] device pending table. Times are float32 seconds RELATIVE
    to the owning DeviceQueue's epoch (wall epochs don't fit f32);
    `parked_until` is the backoff mask bit in time form — a slot is
    eligible iff valid and parked_until <= now."""

    valid: jax.Array          # bool[Q]   slot occupied
    base_priority: jax.Array  # f32[Q]    static pod.spec priority
    slo_target: jax.Array     # f32[Q]    availability SLO
    submitted: jax.Array      # f32[Q]    submit time (epoch-relative)
    run_seconds: jax.Array    # f32[Q]    banked run time across requeues
    parked_until: jax.Array   # f32[Q]    backoff parking; 0 = eligible
    tenant: jax.Array         # i32[Q]    ingest tenant id
    seq: jax.Array            # u32[Q]    arrival sequence (tie-break)


N_FIELDS = len(QueueTable._fields)


def k_bucket(k: int, n: int) -> int:
    """Pow2 compile bucket for a window of k out of n slots, clamped to
    n — the engine's `_k_bucket` discipline, shared here so the queue
    window and the score top-k bucket identically."""
    kb = 1 << (max(int(k), 1) - 1).bit_length()
    return min(kb, int(n))


def empty_table(capacity: int) -> QueueTable:
    """Host-side (numpy) empty table; callers device_put it."""
    q = int(capacity)
    return QueueTable(
        valid=np.zeros(q, bool),
        base_priority=np.zeros(q, np.float32),
        slo_target=np.zeros(q, np.float32),
        submitted=np.zeros(q, np.float32),
        run_seconds=np.zeros(q, np.float32),
        parked_until=np.zeros(q, np.float32),
        tenant=np.zeros(q, np.int32),
        seq=np.zeros(q, np.uint32),
    )


def sortable_u32(prio):
    """Monotone float32 -> uint32 key embedding: a < b in float order
    iff sortable_u32(a) < sortable_u32(b) in unsigned order (finite
    inputs; priorities are finite by construction). Works on jnp and np
    arrays alike — the same pure-uint32 polymorphism as qos.tie_hash,
    so the host reference and the kernel share one definition."""
    xp = jnp if isinstance(prio, jax.Array) else np
    if xp is jnp:
        u = jax.lax.bitcast_convert_type(prio, jnp.uint32)
    else:
        u = np.ascontiguousarray(prio, dtype=np.float32).view(np.uint32)
    sign = xp.uint32(0x80000000)
    return xp.where(u >= sign, ~u, u | sign)


def _rank(table: QueueTable, now, qos_gain):
    """Shared ranking body: per-slot availability-decay priority plus
    the three lexicographic sort keys. `now`/`qos_gain` are traced f32
    scalars (no recompile per cycle)."""
    age = now - table.submitted
    never = age < jnp.float32(MIN_OBSERVED_AGE_S)
    # Reduction site: run/age clamp; never-observed slots take the
    # DEFAULT_OBSERVED_AVAIL grace exactly like qos.observed_availability
    # (the where-guard keeps the dead lane's 0/0 out of the output).
    avail = jnp.where(
        never,
        jnp.float32(DEFAULT_OBSERVED_AVAIL),
        jnp.clip(table.run_seconds / jnp.where(never, jnp.float32(1.0), age),
                 0.0, 1.0),
    )
    pressure = jnp.clip(table.slo_target - avail, 0.0, 1.0)
    # XLA CPU contracts this mul+add into an FMA at the LLVM level
    # (even past an optimization_barrier — contraction happens after
    # HLO); reference_priorities emulates the same single-rounding in
    # f64, which is why the two stay bit-identical.
    prio = table.base_priority + qos_gain * pressure
    eligible = table.valid & (table.parked_until <= now)
    k_elig = jnp.where(eligible, jnp.uint32(0), jnp.uint32(1))
    k_prio = ~sortable_u32(prio)        # ascending sort => priority DESC
    return prio, eligible, k_elig, k_prio


@jax.jit
def rank_full(table: QueueTable, now, qos_gain):
    """Full-table pop order (parity tests, small tables): every slot's
    index in (eligible, priority desc, seq asc) order, plus the
    per-slot priorities and the depth/eligible counts."""
    prio, eligible, k_elig, k_prio = _rank(table, now, qos_gain)
    idx = jnp.arange(table.valid.shape[0], dtype=jnp.int32)
    _, _, _, order = jax.lax.sort(
        (k_elig, k_prio, table.seq, idx), num_keys=3)
    n_eligible = jnp.sum(eligible.astype(jnp.int32))
    depth = jnp.sum(table.valid.astype(jnp.int32))
    return order, prio, n_eligible, depth


def window_select(table: QueueTable, now, qos_gain, kb: int):
    """Top-kb solve window on device: one lexicographic sort over the
    [Q] table, sliced to the pow2 window bucket BEFORE leaving the
    device — the host transfers O(kb) indices, never the table. The
    kb-prefix of the full ranking IS the top-kb (total order), so
    bucketed windows share compiles the way the engine's bucketed
    top-k does. Returns (idx[kb], prio[kb], n_eligible, depth)."""
    return _window_static(kb)(table, jnp.float32(now),
                              jnp.float32(qos_gain))


_WINDOW_CACHE: dict = {}


def _pow2_bucket(kb: int) -> int:
    """Idempotent pow2 round-up: callers already pass k_bucket values,
    but re-deriving the memo key here makes the compile-set bound
    (log2(Q) entries max) local to the cache it protects."""
    return 1 << (max(int(kb), 1) - 1).bit_length()


def _window_static(kb: int):
    kb = _pow2_bucket(kb)
    fn = _WINDOW_CACHE.get(kb)
    if fn is None:
        fn = jax.jit(lambda t, now, g, _kb=kb: _window_body(t, now, g, _kb))
        _WINDOW_CACHE[kb] = fn
    return fn


def _window_body(table: QueueTable, now, qos_gain, kb: int):
    prio, eligible, k_elig, k_prio = _rank(table, now, qos_gain)
    idx = jnp.arange(table.valid.shape[0], dtype=jnp.int32)
    _, _, _, order = jax.lax.sort(
        (k_elig, k_prio, table.seq, idx), num_keys=3)
    win = jax.lax.slice_in_dim(order, 0, kb)
    n_eligible = jnp.sum(eligible.astype(jnp.int32))
    depth = jnp.sum(table.valid.astype(jnp.int32))
    return win, prio[win], n_eligible, depth


# ---------------------------------------------------------------------------
# Host oracle — the "host-sorted reference" the parity tests (and the
# bench's host-sorted baseline arm) compare against, numpy end to end.
# ---------------------------------------------------------------------------


def reference_priorities(table: QueueTable, now: float,
                         qos_gain: float) -> np.ndarray:
    """Numpy twin of the in-kernel priority recompute, float32 op for
    op (divide, clip, multiply, add in the same order) so the sortable
    keys match the device bit-for-bit."""
    submitted = np.asarray(table.submitted, np.float32)
    run = np.asarray(table.run_seconds, np.float32)
    slo = np.asarray(table.slo_target, np.float32)
    base = np.asarray(table.base_priority, np.float32)
    age = np.float32(now) - submitted
    never = age < np.float32(MIN_OBSERVED_AGE_S)
    avail = np.where(
        never,
        np.float32(DEFAULT_OBSERVED_AVAIL),
        np.clip(run / np.where(never, np.float32(1.0), age),
                np.float32(0.0), np.float32(1.0)),
    ).astype(np.float32)
    pressure = np.clip(slo - avail, np.float32(0.0),
                       np.float32(1.0)).astype(np.float32)
    # FMA emulation: the product of two f32s is exact in f64, so
    # f64(base) + f64(gain)*f64(pressure) rounded once to f32 is the
    # fused mul-add XLA CPU actually emits (see _rank).
    fused = (base.astype(np.float64)
             + np.float64(qos_gain) * pressure.astype(np.float64))
    return fused.astype(np.float32)


def rank_reference(table: QueueTable, now: float, qos_gain: float):
    """Full host-sorted ranking under the identical ordering contract:
    np.lexsort (stable, last key primary) over the same three keys.
    Returns (order[Q], prio[Q], n_eligible, depth)."""
    prio = reference_priorities(table, now, qos_gain)
    valid = np.asarray(table.valid, bool)
    eligible = valid & (np.asarray(table.parked_until, np.float32)
                        <= np.float32(now))
    k_elig = np.where(eligible, np.uint32(0), np.uint32(1))
    k_prio = ~sortable_u32(prio)
    seq = np.asarray(table.seq, np.uint32)
    order = np.lexsort((seq, k_prio, k_elig)).astype(np.int32)
    return order, prio, int(eligible.sum()), int(valid.sum())
