"""Commit loops (SURVEY.md C11) and the batched score matrix.

The scheduling cycle (Filter + Score + Normalize; the device analogue of
the reference's `scheduleOne` body, SURVEY.md §3.1) splits into:

  * a STATIC part (taints, node affinity, their scores, per-pod QoS
    plugin weights, signature label-match tables) computed once per
    snapshot — StaticCtx;
  * a DYNAMIC part (resource fit, LeastRequested, BalancedAllocation,
    pairwise terms from domain counts) that depends on node `used` and
    the [S, N] signature counts.

Three drivers:
  * solve_sequential — EXACT stock semantics (parity mode): lax.scan
    over pods in dynamic-priority order; each step updates `used` and
    the domain counts before the next pod scores.
  * solve_rounds — fast mode: optimistic batched rounds. Every pending
    pod scores against round-start state; commits are resolved per node
    by a priority-ordered capacity prefix scan; committed pods with
    pairwise constraints are re-validated against end-of-round counts
    (self-excluded) and violators are rolled back and marked
    "conservative" — a conservative pod only commits in a round where it
    is the globally highest-priority pending pod, which makes its check
    state exactly sequential. Terminates when a round makes no progress.
    Matches sequential placements whenever pods' decisions don't interact
    (the common case); under contention it stays *valid* (capacity
    respected; pairwise constraints hold against commit-time state) but
    may order contended pods differently (SURVEY.md §7 hard parts 1/3).
  * score_batch — the ScoreBatch API of the north star: all pods scored
    at once, no commits — what a Go scheduler calls through the gRPC
    boundary for NormalizeScore + Bind.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from tpusched.config import DO_NOT_SCHEDULE, EngineConfig
from tpusched.kernels.atoms import atom_sat, gather_term_sat
from tpusched.kernels import filter as kfilter
from tpusched.kernels import pairwise as kpair
from tpusched.kernels import preempt as kpreempt
from tpusched.kernels import score as kscore
from tpusched.qos import (
    effective_priority,
    effective_weights,
    pressure_of,
    tie_hash,
)
from tpusched.snapshot import ClusterSnapshot

NEG_INF = -jnp.inf

# Per-round commit/revert tracing via jax.debug.print. Read at TRACE
# time: set it before the Engine's first solve at a given shape — an
# already-compiled executable keeps whatever the flag was when traced.
import os as _os_mod

_DEBUG_ROUNDS = bool(_os_mod.environ.get("TPUSCHED_DEBUG_ROUNDS"))


@struct.dataclass
class StaticCtx:
    """Snapshot-dependent but state-independent precomputation."""

    mask: Any       # [P, N] bool: taints & node affinity & validity
    aff_ok: Any     # [P, N] bool: node-affinity component alone (spread
                    # domain eligibility honors it)
    score: Any      # [P, N] f32: w_na*NodeAffinity + w_tt*TaintToleration
    sig_match: Any  # [S, M+P] bool: signature selector label matches
    w_lr: Any       # [P] f32 per-pod effective plugin weights (QoS)
    w_ba: Any       # [P]
    w_ts: Any       # [P]
    w_ia: Any       # [P]
    rw: Any         # [R] resource score weights


@struct.dataclass
class WarmTableau:
    """The carried warm-start tableau (ROADMAP item 3): every CELL-LOCAL
    static table of the Filter/Score program, resident on device across
    delta cycles inside a lineage. "Cell-local" means cell (p, n) depends
    only on pod p's row, node n's row, and the (vocab-stable) atom/sig
    tables — so a delta cycle can recompute exactly the dirty rows and
    columns and scatter-merge them (refresh_tableau), and the result is
    the same table a from-scratch build would produce. Everything with
    cross-row coupling (per-pod score normalization, QoS weights, pop
    order, pair-state counts) is deliberately EXCLUDED and recomputed
    fresh each solve by finalize_static / the solve drivers — that is
    what makes warm placements bitwise-equal to cold ones.

    Access discipline (tpuschedlint TPL011): the tableau is only valid
    straight after the engine warm path refreshed it against the current
    snapshot; reads outside engine.py / device_state.py / this module
    are the stale-tableau hazard class."""

    node_sat_t: Any    # [A, N] bool  atom satisfaction over node labels
    member_sat_t: Any  # [A, M+P] bool  over member (running|pending) labels
    sig_match: Any     # [S, M+P] bool  signature selector x member
    mask: Any          # [P, N] bool  static feasibility (taints/affinity/cordon)
    aff_ok: Any        # [P, N] bool  node-affinity component alone
    na_raw: Any        # [P, N] f32  pre-normalize preferred-affinity sums
    tt_count: Any      # [P, N] f32  intolerable PreferNoSchedule taint counts


def _tableau_cells(snap: ClusterSnapshot, pods_v, nodes_v, node_sat_v):
    """The cell-local tableau block for any (pods view, nodes view)
    pair: full build passes the whole snapshot, refresh passes gathered
    dirty rows/columns. One shared body so a refreshed cell runs the
    exact op sequence the full build ran (bool ops are exact; the f32
    sums reduce over identical per-cell extents)."""
    aff_ok = kfilter.node_affinity_mask(
        node_sat_v, pods_v.req_term_atoms, pods_v.req_term_valid
    )
    # Cordon (NodeUnschedulable plugin): closed to new pods UNLESS the
    # pod tolerates node.kubernetes.io/unschedulable (DaemonSet pattern).
    cordon_ok = (
        nodes_v.schedulable[None, :] | pods_v.tolerates_unsched[:, None]
    )
    mask = (
        aff_ok
        & kfilter.taint_mask(nodes_v.taint_ids, snap.taint_effect,
                             pods_v.tolerated)
        & nodes_v.valid[None, :]
        & cordon_ok
        & pods_v.valid[:, None]
    )
    na_raw = kscore.node_affinity_raw(
        node_sat_v, pods_v.pref_term_atoms, pods_v.pref_term_valid,
        pods_v.pref_weight,
    )
    tt_count = kscore.taint_intolerable_count(
        nodes_v.taint_ids, snap.taint_effect, pods_v.tolerated
    )
    return mask, aff_ok, na_raw, tt_count


def build_tableau(cfg: EngineConfig, snap: ClusterSnapshot,
                  node_sat_t, member_sat_t, mesh=None) -> WarmTableau:
    """Full (cold) tableau build from the snapshot's sat tables."""
    mask, aff_ok, na_raw, tt_count = _tableau_cells(
        snap, snap.pods, snap.nodes, node_sat_t
    )
    return WarmTableau(
        node_sat_t=node_sat_t, member_sat_t=member_sat_t,
        sig_match=kpair.sig_member_match(snap, member_sat_t, mesh),
        mask=mask, aff_ok=aff_ok, na_raw=na_raw, tt_count=tt_count,
    )


def refresh_tableau(cfg: EngineConfig, snap: ClusterSnapshot,
                    tab: WarmTableau, dirty_pods=None, dirty_nodes=None,
                    dirty_members=None, pod_perm=None, node_perm=None,
                    member_perm=None, mesh=None) -> WarmTableau:
    """O(churn) tableau maintenance: reorder gathers (when record
    insertion/removal shifted the name-sorted row order — exactly the
    permutations device_state applies to the snapshot arrays), then
    recompute and scatter-merge the dirty pod ROWS, node COLUMNS, and
    member columns. Order matters: node sat rows first (the pod-row and
    node-column recomputes read them), then rows, then columns; an
    overlapping (dirty pod, dirty node) cell is written twice with the
    same fresh value. Dirty index arrays may carry repeated indices
    (pow2 padding) — duplicate scatters write identical content.

    Vocabulary growth (new atoms/sigs/taints/topo keys) is NOT
    expressible here — those change rows this function never touches —
    and must force a cold rebuild; device_state.warm_delta() is the
    gatekeeper."""
    nst, mst, sm = tab.node_sat_t, tab.member_sat_t, tab.sig_match
    mask, aff_ok = tab.mask, tab.aff_ok
    na_raw, ttc = tab.na_raw, tab.tt_count
    if node_perm is not None:
        nst = nst[:, node_perm]
        mask = mask[:, node_perm]
        aff_ok = aff_ok[:, node_perm]
        na_raw = na_raw[:, node_perm]
        ttc = ttc[:, node_perm]
    if pod_perm is not None:
        mask = mask[pod_perm]
        aff_ok = aff_ok[pod_perm]
        na_raw = na_raw[pod_perm]
        ttc = ttc[pod_perm]
    if member_perm is not None:
        mst = mst[:, member_perm]
        sm = sm[:, member_perm]
    if dirty_nodes is not None:
        nv = jax.tree.map(lambda a: a[dirty_nodes], snap.nodes)
        sat_rows = atom_sat(snap.atoms, nv.label_pairs, nv.label_keys,
                            nv.label_nums)                   # [D, A]
        nst = nst.at[:, dirty_nodes].set(sat_rows.T)
    if dirty_members is not None:
        lp = kpair.merge_members(
            snap.running.label_pairs, snap.pods.label_pairs, mesh
        )[dirty_members]
        lk = kpair.merge_members(
            snap.running.label_keys, snap.pods.label_keys, mesh
        )[dirty_members]
        mns = kpair.merge_members(
            snap.running.namespace, snap.pods.namespace, mesh
        )[dirty_members]
        sat_cols = atom_sat(snap.atoms, lp, lk, None).T      # [A, D]
        mst = mst.at[:, dirty_members].set(sat_cols)
        match = gather_term_sat(sat_cols, snap.sigs.atoms)   # [S, D]
        ns_ok = kpair.ns_scope_ok(snap.sigs.ns, snap.sigs.ns_all, mns)
        sm = sm.at[:, dirty_members].set(
            match & ns_ok & snap.sigs.valid[:, None]
        )
    if dirty_pods is not None:
        pv = jax.tree.map(lambda a: a[dirty_pods], snap.pods)
        m_r, a_r, n_r, t_r = _tableau_cells(snap, pv, snap.nodes, nst)
        mask = mask.at[dirty_pods].set(m_r)
        aff_ok = aff_ok.at[dirty_pods].set(a_r)
        na_raw = na_raw.at[dirty_pods].set(n_r)
        ttc = ttc.at[dirty_pods].set(t_r)
    if dirty_nodes is not None:
        nv = jax.tree.map(lambda a: a[dirty_nodes], snap.nodes)
        m_c, a_c, n_c, t_c = _tableau_cells(
            snap, snap.pods, nv, nst[:, dirty_nodes]
        )
        mask = mask.at[:, dirty_nodes].set(m_c)
        aff_ok = aff_ok.at[:, dirty_nodes].set(a_c)
        na_raw = na_raw.at[:, dirty_nodes].set(n_c)
        ttc = ttc.at[:, dirty_nodes].set(t_c)
    return WarmTableau(node_sat_t=nst, member_sat_t=mst, sig_match=sm,
                       mask=mask, aff_ok=aff_ok, na_raw=na_raw,
                       tt_count=ttc)


def finalize_static(cfg: EngineConfig, snap: ClusterSnapshot,
                    tab: WarmTableau) -> StaticCtx:
    """StaticCtx from a (fresh or carried) tableau: everything with
    cross-row coupling — per-pod QoS plugin weights (pressure is read
    from the CURRENT snapshot, so a pressure change never needs a dirty
    row) and the per-pod max-normalizations of the NA/TT scores — is
    recomputed here, every solve, warm or cold."""
    nodes, pods = snap.nodes, snap.pods
    w = effective_weights(
        cfg, pressure_of(pods.slo_target, pods.observed_avail)
    )  # dict of [P] arrays
    na = kscore.default_normalize(tab.na_raw, nodes.valid)
    tt = kscore.taint_toleration_from_count(tab.tt_count, nodes.valid)
    static_score = (
        w["node_affinity"][:, None] * na + w["taint_toleration"][:, None] * tt
    ).astype(jnp.float32)
    return StaticCtx(
        mask=tab.mask, aff_ok=tab.aff_ok, score=static_score,
        sig_match=tab.sig_match,
        w_lr=w["least_requested"], w_ba=w["balanced_allocation"],
        w_ts=w["topology_spread"], w_ia=w["interpod_affinity"],
        rw=jnp.asarray(cfg.score_weights_vector(), jnp.float32),
    )


def precompute_static(cfg: EngineConfig, snap: ClusterSnapshot, node_sat_t,
                      member_sat_t, mesh=None) -> StaticCtx:
    return finalize_static(
        cfg, snap, build_tableau(cfg, snap, node_sat_t, member_sat_t, mesh)
    )


def batched_cycle(cfg: EngineConfig, snap: ClusterSnapshot,
                  static: StaticCtx, used, pair_st,
                  exclude_self_node=None, return_relaxed: bool = False):
    """Full [P, N] Filter + Score against the given state. Score-sum
    grouping mirrors oracle.feasible_and_score exactly.

    return_relaxed=True additionally returns the SPREAD-RELAXED
    feasibility (all predicates except the DoNotSchedule skew filter):
    the fast mode's water-fill dealer may target domains whose skew is
    over the bound against ROUND-START counts but legal against
    end-of-round counts (the state its validator — and the fast-mode
    contract — actually checks); see _spread_waterfill_deal."""
    nodes = snap.nodes
    nvalid = nodes.valid
    base_feasible = static.mask & kfilter.resource_fit(
        nodes.allocatable, used, snap.pods.requests
    )
    base_score = (
        static.w_lr[:, None]
        * kscore.least_requested(nodes.allocatable, used, snap.pods.requests, static.rw)
        + static.w_ba[:, None]
        * kscore.balanced_allocation(nodes.allocatable, used, snap.pods.requests, static.rw)
        + static.score
    )
    if snap.sigs.key.shape[0] == 0:
        # No pairwise constraints anywhere (trace-time fact): penalty is
        # 0 everywhere -> inverse_normalize == 100, raw 0 -> minmax == 0,
        # matching the oracle's formulas exactly without [P, N] work.
        score = base_score + static.w_ts[:, None] * 100.0
        if return_relaxed:
            return base_feasible, score.astype(jnp.float32), base_feasible
        return base_feasible, score.astype(jnp.float32)
    spread_ok, spread_pen, ia_ok, ia_raw = kpair.pairwise_from_counts(
        snap, pair_st, static.aff_ok, static.sig_match, exclude_self_node
    )
    feasible = base_feasible & spread_ok & ia_ok
    score = (
        base_score
        + static.w_ts[:, None] * kscore.inverse_normalize(spread_pen, nvalid)
        + static.w_ia[:, None] * kscore.minmax_normalize(ia_raw, nvalid)
    ).astype(jnp.float32)
    if return_relaxed:
        return feasible, score, base_feasible & ia_ok
    return feasible, score


def pod_cycle(cfg: EngineConfig, snap: ClusterSnapshot, static: StaticCtx,
              p, used, pair_st):
    """Single-pod [N] Filter + Score (sequential scan body). Also
    returns the non-resource feasibility (static & pairwise) so the
    preemption branch can reuse it without recomputing pairwise_row."""
    nodes = snap.nodes
    nvalid = nodes.valid
    req = snap.pods.requests[p]
    spread_ok, spread_pen, ia_ok, ia_raw = kpair.pairwise_row(
        snap, pair_st, static.sig_match, p, static.aff_ok[p]
    )
    allowed = static.mask[p] & spread_ok & ia_ok
    feasible = allowed & kfilter.resource_fit(nodes.allocatable, used, req)
    score = (
        static.w_lr[p] * kscore.least_requested(nodes.allocatable, used, req, static.rw)
        + static.w_ba[p] * kscore.balanced_allocation(nodes.allocatable, used, req, static.rw)
        + static.score[p]
        + static.w_ts[p] * kscore.inverse_normalize(spread_pen, nvalid)
        + static.w_ia[p] * kscore.minmax_normalize(ia_raw, nvalid)
    ).astype(jnp.float32)
    return feasible, score, allowed


def gang_rollback(snap: ClusterSnapshot, used, assigned, chosen, pair_st,
                  sig_match):
    """All-or-nothing Permit gate (SURVEY.md C8, coscheduling): a pod
    group with fewer than group_min_member placed members rolls back
    entirely — capacity, pair state, and assignments. minMember is a
    floor, not a cap: extra members above quorum stay placed. Quorum is
    batch-local (running members are not tracked against minMember).
    Returns (used, assigned, chosen, pair_st, rolled_mask)."""
    pods = snap.pods
    P = assigned.shape[0]
    G = snap.group_min_member.shape[0]
    if G == 0:
        return used, assigned, chosen, pair_st, jnp.zeros(P, bool)
    g = pods.group
    placed = (assigned >= 0) & pods.valid & (g >= 0)
    gclip = jnp.clip(g, 0, None)
    cnt = jnp.zeros(G, jnp.float32).at[gclip].add(placed.astype(jnp.float32))
    quorum = cnt >= snap.group_min_member.astype(jnp.float32)
    roll = placed & ~quorum[gclip]
    used = used.at[jnp.clip(assigned, 0, None)].add(  # tpl: disable=TPL203(rollback subtraction order matches the oracle's sequential gang rollback bit-for-bit on the parity contract; co-located rolled members are rare and integer-valued in every workload — conversion to _node_add tracked in the ledger for item 1)
        -jnp.where(roll[:, None], pods.requests, 0.0)
    )
    if snap.sigs.key.shape[0]:
        pair_st = kpair.pair_state_commit(
            snap, pair_st, sig_match, assigned, roll, sign=-1.0
        )
    assigned = jnp.where(roll, -1, assigned)
    chosen = jnp.where(roll, NEG_INF, chosen)
    return used, assigned, chosen, pair_st, roll


def pick_node(cfg: EngineConfig, masked, p):
    """Select among score maxima (C5 'max-score node wins'): lowest
    index ("first") or a seeded uniform pick ("seeded", the upstream
    rand-among-max analogue; oracle mirrors bit-for-bit)."""
    if cfg.tie_break == "first":
        return jnp.argmax(masked)
    mx = jnp.max(masked)
    ties = masked == mx
    cnt = jnp.maximum(jnp.sum(ties), 1).astype(jnp.uint32)
    h = (tie_hash(cfg.tie_seed, p) % cnt).astype(jnp.int32)
    rank = jnp.cumsum(ties) - 1
    return jnp.argmax(ties & (rank == h))


def pick_node_batch(cfg: EngineConfig, masked, pod_idx):
    """Row-wise pick_node over a [P?, N] score block: each row's seeded
    uniform pick among its maxima, hash-keyed by the ORIGINAL pod index
    (so compacted residual views pick identically to full-width rows
    and to the oracle). Returns None for tie_break='first' — callers
    use it as 'no override'."""
    if cfg.tie_break == "first":
        return None
    mx = jnp.max(masked, axis=1, keepdims=True)
    ties = masked == mx
    cnt = jnp.maximum(jnp.sum(ties, axis=1), 1).astype(jnp.uint32)
    h = (tie_hash(cfg.tie_seed, pod_idx) % cnt).astype(jnp.int32)
    rank = jnp.cumsum(ties, axis=1) - 1
    return jnp.argmax(
        ties & (rank == h[:, None]), axis=1
    ).astype(jnp.int32)


def pop_order(cfg: EngineConfig, snap: ClusterSnapshot):
    """Queue order (SURVEY.md C10): stable descending sort by dynamic
    QoS priority; invalid pods sink to the end."""
    pods = snap.pods
    prio = effective_priority(
        cfg, pods.base_priority, pods.slo_target, pods.observed_avail
    )
    key = jnp.where(pods.valid, prio, NEG_INF)
    return jnp.argsort(-key, stable=True)


def _preempt_branch(cfg: EngineConfig, snap: ClusterSnapshot, static,
                    pctx, prio_p, p, allowed, used, st, evicted):
    """PostFilter for one pod: victim search + state updates. `allowed`
    is the pod's non-resource feasibility row from pod_cycle. Returns
    (used, st, evicted, node-or-minus-1)."""
    best_n, can, evict_m, freed = kpreempt.preempt_step(
        cfg, snap, pctx, prio_p, snap.pods.requests[p], allowed, used, evicted
    )
    used = used - freed
    used = used.at[best_n].add(
        jnp.where(can, snap.pods.requests[p], 0.0)
    )
    st = kpair.pair_state_evict(snap, st, static.sig_match, evict_m)
    st = kpair.pair_state_add_pod(snap, st, static.sig_match, p, best_n, can)
    evicted = evicted | evict_m
    return used, st, evicted, jnp.where(can, best_n, -1).astype(jnp.int32)


def solve_sequential(cfg: EngineConfig, snap: ClusterSnapshot,
                     node_sat_t, member_sat_t, init_counts=None,
                     explain: bool = False, static=None, mesh=None):
    """Exact sequential commit: stock scheduleOne semantics on device,
    including inline PostFilter preemption (cfg.preemption) at the exact
    point upstream runs it — immediately after a pod fails Filter.
    Returns (assigned, chosen, used, order, evicted); with explain=True
    an extra trailing tuple (rolled, evictor, evict_round, zeros-shaped
    auction table) — in parity mode "evict_round" is the pop-order step
    at which the eviction committed, and the auction table is all-zero
    (there is no auction; the shape is kept so the engine's packed
    explain layout is mode-independent). static: optional precomputed
    StaticCtx (the warm path's finalize_static output); None computes
    it from the sat tables."""
    if static is None:
        static = precompute_static(cfg, snap, node_sat_t, member_sat_t,
                                   mesh)
    P = snap.pods.valid.shape[0]
    M = snap.running.valid.shape[0]
    order = pop_order(cfg, snap)
    st0 = kpair.pair_state_init(snap, static.sig_match, counts=init_counts,
                                mesh=mesh)
    do_preempt = cfg.preemption and M > 0
    if do_preempt:
        pctx = kpreempt.precompute(cfg, snap)
        prio = effective_priority(
            cfg, snap.pods.base_priority, snap.pods.slo_target,
            snap.pods.observed_avail,
        )

    def body(carry, x):
        if explain:
            p, pos = x
            used, assigned, st, evicted, evictor, evict_rd = carry
        else:
            p = x
            used, assigned, st, evicted = carry
        feasible, score, allowed = pod_cycle(cfg, snap, static, p, used, st)
        masked = jnp.where(feasible, score, NEG_INF)
        n = pick_node(cfg, masked, p)
        commit = jnp.any(feasible)
        used = used.at[n].add(jnp.where(commit, snap.pods.requests[p], 0.0))
        st = kpair.pair_state_add_pod(snap, st, static.sig_match, p, n, commit)
        a_p = jnp.where(commit, n, -1).astype(jnp.int32)
        if do_preempt:
            # Gang members never preempt: their placement is provisional
            # until quorum (gang_rollback), and evicting real workloads
            # for a provisional placement would strand the victims.
            prev_evicted = evicted
            used, st, evicted, pn = jax.lax.cond(
                ~commit & snap.pods.valid[p] & (snap.pods.group[p] < 0),
                lambda ops: _preempt_branch(
                    cfg, snap, static, pctx, prio[p], p, allowed, *ops
                ),
                lambda ops: (*ops, jnp.int32(-1)),
                (used, st, evicted),
            )
            a_p = jnp.where(commit, a_p, pn)
            if explain:
                new_ev = evicted & ~prev_evicted
                evictor = jnp.where(new_ev, p, evictor)
                evict_rd = jnp.where(new_ev, pos, evict_rd)
        assigned = assigned.at[p].set(a_p)
        out = (used, assigned, st, evicted)
        if explain:
            out = out + (evictor, evict_rd)
        # Preempted placements carry no score (upstream nominates without
        # rescoring); chosen stays -inf for them, as in the oracle.
        return out, jnp.where(commit, masked[n], NEG_INF)

    init = (
        snap.nodes.used, jnp.full(P, -1, jnp.int32), st0,
        jnp.zeros(M, bool),
    )
    xs = order
    if explain:
        init = init + (jnp.full(M, -1, jnp.int32),
                       jnp.full(M, -1, jnp.int32))
        xs = (order, jnp.arange(P, dtype=jnp.int32))
    # unroll=4: purely an XLA loop-overhead optimization (4 pod steps
    # per while iteration, same sequential dataflow — placements are
    # bit-identical); ~15% off the 10k-pod scan on v5e.
    final, chosen_in_order = jax.lax.scan(body, init, xs, unroll=4)
    used, assigned, st, evicted = final[:4]
    chosen = jnp.full(P, NEG_INF, jnp.float32).at[order].set(chosen_in_order)
    used, assigned, chosen, _, rolled = gang_rollback(
        snap, used, assigned, chosen, st, static.sig_match
    )
    if explain:
        astats = jnp.zeros(
            (_PREEMPT_MAX_ROUNDS, len(EXPLAIN_AUCTION_STATS)), jnp.float32
        )
        return (assigned, chosen, used, order, evicted,
                (rolled, final[4], final[5], astats))
    return assigned, chosen, used, order, evicted


def score_batch(cfg: EngineConfig, snap: ClusterSnapshot, node_sat_t,
                member_sat_t, init_counts=None, static=None, mesh=None):
    """One-shot [P, N] feasibility + scores against the current snapshot
    (no commits): the ScoreBatch gRPC surface (SURVEY.md C12)."""
    if static is None:
        static = precompute_static(cfg, snap, node_sat_t, member_sat_t,
                                   mesh)
    st0 = kpair.pair_state_init(snap, static.sig_match, counts=init_counts,
                                mesh=mesh)
    return batched_cycle(cfg, snap, static, snap.nodes.used, st0)


# ---------------------------------------------------------------------------
# Delta-update entry points (device-resident cluster state,
# tpusched/device_state.py): one XLA scatter / gather over a whole
# struct-of-arrays group. jit caches per (pytree structure, shapes) —
# callers bucket the churned-row count to powers of two so the compile
# set stays bounded. Duplicate scatter indices are only ever written
# with IDENTICAL row content (idx padding repeats a real row), so the
# unspecified duplicate-write order cannot change the result.
# ---------------------------------------------------------------------------


@jax.jit
def scatter_rows(tree, idx, rows):
    """tree.leaf[idx[j]] = rows.leaf[j] for every leaf of a
    struct-of-arrays pytree (NodeArrays / PodArrays / ... or a bare
    array): the O(churn) device-side write of a delta update."""
    return jax.tree.map(lambda a, r: a.at[idx].set(r), tree, rows)


@jax.jit
def permute_rows(tree, perm):
    """Row gather tree.leaf[perm] over a struct-of-arrays pytree: the
    device-side reorder when record insertion/removal shifts the
    name-sorted row order (host ships one [rows] int32 permutation, not
    the arrays)."""
    return jax.tree.map(lambda a: a[perm], tree)


def _spread_waterfill_deal(snap: ClusterSnapshot, pair_st, used, relaxed,
                           score, allowed, rank, K: int):
    """Domain-balanced dealing for spread-constrained pods (round-4):
    the global capacity dealer sends same-sig members to ADJACENT
    ranked nodes — one topology domain — and the skew validator then
    reverts all but ~maxSkew of them, draining spread-heavy workloads
    at ~(sigs x domains) commits per round (146 rounds at 10k x 5k).
    Instead, each sig's members (rank order) are water-filled across
    its existing domains — member q goes to the domain that keeps the
    per-domain fill levels flattest given current counts — and each
    member gets K+1 candidate nodes INSIDE its assigned domain
    (successive free-capacity rotation positions, so capacity misses
    spill to the domain's next node within the same round instead of
    escaping to a global — wrong-domain — fallback and being reverted).

    `relaxed` is the SPREAD-RELAXED feasibility (batched_cycle
    return_relaxed): the start-state DoNotSchedule filter forbids every
    domain above min_start + maxSkew, which under imbalance is ALL
    domains but the emptiest — upstream's sequential loop escapes this
    because its counts move per pod, and the fast mode escapes it here
    by targeting against end-of-round semantics and letting the skew
    validator (which checks exactly that state) confirm or revert.
    Returns (cand[P, K+1] int32, val[P, K+1] f32 scores at those
    candidates, ok[P] bool); ok=False falls back to the capacity
    dealer's choice (e.g. no relax-feasible node in the domain)."""
    pods = snap.pods
    S = snap.sigs.key.shape[0]
    P = rank.shape[0]
    if pods.ts_valid.shape[1] == 0 or S == 0:
        # No spread-constraint slots in this snapshot (trace-time):
        # nothing to water-fill.
        return (jnp.zeros((P, K + 1), jnp.int32),
                jnp.full((P, K + 1), NEG_INF, jnp.float32),
                jnp.zeros(P, bool))
    BIG = jnp.int32(2**31 - 1)
    LARGE = jnp.float32(1e9)  # finite stand-in for "domain absent"
    dom_s = kpair.sig_domains(snap)                          # [S, N]
    N = dom_s.shape[1]
    # Members are pods with a DoNotSchedule constraint, keyed by their
    # FIRST DNS slot — that is the filter that serializes them.
    # ScheduleAnyway-only pods keep the normal score-driven dealing
    # (their spread score already penalizes crowded domains, and the
    # skew validator never reverts them).
    dns = pods.ts_valid & (pods.ts_when == DO_NOT_SCHEDULE)
    has_dns = jnp.any(dns, axis=1)
    first_c = jnp.argmax(dns, axis=1)
    s_p = jnp.clip(
        pods.ts_sig[jnp.arange(P), first_c], 0, None
    )                                                        # [P]
    member = allowed & has_dns
    # In-sig 0-based rank positions among this round's members.
    gid = jnp.where(member, s_p, S)
    perm = jnp.lexsort((rank, gid))
    gid_sorted = gid[perm]
    mem_sorted = member[perm]
    boundary = jnp.concatenate(
        [jnp.ones(1, bool), gid_sorted[1:] != gid_sorted[:-1]]
    )
    idx = jnp.arange(P, dtype=jnp.int32)
    cum = jnp.cumsum(mem_sorted.astype(jnp.float32))
    seg_start = jax.lax.cummax(jnp.where(boundary, idx, 0))
    q_off = jnp.where(
        seg_start > 0, cum[jnp.clip(seg_start - 1, 0, None)], 0.0
    )
    q_sorted = cum - q_off - 1.0                             # 0-based
    q = jnp.zeros(P, jnp.float32).at[perm].set(q_sorted)
    # Per-sig water-fill tables over the domain-count rows.
    exist = jnp.zeros((S, N), bool).at[
        jnp.arange(S)[:, None], jnp.clip(dom_s, 0, None)
    ].max(dom_s >= 0)
    cnt = jnp.where(exist, pair_st.counts, LARGE)            # [S, N]
    ord_dom = jnp.argsort(cnt, axis=1)                       # [S, N]
    csort = jnp.take_along_axis(cnt, ord_dom, axis=1)
    presum = jnp.concatenate(
        [jnp.zeros((S, 1), jnp.float32),
         jnp.cumsum(csort, axis=1)[:, :-1]], axis=1  # tpl: disable=TPL201(water-fill level table: counts mixed with the LARGE=1e9 absent-domain sentinel do round, but the table only DEALS members to domains — the skew validator (_spread_excess_mask, integer-exact) confirms or reverts every commit)
    )
    js = jnp.arange(N, dtype=jnp.float32)[None, :]
    fill = js * csort - presum                               # [S, N] nondecr.
    fill_p = fill[s_p]                                       # [P, N]
    # searchsorted(fill_p[p], q[p], right) == count of entries <= q[p]:
    # one [P, N] compare+reduce (a vmapped searchsorted lowers to P
    # serial row searches — ~20 ms/round at 10k x 5k).
    j_p = jnp.clip(
        jnp.sum((fill_p <= q[:, None]).astype(jnp.int32), axis=1) - 1,
        0, N - 1,
    )
    r_p = (q - jnp.take_along_axis(fill_p, j_p[:, None], axis=1)[:, 0])
    r_i = r_p.astype(jnp.int32)
    slot = jnp.mod(r_i, j_p + 1)
    dchoice = jnp.take_along_axis(
        ord_dom[s_p], slot[:, None], axis=1
    )[:, 0]                                                  # [P] domain id
    in_dom = dom_s[s_p] == dchoice[:, None]                  # [P, N]
    sel = relaxed & in_dom
    # Within the domain, members must also fan out across NODES: the
    # best-scoring node is nearly the same for every member (the load
    # balancing scores barely separate them), and one node holds only a
    # few pods — argmax here re-creates the herding one level down
    # (observed: the commit rate stayed capacity-capped at ~15/round).
    # Member m of its (sig, domain) takes the (m mod n_feasible)-th
    # feasible domain node in free-capacity order, with the next K
    # rotation positions as its spill candidates.
    m_p = r_i // (j_p + 1)                                   # [P] level offset
    alloc = snap.nodes.allocatable
    free_frac = jnp.mean(  # tpl: disable=TPL201(per-node mean over the FIXED R resource axis — cell-local, never padded or sharded; orders a dealing rotation that the capacity-prefix commit validates)
        jnp.where(alloc > 0, (alloc - used) / jnp.maximum(alloc, 1e-9), 0.0),
        axis=1,
    )                                                        # [N]
    cap_order = jnp.argsort(-free_frac).astype(jnp.int32)    # [N]
    sel_sorted = sel[:, cap_order]                           # [P, N]
    csum = jnp.cumsum(sel_sorted.astype(jnp.float32), axis=1)
    n_feas = csum[:, -1]
    targets = jnp.mod(
        m_p.astype(jnp.float32)[:, None]
        + jnp.arange(K + 1, dtype=jnp.float32)[None, :],
        jnp.maximum(n_feas, 1.0)[:, None],
    ) + 1.0                                                  # [P, K+1]
    # searchsorted(csum[p], t, left) == count of entries < t; K+1 small
    # compare+reduce passes instead of P serial row searches.
    j_node = jnp.stack(
        [
            jnp.sum(
                (csum < targets[:, k][:, None]).astype(jnp.int32), axis=1
            )
            for k in range(K + 1)
        ],
        axis=1,
    )
    cand = cap_order[jnp.clip(j_node, 0, cap_order.shape[0] - 1)]
    ok = member & (n_feas > 0)
    sel_at = jnp.take_along_axis(sel, cand, axis=1)
    val = jnp.where(
        sel_at, jnp.take_along_axis(score, cand, axis=1), NEG_INF
    )
    return cand, val, ok


def _node_add(used, node, mask, requests, rank, width: int, sign=1.0):
    """used.at[node[p]].add(sign * requests[p]) for masked rows, as ONE
    unique-index add per node: rows sort by (node, rank), per-node
    request totals come off a segmented prefix sum PADDED to `width`
    rows, and only each segment's last row scatters. Replaces the
    order-unspecified duplicate f32 scatter-add, which made `used`
    depend on the pod-axis layout: the frontier-compaction contract
    (compacted [F, N] rounds bitwise == full-width [P, N] rounds) needs
    every f32 reduction over the pod axis to be width-invariant, and a
    width-padded front-packed cumsum + disjoint single adds is exactly
    that (masked rows sort to the front in the same (node, rank) order
    at any width; the tail is zeros)."""
    P = node.shape[0]
    N = used.shape[0]
    node_m = jnp.where(mask, jnp.clip(node, 0, N - 1), N)
    perm = jnp.lexsort((rank, node_m))
    node_s = node_m[perm]
    mask_s = mask[perm]
    req_s = jnp.where(mask_s[:, None], requests[perm], 0.0)
    if width > P:
        req_pad = jnp.concatenate(
            [req_s, jnp.zeros((width - P, req_s.shape[1]), req_s.dtype)]
        )
    else:
        req_pad = req_s
    cum = jnp.cumsum(req_pad, axis=0)[:P]                    # [P, R]  # tpl: disable=TPL202(this IS the width-pad idiom: width > P concatenates zeros out to `width`; width == P is already the full layout — both branches cumsum exactly `width` rows, which the branch-join analysis cannot see)
    idx = jnp.arange(P, dtype=jnp.int32)
    boundary = jnp.concatenate(
        [jnp.ones(1, bool), node_s[1:] != node_s[:-1]]
    )
    seg_start = jax.lax.cummax(jnp.where(boundary, idx, 0))
    offset = jnp.where(
        (seg_start > 0)[:, None], cum[jnp.clip(seg_start - 1, 0, None)], 0.0
    )
    total = cum - offset                                     # incl. own row
    is_last = jnp.concatenate([node_s[1:] != node_s[:-1], jnp.ones(1, bool)])
    is_last &= mask_s
    # Non-last rows add exact 0.0 at node 0 (a no-op); last rows hit
    # DISTINCT nodes, so the unspecified duplicate-add order never sees
    # two real contributions.
    return used.at[jnp.where(is_last, node_s, 0)].add(
        jnp.where(is_last[:, None], sign * total, 0.0)
    )


def _deal_commit(allocatable, requests, used, feasible, masked, allowed,
                 rank, K: int, dealt_override=None,
                 dealt_override_val=None, dealt_override_ok=None,
                 score_full=None, tie_pick=None,
                 rank_is_sorted: bool = False,
                 cum_width: "int | None" = None):
    """One round's dealing + capacity-prefix conflict resolution +
    rescue, shape-generic over the pod axis (used on the full [P, N]
    matrices and on the compacted residual view — same math per pod;
    see _RESIDUAL_CAP for the f32 reduction-order caveat). Returns
    (used2, choice, chosen_val); choice[p] = committed node or -1.

    cum_width (the frontier-compaction contract, ISSUE 12): when set,
    every f32 reduction over the pod axis is made WIDTH-INVARIANT so a
    compacted [F, N] call is bitwise-identical to the full-width [P, N]
    call it stands in for: node desirability sums go through int32
    fixed-point (integer adds are associativity-exact; f32 column sums
    change with the reduction tree when the row count changes), demand
    and per-node capacity prefixes cumsum over arrays padded/scattered
    to `cum_width` rows (identical layouts at any view width — real
    rows front-packed or rank-scattered, zeros elsewhere), and `used`
    updates apply as unique-per-node segment totals (_node_add) instead
    of order-unspecified duplicate scatter-adds. None keeps the legacy
    reductions (the no-signature paths, whose residual compaction
    predates — and documents — the non-bitwise caveat).

    Load-balancing scores give every pod nearly the SAME global node
    ranking, so per-pod argmax/top-K concentrates all commits on the
    few best nodes and serializes rounds. Deal pods into the ranked
    node list by cumulative request mass instead: the q-th pending pod
    (by priority) targets the node where the cumulative remaining
    capacity first covers the cumulative demand of pods 0..q, for
    every resource. Pods whose dealt node is infeasible for them fall
    back to their own top-K; the capacity-prefix commit corrects any
    estimate error, and misses retry next round.

    tie_pick: optional [P] seeded argmax per pod (pick_node_batch) —
    the upstream rand-among-max analogue for fast mode (C5). When
    given, it replaces the lowest-index maximum as each pod's OWN top
    choice (the first top-K candidate and the rescue pick); the
    lowest-index maximum stays in the list as a later fallback, so
    under capacity pressure behavior is unchanged and on uncontended
    rows the committed node is exactly the oracle's seeded pick."""
    P = requests.shape[0]
    N = allocatable.shape[0]
    BIG = jnp.int32(2**31 - 1)
    allowed_col = allowed[:, None]
    n_allowed = jnp.maximum(allowed.sum(), 1)
    if cum_width is None:
        desir = jnp.sum(  # tpl: disable=TPL201(legacy cum_width=None reduction kept as the documented _RESIDUAL_CAP non-bitwise caveat — the nosig residual compaction predates the width-invariance contract; the sig path always passes cum_width and takes the int32 fixed-point branch below)
            jnp.where(feasible & allowed_col, masked, 0.0), axis=0
        ) / n_allowed                                        # [N]
    else:
        # Fixed-point desirability (see docstring): 1/16 granularity is
        # ample for a dealing-order heuristic, and clipping bounds the
        # int32 column sums at P * (2^15 - 1) (exact for P <= 64k; the
        # old +-2^15 bound could reach exactly 2^31 and wrap — TPL204.
        # The clip never binds in practice: scores are O(400), so
        # |round(contrib*16)| tops out around 6400).
        contrib = jnp.where(feasible & allowed_col, masked, 0.0)
        iq = jnp.clip(
            jnp.round(contrib * 16.0), -32767.0, 32767.0
        ).astype(jnp.int32)
        desir = jnp.sum(iq, axis=0).astype(jnp.float32) / (
            16.0 * n_allowed.astype(jnp.float32)
        )
    desir = jnp.where(
        jnp.any(feasible & allowed_col, axis=0), desir, NEG_INF
    )
    node_order = jnp.argsort(-desir)                         # [N]
    remaining = jnp.maximum(allocatable - used, 0.0)         # [N, R]
    remaining = jnp.where(
        jnp.isfinite(desir)[:, None], remaining, 0.0
    )
    # Inclusive cumulative demand of allowed pods in rank order,
    # WITHOUT sorting (a [P] argsort costs ~4.5 ms on this TPU's sort
    # path, per round, and the two rank layouts both admit a sortless
    # form): rank_is_sorted views (tranches, drains — sel was chosen
    # by rank) cumsum directly; full-width callers have rank as a
    # permutation of 0..P-1 and scatter into rank-major layout.
    dem = jnp.where(allowed[:, None], requests, 0.0)
    if cum_width is not None:
        # Width-invariant layout: scatter the view's demand at GLOBAL
        # rank positions of a [cum_width, R] array — byte-identical to
        # the full-width rank-major scatter (absent pods demand 0) — so
        # the f32 prefix sums agree bitwise at any view width.
        rm = jnp.zeros((cum_width, dem.shape[1]), dem.dtype).at[rank].set(dem)
        my_dem = jnp.cumsum(rm, axis=0)[rank]                # [P, R]
    elif rank_is_sorted:
        my_dem = jnp.cumsum(dem, axis=0)                     # [P, R]  # tpl: disable=TPL201(legacy rank_is_sorted demand prefix at the view's own fixed width — the documented nosig non-bitwise caveat; sig-path callers pass cum_width and take the width-padded branch)
    else:
        rm = jnp.zeros_like(dem).at[rank].set(dem)
        my_dem = jnp.cumsum(rm, axis=0)[rank]                # [P, R]
    cum_rem = jnp.cumsum(remaining[node_order], axis=0)      # [N, R]  # tpl: disable=TPL201(node-axis capacity prefix at fixed [N] — the node axis is never view-compacted; dealing estimate only, corrected by the capacity-prefix commit and re-tried next round on a miss)
    pos = jnp.zeros(P, jnp.int32)
    for ri in range(cum_rem.shape[1]):
        pos = jnp.maximum(
            pos,
            jnp.searchsorted(
                cum_rem[:, ri], my_dem[:, ri], side="left"
            ).astype(jnp.int32),
        )
    dealt = node_order[jnp.clip(pos, 0, N - 1)].astype(jnp.int32)
    dealt_ok = jnp.take_along_axis(
        feasible, dealt[:, None], axis=1
    )[:, 0]
    # Candidate list: dealt node first (when feasible), then the pod's
    # own top-K by score; K capacity sub-iterations.
    topv, topi = jax.lax.top_k(masked, K)                    # [P, K]
    if tie_pick is not None:
        # The pod's own top choice becomes the seeded pick (same max
        # score by construction; equal to topi[:, 0] when untied).
        tp_val = jnp.take_along_axis(masked, tie_pick[:, None], axis=1)
        topi = topi.at[:, 0].set(tie_pick)
        topv = topv.at[:, 0].set(tp_val[:, 0])
    dealt_score = jnp.take_along_axis(masked, dealt[:, None], axis=1)
    use_dealt = dealt_ok
    if tie_pick is not None:
        # Seeded semantics: when the dealt node merely ties the pod's
        # max score (the dealer's redirect is arbitrary among equals),
        # the hash pick leads — uniform hashes spread ties like the
        # dealer would. A strictly lower-scored dealt node keeps its
        # slot: that redirect is the capacity dealer doing real work.
        use_dealt = dealt_ok & (dealt_score[:, 0] < topv[:, 0])
    topi = jnp.concatenate(
        [jnp.where(use_dealt, dealt, topi[:, 0])[:, None], topi], axis=1
    )
    topv = jnp.concatenate(
        [jnp.where(use_dealt, dealt_score[:, 0], topv[:, 0])[:, None], topv],
        axis=1,
    )
    if dealt_override is not None:
        # Spread water-fill (see _spread_waterfill_deal): a constrained
        # pod's WHOLE candidate list becomes its in-domain rotation —
        # spills stay inside the assigned domain. Values come with the
        # candidates (relaxed placements are -inf in `masked`).
        okc = dealt_override_ok[:, None]
        topi = jnp.where(okc, dealt_override, topi)
        topv = jnp.where(okc, dealt_override_val, topv)

    KC = K + 1  # dealt candidate + top-K fallbacks

    def sub_cond(sub_state):
        used_j, choice_j, ptr = sub_state
        ptr_c = jnp.clip(ptr, 0, KC - 1)
        cand_ok = jnp.take_along_axis(topv, ptr_c[:, None], axis=1)[:, 0] > NEG_INF
        return jnp.any(allowed & (choice_j < 0) & (ptr < KC) & cand_ok)

    def sub(sub_state):
        used_j, choice_j, ptr = sub_state
        ptr_c = jnp.clip(ptr, 0, KC - 1)
        cand = jnp.take_along_axis(topi, ptr_c[:, None], axis=1)[:, 0]
        cand_ok = jnp.take_along_axis(topv, ptr_c[:, None], axis=1)[:, 0] > NEG_INF
        active = allowed & (choice_j < 0) & (ptr < KC) & cand_ok
        # Capacity-prefix conflict resolution per node, in priority
        # order: sort by (candidate node, rank); within each node's
        # segment commit the longest prefix whose cumulative requests
        # fit the node's remaining capacity.
        cand_m = jnp.where(active, cand, N)  # inactive -> sentinel seg
        perm = jnp.lexsort((rank, cand_m))
        cand_s = cand_m[perm]
        act_s = active[perm]
        req_s = jnp.where(act_s[:, None], requests[perm], 0.0)
        if cum_width is not None and cum_width > P:
            # Active rows front-pack identically at any width (inactive
            # rows sort to the sentinel tail with zero demand), so a
            # zero-padded cumsum is bitwise width-invariant.
            req_pad = jnp.concatenate([
                req_s, jnp.zeros((cum_width - P, req_s.shape[1]),
                                 req_s.dtype),
            ])
            cum = jnp.cumsum(req_pad, axis=0)[:P]            # [P, R]
        else:
            cum = jnp.cumsum(req_s, axis=0)                  # [P, R]  # tpl: disable=TPL201(else-branch of the width-pad idiom: cum_width None or == P means this width IS the full layout; the compacted sig path always takes the padded branch above)
        idx = jnp.arange(P, dtype=jnp.int32)
        boundary = jnp.concatenate(
            [jnp.ones(1, bool), cand_s[1:] != cand_s[:-1]]
        )
        seg_start = jax.lax.cummax(jnp.where(boundary, idx, 0))
        offset = jnp.where(
            (seg_start > 0)[:, None],
            cum[jnp.clip(seg_start - 1, 0, None)], 0.0,
        )
        within = cum - offset                                # incl. own
        cap_node = jnp.clip(cand_s, 0, N - 1)
        fits = jnp.all(
            used_j[cap_node] + within <= allocatable[cap_node],
            axis=-1,
        ) & act_s
        bad = act_s & ~fits
        last_bad = jax.lax.cummax(jnp.where(bad, idx, -1))
        prefix_ok = last_bad < seg_start
        commit_s = fits & prefix_ok
        commit_j = jnp.zeros(P, bool).at[perm].set(commit_s)
        nofit = jnp.zeros(P, bool).at[perm].set(bad)
        if cum_width is not None:
            used_j = _node_add(used_j, cand, commit_j, requests, rank,
                               cum_width)
        else:
            used_j = used_j.at[jnp.clip(cand, 0, N - 1)].add(  # tpl: disable=TPL203(legacy cum_width=None commit add — the documented nosig non-bitwise caveat; the sig path routes through _node_add's unique-per-node totals in the branch above)
                jnp.where(commit_j[:, None], requests, 0.0)
            )
        choice_j = jnp.where(commit_j, cand, choice_j)
        # Only pods whose own node is full advance their pointer;
        # prefix-blocked pods retry the same node next sub-step.
        # Progress: every sub-step either commits or advances a
        # pointer, and pointers are bounded by KC, so the while
        # terminates; it usually exits after 2-3 steps.
        ptr = jnp.where(
            nofit, ptr + 1, jnp.where(commit_j, KC, ptr)
        )
        return used_j, choice_j, ptr

    used2, choice, _ = jax.lax.while_loop(
        sub_cond, sub,
        (used, jnp.full(P, -1, jnp.int32), jnp.zeros(P, jnp.int32)),
    )
    commit = choice >= 0
    # Rescue: if the dealing pass committed NOTHING while some allowed
    # pod still has a feasible node (its dealt + top-K candidates were
    # all prefix-blocked, but a node further down its row has room),
    # commit the first such pod (by rank) at its best feasible node.
    # Feasibility was computed against round-start state and no other
    # commit landed this round, so the placement is valid; this
    # guarantees every round places at least one pod until nothing
    # pending is placeable — the same drain point as the sequential
    # semantics.
    want = jnp.any(feasible, axis=1)
    can_rescue = ~jnp.any(commit) & jnp.any(allowed & want)
    rk = jnp.where(allowed & want, rank, BIG)
    p_star = jnp.argmin(rk)
    n_star = (
        tie_pick[p_star] if tie_pick is not None
        else jnp.argmax(masked[p_star]).astype(jnp.int32)
    )
    used2 = used2.at[n_star].add(
        jnp.where(can_rescue, requests[p_star], 0.0)
    )
    choice = choice.at[p_star].set(
        jnp.where(can_rescue, n_star, choice[p_star])
    )
    # Relaxed (water-fill) placements are -inf in `masked`; their real
    # score lives in score_full when the caller provides it.
    chosen_val = jnp.take_along_axis(
        masked if score_full is None else score_full,
        jnp.clip(choice, 0, N - 1)[:, None], axis=1
    )[:, 0]
    return used2, choice, chosen_val


def _top_by_rank(pend, order, C: int):
    """Indices of the C lowest-rank True pods of `pend`, in ascending
    rank order, plus the number of True pods — SORTLESS (order is the
    precomputed pop order, i.e. pods by ascending rank). Replaces the
    per-round argsort(where(pend, rank, BIG))[:C] selections, each of
    which paid ~4.5 ms on this TPU's sort path. Requires C <= P: every
    slot gets a DISTINCT pod (callers scatter through the result, and
    duplicate tail indices would race); with C > P distinct fillers
    cannot exist."""
    assert C <= order.shape[0], (C, order.shape)
    pend_rm = pend[order]                                    # rank-major
    cpend = jnp.cumsum(pend_rm.astype(jnp.int32))
    cnon = jnp.cumsum((~pend_rm).astype(jnp.int32))
    n_pend = cpend[-1]
    # Every pod gets a DISTINCT slot (pending pods 0..n_pend-1 by rank,
    # then non-pending by rank): tail slots must not repeat a pod —
    # callers scatter through sel, and duplicate indices make the
    # unkept-slot writes race the kept one.
    slot = jnp.where(pend_rm, cpend - 1, n_pend + cnon - 1)
    take = slot < C
    buf = jnp.zeros(C + 1, order.dtype).at[
        jnp.where(take, slot, C)
    ].set(order)
    return buf[:C], n_pend


def _fallback_depth(N: int) -> int:
    """Per-pod fallback-candidate depth for dealing commits: deeper
    lists on SMALL clusters close most of the fast-mode placement gap
    (mixed-preset placed_delta -20 -> -9 of 480 at K=16; stranded
    large pods' top-8 fill up same-round and 8 of 16 nodes left no
    alternates), while on big clusters a deeper [P, N] top_k costs
    more than it recovers (pairwise fast +150 ms at 10k x 5k)."""
    return min(16, N) if N <= 256 else 8


# Residual compaction width: after the first full round, the few
# still-pending pods are gathered into this many slots and later rounds
# run on the [C, N] view instead of [P, N] (~45 ms -> ~2 ms per round at
# 10k x 5k; headline fast p50 295 -> 185 ms). Semantically equivalent:
# with no pairwise signatures a round's outcome depends only on
# (pending set, node used), both preserved by the view including
# relative rank order. NOT bitwise: the shared node-desirability mean
# in _deal_commit reduces over a different-shaped array, so f32
# rounding can flip near-tied node rankings (34/10000 placements moved
# at the headline shape, all audit-valid — validate_assignment: 0
# violations).
_RESIDUAL_CAP = 1024

# Bid width and round cap of the fast-mode batched preemption auction
# (_preempt_rounds): per round, the top _PREEMPT_BATCH unplaced pods
# bid in parallel; upstream preempts ONE pod per scheduling cycle, so
# even one round x 512 bids is far past parity behavior. 1024 (round
# 6, was 512): plain-feasible bidders share the same slots, and at
# 90% utilization they crowd out preemptors mid-drain — round-5 traces
# show rounds where ~250 of the 512 slots went to plain bidders,
# halving eviction keeps to ~230-260 (the keeps-per-round collapse in
# VERDICT round 5). The wider batch keeps eviction throughput at
# ~full-width even with plain crowding, roughly halving drain rounds;
# it became affordable when preempt_auction dropped its exact
# [C, N, V] tableau for [N, V] candidate tables + [C, V] claimed-node
# validation (per-round cost now scales with C only through [C, N]
# ranking and [C, V] validation).
_PREEMPT_BATCH = 1024
# Width of the per-round plain drain in _preempt_rounds.
_PREEMPT_DRAIN = 1024
# Round cap; the env override exists for per-round cost profiling
# (slope of solve time vs cap) and emergency latency capping.
_PREEMPT_MAX_ROUNDS = int(
    _os_mod.environ.get("TPUSCHED_PREEMPT_MAX_ROUNDS", 128)
)
# Per-node victim cap of the node-major fast-auction tableau
# (kpreempt.PreemptCtxNV): victims are slotted per node in ascending
# cost order and a fast-mode preemptor can evict at most this many on
# one node. Prefixes needing more fall back to other nodes or stay
# pending (the parity path has no cap). 16 covers every BASELINE
# workload (config 5 runs 8 victims/node).
_PREEMPT_VICTIM_CAP = 16

# Per-round auction provenance columns (decision provenance, round 12):
# with explain=True the preemption loop accumulates one row per auction
# round into a [_PREEMPT_MAX_ROUNDS, len(...)] f32 table. Column order
# is the layout contract with tpusched/explain.py — append only.
EXPLAIN_AUCTION_STATS = (
    "considered",      # pods examined this round
    "plain_feasible",  # of those, feasible without any eviction
    "bids",            # entered the victim auction
    "claimed",         # auction claims surviving exact validation
    "kept_evict",      # eviction bids kept past the PDB budget gate
    "kept_plain",      # plain placements kept (claim scan + capacity)
    "drained",         # plain-drain placements (S == 0 pre-pass)
    "evictions",       # victims newly evicted this round
    "pdb_spent",       # PDB budget consumed by kept eviction bids
    "no_bid",          # pods retired spent (no placement or prefix)
)


def _spread_excess_mask(snap: ClusterSnapshot, aff_ok, rank,
                        choice, kept_v, st_v):
    """[P] bool: kept members to revert so every kept DNS-spread
    constraint holds against st_v's (end-of-round) counts. Per (sig,
    domain) group of revert-eligible members, the highest-priority
    prefix whose size respects every kept member's skew bound survives;
    the excess reverts. Shared by solve_rounds' commit-validation
    fixpoint, _preempt_rounds' round validation (round 6), and the
    incremental warm path's carried-placement revalidation + in-kernel
    audit (ISSUE 12). Shape-generic over the pod axis: pass a view
    snapshot (gathered pods rows) plus the matching aff_ok/rank/choice
    rows and the verdict is row-for-row what the full-width call gives
    (all cross-pod reductions here are integer-exact)."""
    pods, nodes = snap.pods, snap.nodes
    P = pods.valid.shape[0]
    N = nodes.valid.shape[0]
    dom_s_v = kpair.sig_domains(snap)                        # [S, N]
    S_sigs = dom_s_v.shape[0]
    dns_any = pods.ts_valid & (pods.ts_when == DO_NOT_SCHEDULE)  # [P, C]
    counts_v = st_v.counts                                   # [S, N]
    node_cnt = jnp.take_along_axis(
        counts_v, jnp.clip(dom_s_v, 0, None), axis=1
    )                                                        # [S, N]
    node_cnt = jnp.where(dom_s_v >= 0, node_cnt, jnp.inf)
    bad = jnp.zeros(P, bool)
    idx = jnp.arange(P, dtype=jnp.int32)
    for c in range(pods.ts_key.shape[1]):
        s_c = jnp.clip(pods.ts_sig[:, c], 0, None)           # [P]
        d_c = dom_s_v[s_c, jnp.clip(choice, 0, N - 1)]
        member = (
            kept_v & dns_any[:, c] & (choice >= 0) & (d_c >= 0)
        )
        # Per-pod allowance T = min over eligible domains of the
        # END-state count, plus the pod's own maxSkew.
        nc_p = node_cnt[s_c]                                 # [P, N]
        eligible = nodes.valid[None, :] & aff_ok & (
            dom_s_v[s_c] >= 0
        )
        min_end = jnp.min(
            jnp.where(eligible, nc_p, jnp.inf), axis=1
        )
        min_end = jnp.where(jnp.isfinite(min_end), min_end, 0.0)
        T = min_end + pods.ts_max_skew[:, c]                 # [P]
        cnt_total = counts_v[s_c, jnp.clip(d_c, 0, None)]
        # Rank-ordered position within each (sig, domain) group
        # of revert-eligible members, and the group's size.
        gid = jnp.where(
            member, s_c * N + jnp.clip(d_c, 0, None), S_sigs * N
        )
        g_tab = jnp.zeros(S_sigs * N + 1, jnp.float32).at[gid].add(
            member.astype(jnp.float32)
        )
        g_elig = g_tab[gid]                                  # [P]
        b_fixed = cnt_total - g_elig  # non-revertable contribution
        perm2 = jnp.lexsort((rank, gid))
        gid_s = gid[perm2]
        mem_s = member[perm2]
        boundary = jnp.concatenate(
            [jnp.ones(1, bool), gid_s[1:] != gid_s[:-1]]
        )
        q_cum = jnp.cumsum(mem_s.astype(jnp.float32))
        seg_start2 = jax.lax.cummax(jnp.where(boundary, idx, 0))
        q_off = jnp.where(
            seg_start2 > 0,
            q_cum[jnp.clip(seg_start2 - 1, 0, None)], 0.0,
        )
        q_incl = q_cum - q_off                               # 1-based position
        # Segmented prefix-min of T in rank order: the k-member
        # prefix is admissible iff b + k <= min over its
        # members' allowances.
        T_s = jnp.where(mem_s, T[perm2], jnp.inf)

        def comb(a, bpair):
            av, ab = a
            bv, bb = bpair
            return (jnp.where(bb, bv, jnp.minimum(av, bv)), ab | bb)

        pm_s, _ = jax.lax.associative_scan(comb, (T_s, boundary))  # tpl: disable=TPL202(segmented prefix-MIN: comb combines by jnp.minimum, order-free-exact in any tree; operand is inf-masked — the analyzer sees only an opaque f32 scan)
        survive_s = mem_s & (b_fixed[perm2] + q_incl <= pm_s)
        bad_c = jnp.zeros(P, bool).at[perm2].set(mem_s & ~survive_s)
        bad |= bad_c
    return bad


def _compact_cap(cfg: EngineConfig, P: int) -> int:
    """Resolved signature-path frontier-compaction cap (ISSUE 12):
    0 = compaction off (the full-width reference the bitwise twin tests
    compare against), cfg.compact_cap -1 = auto (_RESIDUAL_CAP), else
    the explicit cap. Disabled when P is not meaningfully larger than
    the cap (the gathers would not pay for themselves) — except for an
    EXPLICIT positive cap, which tests use to exercise the compacted
    program on small clusters."""
    cap = _RESIDUAL_CAP if cfg.compact_cap < 0 else cfg.compact_cap
    if cap <= 0:
        return 0
    if cfg.compact_cap < 0 and P <= cap:
        return 0
    return min(cap, P)


def _pods_view(snap: ClusterSnapshot, static: StaticCtx, sel):
    """Compacted pod-axis view (the frontier gather): pod rows, static
    rows, and the sig_match MEMBER columns of the selected pods, as a
    (view snapshot, view StaticCtx) pair every shape-generic kernel in
    this module accepts in place of the full-width pair. Running
    members, nodes, sigs, and all [S, N]/[N, R] state stay full — the
    compaction only narrows the pod axis."""
    M = snap.running.valid.shape[0]
    pods_v = jax.tree.map(lambda a: a[sel], snap.pods)
    snap_v = snap.replace(pods=pods_v)
    sig_v = jnp.concatenate(
        [static.sig_match[:, :M], static.sig_match[:, M + sel]], axis=1
    )
    static_v = StaticCtx(
        mask=static.mask[sel], aff_ok=static.aff_ok[sel],
        score=static.score[sel], sig_match=sig_v,
        w_lr=static.w_lr[sel], w_ba=static.w_ba[sel],
        w_ts=static.w_ts[sel], w_ia=static.w_ia[sel], rw=static.rw,
    )
    return snap_v, static_v


def _preempt_rounds(cfg: EngineConfig, snap: ClusterSnapshot,
                    static: StaticCtx, rank, order, base_rounds,
                    used, assigned, st, evicted, round_of, chosen,
                    has_pair=None, explain: bool = False):
    """Fast-mode PostFilter as BATCHED AUCTION ROUNDS (round-4; replaces
    a sequential per-pod scan that cost ~3 ms per preemptor — 9.6 s for
    2.7k preemptors at 10k x 5k). Each round:

      1. The top _PREEMPT_BATCH still-unplaced pods (dynamic-priority
         order) are evaluated IN PARALLEL against round-start state:
         plain feasibility first (an earlier round's evictions may have
         left room), else the batched victim-prefix auction
         (kpreempt.preempt_auction): bidders rank nodes off
         bidder-INDEPENDENT [N, V] prefix tables (priority-quantile
         buckets of the active bidders; round 6 — the exact [C, N, V]
         tableau was the per-round cost floor), parallel claim
         iterations deal bidders distinct cheap STILL-UNCLAIMED
         nodes — one claimant per node, so same-round victim sets never
         overlap (a bidder unclaimed after the fixed iteration count
         defers to the next round, a retry the old rank-ordered scan
         never needed) — and each claimed node gets an EXACT [C, V]
         victim-prefix validation. Plain bidders WITHOUT pairwise involvement (has_pair
         False) bypass the one-claim-per-node scan entirely: the load-
         balancing scores herd their argmaxes onto the same few nodes,
         which capped keeps at ~one per node per round (a 25-round
         drain tail for ~200 pods, measured round 5); a capacity-
         prefix commit per node (the same rule as _deal_commit's sub-
         step) admits every same-node bidder that fits, on nodes no
         eviction bid claimed this round. Pairwise-involved plain
         bidders stay on the claim scan — node exclusivity bounds
         their same-round interactions.
      2. A rank-ordered claimed-cumulative budget gate (O(1)-depth
         cumsums over [C, GP]) enforces PodDisruptionBudgets as a
         priority prefix over the claimants; a bid whose conservative
         budget accounting overdraws is deferred and re-bids next
         round against exact consumption.
      3. Kept bids apply as BATCHED scatters (evictions, capacity,
         pair state); deferred pods re-bid against the updated state.

    Victim sets of same-round keeps cannot overlap (victims are node-
    local and each node keeps one bid) and every kept bid was feasible
    against its round-start state, so validity matches the sequential
    pass; under contention the ORDER of preemptors can differ — the
    standard fast-mode divergence contract. Terminates when a round
    keeps nothing or the cap hits (leftovers stay unplaced)."""
    pods, nodes = snap.pods, snap.nodes
    P = pods.valid.shape[0]
    N = nodes.valid.shape[0]
    BIG = jnp.int32(2**31 - 1)
    C = min(P, _PREEMPT_BATCH)
    pctx = kpreempt.precompute_nv(cfg, snap, _PREEMPT_VICTIM_CAP)
    prio = effective_priority(
        cfg, pods.base_priority, pods.slo_target, pods.observed_avail
    )
    GP = snap.pdb_allowed.shape[0]
    run_pdb = snap.running.pdb_group
    run_valid = snap.running.valid
    M_run = run_valid.shape[0]
    S = snap.sigs.key.shape[0]
    if has_pair is None:
        has_pair = jnp.zeros(P, bool)

    def cond(carry):
        # Explicit indices: with explain=True the provenance tuple rides
        # at the END of the carry, so -2/-1 would land on it.
        return carry[7] & (carry[8] < _PREEMPT_MAX_ROUNDS)

    def body(carry):
        if explain:
            (used, assigned, st, evicted, round_of, chosen, tried, _, r,
             exp) = carry
            evictor, evict_rd, astats = exp
        else:
            used, assigned, st, evicted, round_of, chosen, tried, _, r = \
                carry
        drained = jnp.array(False)
        drained_n = jnp.float32(0.0)
        if S == 0:
            # Plain drain (round 5): one dealing round over the top
            # _RESIDUAL_CAP pending pods absorbs everything that FITS
            # current capacity (~2 ms) BEFORE the auction, so the C
            # auction slots carry true preemptors — previously
            # plain-feasible pods crowded the slots and eviction
            # throughput collapsed mid-drain. S == 0 only: the dealing
            # view has no pairwise state (exactly the no-sig main-round
            # body); with signatures present the mixed slot path below
            # handles plain bidders under node exclusivity.
            alloc = nodes.allocatable
            pend0 = (assigned < 0) & pods.valid
            dsel, _ = _top_by_rank(
                pend0, order, min(_PREEMPT_DRAIN, P)
            )
            dreal = pend0[dsel]
            feas_d, score_d = _cycle_nosig(
                alloc, used, pods.requests[dsel], static.mask[dsel],
                static.score[dsel], static.w_lr[dsel], static.w_ba[dsel],
                static.w_ts[dsel], static.rw,
            )
            feas_d &= dreal[:, None]
            masked_d = jnp.where(feas_d, score_d, NEG_INF)
            used, choice_d, chosen_d = _deal_commit(
                alloc, pods.requests[dsel], used, feas_d, masked_d,
                jnp.any(feas_d, axis=1), rank[dsel], _fallback_depth(N),
                tie_pick=pick_node_batch(cfg, masked_d, dsel),
                rank_is_sorted=True,
            )
            hit_d = choice_d >= 0
            assigned = assigned.at[dsel].set(
                jnp.where(hit_d, choice_d, assigned[dsel])
            )
            chosen = chosen.at[dsel].set(
                jnp.where(hit_d, chosen_d, chosen[dsel])
            )
            # Shared per-round key, like the auction keeps below (the
            # drain is S == 0-only, so only capacity semantics ride on
            # it — validated jointly by _deal_commit's prefix rule).
            round_of = round_of.at[dsel].set(
                jnp.where(hit_d, base_rounds + r, round_of[dsel])
            )
            drained = jnp.any(hit_d)
            if explain:
                drained_n = jnp.sum(hit_d.astype(jnp.float32))
        # Like the sequential pass, each pod gets ONE bid (tried); a bid
        # deferred by the conflict scan is NOT tried — it re-bids
        # against the updated state next round.
        pend = (assigned < 0) & pods.valid & ~tried
        sel, _ = _top_by_rank(pend, order, C)
        real = pend[sel]

        def eval_plain(p):
            feasible, score, allowed = pod_cycle(
                cfg, snap, static, p, used, st
            )
            masked = jnp.where(feasible, score, NEG_INF)
            n_plain = pick_node(cfg, masked, p).astype(jnp.int32)
            return (n_plain, jnp.any(feasible), masked[n_plain], allowed,
                    feasible, masked)

        (n_plain, can_plain, sc_plain, allowed_rows, feas_pl,
         masked_pl) = jax.vmap(eval_plain)(sel)
        can_plain &= real
        # Pairwise-involved plain bidders go through the auction's
        # claim scan (node exclusivity bounds their same-round
        # interactions); free plain bidders take the capacity-prefix
        # commit below instead.
        plain_excl = can_plain & has_pair[sel]
        plain_cap = can_plain & ~has_pair[sel]
        # Gangs never preempt (see solve_sequential); inactive bidders
        # enter the auction with all-False allowed rows.
        pre_active = real & ~can_plain & (pods.group[sel] < 0)
        allowed_rows &= pre_active[:, None]
        (target, claimed, takes_evict, vidx_t, freed_req, usage,
         could_bid) = kpreempt.preempt_auction(
            cfg, snap, pctx, prio[sel], pods.requests[sel],
            allowed_rows, used, evicted, plain_excl, n_plain,
            rank=rank[sel],
        )
        could_bid = could_bid | plain_cap
        if GP:
            consumed0 = jnp.zeros(GP, jnp.float32).at[
                jnp.clip(run_pdb, 0, None)
            ].add(
                (evicted & (run_pdb >= 0) & run_valid).astype(jnp.float32)
            )
            remaining0 = snap.pdb_allowed.astype(jnp.float32) - consumed0
            # Budget-respecting bids parallelize as a rank-ordered
            # prefix (sel IS ascending-rank order): keep while the
            # CLAIMED-cumulative consumption stays inside every touched
            # budget's remaining allowance. Counting claimed (not just
            # kept) bids in the cumulative is conservative — a bid the
            # exact sequential accounting would keep can be deferred —
            # and deferred bids re-bid next round against exact
            # consumption; safety is one-sided (kept subset of claimed,
            # so real consumption never exceeds the bound checked). A
            # bid that DECLARED a violation (its own usage alone
            # overdraws — upstream's evict-PDB-pods-as-last-resort)
            # keeps unconditionally: `remaining` only decreases, so a
            # bid violating against round-start budgets would violate
            # against ANY later sequential state too — the sequential
            # pass would evict it as last resort just the same, and
            # serializing these (the old rule admitted one per budget
            # per round via a no-earlier-toucher check) stretched the
            # drain by ~10 one-keep rounds at 6k x 3k (round-6 trace).
            # This replaces a C-step lax.scan with O(1)-depth cumsums
            # (the scan's sequential steps dominated the round wall).
            usage_cl = jnp.where(claimed[:, None], usage, 0.0)
            cum_usage = jnp.cumsum(usage_cl, axis=0)          # [C, GP]
            # Only budgets the bid itself touches gate it: an earlier
            # (kept or dropped) overdraw on budget g must not block
            # bids that never evict from g.
            fits_budget = jnp.all(
                jnp.where(
                    usage > 0.0,
                    cum_usage <= remaining0[None, :] + 1e-6, True,
                ),
                axis=1,
            )
            alone_viol = jnp.any(usage > remaining0[None, :] + 1e-6, axis=1)
            keep = claimed & (fits_budget | alone_viol)
        else:
            keep = claimed
        keep_evict = keep & takes_evict
        # vidx_t carries M at non-victim slots, so the scatter only
        # marks the kept bidders' actual prefixes.
        ev_round = jnp.zeros(M_run, bool).at[
            jnp.clip(vidx_t, 0, M_run - 1)
        ].max(keep_evict[:, None] & (vidx_t < M_run))
        evicted2 = evicted | ev_round
        tgt_c = jnp.clip(target, 0, N - 1)
        # Pairwise-free plain bidders commit through a full dealing
        # round on the compacted [C, N] view (see docstring): the same
        # _deal_commit the main rounds use — demand-aware dealing
        # across the node list, top-K fallback, capacity-prefix
        # resolution, rescue. Nodes an auction keep claimed this round
        # are excluded (their round-start capacity is stale after
        # evictions/placement), so the two commit families touch
        # disjoint nodes and their capacity deltas compose.
        taken = jnp.zeros(N, bool).at[tgt_c].max(keep)
        req_sel = pods.requests[sel]
        feas_c = feas_pl & plain_cap[:, None] & ~taken[None, :]
        masked_c = jnp.where(feas_c, masked_pl, NEG_INF)
        allowed_c = plain_cap & jnp.any(feas_c, axis=1)
        _, choice_pl, chosen_pl = _deal_commit(
            nodes.allocatable, req_sel, used, feas_c, masked_c,
            allowed_c, rank[sel], _fallback_depth(N),
            tie_pick=pick_node_batch(cfg, masked_c, sel),
            rank_is_sorted=True,
        )
        keep_pl = choice_pl >= 0
        keep_all = keep | keep_pl
        target_all = jnp.where(keep_pl, choice_pl, target)
        st2 = st
        if S:
            # Pairwise state stays EVICTION-FREE through the preemption
            # rounds (round 6; pair_state_evict is deliberately NOT
            # applied): a pod validated against an INTERMEDIATE
            # eviction state — some victims gone, later rounds' not
            # yet — can be legal there yet illegal under BOTH timings
            # the external audit accepts (validate_assignment checks
            # with ALL evictions applied and with none; pod counts are
            # key-filtered but the evicted mask is not). Counting
            # still-evicted members keeps every check equal to the
            # audit's no-eviction arm: spread and required-anti only
            # get stricter with more members, and a positive-affinity
            # match on an evicted member is precisely what that arm
            # accepts. The cost is bounded conservatism: a pairwise
            # slot freed only by this batch's evictions opens next
            # batch (the snapshot then has the victims gone), exactly
            # like upstream's nominate-then-requeue.
            # Same-round cross-commit validation (round 6): the claim
            # scan's NODE exclusivity does not bound pairwise
            # interactions — spread constraints are per-DOMAIN (many
            # nodes share a zone, so two same-sig keeps on different
            # nodes can jointly breach a skew bound), and this round's
            # evictions can remove the match another keep's required
            # affinity relied on. Re-check every keep against
            # end-of-round state exactly as solve_rounds' commit
            # validation does (same helpers), reverting violators to
            # PENDING — they re-bid next round against true counts.
            # Their victims stay evicted (the eviction was decided
            # against valid round-start state; upstream's
            # nominate-then-requeue can strand evictions the same way).
            #
            # Frontier compaction (ISSUE 12): every keep is in `sel`,
            # so with compaction on the whole fixpoint runs on the
            # [C]-wide view — pair_state_commit / ia_ok_at_choice /
            # _spread_excess_mask only ever touch exact (integer-
            # valued) reductions, so the view verdicts are bitwise the
            # full-width ones (the compact-off engine keeps the [P]
            # arrays as the twin-test reference).
            compact_pv = _compact_cap(cfg, P) > 0
            if compact_pv:
                snap_pv, static_pv = _pods_view(snap, static, sel)
                choice_pv = jnp.where(keep_all, target_all, -1)
                keep_pv = keep_all
                hp_pv = has_pair[sel]
                rank_pv = rank[sel]
            else:
                snap_pv, static_pv = snap, static
                choice_pv = jnp.full(P, -1, jnp.int32).at[sel].set(
                    jnp.where(keep_all, target_all, -1)
                )
                keep_pv = jnp.zeros(P, bool).at[sel].set(keep_all)
                hp_pv = has_pair
                rank_pv = rank
            st2 = kpair.pair_state_commit(
                snap_pv, st2, static_pv.sig_match, choice_pv, keep_pv
            )

            def pv_cond(vs):
                return vs[-1]

            def pv_body(vs):
                st_v, kept_v, _ = vs
                ia_ok = kpair.ia_ok_at_choice(
                    snap_pv, st_v, static_pv.sig_match, choice_pv,
                    jnp.where(kept_v, choice_pv, -1),
                )
                bad = kept_v & hp_pv & ~ia_ok
                bad = bad | (kept_v & _spread_excess_mask(
                    snap_pv, static_pv.aff_ok, rank_pv, choice_pv,
                    kept_v, st_v
                ))
                st_v = kpair.pair_state_commit(
                    snap_pv, st_v, static_pv.sig_match, choice_pv, bad,
                    sign=-1.0,
                )
                return st_v, kept_v & ~bad, jnp.any(bad)

            st2, kept_final, _ = jax.lax.while_loop(
                pv_cond, pv_body,
                (st2, keep_pv, jnp.any(keep_pv & hp_pv)),
            )
            keep_valid = kept_final if compact_pv else kept_final[sel]
            keep = keep & keep_valid
            keep_pl = keep_pl & keep_valid
            keep_all = keep | keep_pl
        used2 = used.at[tgt_c].add(  # tpl: disable=TPL203(one auction claimant per node: kept rows hit DISTINCT tgt_c, non-kept rows add exact 0.0 at a parked slot — duplicate order never sees two real contributions)
            jnp.where(keep_evict[:, None], -freed_req, 0.0)
        )
        used2 = used2.at[tgt_c].add(  # tpl: disable=TPL203(same claim-exclusivity argument as the eviction add above; keep is a subset of claimed, one per node)
            jnp.where(keep[:, None], req_sel, 0.0)
        )
        # Plain-capacity commits CAN share a node (the capacity-prefix
        # rule admits every same-node bidder that fits), so this add —
        # unlike the claim-exclusive ones above — had real duplicate
        # f32 scatter-adds (TPL203, the class PR 12's _node_add
        # replaced in the main rounds; this was the one commit path it
        # missed). Unique-per-node segment totals; bitwise parity with
        # the old duplicate add pinned by
        # tests/test_kernelflow.py::test_preempt_plain_commit_node_add_parity
        # and the existing preempt/fast suites.
        used2 = _node_add(used2, choice_pl, keep_pl, req_sel, rank[sel], C)
        assigned2 = assigned.at[sel].set(
            jnp.where(keep_all, target_all, assigned[sel])
        )
        # Preempted placements carry no score (upstream nominates
        # without rescoring), matching the sequential path.
        chosen2 = chosen.at[sel].set(
            jnp.where(keep_pl, chosen_pl,
                      jnp.where(keep & can_plain, sc_plain,
                                jnp.where(keep, NEG_INF, chosen[sel])))
        )
        # Commit keys: strictly after the main rounds, one SHARED key
        # per preemption round — same-round keeps did NOT see each
        # other's state (they were all checked against round-start
        # state and then jointly validated against end-of-round state
        # above), so rank-ordered intra-round keys would promise the
        # external audit a sequential consistency the engine never
        # enforced; a shared key makes validate_assignment judge each
        # keep against exactly the end-of-round set the engine
        # validated — the same contract solve_rounds' main rounds use.
        round_of2 = round_of.at[sel].set(
            jnp.where(keep_all, base_rounds + r, round_of[sel])
        )
        # A no-bid pod (nothing feasible, no victim prefix anywhere) is
        # spent; a kept pod is placed; a DEFERRED pod (could bid but
        # lost the node race or the budget prefix) bids again. If a
        # round keeps nothing, the first claimant would have kept, so
        # there were no claims: every real pod was a no-bid and gets
        # marked — progress is monotone and the loop terminates.
        if _DEBUG_ROUNDS:
            jax.debug.print(
                "preempt round {r}: real={re} plain={pl} pre={pr} "
                "claimed={a} keep={k} keep_pl={kp} evicts={e}",
                r=r, re=real.sum(), pl=(real & can_plain).sum(),
                pr=takes_evict.sum(), a=claimed.sum(), k=keep.sum(),
                kp=keep_pl.sum(), e=ev_round.sum(),
            )
        newly_tried = real & (keep_all | ~could_bid)
        tried2 = tried.at[sel].set(tried[sel] | newly_tried)
        # Any keep changes the state (evictions free capacity), so
        # earlier no-bid verdicts are stale: clear them and re-bid.
        # Termination: a keep-less round marks every real pod tried
        # (monotone), and rounds with keeps shrink the pending set.
        tried2 = jnp.where(
            jnp.any(keep_all) | drained, jnp.zeros_like(tried2), tried2
        )
        progress = jnp.any(keep_all) | jnp.any(newly_tried) | drained
        out_state = (used2, assigned2, st2, evicted2, round_of2, chosen2,
                     tried2, progress, r + 1)
        if explain:
            # Provenance accumulation (round 12) — traced ONLY under
            # explain=True, so the unexplained program is unchanged.
            # Victim attribution: keep_evict is pre-validation (a
            # reverted keep's victims stay evicted — see above), which
            # is exactly the evicted2 scatter's mask, so evictor /
            # evict_rd cover the evicted set bit-for-bit. Each victim
            # is evicted at most once, so .max over a -1 init is a
            # masked set.
            f32 = jnp.float32
            if M_run:
                vclip = jnp.clip(vidx_t, 0, M_run - 1)
                vmask = keep_evict[:, None] & (vidx_t < M_run)
                evictor = evictor.at[vclip].max(
                    jnp.where(vmask, sel[:, None], -1))
                evict_rd = evict_rd.at[vclip].max(
                    jnp.where(vmask, base_rounds + r, -1))
            if GP:
                pdb_spent = jnp.sum(
                    jnp.where(keep_evict[:, None], usage, 0.0))
            else:
                pdb_spent = f32(0.0)
            row = jnp.stack([
                jnp.sum(real.astype(f32)),
                jnp.sum((real & can_plain).astype(f32)),
                jnp.sum(pre_active.astype(f32)),
                jnp.sum(claimed.astype(f32)),
                jnp.sum(keep_evict.astype(f32)),
                jnp.sum((keep_all & ~takes_evict).astype(f32)),
                drained_n,
                jnp.sum(ev_round.astype(f32)),
                pdb_spent.astype(f32),
                jnp.sum((real & ~could_bid).astype(f32)),
            ])
            astats = astats.at[jnp.clip(r, 0, astats.shape[0] - 1)].set(row)
            out_state = out_state + ((evictor, evict_rd, astats),)
        return out_state

    init = (used, assigned, st, evicted, round_of, chosen,
            jnp.zeros(P, bool), jnp.array(True), jnp.int32(0))
    if explain:
        init = init + ((
            jnp.full(M_run, -1, jnp.int32),
            jnp.full(M_run, -1, jnp.int32),
            jnp.zeros((_PREEMPT_MAX_ROUNDS, len(EXPLAIN_AUCTION_STATS)),
                      jnp.float32),
        ),)
    out = jax.lax.while_loop(cond, body, init)
    base = out[:6] + (out[8],)
    return base + ((out[9],) if explain else ())


def _cycle_nosig(alloc, used, req, mask, sscore, w_lr, w_ba, w_ts, rw):
    """batched_cycle's no-signature body, shape-generic over the pod
    axis (op order identical to batched_cycle so full-width and
    compacted rounds score bitwise the same)."""
    feasible = mask & kfilter.resource_fit(alloc, used, req)
    score = (
        w_lr[:, None] * kscore.least_requested(alloc, used, req, rw)
        + w_ba[:, None] * kscore.balanced_allocation(alloc, used, req, rw)
        + sscore
        + w_ts[:, None] * 100.0
    )
    return feasible, score.astype(jnp.float32)


def _make_round_nosig(cfg, alloc, req, mask, sscore, valid, rank, pod_ids,
                      w_lr, w_ba, w_ts, rw, max_rounds, K,
                      round_cap=None, rank_is_sorted=False):
    """(cond, body) for the no-signature commit rounds over whatever
    pod-axis width the given arrays carry. pod_ids: original pod
    indices of the rows (seeded tie-break hashes by pod identity, so
    compacted views pick like full-width ones). round_cap: optional
    (start_r, n) — stop after n rounds from start_r even with commits
    left (tranche mode: stragglers carry into the next tranche instead
    of dribbling through 1-commit fixpoint rounds). State: (used,
    assigned, chosen, round_of, progress, r)."""

    def cond(st):
        ok = st[4] & (st[5] < max_rounds)
        if round_cap is not None:
            ok = ok & (st[5] < round_cap[0] + round_cap[1])
        return ok

    def body(st):
        used, asg, chosen, rnd, _, r = st
        pending = (asg == -1) & valid
        feasible, score = _cycle_nosig(
            alloc, used, req, mask, sscore, w_lr, w_ba, w_ts, rw
        )
        feasible &= pending[:, None]
        masked = jnp.where(feasible, score, NEG_INF)
        allowed = jnp.any(feasible, axis=1)
        used2, choice, chosen_val = _deal_commit(
            alloc, req, used, feasible, masked, allowed, rank, K,
            tie_pick=pick_node_batch(cfg, masked, pod_ids),
            rank_is_sorted=rank_is_sorted,
        )
        commit = choice >= 0
        asg2 = jnp.where(commit, choice, asg)
        chosen2 = jnp.where(commit, chosen_val, chosen)
        rnd2 = jnp.where(commit, r, rnd)
        all_done = jnp.all((asg2 >= 0) | ~valid)
        return (used2, asg2, chosen2, rnd2,
                jnp.any(commit) & ~all_done, r + 1)

    return cond, body


def _solve_rounds_nosig(cfg: EngineConfig, snap: ClusterSnapshot,
                        static: StaticCtx, rank, order, max_rounds: int,
                        K: int, init=None, skip_full: bool = False,
                        cap: "int | None" = None):
    """Fast-mode rounds when the snapshot has NO pairwise signatures
    (trace-time fact; the common resource/affinity-only serving case):
    tranches of the top-_RESIDUAL_CAP pending pods by rank run [C, N]
    views to fixpoint (see tranche_path below). Returns
    (used, assigned, chosen, round_of, rounds).

    init: optional seeded (used, assigned, chosen, round_of, progress,
    r) — the incremental warm path enters with carried placements
    already assigned and their capacity applied. skip_full=True also
    skips the full-width round 1 (with a small pending frontier it
    would cost [P, N] to place a handful of pods; the tranche loop is
    strictly cheaper there). cap: explicit tranche width — the
    incremental path passes its pow2 FRONTIER bucket so the [C, N]
    view tracks the frontier, not the residual cap (at 2000 pods the
    default small-P guard would otherwise run full-width rounds and
    hand back the very cost the mode exists to shed)."""
    pods, nodes = snap.pods, snap.nodes
    P = pods.valid.shape[0]
    C = _RESIDUAL_CAP if cap is None else max(1, min(cap, P))
    BIG = jnp.int32(2**31 - 1)
    cond_f, body_f = _make_round_nosig(
        cfg, nodes.allocatable, pods.requests, static.mask, static.score,
        pods.valid, rank, jnp.arange(P, dtype=jnp.int32),
        static.w_lr, static.w_ba, static.w_ts,
        static.rw, max_rounds, K,
    )
    if init is None:
        init = (
            nodes.used, jnp.full(P, -1, jnp.int32),
            jnp.full(P, NEG_INF, jnp.float32), jnp.full(P, -1, jnp.int32),
            jnp.array(True), jnp.int32(0),
        )
    if P <= (2 * C if cap is None else C):
        # Too small for compaction to pay for its gathers.
        st = jax.lax.while_loop(cond_f, body_f, init)
        used, assigned, chosen, round_of, _, rounds = st
        return used, assigned, chosen, round_of, rounds

    # Full-width round 1: one deal over all P places the uncontended
    # bulk more cheaply than ~P/C tranches' fixed costs (headline fast
    # regressed ~45 ms device without it). SKIPPED when preemption is
    # enabled — that config exists because the cluster is near
    # capacity, round 1 then places little and costs ~50 ms, and the
    # tranche loop handles a large pending set strictly cheaper.
    state1 = init if (cfg.preemption or skip_full) else body_f(init)

    # TRANCHE processing (round 5; replaces the full-width rounds whose
    # 13 x ~45 ms sweeps dominated the preemption-config solve):
    # capacity only SHRINKS in the no-signature loop, so a pod
    # infeasible against current `used` is infeasible forever — a
    # compacted view run to fixpoint therefore resolves every one of
    # its pods as placed or permanently SPENT (the rescue guarantees
    # fixpoint means no view pod has any feasible node left). Outer
    # loop: take the top-C still-unspent pending pods by rank, run the
    # [C, N] view to fixpoint, mark, repeat — pending strictly shrinks
    # by C per tranche, so ~P/C cheap tranches replace O(rounds) full
    # [P, N] sweeps. Placement parity with the old full path holds
    # because spent pods could never have committed later anyway;
    # rank-ordered tranches track the sequential semantics at least as
    # closely.
    # A positive cfg.max_rounds caps the PER-TRANCHE inner rounds here
    # (each selected pod's view gets up to that many rounds — the
    # closest analogue of the old full-width "every pod considered up
    # to max_rounds times"); gating the OUTER loop on the cumulative
    # counter instead would exhaust the budget on the first few
    # tranches and silently never examine later-ranked pods at all.
    # The outer loop is bounded by its own progress guarantee (every
    # tranche places or spends >= 1 pod) plus a P-sized safety cap.
    # With preemption on the cap drops to 2: the cluster is near
    # capacity (that is why preemption is configured), deep per-tranche
    # fixpoints dribble the last few commits through extra [C, N]
    # rounds (~P/C x cap rounds total — 39 of the 55 rounds at
    # 10k x 5k were main-loop rounds, round-6 trace), and any feasible
    # straggler a capped tranche leaves behind is re-examined every
    # preemption round by _preempt_rounds' plain drain anyway.
    base_cap = 2 if cfg.preemption else 4
    tranche_cap = min(base_cap, max_rounds) if cfg.max_rounds > 0 else base_cap

    def tranche_path(st):
        used, assigned, chosen, round_of, progress, r = st
        alloc, req = nodes.allocatable, pods.requests

        def outer_cond(os):
            _, assigned, _, _, spent, r, t, progress = os
            return (
                progress & (t < P)
                & jnp.any((assigned == -1) & pods.valid & ~spent)
            )

        def outer_body(os):
            used, assigned, chosen, round_of, spent, r, t, _ = os
            pend = (assigned == -1) & pods.valid & ~spent
            sel, _ = _top_by_rank(pend, order, C)
            real = pend[sel]
            cond_c, body_c = _make_round_nosig(
                cfg, alloc, req[sel], static.mask[sel],
                static.score[sel], real, rank[sel], sel,
                static.w_lr[sel], static.w_ba[sel], static.w_ts[sel],
                static.rw, 2**30, K, round_cap=(r, tranche_cap),
                rank_is_sorted=True,
            )
            init_c = (
                used, jnp.full(C, -1, jnp.int32),
                jnp.full(C, NEG_INF, jnp.float32),
                jnp.full(C, -1, jnp.int32), jnp.array(True), r,
            )
            used_c, asg_c, chosen_c, rnd_c, _, r_c = jax.lax.while_loop(
                cond_c, body_c, init_c
            )
            hit = asg_c >= 0
            assigned = assigned.at[sel].set(
                jnp.where(hit, asg_c, assigned[sel])
            )
            chosen = chosen.at[sel].set(
                jnp.where(hit, chosen_c, chosen[sel])
            )
            round_of = round_of.at[sel].set(
                jnp.where(hit, rnd_c, round_of[sel])
            )
            # With the round cap, an unplaced view pod is spent ONLY if
            # it has no feasible node against the tranche-final state
            # (permanent — capacity never grows here); feasible
            # stragglers stay pending and merge into the next tranche.
            feas_left = static.mask[sel] & kfilter.resource_fit(
                alloc, used_c, req[sel]
            )
            no_node = ~jnp.any(feas_left, axis=1)
            spent = spent.at[sel].set(spent[sel] | (real & ~hit & no_node))
            # Progress: placements, newly-spent pods, or a shrinking...
            # a capped tranche with feasible stragglers and no commits
            # cannot happen (the rescue commits one while any view pod
            # is feasible), so any(real) still implies forward motion.
            return (used_c, assigned, chosen, round_of, spent, r_c,
                    t + 1, jnp.any(real))

        used, assigned, chosen, round_of, _, rounds, _, _ = (
            jax.lax.while_loop(
                outer_cond, outer_body,
                (used, assigned, chosen, round_of,
                 jnp.zeros(P, bool), r, jnp.int32(0), progress),
            )
        )
        return used, assigned, chosen, round_of, rounds

    return tranche_path(state1)


def _sig_involvement(snap: ClusterSnapshot, static: StaticCtx, st0):
    """(invol [P, S] bool | None, has_pair [P] bool).

    has_pair: pods whose pairwise validation can ever fail — own spread
    or inter-pod terms, plus symmetric-anti TARGETS: a pod with NO
    constraints of its own can still be displaced by symmetric
    anti-affinity, so it must revalidate if any live anti term (running
    holders via st0.anti — domain-aware, so key-less holders don't
    count — or pending holders, whose node is unknown yet) has a
    selector matching it.

    invol: signature-involvement — the sigs whose counts a pod's checks
    read (its own constraint sigs) or whose counts its commit writes
    (selectors matching it). Pods with DISJOINT involvement cannot
    affect each other's pairwise validation, so conservative pods may
    commit concurrently one-per-sig-cluster instead of one-per-round
    globally — the difference between O(#conservative) and
    O(#sig-clusters) rounds on spread-heavy workloads. It is also the
    incremental warm path's signature-cluster CLOSURE relation: a dirty
    pod drags every invol-overlapping pod into the re-solve frontier
    (ISSUE 12). None when the snapshot has no signatures."""
    pods = snap.pods
    P = pods.valid.shape[0]
    has_pair = jnp.any(pods.ts_valid, axis=1) | jnp.any(pods.ia_valid, axis=1)
    if snap.sigs.key.shape[0] == 0:
        return None, has_pair
    M = snap.running.valid.shape[0]
    anti_possible = st0.anti.sum(axis=1) > 0
    for t in range(pods.ia_key.shape[1]):
        s_t = jnp.clip(pods.ia_sig[:, t], 0, None)
        hold = kpair._pod_anti_holds(snap, t) & pods.valid
        anti_possible = anti_possible.at[s_t].max(hold)
    sym_target = jnp.any(
        static.sig_match[:, M:] & anti_possible[:, None], axis=0
    )
    has_pair = has_pair | sym_target
    invol = static.sig_match[:, M:].T & pods.valid[:, None]  # [P, S]
    for c in range(pods.ts_key.shape[1]):
        s_c = jnp.clip(pods.ts_sig[:, c], 0, None)
        invol = invol.at[jnp.arange(P), s_c].max(pods.ts_valid[:, c])
    for t in range(pods.ia_key.shape[1]):
        s_t = jnp.clip(pods.ia_sig[:, t], 0, None)
        invol = invol.at[jnp.arange(P), s_t].max(pods.ia_valid[:, t])
    return invol, has_pair


def _solve_rounds_sig(cfg: EngineConfig, snap: ClusterSnapshot,
                      static: StaticCtx, rank, order, invol, has_pair,
                      init, max_rounds: int, K: int, cap: int):
    """The signature-path (S > 0) commit-round loop, frontier-compacted
    (ISSUE 12): full-width [P, N] rounds run only while the pending
    frontier exceeds `cap`; once it fits, each round gathers the WHOLE
    pending frontier (top-`cap` by rank — a superset, so every pod that
    could commit, gate, or validate is in view) into a [cap, N] view via
    _pods_view, runs the identical round math there, and scatters the
    commits back. cap == 0 keeps every round full-width — the reference
    the bitwise twin tests compare against.

    BITWISE CONTRACT: compacted rounds equal full-width rounds on
    assignment/chosen_score/evicted. Every cross-pod reduction in the
    round is width-invariant by construction — integer/boolean/min
    reductions are exact in any tree; the f32 ones go through
    _deal_commit(cum_width=P) and _node_add (fixed-point desirability
    sums, width-padded rank-major cumsums, unique-per-node adds); sorts
    key on globally-unique ranks so view layouts gather to identical
    sequences. Pinned by tests/test_frontier.py across structural-churn
    twins incl. preemption and gang admission.

    init/returns: (used, assigned, pair_st, conservative, chosen,
    round_of, progress, r) — `init` may carry a warm-seeded state (the
    incremental path: carried assignments pre-committed into used and
    pair_st, r starting past the carried commit key)."""
    pods, nodes = snap.pods, snap.nodes
    P = pods.valid.shape[0]
    N = nodes.valid.shape[0]
    BIG = jnp.int32(2**31 - 1)

    def round_math(snap_v, static_v, invol_v, hp_v, rank_v, pod_ids,
                   pending_v, conservative_v, used, pair_st, r):
        """One commit round over a (possibly compacted) pod-axis view.
        Returns (used3, st3, kept, choice, chosen_val, fb_mask)."""
        feasible, score, relaxed = batched_cycle(
            cfg, snap_v, static_v, used, pair_st, return_relaxed=True
        )
        feasible &= pending_v[:, None]
        relaxed &= pending_v[:, None]
        masked = jnp.where(feasible, score, NEG_INF)
        want = jnp.any(feasible, axis=1)
        # Conservative pods commit only when first among wanting pods
        # they could INTERACT with: minimal rank within every signature
        # cluster they touch (pods with disjoint involvement are
        # independent).
        cons_want = want & conservative_v
        rank_or_big = jnp.where(cons_want, rank_v, BIG)         # [F]
        min_rank_sig = jnp.min(
            jnp.where(invol_v, rank_or_big[:, None], BIG), axis=0
        )                                                       # [S]
        ok_cons = jnp.all(
            jnp.where(invol_v, rank_v[:, None] == min_rank_sig[None, :],
                      True),
            axis=1,
        )
        allowed = want & (~conservative_v | ok_cons)

        # Water-fill membership and activation use the RELAXED rows: a
        # DNS pod whose every in-bound domain is skew-blocked against
        # round-start counts can still legally place under end-of-round
        # semantics (the validator's state) — see _spread_waterfill_deal.
        allowed_r = jnp.any(relaxed, axis=1) & (~conservative_v | ok_cons)
        sp_cand, sp_val, sp_ok = _spread_waterfill_deal(
            snap_v, pair_st, used, relaxed, score, allowed_r, rank_v, K
        )
        used2, choice, chosen_val = _deal_commit(
            nodes.allocatable, snap_v.pods.requests, used, feasible,
            masked, allowed | sp_ok, rank_v, K, dealt_override=sp_cand,
            dealt_override_val=sp_val, dealt_override_ok=sp_ok,
            score_full=score,
            tie_pick=pick_node_batch(cfg, masked, pod_ids),
            cum_width=P,
        )
        commit = choice >= 0
        st2 = kpair.pair_state_commit(
            snap_v, pair_st, static_v.sig_match, choice, commit
        )

        # Validate committed pairwise pods against end-of-round counts;
        # roll back violators. Iterated to a fixpoint: a revert can
        # strip the match that satisfied another same-round pod's
        # positive affinity, so each pass re-checks the still-kept pods
        # until no new violations (each pass reverts >= 1 pod, so it
        # terminates). Two violation classes with different policies:
        #   * AFFINITY (required inter-pod / symmetric anti):
        #     rank-ordered partial reverts — the cluster-minimal
        #     violator is protected (its violation is usually induced
        #     by same-round lower-priority commits) and the rest revert
        #     and retry optimistically next round; see vbody. The
        #     conservative one-per-cluster gate survives only as the
        #     zero-progress backstop after the loop.
        #   * DoNotSchedule SPREAD: revert only the EXCESS members per
        #     (sig, domain) — keep the highest-priority prefix whose
        #     size respects every kept member's skew bound. Reverted
        #     pods retry WITHOUT the conservative gate: next round's
        #     start-state counts mask the full domains, so the dealer
        #     redirects them. Reverting ALL violators and serializing
        #     them cost O(pods-with-spread) rounds on spread-heavy
        #     workloads (~141 rounds on BASELINE config 3); excess-only
        #     reverts converge in a handful.
        def vcond(vs):
            return vs[-1]

        def vbody(vs):
            st_v, used_v, kept_v, _ = vs
            # Chosen-node-only IA verdict: the full [P, N]
            # pairwise_from_counts made each validation pass as
            # expensive as a scoring round; the fixpoint only reads
            # the chosen-node column.
            ia_ok_at = kpair.ia_ok_at_choice(
                snap_v, st_v, static_v.sig_match, choice,
                jnp.where(kept_v, choice, -1),
            )
            ia_bad_all = kept_v & hp_v & ~ia_ok_at
            # Rank-ordered partial reverts (round-4): PROTECT the
            # violator that precedes every other violator it could
            # interact with (minimal rank across all its involved
            # sigs): its violation is usually induced by same-round
            # higher-rank commits, which revert first; the fixpoint
            # then re-checks it against the surviving state. If a pass
            # finds only protected violators left, they are genuinely
            # invalid against the kept state — revert them too (also
            # guarantees each pass reverts >= 1, so the loop
            # terminates).
            bad_rank = jnp.where(ia_bad_all, rank_v, BIG)
            min_bad_sig = jnp.min(
                jnp.where(invol_v, bad_rank[:, None], BIG), axis=0
            )                                                   # [S]
            protected = ia_bad_all & jnp.all(
                jnp.where(invol_v,
                          rank_v[:, None] == min_bad_sig[None, :], True),
                axis=1,
            )
            ia_bad = ia_bad_all & ~protected
            sp_bad = _spread_excess_mask(
                snap_v, static_v.aff_ok, rank_v, choice, kept_v, st_v
            ) & ~ia_bad_all
            stuck = ~jnp.any(ia_bad | sp_bad) & jnp.any(ia_bad_all)
            ia_bad = ia_bad | (ia_bad_all & stuck)
            new_viol = ia_bad | sp_bad
            used_v = _node_add(used_v, choice, new_viol,
                               snap_v.pods.requests, rank_v, P, sign=-1.0)
            st_v = kpair.pair_state_commit(
                snap_v, st_v, static_v.sig_match, choice, new_viol,
                sign=-1.0,
            )
            return (st_v, used_v, kept_v & ~new_viol, jnp.any(new_viol))

        st3, used3, kept, _ = jax.lax.while_loop(
            vcond, vbody, (st2, used2, commit, jnp.any(commit & hp_v)),
        )
        viol = commit & ~kept
        if _DEBUG_ROUNDS:
            jax.debug.print(
                "round {r}: allowed={a} commit={c} kept={k} viol={v}",
                r=r, a=allowed.sum(), c=commit.sum(), k=kept.sum(),
                v=viol.sum(),
            )
        # Progress backstop: reverted pods retry optimistically against
        # next round's start-state counts (which now mask the domains
        # they lost), so they normally converge without any gating. But
        # if EVERY commit of this round was reverted, optimism alone
        # proves nothing placed — mark the first reverted pod (by rank)
        # conservative so the ordered one-per-cluster path guarantees
        # progress.
        need_fb = ~jnp.any(kept) & jnp.any(viol)
        fb_first = rank_v == jnp.min(jnp.where(viol, rank_v, BIG))
        fb_mask = viol & fb_first & need_fb
        return used3, st3, kept, choice, chosen_val, fb_mask

    ids = jnp.arange(P, dtype=jnp.int32)

    def full_body(state):
        used, assigned, pair_st, conservative, chosen, round_of, _, r = state
        pending = assigned == -1
        used3, st3, kept, choice, chosen_val, fb_mask = round_math(
            snap, static, invol, has_pair, rank, ids, pending,
            conservative, used, pair_st, r,
        )
        assigned2 = jnp.where(kept, choice, assigned)
        chosen2 = jnp.where(kept, chosen_val, chosen)
        round_of2 = jnp.where(kept, r, round_of)
        new_conservative = fb_mask & ~conservative
        conservative2 = conservative | fb_mask
        all_done = jnp.all((assigned2 >= 0) | ~pods.valid)
        progress = (jnp.any(kept) | jnp.any(new_conservative)) & ~all_done
        return (used3, assigned2, st3, conservative2, chosen2,
                round_of2, progress, r + 1)

    def full_cond(state):
        progress, r = state[-2], state[-1]
        ok = progress & (r < max_rounds)
        if cap:
            # Hand off to the compacted loop once the whole pending
            # frontier fits one view (never before: the view must hold
            # EVERY pending pod for the bitwise contract to hold).
            ok &= jnp.sum(
                ((state[1] == -1) & pods.valid).astype(jnp.int32)
            ) > cap
        return ok

    state = jax.lax.while_loop(full_cond, full_body, init)
    if not cap:
        return state

    def compact_body(state):
        used, assigned, pair_st, conservative, chosen, round_of, _, r = state
        pend = (assigned == -1) & pods.valid
        sel, _ = _top_by_rank(pend, order, cap)
        snap_v, static_v = _pods_view(snap, static, sel)
        used3, st3, kept, choice, chosen_val, fb_mask = round_math(
            snap_v, static_v, invol[sel], has_pair[sel], rank[sel], sel,
            pend[sel], conservative[sel], used, pair_st, r,
        )
        assigned2 = assigned.at[sel].set(
            jnp.where(kept, choice, assigned[sel])
        )
        chosen2 = chosen.at[sel].set(
            jnp.where(kept, chosen_val, chosen[sel])
        )
        round_of2 = round_of.at[sel].set(
            jnp.where(kept, r, round_of[sel])
        )
        new_conservative = fb_mask & ~conservative[sel]
        conservative2 = conservative.at[sel].set(
            conservative[sel] | fb_mask
        )
        all_done = jnp.all((assigned2 >= 0) | ~pods.valid)
        progress = (jnp.any(kept) | jnp.any(new_conservative)) & ~all_done
        return (used3, assigned2, st3, conservative2, chosen2,
                round_of2, progress, r + 1)

    def compact_cond(state):
        progress, r = state[-2], state[-1]
        return progress & (r < max_rounds)

    return jax.lax.while_loop(compact_cond, compact_body, state)


def solve_rounds(cfg: EngineConfig, snap: ClusterSnapshot,
                 node_sat_t, member_sat_t, init_counts=None,
                 explain: bool = False, static=None, mesh=None):
    """Fast mode: optimistic batched rounds with validate-and-rollback.
    Returns (assigned, chosen, used, order, round_of, rounds, evicted);
    with explain=True (decision provenance, round 12) an extra trailing
    tuple (rolled, evictor, evict_round, auction_stats) — gang-rollback
    mask [P], per-victim preemptor pod index / commit-round [M] (-1 =
    not evicted), and the [_PREEMPT_MAX_ROUNDS, EXPLAIN_AUCTION_STATS]
    per-round auction table. The explain accumulation is traced only
    when requested, so the default program is unchanged. static:
    optional precomputed StaticCtx (the warm path)."""
    if static is None:
        static = precompute_static(cfg, snap, node_sat_t, member_sat_t,
                                   mesh)
    pods, nodes = snap.pods, snap.nodes
    P = pods.valid.shape[0]
    N = nodes.valid.shape[0]
    order = pop_order(cfg, snap)
    rank = jnp.zeros(P, jnp.int32).at[order].set(jnp.arange(P, dtype=jnp.int32))
    st0 = kpair.pair_state_init(snap, static.sig_match, counts=init_counts,
                                mesh=mesh)
    S = snap.sigs.key.shape[0]
    invol, has_pair = _sig_involvement(snap, static, st0)
    BIG = jnp.int32(2**31 - 1)
    # Round bound: worst case is one conservative pod committing per
    # round, so the auto bound is O(P); cfg.max_rounds > 0 caps it lower
    # (pods still pending at the cap stay unassigned that batch).
    max_rounds = cfg.max_rounds if cfg.max_rounds > 0 else 2 * P + 8
    K = _fallback_depth(N)

    if S == 0:
        # No pairwise signatures (trace-time): dedicated path with
        # residual compaction after round 1 (the conservative/
        # validation machinery is inert at S == 0).
        used, assigned, chosen, round_of, rounds = _solve_rounds_nosig(
            cfg, snap, static, rank, order, max_rounds, K
        )
        st_f = st0
    else:
        init = (
            nodes.used, jnp.full(P, -1, jnp.int32), st0,
            jnp.zeros(P, bool), jnp.full(P, NEG_INF, jnp.float32),
            jnp.full(P, -1, jnp.int32), jnp.array(True), jnp.int32(0),
        )
        out = _solve_rounds_sig(
            cfg, snap, static, rank, order, invol, has_pair, init,
            max_rounds, K, _compact_cap(cfg, P),
        )
        used, assigned, st_f, _, chosen, round_of, _, rounds = out
    M = snap.running.valid.shape[0]
    evicted = jnp.zeros(M, bool)
    evictor = evict_rd = astats = None
    if explain:
        evictor = jnp.full(M, -1, jnp.int32)
        evict_rd = jnp.full(M, -1, jnp.int32)
        astats = jnp.zeros(
            (_PREEMPT_MAX_ROUNDS, len(EXPLAIN_AUCTION_STATS)), jnp.float32
        )
    if cfg.preemption and M > 0:
        pr_out = _preempt_rounds(
            cfg, snap, static, rank, order, rounds,
            used, assigned, st_f, evicted, round_of, chosen,
            has_pair=has_pair, explain=explain,
        )
        (used, assigned, st_f, evicted, round_of, chosen,
         preempt_r) = pr_out[:7]
        if explain:
            evictor, evict_rd, astats = pr_out[7]
        # Total commit rounds surfaces the preemption drain too (the
        # bench and host logs read SolveResult.rounds).
        rounds = rounds + preempt_r
    used, assigned, chosen, st_f, rolled = gang_rollback(
        snap, used, assigned, chosen, st_f, static.sig_match
    )
    round_of = jnp.where(rolled, -1, round_of)
    # Commit key for external validity audits: pods committed in earlier
    # rounds precede later ones; within a round all commits share a key
    # (the engine validated them against end-of-round state).
    base = (assigned, chosen, used, order, round_of, rounds, evicted)
    if explain:
        return base + ((rolled, evictor, evict_rd, astats),)
    return base


def _capacity_prefix_keep(alloc, used_base, requests, node, rank, active):
    """[P] bool: per node, the longest rank-ordered prefix of `active`
    rows whose cumulative requests fit alloc - used_base — the same
    capacity-prefix rule _deal_commit's sub-step commits by, applied to
    the incremental warm path's CARRIED placements: a node whose
    allocatable shrank (or whose carried demand grew) spills its
    lowest-priority carried pods back into the pending frontier instead
    of overflowing."""
    P = node.shape[0]
    N = alloc.shape[0]
    node_m = jnp.where(active, jnp.clip(node, 0, N - 1), N)
    perm = jnp.lexsort((rank, node_m))
    node_s = node_m[perm]
    act_s = active[perm]
    req_s = jnp.where(act_s[:, None], requests[perm], 0.0)
    cum = jnp.cumsum(req_s, axis=0)  # tpl: disable=TPL201(carried-placement capacity prefix at the lineage's fixed full [P] width — never view-compacted; mirrors _deal_commit's commit rule, and a spill only re-enters the frontier (re-solved), never overflows)
    idx = jnp.arange(P, dtype=jnp.int32)
    boundary = jnp.concatenate(
        [jnp.ones(1, bool), node_s[1:] != node_s[:-1]]
    )
    seg_start = jax.lax.cummax(jnp.where(boundary, idx, 0))
    offset = jnp.where(
        (seg_start > 0)[:, None], cum[jnp.clip(seg_start - 1, 0, None)], 0.0
    )
    within = cum - offset
    cap_node = jnp.clip(node_s, 0, N - 1)
    fits = jnp.all(
        used_base[cap_node] + within <= alloc[cap_node], axis=-1
    ) & act_s
    bad = act_s & ~fits
    last_bad = jax.lax.cummax(jnp.where(bad, idx, -1))
    keep_s = fits & (last_bad < seg_start)
    return jnp.zeros(P, bool).at[perm].set(keep_s)


# Layout of the incremental solve's in-kernel audit vector (appended to
# the packed solve buffer by the engine's incremental program):
#   [cap_violations, carried_static_violations, carried_pair_violations,
#    carried_count, frontier_count]
INC_AUDIT_LEN = 5


def solve_incremental(cfg: EngineConfig, snap: ClusterSnapshot, tab,
                      carry, carry_chosen, frontier0, dirty_node_mask,
                      cap: int, mesh=None):
    """Bounded-divergence warm commit rounds (ISSUE 12, tentpole 2):
    seed the round loop with the previous cycle's assignment for clean
    pods and run commit rounds only over the pending FRONTIER, so solve
    time scales with churn, not the cluster.

      1. The frontier starts from the lineage's dirty pods (frontier0)
         and expands to its SIGNATURE-CLUSTER closure (pods whose invol
         rows overlap a dirty pod's — their counts a dirty commit can
         read or write) and NODE closure (carried pods sitting on a
         dirty node: its capacity/labels may have moved under them).
      2. Every remaining carried placement is revalidated against
         CURRENT state in one batched pass per class: static mask at
         the carried node (taints/affinity/cordon), per-node rank-
         ordered capacity prefix vs current allocatable, and — when
         signatures exist — the pairwise fixpoint (ia_ok_at_choice +
         _spread_excess_mask, the exact validators the cold rounds
         use). Violations SPILL into the frontier.
      3. Survivors pre-commit (capacity + pair state + commit key 0)
         and the normal round machinery — frontier-compacted — places
         the frontier; preemption rounds and the gang Permit gate run
         unchanged on top.

    NOT bitwise vs a cold solve (the round fixpoint is globally
    coupled); governed instead by the validity contract — no capacity
    overflow, no pairwise violation, carried pods still feasible on
    their nodes — enforced by the passes above and re-checked by the
    in-kernel audit appended to the result (INC_AUDIT_LEN tail;
    `divergence --warm-audit --incremental` additionally reports the
    placement-quality drift vs a cold twin). One known soft spot,
    shared with the cold fast path: a post-rollback gang member's
    departure can strip a match another pod's REQUIRED positive
    affinity relied on — the audit reports it rather than masking it.

    carry: [P] int32 previous-cycle node per pod in CURRENT row order
    (-1 = no carry); carry_chosen: [P] f32 their as-of-placement
    scores (carried placements keep them — upstream nominates without
    rescoring); frontier0: [P] bool dirty basis; dirty_node_mask: [N]
    bool or None; cap: frontier-compaction width for the rounds (0 =
    full-width).

    Returns (assigned, chosen, used, order, round_of, rounds, evicted,
    audit[INC_AUDIT_LEN] f32)."""
    static = finalize_static(cfg, snap, tab)
    pods, nodes = snap.pods, snap.nodes
    P = pods.valid.shape[0]
    N = nodes.valid.shape[0]
    order = pop_order(cfg, snap)
    rank = jnp.zeros(P, jnp.int32).at[order].set(
        jnp.arange(P, dtype=jnp.int32)
    )
    st0 = kpair.pair_state_init(snap, static.sig_match, mesh=mesh)
    S = snap.sigs.key.shape[0]
    invol, has_pair = _sig_involvement(snap, static, st0)
    max_rounds = cfg.max_rounds if cfg.max_rounds > 0 else 2 * P + 8
    K = _fallback_depth(N)

    carry = jnp.where(pods.valid, carry, -1)
    fr = frontier0 & pods.valid
    if invol is not None:
        hot = jnp.any(invol & fr[:, None], axis=0)           # [S]
        fr = fr | jnp.any(invol & hot[None, :], axis=1)
    if dirty_node_mask is not None:
        fr = fr | ((carry >= 0)
                   & dirty_node_mask[jnp.clip(carry, 0, None)])
    carried = pods.valid & (carry >= 0) & ~fr
    frontier_n = jnp.sum((pods.valid & (carry < 0) | fr).astype(jnp.float32))
    # Revalidation pass 1: static feasibility at the carried node.
    ok_static = tab.mask[jnp.arange(P), jnp.clip(carry, 0, None)]
    carried &= ok_static
    # Pass 2: per-node capacity prefix vs CURRENT allocatable.
    carried &= _capacity_prefix_keep(
        nodes.allocatable, nodes.used, pods.requests, carry, rank, carried
    )
    used = _node_add(nodes.used, carry, carried, pods.requests, rank, P)
    st = st0
    if S:
        st = kpair.pair_state_commit(
            snap, st, static.sig_match, carry, carried
        )

        # Pass 3: pairwise revalidation to fixpoint — a spill can strip
        # the match another carried pod's positive affinity relied on,
        # so iterate until clean (each pass spills >= 1, so it
        # terminates; in the common cycle it exits after one check).
        def rcond(vs):
            return vs[-1]

        def rbody(vs):
            st_v, used_v, kept_v, _ = vs
            ia = kpair.ia_ok_at_choice(
                snap, st_v, static.sig_match, carry,
                jnp.where(kept_v, carry, -1),
            )
            bad = kept_v & has_pair & ~ia
            bad = bad | (kept_v & _spread_excess_mask(
                snap, tab.aff_ok, rank, carry, kept_v, st_v
            ))
            st_v = kpair.pair_state_commit(
                snap, st_v, static.sig_match, carry, bad, sign=-1.0
            )
            used_v = _node_add(used_v, carry, bad, pods.requests, rank, P,
                               sign=-1.0)
            return st_v, used_v, kept_v & ~bad, jnp.any(bad)

        st, used, carried, _ = jax.lax.while_loop(
            rcond, rbody, (st, used, carried, jnp.any(carried & has_pair))
        )
    assigned = jnp.where(carried, carry, -1)
    chosen = jnp.where(carried, carry_chosen, NEG_INF)
    round_of = jnp.where(carried, 0, -1)
    carried_n = jnp.sum(carried.astype(jnp.float32))
    if S == 0:
        used, assigned, chosen, round_of, rounds = _solve_rounds_nosig(
            cfg, snap, static, rank, order, max_rounds, K,
            init=(used, assigned, chosen, round_of, jnp.array(True),
                  jnp.int32(1)),
            skip_full=True, cap=(cap if cap > 0 else None),
        )
        st_f = st
    else:
        init = (used, assigned, st, jnp.zeros(P, bool), chosen, round_of,
                jnp.array(True), jnp.int32(1))
        out = _solve_rounds_sig(
            cfg, snap, static, rank, order, invol, has_pair, init,
            max_rounds, K, cap,
        )
        used, assigned, st_f, _, chosen, round_of, _, rounds = out
    M = snap.running.valid.shape[0]
    evicted = jnp.zeros(M, bool)
    if cfg.preemption and M > 0:
        pr_out = _preempt_rounds(
            cfg, snap, static, rank, order, rounds,
            used, assigned, st_f, evicted, round_of, chosen,
            has_pair=has_pair,
        )
        (used, assigned, st_f, evicted, round_of, chosen,
         preempt_r) = pr_out[:7]
        rounds = rounds + preempt_r
    used, assigned, chosen, st_f, rolled = gang_rollback(
        snap, used, assigned, chosen, st_f, static.sig_match
    )
    round_of = jnp.where(rolled, -1, round_of)

    # In-kernel validity audit (the contract's enforcement receipt).
    # Relative tolerance: request magnitudes span cpu-millis to memory
    # bytes, so an absolute epsilon would be meaningless at one end.
    alloc = nodes.allocatable
    tol = jnp.maximum(jnp.abs(alloc) * 1e-5, 1e-4)
    cap_bad = (used > alloc + tol) & (used > nodes.used + tol)
    final_carried = carried & (assigned == carry) & (assigned >= 0)
    ok_static_f = tab.mask[jnp.arange(P), jnp.clip(assigned, 0, None)]
    s_viol = jnp.sum((final_carried & ~ok_static_f).astype(jnp.float32))
    if S:
        st_car = kpair.pair_state_seed(
            snap, static.sig_match, carry, final_carried, mesh=mesh
        )
        ia_f = kpair.ia_ok_at_choice(
            snap, st_car, static.sig_match, carry,
            jnp.where(final_carried, carry, -1),
        )
        sp_f = _spread_excess_mask(
            snap, tab.aff_ok, rank, carry, final_carried, st_car
        )
        p_viol = (jnp.sum((final_carried & has_pair & ~ia_f)
                          .astype(jnp.float32))
                  + jnp.sum(sp_f.astype(jnp.float32)))
    else:
        p_viol = jnp.float32(0.0)
    audit = jnp.stack([
        jnp.sum(cap_bad.astype(jnp.float32)), s_viol,
        jnp.asarray(p_viol, jnp.float32), carried_n, frontier_n,
    ])
    return (assigned, chosen, used, order, round_of, rounds, evicted,
            audit)
