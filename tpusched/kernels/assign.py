"""Commit loops (SURVEY.md C11) and the one-shot score matrix.

`pod_cycle` is one scheduling cycle (Filter + Score + Normalize for one
pod against all nodes) — the device analogue of the reference's
`scheduleOne` body (SURVEY.md §3.1). The cycle splits into:

  * a STATIC part (taints, node affinity, their scores, per-pod QoS
    plugin weights) that depends only on the snapshot — computed once
    for all pods as [P, N] matrices before any commit loop runs; and
  * a DYNAMIC part (resource fit, LeastRequested, BalancedAllocation,
    pairwise spread/affinity) that depends on node `used` and on where
    earlier pods landed — recomputed per step/round.

Two drivers wrap it:
  * solve_sequential — EXACT stock semantics: a lax.scan over pods in
    dynamic-priority order, each step updating node `used` before the
    next pod scores (parity mode; SURVEY.md §7 hard part 1).
  * score_batch — the ScoreBatch API of the north star: all pods scored
    at once against the current snapshot (no commits), vmapped over the
    pod axis — what a Go scheduler calls through the gRPC boundary for
    NormalizeScore + Bind.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from tpusched.config import EngineConfig
from tpusched.kernels import filter as kfilter
from tpusched.kernels import pairwise as kpair
from tpusched.kernels import score as kscore
from tpusched.qos import effective_priority, effective_weights, pressure_of
from tpusched.snapshot import ClusterSnapshot

NEG_INF = -jnp.inf


@struct.dataclass
class StaticCtx:
    """Snapshot-dependent but state-independent precomputation."""

    mask: Any       # [P, N] bool: taints & node affinity & validity
    aff_ok: Any     # [P, N] bool: node-affinity component alone (pairwise
                    # kernels need it for spread domain eligibility)
    score: Any      # [P, N] f32: w_na*NodeAffinity + w_tt*TaintToleration
    w_lr: Any       # [P] f32 per-pod effective plugin weights (QoS)
    w_ba: Any       # [P]
    w_ts: Any       # [P]
    w_ia: Any       # [P]
    rw: Any         # [R] resource score weights


def precompute_static(cfg: EngineConfig, snap: ClusterSnapshot, node_sat_t) -> StaticCtx:
    nodes, pods = snap.nodes, snap.pods
    aff_ok = kfilter.node_affinity_mask(
        node_sat_t, pods.req_term_atoms, pods.req_term_valid
    )
    mask = (
        aff_ok
        & kfilter.taint_mask(nodes.taint_ids, snap.taint_effect, pods.tolerated)
        & nodes.valid[None, :]
        & pods.valid[:, None]
    )
    w = effective_weights(
        cfg, pressure_of(pods.slo_target, pods.observed_avail)
    )  # dict of [P] arrays
    na = kscore.node_affinity_score(
        node_sat_t, pods.pref_term_atoms, pods.pref_term_valid,
        pods.pref_weight, nodes.valid,
    )
    tt = kscore.taint_toleration_score(
        nodes.taint_ids, snap.taint_effect, pods.tolerated, nodes.valid
    )
    static_score = (
        w["node_affinity"][:, None] * na + w["taint_toleration"][:, None] * tt
    ).astype(jnp.float32)
    return StaticCtx(
        mask=mask, aff_ok=aff_ok, score=static_score,
        w_lr=w["least_requested"], w_ba=w["balanced_allocation"],
        w_ts=w["topology_spread"], w_ia=w["interpod_affinity"],
        rw=jnp.asarray(cfg.score_weights_vector(), jnp.float32),
    )


def pod_cycle(cfg: EngineConfig, snap: ClusterSnapshot, member_sat_t,
              static: StaticCtx, p, used, assigned):
    """Dynamic Filter + Score for pod p (traced index): returns
    (feasible [N] bool, total weighted score [N] f32). Grouping of the
    score sum mirrors oracle.feasible_and_score exactly."""
    nodes = snap.nodes
    nvalid = nodes.valid
    req = snap.pods.requests[p]

    spread_ok, spread_pen, ia_ok, ia_raw = kpair.pod_pairwise(
        snap, member_sat_t, p, assigned, static.aff_ok[p]
    )
    feasible = (
        static.mask[p]
        & kfilter.resource_fit(nodes.allocatable, used, req)
        & spread_ok
        & ia_ok
    )
    score = (
        static.w_lr[p] * kscore.least_requested(nodes.allocatable, used, req, static.rw)
        + static.w_ba[p] * kscore.balanced_allocation(nodes.allocatable, used, req, static.rw)
        + static.score[p]
        + static.w_ts[p] * kscore.inverse_normalize(spread_pen, nvalid)
        + static.w_ia[p] * kscore.minmax_normalize(ia_raw, nvalid)
    ).astype(jnp.float32)
    return feasible, score


def pop_order(cfg: EngineConfig, snap: ClusterSnapshot):
    """Queue order (SURVEY.md C10): stable descending sort by dynamic
    QoS priority; invalid pods sink to the end."""
    pods = snap.pods
    prio = effective_priority(
        cfg, pods.base_priority, pods.slo_target, pods.observed_avail
    )
    key = jnp.where(pods.valid, prio, NEG_INF)
    return jnp.argsort(-key, stable=True)


def solve_sequential(cfg: EngineConfig, snap: ClusterSnapshot,
                     node_sat_t, member_sat_t):
    """Exact sequential commit: stock scheduleOne semantics on device."""
    static = precompute_static(cfg, snap, node_sat_t)
    P = snap.pods.valid.shape[0]
    order = pop_order(cfg, snap)

    def body(carry, p):
        used, assigned = carry
        feasible, score = pod_cycle(
            cfg, snap, member_sat_t, static, p, used, assigned
        )
        masked = jnp.where(feasible, score, NEG_INF)
        n = jnp.argmax(masked)  # tie-break: first max (EngineConfig.tie_break)
        commit = jnp.any(feasible)
        used = used.at[n].add(jnp.where(commit, snap.pods.requests[p], 0.0))
        assigned = assigned.at[p].set(jnp.where(commit, n, -1).astype(jnp.int32))
        return (used, assigned), jnp.where(commit, masked[n], NEG_INF)

    init = (snap.nodes.used, jnp.full(P, -1, jnp.int32))
    (used, assigned), chosen_in_order = jax.lax.scan(body, init, order)
    chosen = jnp.full(P, NEG_INF, jnp.float32).at[order].set(chosen_in_order)
    return assigned, chosen, used, order


def score_batch(cfg: EngineConfig, snap: ClusterSnapshot, node_sat_t,
                member_sat_t):
    """One-shot [P, N] feasibility + scores against the current snapshot
    (no commits): the ScoreBatch gRPC surface (SURVEY.md C12)."""
    static = precompute_static(cfg, snap, node_sat_t)
    P = snap.pods.valid.shape[0]
    no_assigned = jnp.full(P, -1, jnp.int32)

    def one(p):
        return pod_cycle(
            cfg, snap, member_sat_t, static, p, snap.nodes.used, no_assigned
        )

    return jax.vmap(one)(jnp.arange(P))
