"""Scoring plugins as fused [P, N] kernels (SURVEY.md C3-C5).

Each function mirrors one upstream Score plugin; formulas are written
with the exact op order of oracle.py so parity holds bitwise in f32.
Normalization helpers implement the NormalizeScore extension point
(per-pod rescale across nodes) with padded nodes masked out.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

# Same Any-alias convention as kernels/filter.py (no jax stubs).
Array = Any

from tpusched.config import EFFECT_PREFER_NO_SCHEDULE, MAX_NODE_SCORE
from tpusched.kernels.atoms import gather_term_sat


def least_requested(alloc: Array, used: Array, requests: Array,
                    resource_weights: Array) -> Array:
    """NodeResourcesFit/LeastAllocated (C3):
    sum_r w_r * (alloc - used - req) * 100 / alloc / sum_r w_r.
    alloc/used: [N, R]; requests: [P, R] or [R]; resource_weights: [R]."""
    if requests.ndim == 1:
        free = alloc - used - requests[None, :]
    else:
        free = alloc[None] - used[None] - requests[:, None, :]
    per_r = jnp.where(alloc > 0, free * MAX_NODE_SCORE / alloc, 0.0)
    per_r = jnp.where(per_r < 0, 0.0, per_r)
    wsum = jnp.maximum(resource_weights.sum(), 1e-9)
    return jnp.sum(per_r * resource_weights, axis=-1) / wsum


def balanced_allocation(alloc: Array, used: Array, requests: Array,
                        resource_weights: Array) -> Array:
    """NodeResourcesBalancedAllocation (C4): (1 - stddev(fractions)) * 100
    over resources with positive score weight."""
    if requests.ndim == 1:
        tot = used + requests[None, :]
    else:
        tot = used[None] + requests[:, None, :]
    frac = jnp.where(alloc > 0, tot / alloc, 1.0)
    frac = jnp.clip(frac, 0.0, 1.0)
    sel = (resource_weights > 0).astype(frac.dtype)
    k = jnp.maximum(sel.sum(), 1.0)
    mean = jnp.sum(frac * sel, axis=-1, keepdims=True) / k
    var = jnp.sum(((frac - mean) ** 2) * sel, axis=-1) / k
    return (1.0 - jnp.sqrt(var)) * MAX_NODE_SCORE


def node_affinity_raw(node_sat_t: Array, pref_term_atoms: Array,
                      pref_term_valid: Array,
                      pref_weight: Array) -> Array:
    """Pre-normalization preferred-affinity score: sum of satisfied term
    weights per (pod, node). CELL-LOCAL (each output cell depends only on
    its pod row and node sat column) — the cacheable half of
    node_affinity_score; the per-pod max-normalization couples a row to
    every node and is re-applied from this raw table each solve (the
    warm-start tableau split)."""
    term_ok = gather_term_sat(node_sat_t, pref_term_atoms)    # [..., PT, N]
    term_ok &= pref_term_valid[..., None]
    return jnp.sum(pref_weight[..., None] * term_ok, axis=-2)  # [..., N]


def node_affinity_score(node_sat_t: Array, pref_term_atoms: Array,
                        pref_term_valid: Array, pref_weight: Array,
                        node_valid: Array) -> Array:
    """Preferred node affinity: sum of satisfied term weights, then
    DefaultNormalizeScore (max -> 100) per pod."""
    raw = node_affinity_raw(node_sat_t, pref_term_atoms, pref_term_valid,
                            pref_weight)
    return default_normalize(raw, node_valid)


def taint_intolerable_count(node_taint_ids: Array, taint_effect: Array,
                            tolerated: Array) -> Array:
    """Intolerable PreferNoSchedule taints per (pod, node), as f32.
    Cell-local (see node_affinity_raw): the cacheable half of
    taint_toleration_score."""
    tid = jnp.clip(node_taint_ids, 0, None)
    soft = (node_taint_ids >= 0) & (taint_effect[tid] == EFFECT_PREFER_NO_SCHEDULE)
    if tolerated.ndim == 1:
        intol = soft & ~tolerated[tid]
    else:
        intol = soft[None] & ~tolerated[:, tid]
    return jnp.sum(intol, axis=-1).astype(jnp.float32)        # [..., N]


def taint_toleration_from_count(count: Array, node_valid: Array) -> Array:
    """Inverse-normalize the intolerable-taint counts (per-pod row max
    coupling — the non-cacheable half of taint_toleration_score)."""
    mx = jnp.max(jnp.where(node_valid, count, 0.0), axis=-1, keepdims=True)
    return jnp.where(
        mx > 0, (mx - count) * MAX_NODE_SCORE / jnp.maximum(mx, 1e-9), MAX_NODE_SCORE
    )


def taint_toleration_score(node_taint_ids: Array, taint_effect: Array,
                           tolerated: Array, node_valid: Array) -> Array:
    """Count intolerable PreferNoSchedule taints, inverse-normalized."""
    count = taint_intolerable_count(node_taint_ids, taint_effect, tolerated)
    return taint_toleration_from_count(count, node_valid)


# -- NormalizeScore helpers (C5) --------------------------------------------


def default_normalize(raw: Array, node_valid: Array) -> Array:
    """Upstream DefaultNormalizeScore: scale so the max becomes 100;
    all-zero (or no valid nodes) -> 0."""
    mx = jnp.max(jnp.where(node_valid, raw, 0.0), axis=-1, keepdims=True)
    return jnp.where(mx > 0, raw * MAX_NODE_SCORE / jnp.maximum(mx, 1e-9), 0.0)


def inverse_normalize(penalty: Array, node_valid: Array) -> Array:
    """Lower penalty -> higher score; all-equal -> 100 (spread score)."""
    big = jnp.where(node_valid, penalty, -jnp.inf)
    sml = jnp.where(node_valid, penalty, jnp.inf)
    mx = jnp.max(big, axis=-1, keepdims=True)
    mn = jnp.min(sml, axis=-1, keepdims=True)
    return jnp.where(
        mx > mn,
        (mx - penalty) * MAX_NODE_SCORE / jnp.maximum(mx - mn, 1e-9),
        MAX_NODE_SCORE,
    )


def minmax_normalize(raw: Array, node_valid: Array) -> Array:
    """Upstream InterPodAffinity normalize: (raw-min)/(max-min)*100,
    max==min -> 0."""
    big = jnp.where(node_valid, raw, -jnp.inf)
    sml = jnp.where(node_valid, raw, jnp.inf)
    mx = jnp.max(big, axis=-1, keepdims=True)
    mn = jnp.min(sml, axis=-1, keepdims=True)
    return jnp.where(
        mx > mn, (raw - mn) * MAX_NODE_SCORE / jnp.maximum(mx - mn, 1e-9), 0.0
    )
