"""Kubernetes API client for the host scheduler (SURVEY.md C13, §1.2 L1).

The reference's only process boundary is the API-server client
(client-go informers + the Bind subresource POST; SURVEY.md §3.1). This
module is that boundary for the TPU host shim: `KubeApiClient` speaks
the same read/write interface as `host.FakeApiServer` (list_nodes /
pending_pods / bound_pods / bind / delete_pod) over plain Kubernetes
REST — list, watch, the Binding subresource, and the Eviction
subresource — translating V1Node/V1Pod JSON into the builder-style
records the wire codec consumes (rpc.codec.snapshot_to_proto).

No kubernetes client library exists in this image, so the transport is
stdlib urllib with kubeconfig/in-cluster auth:

  * kubeconfig (~/.kube/config or $KUBECONFIG): current-context server,
    CA bundle, bearer token or client certificate;
  * in-cluster: /var/run/secrets/kubernetes.io/serviceaccount token +
    KUBERNETES_SERVICE_HOST, the same resolution order client-go uses.

A `KubeWatcher` runs list+watch streams over pods/nodes and accumulates
the names of objects each event touched; `drain_changed()` feeds the
DeltaSession's `changed` hints so per-cycle diffs are O(churn)
(rpc.codec.delta_between). On watch failure it re-lists and reports one
`None` (hints unknown -> the session does a full byte-diff), mirroring
informer resync semantics (SURVEY.md §5 "Failure detection").
"""

from __future__ import annotations

import atexit
import base64
import json
import os
import random
import ssl
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

from tpusched import metrics as pm
from tpusched import trace as tracing
from tpusched.faults import NO_FAULTS, FaultError
from tpusched.host import Conflict
from tpusched.config import (
    DEFAULT_OBSERVED_AVAIL,
    DEFAULT_SLO_TARGET,
    clamp01,
)
from tpusched.snapshot import (
    MatchExpression,
    NodeSelectorTerm,
    PodAffinityTerm,
    PreferredTerm,
    Toleration,
    TopologySpreadConstraint,
)

# Annotations carrying the QoS-driven scheduler's per-pod SLO signal
# (the reference stores availability targets/observations out of band;
# annotations are the conventional k8s side channel for them).
ANN_SLO_TARGET = "tpusched.io/slo-target"
ANN_OBSERVED = "tpusched.io/observed-availability"
# scheduler-plugins coscheduling convention for gang membership.
LABEL_POD_GROUP = "scheduling.x-k8s.io/pod-group"
ANN_MIN_MEMBER = "scheduling.x-k8s.io/min-member"

DEFAULT_SCHEDULER_NAME = "tpu-scheduler"


def _ann_float(ann: dict, key: str, default: float) -> float:
    """Tolerant annotation parse: annotations are user-controlled free
    text, and one pod annotated e.g. `slo-target: "high"` must degrade
    to the default for THAT pod — a bare float() here would raise inside
    pending_pods() every cycle and crash-loop the scheduler for the
    whole cluster."""
    try:
        return float(ann.get(key, default))
    except (TypeError, ValueError):
        return float(default)


def _ann_int(ann: dict, key: str, default: int) -> int:
    """Integer twin of _ann_float (same crash-loop rationale). Accepts
    float-shaped strings ("4.0") the way k8s users write them."""
    try:
        return int(float(ann.get(key, default)))
    except (TypeError, ValueError):
        return int(default)


# Rate-limited clamp warnings: (annotation key, direction) -> (last
# emit monotonic time, suppressed count). Same shape as the informer's
# watch-failure limiter — out-of-range annotations on a popular
# deployment would otherwise print once per pod per cycle.
_clamp_warn_lock = threading.Lock()
_clamp_warn_last: dict[tuple[str, str], tuple[float, int]] = {}
CLAMP_WARN_INTERVAL = 30.0


def _warn_clamped(key: str, raw: float, clamped: float) -> None:
    direction = "high" if raw > clamped else "low"
    now = time.monotonic()
    with _clamp_warn_lock:
        last, suppressed = _clamp_warn_last.get((key, direction), (0.0, 0))
        if now - last < CLAMP_WARN_INTERVAL:
            _clamp_warn_last[(key, direction)] = (last, suppressed + 1)
            return
        _clamp_warn_last[(key, direction)] = (now, 0)
    extra = f" ({suppressed} repeats suppressed)" if suppressed else ""
    print(
        f"tpusched: annotation {key}={raw!r} outside [0, 1]; clamped "
        f"to {clamped}{extra}",
        file=sys.stderr, flush=True,
    )


def _ann_unit(ann: dict, key: str, default: float) -> float:
    """_ann_float restricted to the unit interval: slo-target and
    observed-availability are FRACTIONS, and an out-of-range value
    (slo-target "1.5", observed "-3") would otherwise flow straight
    into the pressure math — clip(slo - avail, 0, 1) saturates, every
    such pod pins maximum pressure forever and the queue inverts.
    Clamp on parse, with a rate-limited warning so a misconfigured
    deployment is visible without a per-pod-per-cycle stderr flood."""
    v = _ann_float(ann, key, default)
    if 0.0 <= v <= 1.0:
        return v
    # Non-finite falls back to the DEFAULT, not a clamp edge: NaN
    # carries no ordering information at all (and would sail through
    # min/max), and ±inf is equally meaningless as a fraction.
    clamped = clamp01(v, default=default)
    _warn_clamped(key, v, clamped)
    return clamped

# Sentinel distinguishing "no drain has pinned a PDB resolver yet"
# from a pinned resolver of None (no PDBs / RBAC-denied).
_UNSET = object()


def qualified_name(namespace: str, name: str) -> str:
    """Record identity for pods: 'namespace/name'. Pod names are only
    unique per namespace in Kubernetes; bare names would collide in the
    informer cache, the change-hint set, and the delta transport's
    name-keyed stores (two 'web-0's in different namespaces would
    silently collapse to one). Nodes are cluster-scoped and keep bare
    names."""
    return f"{namespace or 'default'}/{name}"


def split_qualified(qname: str) -> tuple[str, str]:
    """Inverse of qualified_name; tolerates bare names ('default')."""
    ns, sep, name = qname.partition("/")
    if not sep:
        return "default", qname
    return ns, name

_SUFFIX = {
    "Ki": 1024.0, "Mi": 1024.0**2, "Gi": 1024.0**3, "Ti": 1024.0**4,
    "Pi": 1024.0**5, "Ei": 1024.0**6,
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18,
    "m": 1e-3,
}


def parse_quantity(q) -> float:
    """Kubernetes resource.Quantity -> float (base units)."""
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q).strip()
    for suf, mult in _SUFFIX.items():
        if s.endswith(suf):
            return float(s[: -len(suf)]) * mult
    return float(s)


def pod_requests(spec: dict) -> dict[str, float]:
    """Sum container requests the way the scheduler does: max(sum of
    containers, each initContainer) per resource, cpu in millicores,
    memory in bytes, plus the implicit pods=1."""
    total: dict[str, float] = {}

    def acc(out, res):
        for k, v in (res or {}).items():
            val = parse_quantity(v)
            if k == "cpu":
                val *= 1000.0
            out[k] = out.get(k, 0.0) + val

    for c in spec.get("containers", []):
        acc(total, c.get("resources", {}).get("requests"))
    for c in spec.get("initContainers", []):
        init: dict[str, float] = {}
        acc(init, c.get("resources", {}).get("requests"))
        for k, v in init.items():
            total[k] = max(total.get(k, 0.0), v)
    total["pods"] = 1.0
    return total


def _exprs(sel: dict | None) -> tuple[MatchExpression, ...]:
    """labelSelector / nodeSelectorTerm -> MatchExpression tuple."""
    if not sel:
        return ()
    out = []
    for k, v in (sel.get("matchLabels") or {}).items():
        out.append(MatchExpression(k, "In", (str(v),)))
    for e in sel.get("matchExpressions") or []:
        out.append(MatchExpression(
            e["key"], e["operator"],
            tuple(str(v) for v in e.get("values") or ()),
        ))
    return tuple(out)


def node_record(obj: dict) -> dict:
    """V1Node JSON -> builder node record."""
    meta = obj.get("metadata", {})
    spec = obj.get("spec", {})
    status = obj.get("status", {})
    alloc = {}
    for k, v in (status.get("allocatable") or {}).items():
        val = parse_quantity(v)
        if k == "cpu":
            val *= 1000.0
        alloc[k] = val
    return dict(
        name=meta["name"],
        allocatable=alloc,
        labels=dict(meta.get("labels") or {}),
        taints=[
            (t["key"], t.get("value", ""), t.get("effect", "NoSchedule"))
            for t in spec.get("taints") or []
        ],
        unschedulable=bool(spec.get("unschedulable", False)),
    )


def _affinity_terms(spec: dict) -> list[PodAffinityTerm]:
    aff = spec.get("affinity") or {}
    out: list[PodAffinityTerm] = []
    for kind, anti in (("podAffinity", False), ("podAntiAffinity", True)):
        a = aff.get(kind) or {}
        for t in a.get("requiredDuringSchedulingIgnoredDuringExecution") or []:
            out.append(PodAffinityTerm(
                topology_key=t["topologyKey"],
                selector=_exprs(t.get("labelSelector")),
                anti=anti, required=True,
                namespaces=tuple(t.get("namespaces") or ()),
            ))
        for w in a.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
            t = w.get("podAffinityTerm", {})
            out.append(PodAffinityTerm(
                topology_key=t.get("topologyKey", ""),
                selector=_exprs(t.get("labelSelector")),
                anti=anti, required=False,
                weight=float(w.get("weight", 1)),
                namespaces=tuple(t.get("namespaces") or ()),
            ))
    return out


def pending_record(obj: dict) -> dict:
    """Pending V1Pod JSON -> builder pod record."""
    meta = obj.get("metadata", {})
    spec = obj.get("spec", {})
    ann = meta.get("annotations") or {}
    labels = dict(meta.get("labels") or {})
    aff = spec.get("affinity") or {}
    node_aff = aff.get("nodeAffinity") or {}
    req = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    required_terms = tuple(
        NodeSelectorTerm(_exprs(t))
        for t in req.get("nodeSelectorTerms") or []
        if _exprs(t)
    )
    preferred_terms = tuple(
        PreferredTerm(
            float(w.get("weight", 1)),
            NodeSelectorTerm(_exprs(w.get("preference"))),
        )
        for w in node_aff.get(
            "preferredDuringSchedulingIgnoredDuringExecution"
        ) or []
    )
    ns = meta.get("namespace", "default")
    rec = dict(
        name=qualified_name(ns, meta["name"]),
        namespace=ns,
        requests=pod_requests(spec),
        priority=float(spec.get("priority", 0)),
        slo_target=_ann_unit(ann, ANN_SLO_TARGET, DEFAULT_SLO_TARGET),
        observed_avail=_ann_unit(ann, ANN_OBSERVED, DEFAULT_OBSERVED_AVAIL),
        labels=labels,
        node_selector=dict(spec.get("nodeSelector") or {}),
        required_terms=required_terms,
        preferred_terms=preferred_terms,
        tolerations=[
            Toleration(
                key=t.get("key", ""),
                operator=t.get("operator", "Equal"),
                value=t.get("value", ""),
                effect=t.get("effect", ""),
            )
            for t in spec.get("tolerations") or []
        ],
        topology_spread=[
            TopologySpreadConstraint(
                topology_key=c["topologyKey"],
                max_skew=int(c.get("maxSkew", 1)),
                when_unsatisfiable=c.get("whenUnsatisfiable", "DoNotSchedule"),
                selector=_exprs(c.get("labelSelector")),
            )
            for c in spec.get("topologySpreadConstraints") or []
        ],
        pod_affinity=_affinity_terms(spec),
        submitted=meta.get("creationTimestamp"),
    )
    group = labels.get(LABEL_POD_GROUP)
    if group:
        rec["pod_group"] = group
        rec["pod_group_min_member"] = _ann_int(ann, ANN_MIN_MEMBER, 0)
    return rec


def running_record(obj: dict, pdb_of=None) -> dict:
    """Bound V1Pod JSON -> builder running record. pdb_of: optional
    callable (namespace, labels) -> (pdb_name, disruptions_allowed) for
    PodDisruptionBudget coverage."""
    meta = obj.get("metadata", {})
    spec = obj.get("spec", {})
    ann = meta.get("annotations") or {}
    labels = dict(meta.get("labels") or {})
    ns = meta.get("namespace", "default")
    slo = _ann_unit(ann, ANN_SLO_TARGET, DEFAULT_SLO_TARGET)
    observed = _ann_unit(ann, ANN_OBSERVED, DEFAULT_OBSERVED_AVAIL)
    rec = dict(
        name=qualified_name(ns, meta["name"]),
        namespace=ns,
        node=spec.get("nodeName", ""),
        requests=pod_requests(spec),
        priority=float(spec.get("priority", 0)),
        labels=labels,
        pod_affinity=_affinity_terms(spec),
        slack=observed - slo,
    )
    if pdb_of is not None:
        hit = pdb_of(ns, labels)
        if hit is not None:
            rec["pdb_group"], rec["pdb_disruptions_allowed"] = hit
    return rec


# ---------------------------------------------------------------------------
# Transport / auth.
# ---------------------------------------------------------------------------


class KubeConfigError(Exception):
    pass


def _load_cert_chain(sslctx: ssl.SSLContext, cert: "str | bytes",
                     key: "str | bytes") -> None:
    """load_cert_chain where either half may be a filesystem path (str)
    or decoded in-memory PEM (bytes). The ssl module only takes file
    paths, so in-memory material touches disk for the duration of ONE
    call — NamedTemporaryFile (0600) unlinked in `finally`, with an
    atexit backstop for the window where a hard crash inside
    load_cert_chain could skip the finally. Round-5 ADVICE: the old
    `delete=False`-and-forget left decoded client keys in /tmp for the
    life of the host."""
    paths = []
    args = []
    try:
        for blob in (cert, key):
            if isinstance(blob, bytes):
                f = tempfile.NamedTemporaryFile(delete=False, suffix=".pem")
                paths.append(f.name)
                atexit.register(_unlink_quiet, f.name)
                f.write(blob)
                f.close()
                args.append(f.name)
            else:
                args.append(blob)
        sslctx.load_cert_chain(args[0], args[1])
    finally:
        for p in paths:
            _unlink_quiet(p)


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def load_kubeconfig(path: str | None = None) -> dict:
    """Resolve (server, ssl_context, headers) from a kubeconfig file or
    the in-cluster service account, client-go resolution order."""
    import yaml

    path = path or os.environ.get(
        "KUBECONFIG", os.path.expanduser("~/.kube/config")
    )
    if os.path.exists(path):
        with open(path) as f:
            cfg = yaml.safe_load(f) or {}
        ctx_name = cfg.get("current-context")
        ctx = next(
            (c["context"] for c in cfg.get("contexts", [])
             if c["name"] == ctx_name), None,
        )
        if ctx is None:
            raise KubeConfigError(f"no current-context in {path}")
        cluster = next(
            (c["cluster"] for c in cfg.get("clusters", [])
             if c["name"] == ctx["cluster"]), None,
        )
        user = next(
            (u["user"] for u in cfg.get("users", [])
             if u["name"] == ctx.get("user")), {},
        ) or {}
        if cluster is None:
            raise KubeConfigError(f"context {ctx_name} names no cluster")
        server = cluster["server"]
        sslctx = ssl.create_default_context()
        if cluster.get("insecure-skip-tls-verify"):
            sslctx.check_hostname = False
            sslctx.verify_mode = ssl.CERT_NONE
        elif cluster.get("certificate-authority-data"):
            # cadata= takes the decoded PEM directly: the CA bundle
            # never touches disk (round-5 ADVICE: the old tempfile was
            # never removed).
            sslctx = ssl.create_default_context(
                cadata=base64.b64decode(
                    cluster["certificate-authority-data"]
                ).decode()
            )
        elif cluster.get("certificate-authority"):
            sslctx = ssl.create_default_context(
                cafile=cluster["certificate-authority"]
            )
        headers = {}
        if user.get("token"):
            headers["Authorization"] = f"Bearer {user['token']}"
        cert = key = None
        if user.get("client-certificate-data"):
            cert = base64.b64decode(user["client-certificate-data"])
        elif user.get("client-certificate"):
            cert = user["client-certificate"]
        if user.get("client-key-data"):
            key = base64.b64decode(user["client-key-data"])
        elif user.get("client-key"):
            key = user["client-key"]
        if cert is not None and key is not None:
            # bytes halves pass through one scoped tempfile, unlinked
            # before this returns (ssl has no loader for PEM bytes).
            _load_cert_chain(sslctx, cert, key)
        return dict(server=server, ssl=sslctx, headers=headers)
    # In-cluster fallback.
    sa = "/var/run/secrets/kubernetes.io/serviceaccount"
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    if host and os.path.exists(f"{sa}/token"):
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(f"{sa}/token") as f:
            token = f.read().strip()
        sslctx = ssl.create_default_context(cafile=f"{sa}/ca.crt")
        return dict(
            server=f"https://{host}:{port}", ssl=sslctx,
            headers={"Authorization": f"Bearer {token}"},
        )
    raise KubeConfigError(
        f"no kubeconfig at {path} and not running in-cluster"
    )


class KubeApiClient:
    """FakeApiServer-interface adapter over Kubernetes REST.

    `base_url` (e.g. "http://127.0.0.1:8001" via `kubectl proxy`, or a
    test server) bypasses kubeconfig resolution entirely — auth-free
    plain HTTP, which is also what the contract tests use.
    """

    def __init__(
        self,
        base_url: str | None = None,
        kubeconfig: str | None = None,
        scheduler_name: str = DEFAULT_SCHEDULER_NAME,
        timeout: float = 30.0,
    ):
        if base_url is not None:
            self._server = base_url.rstrip("/")
            self._ssl = None
            self._headers: dict[str, str] = {}
        else:
            resolved = load_kubeconfig(kubeconfig)
            self._server = resolved["server"].rstrip("/")
            self._ssl = resolved["ssl"]
            self._headers = resolved["headers"]
        self.scheduler_name = scheduler_name
        self.timeout = timeout
        self.bind_count = 0
        self.delete_count = 0
        # The host issues binds/deletes from a thread pool (round 6):
        # bare += on the counters would lose increments.
        self._count_lock = threading.Lock()

    # -- raw REST -----------------------------------------------------------

    def _request(self, method: str, path: str, body: dict | None = None,
                 timeout: float | None = None,
                 content_type: str = "application/json"):
        url = self._server + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        for k, v in self._headers.items():
            req.add_header(k, v)
        if data is not None:
            req.add_header("Content-Type", content_type)
        kw = {"timeout": timeout or self.timeout}
        if self._ssl is not None:
            kw["context"] = self._ssl
        return urllib.request.urlopen(req, **kw)

    def _json(self, method: str, path: str, body: dict | None = None,
              content_type: str = "application/json"):
        with self._request(method, path, body,
                           content_type=content_type) as resp:
            return json.loads(resp.read() or b"{}")

    # -- reads (FakeApiServer interface) ------------------------------------

    def list_nodes(self) -> list[dict]:
        obj = self._json("GET", "/api/v1/nodes")
        return [node_record(o) for o in obj.get("items", [])]

    def _list_pods(self) -> dict:
        return self._json("GET", "/api/v1/pods")

    def pending_pods(self) -> list[dict]:
        out = []
        for o in self._list_pods().get("items", []):
            spec = o.get("spec", {})
            phase = o.get("status", {}).get("phase", "Pending")
            if spec.get("nodeName") or phase != "Pending":
                continue
            if spec.get("schedulerName", "default-scheduler") != self.scheduler_name:
                continue
            out.append(pending_record(o))
        return out

    def bound_pods(self) -> list[dict]:
        pdb_of = self._pdb_resolver()
        out = []
        for o in self._list_pods().get("items", []):
            if not o.get("spec", {}).get("nodeName"):
                continue
            if o.get("status", {}).get("phase") in ("Succeeded", "Failed"):
                continue
            out.append(running_record(o, pdb_of))
        return out

    def _pdb_resolver(self):
        """(namespace, labels) -> (pdb name, disruptionsAllowed) from
        policy/v1 PodDisruptionBudgets; None resolver on RBAC denial
        (PDB awareness degrades gracefully to 'uncovered')."""
        try:
            obj = self._json("GET", "/apis/policy/v1/poddisruptionbudgets")
        except (urllib.error.URLError, urllib.error.HTTPError, OSError):
            return None
        pdbs = []
        for o in obj.get("items", []):
            meta = o.get("metadata", {})
            sel = _exprs(o.get("spec", {}).get("selector"))
            allowed = int(o.get("status", {}).get("disruptionsAllowed", 0))
            pdbs.append((meta.get("namespace", "default"),
                         meta.get("name", ""), sel, allowed))
        if not pdbs:
            return None

        def match(ns: str, labels: dict):
            for pns, name, sel, allowed in pdbs:
                if pns != ns:
                    continue
                ok = True
                for e in sel:
                    v = labels.get(e.key)
                    if e.op == "In":
                        ok = v in e.values
                    elif e.op == "NotIn":
                        ok = v is not None and v not in e.values
                    elif e.op == "Exists":
                        ok = v is not None
                    elif e.op == "DoesNotExist":
                        ok = v is None
                    if not ok:
                        break
                if ok and sel:
                    return name, allowed
            return None

        return match

    # -- writes -------------------------------------------------------------

    def bind(self, pod_name: str, node_name: str) -> None:
        """POST the Binding subresource; 404/409 -> host.Conflict (the
        idempotent-bind story, SURVEY.md §5 'Failure detection').
        pod_name is the qualified 'namespace/name' record identity."""

        namespace, name = split_qualified(pod_name)
        body = {
            "apiVersion": "v1", "kind": "Binding",
            "metadata": {"name": name, "namespace": namespace},
            "target": {"apiVersion": "v1", "kind": "Node",
                       "name": node_name},
        }
        try:
            self._json(
                "POST",
                f"/api/v1/namespaces/{namespace}/pods/"
                f"{urllib.parse.quote(name)}/binding",
                body,
            )
        except urllib.error.HTTPError as e:
            if e.code in (404, 409):
                raise Conflict(
                    f"bind {pod_name} -> {node_name}: HTTP {e.code}"
                ) from e
            raise
        with self._count_lock:
            self.bind_count += 1

    def annotate_pod(self, pod_name: str, annotations: dict) -> bool:
        """Merge-PATCH annotations onto a pod (RFC 7386: absent keys
        keep their values). The QoS write-back primitive: an
        availability monitor publishes what it measured so the NEXT
        scheduling cycle's pressure math sees it — the out-of-band
        channel the reference stores SLO observations in. pod_name is
        the qualified 'namespace/name' record identity. Same race
        contract as delete_pod: a pod deleted between measure and
        PATCH (404) or a throttled apiserver (429) returns False —
        'try again later', never a cycle-fatal error."""
        namespace, name = split_qualified(pod_name)
        try:
            self._json(
                "PATCH",
                f"/api/v1/namespaces/{namespace}/pods/"
                f"{urllib.parse.quote(name)}",
                {"metadata": {"annotations": {
                    str(k): str(v) for k, v in annotations.items()
                }}},
                content_type="application/merge-patch+json",
            )
        except urllib.error.HTTPError as e:
            if e.code in (404, 410, 429):
                return False
            raise
        return True

    def write_observed_availability(self, pod_name: str,
                                    avail: float) -> bool:
        """Publish one pod's lifecycle-accounted availability to the
        tpusched.io/observed-availability annotation, clamped to the
        unit interval the parse side enforces (_ann_unit) — the two
        ends of the write-back path agree on the domain by
        construction."""
        clamped = clamp01(avail, default=DEFAULT_OBSERVED_AVAIL)
        return self.annotate_pod(pod_name, {ANN_OBSERVED: f"{clamped:.6f}"})

    def delete_pod(self, pod_name: str) -> bool:
        """Eviction subresource; falls back to plain DELETE where the
        eviction API is unavailable. Idempotent and PDB-aware: a
        missing pod OR a budget-blocked eviction (HTTP 429, the
        apiserver's disruptions-exhausted denial) returns False — the
        host treats an un-evicted victim as 'try again later', never as
        a cycle-fatal error. pod_name is the qualified
        'namespace/name' record identity."""
        namespace, name = split_qualified(pod_name)
        ev = {
            "apiVersion": "policy/v1", "kind": "Eviction",
            "metadata": {"name": name, "namespace": namespace},
        }
        quoted = urllib.parse.quote(name)
        try:
            self._json(
                "POST",
                f"/api/v1/namespaces/{namespace}/pods/{quoted}/eviction",
                ev,
            )
        except urllib.error.HTTPError as e:
            if e.code == 404:
                try:
                    self._json(
                        "DELETE",
                        f"/api/v1/namespaces/{namespace}/pods/{quoted}",
                    )
                except urllib.error.HTTPError as e2:
                    if e2.code == 404:
                        return False
                    raise
            elif e.code in (410, 429):
                return False
            else:
                raise
        with self._count_lock:
            self.delete_count += 1
        return True


# ---------------------------------------------------------------------------
# Informer cache: list+watch -> local object cache + exact change hints.
# ---------------------------------------------------------------------------


class KubeInformer:
    """Informer-fed cluster cache (the reference's L2 layer, SURVEY.md
    §1.2): one list establishes the cache, watch streams apply events
    to it, and each cycle's snapshot is served FROM the cache — so
    drain_changed() is exactly the set of objects whose events arrived
    since the last drain, the hint contract codec.delta_between wants
    (a fresh re-list per cycle could include state whose watch event
    had not arrived yet, shipping a stale delta record).

    bind()/delete_pod() delegate to the client and optimistically apply
    the result to the cache (upstream's "assume" step) so the next
    cycle doesn't re-schedule a pod whose Bound event is still in
    flight; the real event confirms or corrects.

    On watch failure (HTTP error, 410 Gone) the informer re-lists,
    rebuilds its cache, and the next drain_changed() returns None ONCE
    ("hints unknown — diff everything"), the informer-resync contract
    the DeltaSession expects (SURVEY.md §5 'Failure detection')."""

    _POD_PATH = "/api/v1/pods"
    _NODE_PATH = "/api/v1/nodes"

    def __init__(self, client: KubeApiClient, poll_timeout: float = 30.0,
                 faults=None, backoff_seed: int | None = None):
        self.client = client
        self.poll_timeout = poll_timeout
        # faults: optional tpusched.faults.FaultPlan; site "kube.watch"
        # fires at the top of every watch-stream attempt (an error rule
        # is a flapping apiserver: the loop takes its relist/backoff
        # path, exactly like a real watch failure).
        self._faults = faults if faults is not None else NO_FAULTS
        # Span collector for kube.watch.reconnect events; None = the
        # process default at emit time.
        self.tracer = None
        self.scheduler_name = client.scheduler_name
        self._lock = threading.Lock()
        self._objs: dict[str, dict[str, dict]] = {
            self._POD_PATH: {}, self._NODE_PATH: {},
        }
        self._changed: set[str] = set()
        self._dirty_all = True
        # Bumped on every cache-replacing re-list: a host that drained
        # hints BEFORE a relist landed must not trust them for the
        # snapshot it builds AFTER (see relist_epoch()).
        self._epoch = 0
        # Previous cycle's per-pod PDB resolution, so budget changes
        # (which arrive with no pod watch event) still hint the pods
        # whose running records they alter; _pdb_of_current pins the
        # resolver drain_changed fetched so bound_pods builds records
        # from the same data the hints cover.
        self._pdb_seen: dict[str, tuple] = {}
        self._pdb_of_current = _UNSET
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.bind_count = 0
        self.delete_count = 0
        # Rate-limited watch-failure reporting: (path, failure class) ->
        # (last emit monotonic time, suppressed-since-then count). A
        # watch loop stuck on 401s must be VISIBLE — the host otherwise
        # just sees an ever-staler cache — without a 2-lines-per-second
        # stderr flood from the 0.5 s retry loop.
        self._err_log_lock = threading.Lock()
        self._err_last: dict[tuple[str, str], tuple[float, int]] = {}
        self.watch_err_interval = 30.0
        # Watch-retry backoff (ISSUE 3 satellite): consecutive failures
        # back off exponentially from watch_backoff_initial to the
        # ~watch_backoff_max cap, jittered, instead of the old fixed
        # 0.5 s relist spin against an unreachable apiserver. The
        # jitter rng seeds from ENTROPY by default — K replicas
        # sharing one fixed seed would relist in lockstep, the exact
        # herd the jitter exists to break; tests/chaos pass
        # backoff_seed to pin the sequence.
        self.watch_backoff_initial = 0.5
        self.watch_backoff_max = 30.0
        self._watch_rng = random.Random(backoff_seed)
        # Prometheus export (round 9, ISSUE 4 satellite): reconnects and
        # backoff time were in-memory-only state; now they're counters
        # in the process-default registry (tpusched.metrics.render_
        # default()) — shared across informers in one process, like
        # prometheus_client families — plus instance mirrors for tests.
        self.watch_reconnects = 0
        self.watch_backoff_s = 0.0
        self._m_reconnects = pm.Counter(
            "tpusched_kube_watch_reconnects_total",
            "watch-stream failures that took the relist/backoff path",
            ("path",))
        self._m_backoff = pm.Counter(
            "tpusched_kube_watch_backoff_seconds_total",
            "seconds spent backing off failed watch streams", ("path",))

    def _log_watch_failure(self, path: str, exc: BaseException) -> None:
        """One stderr line per (path, failure class) per
        watch_err_interval, with a count of suppressed repeats."""
        if isinstance(exc, urllib.error.HTTPError):
            klass = f"http-{exc.code}"
        elif isinstance(exc, urllib.error.URLError):
            klass = f"url-{type(getattr(exc, 'reason', exc)).__name__}"
        elif isinstance(exc, json.JSONDecodeError):
            klass = "json-decode"
        else:
            klass = type(exc).__name__
        now = time.monotonic()
        with self._err_log_lock:
            last, suppressed = self._err_last.get((path, klass), (0.0, 0))
            if now - last < self.watch_err_interval:
                self._err_last[(path, klass)] = (last, suppressed + 1)
                return
            self._err_last[(path, klass)] = (now, 0)
        extra = f" ({suppressed} repeats suppressed)" if suppressed else ""
        print(
            f"tpusched informer: watch {path} failed [{klass}]: "
            f"{exc}{extra}; re-listing and retrying",
            file=sys.stderr, flush=True,
        )

    @staticmethod
    def _key_of(path: str, obj: dict) -> str | None:
        """Cache/hint key: pods are namespace-qualified (names are only
        unique per namespace), nodes cluster-scoped."""
        meta = obj.get("metadata", {})
        name = meta.get("name")
        if not name:
            return None
        if path == KubeInformer._POD_PATH:
            return qualified_name(meta.get("namespace", "default"), name)
        return name

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        for path in (self._POD_PATH, self._NODE_PATH):
            rv = self._relist(path)
            t = threading.Thread(
                target=self._watch_loop, args=(path, rv), daemon=True,
                name=f"tpusched-kube-watch-{path.rsplit('/', 1)[-1]}",
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()

    def _relist(self, path: str) -> str:
        obj = self.client._json("GET", path)
        fresh = {}
        for o in obj.get("items", []):
            k = self._key_of(path, o)
            if k:
                fresh[k] = o
        with self._lock:
            self._objs[path] = fresh
            self._dirty_all = True
            self._epoch += 1
            self._changed.clear()
        return obj.get("metadata", {}).get("resourceVersion", "")

    def _watch_backoff(self, failures: int) -> float:
        """Delay before watch-relist attempt number `failures` (1-based):
        0.5 s, 1 s, 2 s, ... capped near watch_backoff_max, scaled by a
        uniform [0.5, 1.0) jitter so K informers hammering one
        unreachable apiserver desynchronize instead of relisting in
        lockstep. The failure counter resets as soon as a watch stream
        connects again."""
        # Exponent clamped BEFORE the power: an hours-long outage grows
        # `failures` unbounded and 2.0**1025 raises OverflowError inside
        # the except handler — killing the watch thread for good.
        base = min(
            self.watch_backoff_initial
            * 2.0 ** min(max(failures - 1, 0), 16),
            self.watch_backoff_max,
        )
        return base * (0.5 + 0.5 * self._watch_rng.random())

    def _watch_loop(self, path: str, rv: str = ""):
        failures = 0
        while not self._stop.is_set():
            try:
                self._faults.fire("kube.watch")
                if not rv:
                    rv = self._relist(path)
                q = urllib.parse.urlencode(
                    {"watch": "1", "resourceVersion": rv,
                     "timeoutSeconds": int(self.poll_timeout)}
                )
                with self.client._request(
                    "GET", f"{path}?{q}",
                    timeout=self.poll_timeout + 10.0,
                ) as resp:
                    # Connected: the apiserver is back, stop backing off.
                    failures = 0
                    for line in resp:
                        if self._stop.is_set():
                            return
                        if not line.strip():
                            continue
                        evt = json.loads(line)
                        if evt.get("type") == "ERROR":
                            rv = ""  # 410 Gone: re-list
                            break
                        obj = evt.get("object", {})
                        rv = obj.get("metadata", {}).get(
                            "resourceVersion", rv
                        )
                        key = self._key_of(path, obj)
                        if not key:
                            continue
                        with self._lock:
                            if evt.get("type") == "DELETED":
                                self._objs[path].pop(key, None)
                            else:
                                self._objs[path][key] = obj
                            self._changed.add(key)
            except (urllib.error.URLError, urllib.error.HTTPError,
                    OSError, json.JSONDecodeError, FaultError) as e:
                self._log_watch_failure(path, e)
                rv = ""
                failures += 1
                delay = self._watch_backoff(failures)
                self.watch_reconnects += 1
                self._m_reconnects.labels(path).inc()
                (self.tracer or tracing.DEFAULT).record(
                    "kube.watch.reconnect", cat="kube", path=path,
                    failures=failures, backoff_s=round(delay, 3),
                )
                t0 = time.monotonic()
                stopped = self._stop.wait(delay)
                # Seconds actually SPENT backing off — stop() mid-wait
                # must not bank the full capped delay.
                waited = time.monotonic() - t0
                self.watch_backoff_s += waited
                self._m_backoff.labels(path).inc(waited)
                if stopped:
                    return

    # -- FakeApiServer read interface, served from the cache ----------------

    def _pods(self) -> list[dict]:
        with self._lock:
            return list(self._objs[self._POD_PATH].values())

    def list_nodes(self) -> list[dict]:
        with self._lock:
            nodes = list(self._objs[self._NODE_PATH].values())
        return [node_record(o) for o in nodes]

    def pending_pods(self) -> list[dict]:
        out = []
        for o in self._pods():
            spec = o.get("spec", {})
            phase = o.get("status", {}).get("phase", "Pending")
            if spec.get("nodeName") or phase != "Pending":
                continue
            if spec.get("schedulerName", "default-scheduler") != self.scheduler_name:
                continue
            out.append(pending_record(o))
        return out

    def _bound_objs(self) -> list[dict]:
        return [
            o for o in self._pods()
            if o.get("spec", {}).get("nodeName")
            and o.get("status", {}).get("phase") not in
            ("Succeeded", "Failed")
        ]

    def bound_pods(self) -> list[dict]:
        # Use the PDB resolution pinned by the last drain_changed() so
        # the records match the hints computed there; standalone use
        # (no delta host) fetches fresh.
        pdb_of = self._pdb_of_current
        if pdb_of is _UNSET:
            pdb_of = self.client._pdb_resolver()
        return [running_record(o, pdb_of) for o in self._bound_objs()]

    def _refresh_pdb_hints(self) -> None:
        """PDB status changes arrive with NO pod watch event but alter
        running records: fetch the budgets ONCE per cycle (here, at
        drain time — before the host reads the cache, so the hints
        cover exactly the resolution the snapshot will use), and hint
        every pod whose resolved budget moved since the last cycle
        (codec contract: 'name everything you touch')."""
        pdb_of = self.client._pdb_resolver()
        pdb_now: dict[str, tuple] = {}
        for o in self._bound_objs():
            meta = o.get("metadata", {})
            ns = meta.get("namespace", "default")
            key = qualified_name(ns, meta.get("name", ""))
            hit = pdb_of(ns, dict(meta.get("labels") or {})) if pdb_of else None
            pdb_now[key] = hit
        with self._lock:
            self._pdb_of_current = pdb_of
            for name, cur in pdb_now.items():
                if name in self._pdb_seen and self._pdb_seen[name] != cur:
                    self._changed.add(name)
            self._pdb_seen = pdb_now

    # -- writes: delegate + assume ------------------------------------------

    def bind(self, pod_name: str, node_name: str) -> None:
        self.client.bind(pod_name, node_name)
        with self._lock:
            # Counter under the lock: the host issues binds from a
            # thread pool (round 6) and bare += loses increments.
            self.bind_count += 1
            obj = self._objs[self._POD_PATH].get(pod_name)
            if obj is not None:
                obj.setdefault("spec", {})["nodeName"] = node_name
                self._changed.add(pod_name)

    def annotate_pod(self, pod_name: str, annotations: dict) -> bool:
        """Delegate + assume, like bind(): the cache applies the merge
        immediately so the next cycle's records already carry the
        written values (the real MODIFIED event confirms or corrects),
        and the pod is hinted — an annotation change alters its wire
        record, and the delta codec's contract is 'name everything you
        touch'. A raced-away pod (False from the client) leaves the
        cache untouched: the DELETED event is already in flight."""
        if not self.client.annotate_pod(pod_name, annotations):
            return False
        with self._lock:
            obj = self._objs[self._POD_PATH].get(pod_name)
            if obj is not None:
                anns = obj.setdefault("metadata", {}).setdefault(
                    "annotations", {})
                anns.update(
                    {str(k): str(v) for k, v in annotations.items()}
                )
                self._changed.add(pod_name)
        return True

    def write_observed_availability(self, pod_name: str,
                                    avail: float) -> bool:
        clamped = clamp01(avail, default=DEFAULT_OBSERVED_AVAIL)
        return self.annotate_pod(pod_name, {ANN_OBSERVED: f"{clamped:.6f}"})

    def delete_pod(self, pod_name: str) -> bool:
        ok = self.client.delete_pod(pod_name)
        if ok:
            # Assume-delete only on success: a False return can mean
            # PDB-blocked (HTTP 429) with the pod STILL RUNNING — and
            # since the object never changes, no watch event would ever
            # restore a wrongly-evicted cache entry, silently
            # under-counting that node's used capacity. (The
            # pod-already-gone case needs no pop either: its DELETED
            # event handles it.)
            with self._lock:
                self.delete_count += 1
                if self._objs[self._POD_PATH].pop(pod_name, None) is not None:
                    self._changed.add(pod_name)
        return ok

    # -- delta hints --------------------------------------------------------

    def drain_changed(self) -> set[str] | None:
        self._refresh_pdb_hints()
        with self._lock:
            if self._dirty_all:
                self._dirty_all = False
                self._changed.clear()
                return None
            out = self._changed
            self._changed = set()
            return out

    def restore_changed(self, names: set[str] | None) -> None:
        """Un-drain hints a caller consumed but never shipped (see
        host.FakeApiServer.restore_changed)."""
        with self._lock:
            if names is None:
                self._dirty_all = True
            else:
                self._changed |= names

    def relist_epoch(self) -> int:
        """Monotone count of cache-replacing re-lists. A host compares
        it before draining hints and after building its snapshot: a
        bump in between means the snapshot holds relist-discovered
        state the drained hints cannot cover — diff everything."""
        with self._lock:
            return self._epoch
