"""Wire ledger (ISSUE 19): per-cycle round-trip decomposition.

BENCH_r04/r05 showed the solver is no longer the bottleneck — fast
solve p50 is ~152 ms at 10k x 5k while the measured transport RTT is
~100-120 ms — yet the repo's only wire number was a single
process-global p50 measured once at bench startup. This module is the
PR 13 move applied to the transport: every client<->server cycle emits
ONE schema-validated `WireRecord` that decomposes the full round trip
into budgeted components, so ROADMAP item 2 (streaming wire +
on-device response pack) has a baseline to beat per component instead
of one opaque wall number.

Three pieces:

  * `ClockOffsetEstimator` — NTP-style offset between the client's and
    the server's wall clocks from the (send, recv, reply, join)
    timestamp quadruple: the client's `client.send` span gives t0/t3,
    the server's `server.<rpc>` request-root span gives t1/t2, joined
    by request_id. offset = ((t1-t0) + (t2-t3)) / 2; the residual path
    asymmetry bounds the error (uncertainty = delay/2 where delay =
    (t3-t0) - (t2-t1)). Candidate (send, root) pairs are validated by
    DURATION arithmetic only (busy <= window), so pairing survives
    arbitrary clock skew, retries that re-issue under the same rid,
    and resync full-sends; the estimator keeps a min-delay window so
    one congested sample never poisons the offset.
  * `assemble()` — joins one cycle's spans (the ledger does NOT
    re-instrument: client.serialize / client.send / client.retry /
    client.join and the server stages spanned since PR 4 — gate.wait,
    coalesce.wait, decode, delta.apply, dispatch, fetch.join (device
    solve + D2H), reply.names, reply.pack) into a WireRecord. The two
    one-way gaps are offset-corrected: `send.gap` = client send start
    -> server root start (up transit + server ingress queue) and
    `reply.gap` = server root end -> response in the client's hands
    (down transit + client-side reply decode). Unattributed server
    wall lands in `server.other`, so the component sum reconstructs
    the cycle wall by design and `coverage` genuinely measures how
    well the clock stitching resolved the gaps.
  * `WireLedger` — bounded ring + rolling quantiles (the PR 13
    machinery) + a sentinel: a cycle whose wall exceeds the rolling
    p99 (non-interpolated covering-bucket bound) is attributed in
    order — payload well above the rolling byte p95 -> "bytes_burst";
    else the component group with the largest excess over its rolling
    median: gate/coalesce waits -> "queue", serialize/decode/apply ->
    "decode", gaps/fetch/reply -> "transfer"; else "unknown". Each
    anomaly bumps `scheduler_wire_anomalies_total{cause}`, fires the
    attached FlightRecorder with the attributed record, and — when
    `profile_dir` is set — ARMS a one-shot `jax.profiler` device-trace
    capture that the serving path wraps around the next cycle via
    `maybe_profile()` (a capture cannot start retroactively; the next
    cycle in the same regime is the best observable proxy).

Records flow into the Statusz payload as a fleet-mergeable `wire`
panel (raw bucket counts ride along; tools/statusz.py re-derives fleet
quantiles from summed counts) and into tracez's Perfetto export as a
per-cycle breakdown track (`to_chrome`).

Stdlib-only on purpose (like ledger.py/trace.py): importable from
every layer; jax is touched only inside an armed profile capture.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import json
import math
import os
import threading
from collections import deque
from typing import Any, Iterator, TextIO

from tpusched import metrics as pm
from tpusched import trace as tracing

ANOMALY_CAUSES = ("bytes_burst", "queue", "decode", "transfer", "unknown")

# Server-side stage spans the assembler joins (instrumented since PR 4;
# mutually exclusive serving phases, so their sum stays <= the root
# wall). fetch.join includes the device solve AND the D2H result fetch
# (the engine's ordered fetch worker materializes inside it).
SERVER_STAGES = ("gate.wait", "coalesce.wait", "decode", "delta.apply",
                 "dispatch", "fetch.join", "reply.names", "reply.pack")

# Cause-attribution groups (sentinel docstring). server.other is the
# unattributed server residue: store.compose / session.seed / handler
# glue — transfer-adjacent for attribution purposes because it moves
# with the same H2D/device pressure fetch.join does.
_QUEUE = ("gate.wait", "coalesce.wait")
_DECODE = ("serialize", "decode", "delta.apply")
_TRANSFER = ("send.gap", "reply.gap", "fetch.join", "reply.names",
             "reply.pack", "server.other")

# Canonical component order for rendering (statusz panel, the Perfetto
# breakdown track, bench emission): request-path order.
COMPONENT_ORDER = ("serialize", "send.gap", "retry.backoff", "gate.wait",
                   "coalesce.wait", "decode", "delta.apply", "dispatch",
                   "fetch.join", "reply.names", "reply.pack",
                   "server.other", "reply.gap", "unknown")


@dataclasses.dataclass
class WireRecord:
    """One client<->server cycle's wire-ledger entry (module
    docstring). `cycle` is assigned by the ledger at observe() time;
    `anomaly` is written by the sentinel ("" = none). `stages` holds
    per-component wall seconds; component NAMES follow the trace span
    names (plus the derived `send.gap`/`reply.gap`/`server.other`), so
    a wire anomaly points at the same name a trace shows."""

    ts: float = 0.0            # client clock at the first send
    rpc: str = ""              # Assign | ScoreBatch | Score
    rid: str = ""              # request_id == trace_id
    source: str = "call"       # call (blocking) | pipeline (futures)
    attempts: int = 1          # client.send spans under the rid
    resyncs: int = 0           # client.resync re-issues under the rid
    replayed: bool = False     # server answered from the replay cache
    stitched: bool = False     # a server root was joined (gaps real)
    wall_s: float = 0.0        # the quantity the sentinel judges
    offset_s: float = 0.0      # server clock minus client clock
    uncertainty_s: float = 0.0 # half the path asymmetry; -1 = unknown
    bytes_up: int = 0          # serialized request payload
    bytes_down: int = 0        # serialized reply payload
    stages: "dict[str, float]" = dataclasses.field(default_factory=dict)
    coverage: float = 0.0      # sum(stages) / wall_s
    cycle: int = 0
    anomaly: str = ""


# Field name -> accepted types; THE schema authority (ledger.py
# discipline: validate_record is the contract tools/check.py's wirez
# smoke and the statusz fleet merge rely on).
SCHEMA: "dict[str, tuple[type, ...]]" = {
    "cycle": (int,),
    "ts": (int, float),
    "rpc": (str,),
    "rid": (str,),
    "source": (str,),
    "attempts": (int,),
    "resyncs": (int,),
    "replayed": (bool,),
    "stitched": (bool,),
    "wall_s": (int, float),
    "offset_s": (int, float),
    "uncertainty_s": (int, float),
    "bytes_up": (int,),
    "bytes_down": (int,),
    "stages": (dict,),
    "coverage": (int, float),
    "anomaly": (str,),
}


def record_dict(rec: WireRecord) -> "dict[str, Any]":
    """Plain dict in SCHEMA key order (JSONL lines, Statusz payloads)."""
    d = dataclasses.asdict(rec)
    return {k: d[k] for k in SCHEMA}


def validate_record(d: "dict[str, Any]") -> "dict[str, Any]":
    """Schema check for one record dict (the wirez smoke contract).
    Raises ValueError on any drift: missing/extra keys, wrong field
    types (bools are NOT ints outside the declared bool fields),
    non-numeric stage values, an unknown source."""
    missing = [k for k in SCHEMA if k not in d]
    extra = [k for k in d if k not in SCHEMA]
    if missing or extra:
        raise ValueError(
            f"WireRecord schema drift: missing={missing} extra={extra}"
        )
    for k, types in SCHEMA.items():
        if bool in types:
            if not isinstance(d[k], bool):
                raise ValueError(
                    f"WireRecord field {k!r}: {type(d[k]).__name__} "
                    "is not bool"
                )
            continue
        if not isinstance(d[k], types) or isinstance(d[k], bool):
            raise ValueError(
                f"WireRecord field {k!r}: {type(d[k]).__name__} is not "
                f"{'/'.join(t.__name__ for t in types)}"
            )
    for st, v in d["stages"].items():
        if not isinstance(st, str) or isinstance(v, bool) \
                or not isinstance(v, (int, float)):
            raise ValueError(
                f"WireRecord stages entry {st!r}: {v!r} is not a "
                "str -> seconds pair"
            )
    if d["source"] not in ("call", "pipeline"):
        raise ValueError(
            f"WireRecord source {d['source']!r}: want call|pipeline"
        )
    return d


class ClockOffsetEstimator:
    """NTP-style client/server clock-offset estimator (module
    docstring). Thread-safe; keeps a bounded window of (delay, offset)
    samples and answers with the MIN-DELAY sample — the classic NTP
    filter: the tightest round trip bounds the offset best, and a
    congested or retried cycle's loose sample never displaces it."""

    def __init__(self, window: int = 64):
        self._lock = threading.Lock()
        # (delay_s, offset_s); min() keys on delay first by tuple order.
        self._samples: "deque[tuple[float, float]]" = deque(
            maxlen=int(window))

    def add(self, t0: float, t1: float, t2: float,
            t3: float) -> "tuple[float, float] | None":
        """Fold one send/recv/reply/join quadruple (t0/t3 on the client
        clock, t1/t2 on the server clock). Returns (offset_s,
        uncertainty_s) for this sample, or None for an inconsistent
        pairing (server busy exceeding the client window — a retried
        attempt matched against the wrong root). Consistency uses
        DURATIONS only, so it survives arbitrary absolute skew."""
        busy = t2 - t1
        window = t3 - t0
        if busy < 0.0 or window < 0.0 or busy > window:
            return None
        delay = window - busy
        offset = ((t1 - t0) + (t2 - t3)) / 2.0
        with self._lock:
            self._samples.append((delay, offset))
        return offset, delay / 2.0

    def best(self) -> "tuple[float, float] | None":
        """(offset_s, uncertainty_s) of the min-delay sample in the
        window, or None before any consistent sample landed."""
        with self._lock:
            if not self._samples:
                return None
            delay, offset = min(self._samples)
        return offset, delay / 2.0

    def samples(self) -> int:
        with self._lock:
            return len(self._samples)


def _subtree_ids(spans: "list[tracing.Span]", root_id: int) -> "set[int]":
    """Span ids reachable from root_id via parent links (the chosen
    attempt's server-side subtree; a retry's stages parent under a
    DIFFERENT root and must not be double-counted)."""
    children: "dict[int, list[int]]" = {}
    for s in spans:
        children.setdefault(s.parent_id, []).append(s.span_id)
    out = {root_id}
    frontier = [root_id]
    while frontier:
        nxt = []
        for pid in frontier:
            for cid in children.get(pid, ()):
                if cid not in out:
                    out.add(cid)
                    nxt.append(cid)
        frontier = nxt
    return out


def _choose_pair(sends: "list[tracing.Span]",
                 roots: "list[tracing.Span]",
                 t_end: float) -> "tuple[tracing.Span, tracing.Span] | None":
    """The (client send, server root) pairing with the tightest
    duration fit: min over valid pairs of (window - busy). Validity is
    duration-only (skew-proof): the root's busy time must fit inside
    the attempt's client window. For an instant pipeline send (dur 0)
    the window runs to the cycle end t_end."""
    best = None
    best_delay = math.inf
    for send in sends:
        window = send.dur_s if send.dur_s > 0.0 \
            else max(t_end - send.t_wall, 0.0)
        for root in roots:
            delay = window - root.dur_s
            if root.dur_s >= 0.0 and delay >= 0.0 and delay < best_delay:
                best = (send, root)
                best_delay = delay
    return best


def assemble(rid: str, rpc: str, spans: "list[tracing.Span]",
             clock: ClockOffsetEstimator, *,
             bytes_up: int = 0, bytes_down: int = 0,
             source: str = "call") -> "WireRecord | None":
    """One WireRecord from a cycle's spans (module docstring). `spans`
    is the rid's slice of the shared ring — client spans always, the
    server's stage spans whenever the sidecar shares the process ring
    (the in-process sidecar and the loopback-gRPC bench both do).
    Returns None when the rid has no client.send span (nothing was
    sent, or the ring already evicted the cycle)."""
    sends = [s for s in spans if s.name == "client.send"]
    if not sends:
        return None
    sends.sort(key=lambda s: s.t_wall)
    joins = sorted((s for s in spans if s.name == "client.join"),
                   key=lambda s: s.t_wall)
    serializes = [s for s in spans if s.name == "client.serialize"]
    retries = [s for s in spans if s.name == "client.retry"]
    resyncs = sum(1 for s in spans if s.name == "client.resync")
    roots = [s for s in spans
             if s.cat == "server" and s.name == f"server.{rpc}"]

    t0 = sends[0].t_wall
    ser_s = sum(s.dur_s for s in serializes)
    if source == "pipeline" and joins:
        t_end = max(s.end_wall for s in joins)
    else:
        t_end = max(s.end_wall for s in sends)
    # serialize precedes the first send span; it is real cycle wall.
    wall = max(t_end - t0, 0.0) + ser_s

    stages: "dict[str, float]" = {}
    if ser_s > 0.0:
        stages["serialize"] = ser_s
    backoff = sum(s.dur_s for s in retries)
    if backoff > 0.0:
        stages["retry.backoff"] = backoff

    replayed = False
    stitched = False
    offset = 0.0
    uncertainty = -1.0
    pair = _choose_pair(sends, roots, t_end)
    if pair is not None:
        send, root = pair
        stitched = True
        replayed = bool(root.attrs.get("replayed", False))
        p_end = send.end_wall if send.dur_s > 0.0 else t_end
        clock.add(send.t_wall, root.t_wall,
                  root.end_wall, p_end)
        best = clock.best()
        if best is not None:
            offset, uncertainty = best
        subtree = _subtree_ids(spans, root.span_id)
        staged = 0.0
        for s in spans:
            if s.name in SERVER_STAGES and s.span_id in subtree:
                stages[s.name] = stages.get(s.name, 0.0) + s.dur_s
                staged += s.dur_s
        stages["server.other"] = max(root.dur_s - staged, 0.0)
        # Offset-corrected one-way gaps; negative residue (offset error
        # larger than the gap itself) clamps to zero and shows up as a
        # coverage shortfall rather than a negative component.
        stages["send.gap"] = max(root.t_wall - offset - send.t_wall, 0.0)
        stages["reply.gap"] = max(
            p_end - (root.end_wall - offset), 0.0)
    else:
        # No joinable server root (remote sidecar, tracing off there):
        # the middle of the cycle is one unattributed block.
        stages["unknown"] = max(wall - ser_s - backoff, 0.0)
        best = clock.best()
        if best is not None:
            offset, uncertainty = best

    total = sum(stages.values())
    return WireRecord(
        ts=t0, rpc=rpc, rid=rid, source=source,
        attempts=len(sends), resyncs=resyncs,
        replayed=replayed, stitched=stitched,
        wall_s=wall, offset_s=offset, uncertainty_s=uncertainty,
        bytes_up=int(bytes_up), bytes_down=int(bytes_down),
        stages=stages,
        coverage=(total / wall) if wall > 0.0 else 0.0,
    )


class WireLedger:
    """Bounded ring of WireRecords + rolling aggregation + the wire
    sentinel (module docstring).

    registry: where the ledger's metric families live (the sidecar
    passes its per-server registry so wire anomalies render in its
    Metrics rpc). flight/tracer: the FlightRecorder the sentinel fires
    and the span ring it snapshots. min_cycles: rolling-window arming
    threshold. jsonl: optional black-box path. profile_dir: when set,
    an anomaly arms a one-shot jax.profiler device-trace capture for
    the next cycle wrapped in maybe_profile()."""

    def __init__(self, capacity: int = 1024,
                 registry: "pm.Registry | None" = None,
                 flight: "tracing.FlightRecorder | None" = None,
                 tracer: "tracing.TraceCollector | None" = None,
                 min_cycles: int = 32,
                 jsonl: "str | None" = None,
                 profile_dir: "str | None" = None,
                 enabled: bool = True):
        self._lock = threading.Lock()
        self._ring: "deque[WireRecord]" = deque(maxlen=int(capacity))
        self._mint = itertools.count(1)
        self.enabled = enabled
        self.min_cycles = int(min_cycles)
        self.flight = flight
        self.tracer = tracer
        self.clock = ClockOffsetEstimator()
        self._jsonl_path = jsonl
        self._jsonl: "TextIO | None" = None
        self._jsonl_closed = False
        self._io_lock = threading.Lock()
        self._component_names: "set[str]" = set()
        self._bytes_window: "deque[int]" = deque(maxlen=256)
        self.anomalies = 0
        self.bytes_up_total = 0
        self.bytes_down_total = 0
        self.profile_dir = profile_dir
        self._profile_armed = False
        self.profiles: "list[str]" = []
        reg = registry if registry is not None else pm.DEFAULT
        self._h_wall = pm.Histogram(
            "scheduler_wire_wall_seconds",
            "per-cycle client-observed round-trip wall (the wire "
            "sentinel's judged quantity)",
            buckets=pm.DURATION_BUCKETS, registry=reg)
        self._h_comp = pm.Histogram(
            "scheduler_wire_component_seconds",
            "per-cycle wire wall by round-trip component",
            buckets=pm.DURATION_BUCKETS, labelnames=("component",),
            registry=reg)
        self._c_cycles = pm.Counter(
            "scheduler_wire_cycles_total",
            "ledgered wire cycles", ("rpc", "source"), registry=reg)
        self._c_anomalies = pm.Counter(
            "scheduler_wire_anomalies_total",
            "wire-sentinel-flagged cycles by attributed cause",
            ("cause",), registry=reg)

    # -- recording -----------------------------------------------------------

    def observe(self, rec: WireRecord) -> "WireRecord | None":
        """Append one cycle: sentinel check against PRIOR cycles'
        rolling windows, then fold the record into them. Returns the
        (cycle-stamped, anomaly-stamped) record, or None when the
        ledger is disabled."""
        if not self.enabled:
            return None
        cause = self._sentinel(rec)
        rec.anomaly = cause or ""
        rec.cycle = next(self._mint)
        with self._lock:
            self._ring.append(rec)
            self._component_names.update(rec.stages)
            self._bytes_window.append(rec.bytes_up + rec.bytes_down)
            self.bytes_up_total += rec.bytes_up
            self.bytes_down_total += rec.bytes_down
        self._h_wall.observe(rec.wall_s)
        for comp, dur in rec.stages.items():
            self._h_comp.labels(comp).observe(float(dur))
        self._c_cycles.labels(rec.rpc, rec.source).inc()
        if cause:
            self.anomalies += 1
            self._c_anomalies.labels(cause).inc()
            if self.profile_dir is not None:
                self._profile_armed = True
            flight = self.flight
            if flight is not None:
                flight.record("wire_anomaly",
                              self.tracer or tracing.DEFAULT,
                              cause=cause, wire=record_dict(rec),
                              device_trace=(self.profiles[-1]
                                            if self.profiles else None))
        self._write_jsonl(rec)
        return rec

    def _wall_count(self) -> int:
        return int(self._h_wall.labels().count)

    def _sentinel(self, rec: WireRecord) -> "str | None":
        """The wire sentinel (module docstring): None = normal. Wall
        threshold is the NON-interpolated rolling p99 bucket bound;
        attribution is ordered — bytes first (a burst explains every
        downstream component), then the component group with the
        largest excess over its rolling median."""
        if self._wall_count() < self.min_cycles:
            return None
        p99 = self._h_wall.quantile(0.99, interpolate=False)
        if math.isnan(p99) or not rec.wall_s > p99:
            return None
        total_bytes = rec.bytes_up + rec.bytes_down
        with self._lock:
            window = sorted(self._bytes_window)
        if window:
            p95 = window[int(0.95 * (len(window) - 1))]
            # A burst must be SUBSTANTIALLY above the rolling p95 —
            # steady traffic jitters by a few varint bytes per cycle,
            # and that must never out-attribute a real stall.
            if total_bytes > max(1.5 * p95, p95 + 4096):
                return "bytes_burst"
        excess = {"queue": 0.0, "decode": 0.0, "transfer": 0.0}
        for group, comps in (("queue", _QUEUE), ("decode", _DECODE),
                             ("transfer", _TRANSFER)):
            for comp in comps:
                v = rec.stages.get(comp)
                if v is None:
                    continue
                med = self._h_comp.quantile(0.5, comp, interpolate=False)
                if math.isnan(med):
                    med = 0.0
                excess[group] += max(float(v) - med, 0.0)
        # Priority on ties follows the request path: a queue spike
        # usually CAUSES downstream inflation, so it wins equals.
        cause = max(("queue", "decode", "transfer"),
                    key=lambda g: excess[g])
        if excess[cause] <= 0.0:
            return "unknown"
        return cause

    def _write_jsonl(self, rec: WireRecord) -> None:
        if self._jsonl_path is None:
            return
        line = json.dumps(record_dict(rec)) + "\n"
        if self._jsonl is None:
            # Lazy open OUTSIDE the lock (ledger.py discipline): the
            # tiny publish race double-opens at worst; a closed ledger
            # never reopens — late observers drop the line.
            f: "TextIO | None" = open(self._jsonl_path, "a")
            with self._io_lock:
                if self._jsonl is None and not self._jsonl_closed:
                    self._jsonl, f = f, None
            if f is not None:
                f.close()
        with self._io_lock:
            f = self._jsonl
            if f is not None:
                f.write(line)
                f.flush()

    # -- device-trace capture ------------------------------------------------

    @contextlib.contextmanager
    def maybe_profile(self) -> "Iterator[bool]":
        """One-shot jax.profiler device-trace capture armed by the
        previous anomaly (module docstring). Unarmed (the steady
        state) this is two attribute reads; the serving path wraps its
        dispatch region in it unconditionally. Yields whether a
        capture is running so callers can annotate."""
        if not self._profile_armed or self.profile_dir is None:
            yield False
            return
        self._profile_armed = False
        try:
            import jax  # tpl: disable=TPL001(optional dependency: the wire ledger must stay importable from jax-free layers; this import only runs on the one cycle after an armed anomaly)
        except ImportError:
            yield False
            return
        path = os.path.join(self.profile_dir,
                            f"wire_cycle_{next(self._mint)}")
        try:
            jax.profiler.start_trace(path)
        except Exception:
            yield False
            return
        try:
            yield True
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self.profiles.append(path)
            (self.tracer or tracing.DEFAULT).record(
                "wire.device_trace", cat="wire", path=path)

    # -- reading -------------------------------------------------------------

    def records(self, last: "int | None" = None) -> "list[WireRecord]":
        with self._lock:
            out = list(self._ring)
        if last is not None and last >= 0:
            out = out[len(out) - min(last, len(out)):]
        return out

    def _hist_export(self, hist: pm.Histogram,
                     *labels: Any) -> "dict[str, Any]":
        counts = hist.series_counts(*labels)
        return dict(le=list(hist.buckets), counts=counts)

    def statusz(self, last: int = 32) -> "dict[str, Any]":
        """The Statusz `wire` panel: rolling p50/p99 per component and
        for the cycle wall, byte totals, the current clock offset with
        its uncertainty, mean stitched coverage, anomaly counts, the
        last-N records, and RAW bucket counts (tools/statusz.py merges
        counts across replicas and re-derives fleet quantiles)."""
        recs = self.records(last)
        all_recs = self.records()
        anomalies: "dict[str, int]" = {}
        rpcs: "dict[str, int]" = {}
        for r in all_recs:
            rpcs[r.rpc] = rpcs.get(r.rpc, 0) + 1
            if r.anomaly:
                anomalies[r.anomaly] = anomalies.get(r.anomaly, 0) + 1
        with self._lock:
            comp_names = sorted(self._component_names)
            bytes_up, bytes_down = self.bytes_up_total, self.bytes_down_total
        components: "dict[str, Any]" = {}
        for comp in comp_names:
            components[comp] = dict(
                p50_ms=_ms(self._h_comp.quantile(0.50, comp)),
                p99_ms=_ms(self._h_comp.quantile(0.99, comp)),
                hist=self._hist_export(self._h_comp, comp),
            )
        stitched = [r for r in all_recs if r.stitched]
        best = self.clock.best()
        return dict(
            cycles=self._wall_count(),
            anomalies=anomalies,
            anomalies_total=self.anomalies,
            rpcs=rpcs,
            bytes=dict(up=bytes_up, down=bytes_down),
            offset_ms=_ms(best[0]) if best is not None else None,
            uncertainty_ms=_ms(best[1]) if best is not None else None,
            coverage_frac=(
                round(sum(r.coverage for r in stitched) / len(stitched), 4)
                if stitched else None),
            wall=dict(
                p50_ms=_ms(self._h_wall.quantile(0.50)),
                p99_ms=_ms(self._h_wall.quantile(0.99)),
                hist=self._hist_export(self._h_wall),
            ),
            components=components,
            device_traces=list(self.profiles),
            records=[record_dict(r) for r in recs],
        )

    def close(self) -> None:
        """Release the JSONL black box (idempotent; later observers
        drop their lines instead of reopening)."""
        with self._io_lock:
            f, self._jsonl = self._jsonl, None
            self._jsonl_closed = True
        if f is not None:
            f.close()


def to_chrome(records: "list[WireRecord]",
              pid: int = 9) -> "list[dict[str, Any]]":
    """Perfetto breakdown track: one lane of back-to-back "X" events
    per cycle, components laid out in request-path order from the
    cycle's ts, so the per-cycle decomposition reads as a waterfall
    alongside the span tracks trace.to_chrome emits. Merge the two
    event lists into one traceEvents array."""
    events: "list[dict[str, Any]]" = []
    for rec in records:
        t = rec.ts
        order = [c for c in COMPONENT_ORDER if c in rec.stages]
        order += [c for c in sorted(rec.stages) if c not in order]
        for comp in order:
            dur = rec.stages[comp]
            events.append(dict(
                name=comp, cat="wire", ph="X",
                ts=t * 1e6, dur=max(dur, 0.0) * 1e6,
                pid=pid, tid=f"wire:{rec.rpc}",
                args=dict(cycle=rec.cycle, rid=rec.rid,
                          coverage=round(rec.coverage, 3),
                          anomaly=rec.anomaly),
            ))
            t += max(dur, 0.0)
    return events


def _ms(v: float) -> "float | None":
    return None if math.isnan(v) else round(v * 1e3, 3)


# Process default: clients fall back here unless handed the sidecar's
# own ledger (the server builds one per service so its wire panel and
# anomaly counters render in its own Statusz/Metrics rpcs).
# `set_enabled(False)` is the global off switch — bench.py's
# wire-ledger-off arm measures exactly this path.
DEFAULT = WireLedger()


def set_enabled(on: bool) -> None:
    DEFAULT.enabled = bool(on)
