"""tpusched — a TPU-native batched cluster-scheduling engine.

Re-implements the capabilities of the UFCG-LSD QoS-driven Kubernetes
scheduler (reference: /root/reference/README.md:1, project
"k8s-qos-driven-scheduler") as a batched constraint solver in JAX:
instead of the per-pod Filter->Score loop of the kube-scheduler framework,
the full pending-pods x candidate-nodes matrix is materialised on device,
feasibility predicates become boolean masks, scoring plugins become fused
vmap'd kernels, and placement commit is either an exactly-sequential
lax.scan (parity mode) or a round-based batched commit (fast mode).

See SURVEY.md for the layer map and component inventory this implements.
"""

from tpusched.config import (
    Buckets,
    EngineConfig,
    PluginWeights,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
)
from tpusched.snapshot import (
    ClusterSnapshot,
    NodeArrays,
    PodArrays,
    RunningPodArrays,
    SnapshotBuilder,
    AtomTable,
)
from tpusched.engine import Engine, SolveResult
from tpusched.device_state import DeviceSnapshot

__version__ = "0.1.0"

__all__ = [
    "Buckets",
    "EngineConfig",
    "PluginWeights",
    "RESOURCE_CPU",
    "RESOURCE_MEMORY",
    "RESOURCE_PODS",
    "ClusterSnapshot",
    "NodeArrays",
    "PodArrays",
    "RunningPodArrays",
    "SnapshotBuilder",
    "AtomTable",
    "Engine",
    "SolveResult",
    "DeviceSnapshot",
]
