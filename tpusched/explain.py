"""Decision provenance (round 12, ISSUE 8 tentpole).

The QoS terms drive the Filter->Score loop and victim selection, but
until this round those decisions were a black box: traces say where
TIME went (tpusched.trace), metrics say how MUCH happened — nothing
could answer "why did pod P land on node Y", "why is P still pending",
or "who evicted V and what did it cost". This module is the store that
answers them: one `DecisionRecord` per EXPLAINED solve cycle, ring-
buffered in an `ExplainCollector` with the same design rules as
trace.TraceCollector —

  * disabled by default and O(1) when disabled (`record()` returns
    immediately; the engine only runs the provenance programs for
    explained cycles, so the serving hot path is untouched when off);
  * lock-cheap when enabled (one short lock around a deque append;
    records are immutable-after-build plain dataclasses);
  * NEVER spawns threads (tests/conftest.py thread_leak_check);
  * linked to traces: each record carries the wire request_id (`rid`)
    of the request whose solve it explains, so a slow cycle found in
    Perfetto joins its decisions by id (tools/tracez.py args carry the
    same rid; the server also drops a "decision" event span with the
    record's cycle id into the trace ring).

A record captures, per cycle: every pod's OUTCOME (placed / preemptor
/ pending / gang-held), its top-k candidate nodes with the score
decomposed into plugin terms and the QoS inputs (pressure, effective
priority), filter-elimination tallies by reason (an exact partition of
the node axis — kernels/explain.py), and the preemption side: per-
victim evictor + commit round + slack/cost, plus the auction's per-
round stats table (bids, claims, keeps, PDB budget spent, cap hits).

Query surface: `why(pod)` and `who_evicted(victim)` walk the ring
newest-first; `record_dict` renders JSON for the Explainz rpc and
tools/explainz.py; sim/report.py joins records to missed-SLO pods for
the twin-run miss-attribution table.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from tpusched.kernels.assign import EXPLAIN_AUCTION_STATS
from tpusched.kernels.explain import FILTER_REASONS, SCORE_TERMS

OUTCOME_PLACED = "placed"
OUTCOME_PREEMPTOR = "preemptor"      # placed by evicting victims
OUTCOME_PENDING = "pending"
OUTCOME_GANG_HELD = "gang_held"      # rolled back below gang quorum
OUTCOMES = (OUTCOME_PLACED, OUTCOME_PREEMPTOR, OUTCOME_PENDING,
            OUTCOME_GANG_HELD)

# Pending-cause labels (decision-outcome counters by reason and the
# sim's miss attribution share these):
REASON_OUTRANKED = "outranked"       # feasible nodes existed; capacity
#                                      went to higher-priority pods
_NO_FEASIBLE = "no_feasible:"        # prefix + dominant filter reason


@dataclass
class DecisionRecord:
    """One explained solve cycle. Arrays are sliced to the REAL record
    counts (no bucket padding); names index them."""

    rid: str                 # wire request_id ("" = unwired solve)
    ts: float
    rpc: str                 # "Assign" | "host.cycle" | "solve"
    snapshot_id: str
    mode: str
    rounds: int
    cap_hit: bool            # auction hit _PREEMPT_MAX_ROUNDS
    pod_names: list
    node_names: list
    running_names: list
    outcome: np.ndarray      # [P] int8 index into OUTCOMES
    assignment: np.ndarray   # [P] int32 node index or -1
    chosen_score: np.ndarray  # [P] f32 (0 where unscored)
    commit_key: np.ndarray   # [P] int32 (-1 unplaced)
    pressure: np.ndarray     # [P] f32
    priority: np.ndarray     # [P] f32 effective priority
    topk_idx: np.ndarray     # [P, k] int32 (-1 pad)
    topk_score: np.ndarray   # [P, k] f32
    topk_terms: np.ndarray   # [P, k, T] f32
    filter_counts: np.ndarray   # [P, NR] int32
    feasible_nodes: np.ndarray  # [P] int32
    evicted: np.ndarray      # [M] bool
    evictor: np.ndarray      # [M] int32 pod index (-1)
    evict_round: np.ndarray  # [M] int32 commit-round key (-1)
    victim_priority: np.ndarray  # [M] f32
    victim_slack: np.ndarray     # [M] f32
    evict_cost: np.ndarray       # [M] f32 (auction's shifted cost)
    qos_gain: float = 0.0    # config.qos.qos_gain at solve time
    auction: list = field(default_factory=list)  # per-round stat dicts
    cycle: int = 0           # collector-minted on record()
    nbytes: int = 0          # retained-size estimate (collector budget)


def build_record(config, meta, res, exd, probe, rid: str = "",
                 snapshot_id: str = "", rpc: str = "solve",
                 ts: "float | None" = None) -> DecisionRecord:
    """Assemble one DecisionRecord from a solve_explained triple. meta:
    SnapshotMeta (slices bucket-padded arrays to real counts); res/exd:
    (SolveResult, ExplainData); probe: ScoreExplain."""
    nP = int(meta.n_pods)
    nM = int(meta.n_running)
    running = list(meta.running_names or [])[:nM]
    if len(running) < nM:
        # Builder-level metas don't track running names (only the gRPC
        # codec and host shim do); synthesize stable placeholders so
        # victim views still index.
        running += [f"running-{i}" for i in range(len(running), nM)]
    a = np.asarray(res.assignment[:nP], dtype=np.int32)
    sc = np.asarray(res.chosen_score[:nP], dtype=np.float32).copy()
    sc[~np.isfinite(sc)] = 0.0
    ck = (np.asarray(res.commit_key[:nP], dtype=np.int32)
          if res.commit_key is not None else np.full(nP, -1, np.int32))
    rolled = np.asarray(exd.rolled[:nP], dtype=bool)
    evictor = np.asarray(exd.evictor[:nM], dtype=np.int32)
    evicted = (np.asarray(res.evicted[:nM], dtype=bool)
               if res.evicted is not None else np.zeros(nM, bool))
    # Outcome codes: gang-held beats everything (its assignment is -1
    # already); a placed pod that evicted someone is a preemptor.
    is_preemptor = np.isin(
        np.arange(nP, dtype=np.int32), evictor[evictor >= 0]
    )
    outcome = np.full(nP, OUTCOMES.index(OUTCOME_PENDING), np.int8)
    outcome[a >= 0] = OUTCOMES.index(OUTCOME_PLACED)
    outcome[(a >= 0) & is_preemptor] = OUTCOMES.index(OUTCOME_PREEMPTOR)
    outcome[rolled] = OUTCOMES.index(OUTCOME_GANG_HELD)
    # Auction table: keep rows up to the last one with any activity.
    astats = np.asarray(exd.auction_stats, dtype=np.float32)
    nz = np.flatnonzero(np.any(astats != 0.0, axis=1))
    n_rows = int(nz[-1]) + 1 if nz.size else 0
    auction = [
        dict(round=i, **{
            name: float(astats[i, j])
            for j, name in enumerate(EXPLAIN_AUCTION_STATS)
        })
        for i in range(n_rows)
    ]
    return DecisionRecord(
        rid=rid, ts=time.time() if ts is None else float(ts), rpc=rpc,
        snapshot_id=snapshot_id, mode=config.mode, rounds=int(res.rounds),
        cap_hit=n_rows >= astats.shape[0],
        pod_names=list(meta.pod_names)[:nP],
        node_names=list(meta.node_names)[:int(meta.n_nodes)],
        running_names=running,
        outcome=outcome, assignment=a, chosen_score=sc, commit_key=ck,
        pressure=np.asarray(probe.pressure[:nP], np.float32),
        priority=np.asarray(probe.priority[:nP], np.float32),
        topk_idx=np.asarray(probe.topk_idx[:nP], np.int32),
        topk_score=np.asarray(probe.topk_score[:nP], np.float32),
        topk_terms=np.asarray(probe.topk_terms[:nP], np.float32),
        filter_counts=np.asarray(probe.filter_counts[:nP], np.int32),
        feasible_nodes=np.asarray(probe.feasible_nodes[:nP], np.int32),
        evicted=evicted, evictor=evictor,
        evict_round=np.asarray(exd.evict_round[:nM], np.int32),
        victim_priority=np.asarray(probe.victim_priority[:nM], np.float32),
        victim_slack=np.asarray(probe.victim_slack[:nM], np.float32),
        evict_cost=np.asarray(probe.evict_cost[:nM], np.float32),
        qos_gain=float(config.qos.qos_gain),
        auction=auction,
    )


_ARRAY_FIELDS = (
    "outcome", "assignment", "chosen_score", "commit_key", "pressure",
    "priority", "topk_idx", "topk_score", "topk_terms", "filter_counts",
    "feasible_nodes", "evicted", "evictor", "evict_round",
    "victim_priority", "victim_slack", "evict_cost",
)


def record_nbytes(rec: DecisionRecord) -> int:
    """Retained-size estimate of one record (array nbytes + a rough
    per-string overhead): the collector's byte budget counts these —
    at the 10k x 5k headline shape one record holds ~2 MB, so a
    count-only ring would quietly pin hundreds of MB."""
    n = sum(int(getattr(rec, f).nbytes) for f in _ARRAY_FIELDS)
    for names in (rec.pod_names, rec.node_names, rec.running_names):
        n += sum(len(s) + 56 for s in names)
    return n + 240 * len(rec.auction) + 512


# ---------------------------------------------------------------------------
# Per-record views (JSON-safe plain dicts).
# ---------------------------------------------------------------------------


def pod_decision(rec: DecisionRecord, i: int) -> dict:
    """One pod's decision: outcome, QoS inputs, candidate nodes with
    the score decomposed into terms, and the filter tallies."""
    boost = float(rec.qos_gain) * float(rec.pressure[i])
    d = dict(
        pod=rec.pod_names[i],
        outcome=OUTCOMES[int(rec.outcome[i])],
        pressure=round(float(rec.pressure[i]), 6),
        priority=round(float(rec.priority[i]), 6),
        # qos.priority_terms inverted through the record's qos_gain:
        # base + qos_boost == the effective priority the queue sorted
        # by (f32 round-trip, so display-exact, not bit-exact).
        priority_base=round(float(rec.priority[i]) - boost, 6),
        qos_boost=round(boost, 6),
        feasible_nodes=int(rec.feasible_nodes[i]),
        filter_eliminated={
            FILTER_REASONS[j]: int(c)
            for j, c in enumerate(rec.filter_counts[i]) if c
        },
    )
    n = int(rec.assignment[i])
    if n >= 0:
        d["node"] = rec.node_names[n]
        d["score"] = round(float(rec.chosen_score[i]), 4)
        d["commit_key"] = int(rec.commit_key[i])
    cands = []
    for s in range(rec.topk_idx.shape[1]):
        ni = int(rec.topk_idx[i, s])
        if ni < 0:
            continue
        cands.append(dict(
            node=rec.node_names[ni],
            total=round(float(rec.topk_score[i, s]), 4),
            terms={
                SCORE_TERMS[t]: round(float(rec.topk_terms[i, s, t]), 4)
                for t in range(len(SCORE_TERMS))
            },
        ))
    d["candidates"] = cands
    if d["outcome"] == OUTCOME_PENDING:
        d["pending_reason"] = _pending_reason(rec, i)
    return d


def victim_decision(rec: DecisionRecord, m: int) -> dict:
    """One running pod's eviction verdict (evicted or spared) with the
    auction-side numbers that drove it."""
    ev = int(rec.evictor[m])
    d = dict(
        victim=rec.running_names[m],
        evicted=bool(rec.evicted[m]),
        victim_priority=round(float(rec.victim_priority[m]), 6),
        victim_slack=round(float(rec.victim_slack[m]), 6),
        evict_cost=round(float(rec.evict_cost[m]), 6),
    )
    if rec.evicted[m]:
        d["round"] = int(rec.evict_round[m])
        if 0 <= ev < len(rec.pod_names):
            d["evictor"] = rec.pod_names[ev]
    return d


def _pending_reason(rec: DecisionRecord, i: int) -> str:
    if int(rec.feasible_nodes[i]) > 0:
        return REASON_OUTRANKED
    counts = rec.filter_counts[i]
    if not counts.any():
        return _NO_FEASIBLE + "none"
    return _NO_FEASIBLE + FILTER_REASONS[int(np.argmax(counts))]


def outcome_counts(rec: DecisionRecord) -> dict:
    """{outcome: pods} for one record (decision-outcome counters)."""
    return {
        name: int(np.sum(rec.outcome == code))
        for code, name in enumerate(OUTCOMES)
    }


def pending_reasons(rec: DecisionRecord) -> dict:
    """{pending-cause label: pods} for one record."""
    out: dict = {}
    pend = OUTCOMES.index(OUTCOME_PENDING)
    for i in np.flatnonzero(rec.outcome == pend):
        r = _pending_reason(rec, int(i))
        out[r] = out.get(r, 0) + 1
    return out


def record_dict(rec: DecisionRecord, pods: "list[str] | None" = None,
                include_auction: bool = True,
                max_victims: int = 64) -> dict:
    """JSON-safe summary of one record: counts + victims (+ auction);
    full per-pod decisions only for the requested `pods`, so Explainz
    responses stay bounded at 10k-pod batches."""
    d = dict(
        cycle=rec.cycle, rid=rec.rid, ts=rec.ts, rpc=rec.rpc,
        snapshot_id=rec.snapshot_id, mode=rec.mode, rounds=rec.rounds,
        cap_hit=rec.cap_hit,
        pods=len(rec.pod_names), nodes=len(rec.node_names),
        running=len(rec.running_names),
        outcomes=outcome_counts(rec),
        pending_reasons=pending_reasons(rec),
        evictions=[
            victim_decision(rec, int(m))
            for m in np.flatnonzero(rec.evicted)[:max_victims]
        ],
    )
    if include_auction:
        d["auction"] = rec.auction
    if pods:
        want = set(pods)
        d["decisions"] = {
            name: pod_decision(rec, i)
            for i, name in enumerate(rec.pod_names) if name in want
        }
    return d


# ---------------------------------------------------------------------------
# The collector.
# ---------------------------------------------------------------------------


class ExplainCollector:
    """Ring-buffered DecisionRecord store (module docstring). `topk` is
    the candidate depth explained cycles request from the engine. The
    ring is bounded by BOTH a record count and a byte budget
    (`max_bytes`, default 128 MB): records scale with the batch shape
    (~2 MB each at 10k pods x 5k running), so a count-only cap would
    let an --explain sidecar quietly pin hundreds of MB of host RSS.
    The newest record always survives even if it alone exceeds the
    budget."""

    def __init__(self, capacity: int = 256, enabled: bool = False,
                 topk: int = 3, max_bytes: int = 128 << 20):
        self._lock = threading.Lock()
        self._ring: deque = deque()
        self._mint = itertools.count(1)
        self.capacity = int(capacity)
        self.max_bytes = int(max_bytes)
        self.enabled = bool(enabled)
        self.topk = int(topk)
        self.recorded = 0
        self.retained_bytes = 0

    def record(self, rec: DecisionRecord) -> int:
        """Append; returns the record's minted cycle id (0 = dropped
        because disabled)."""
        if not self.enabled:
            return 0
        rec.cycle = next(self._mint)
        rec.nbytes = record_nbytes(rec)
        with self._lock:
            self._ring.append(rec)
            self.retained_bytes += rec.nbytes
            self.recorded += 1
            while len(self._ring) > 1 and (
                len(self._ring) > self.capacity
                or self.retained_bytes > self.max_bytes
            ):
                self.retained_bytes -= self._ring.popleft().nbytes
        return rec.cycle

    def records(self) -> list:
        with self._lock:
            return list(self._ring)

    def last(self, n: int) -> list:
        if int(n) <= 0:
            return []
        with self._lock:
            out = list(self._ring)
        return out[-int(n):]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.retained_bytes = 0

    # -- queries -------------------------------------------------------------

    def why(self, pod: str) -> "dict | None":
        """Most recent decision for `pod` (newest record wins): the
        operator's "why is P pending / why did P land on Y"."""
        for rec in reversed(self.records()):
            try:
                i = rec.pod_names.index(pod)
            except ValueError:
                continue
            d = pod_decision(rec, i)
            d.update(cycle=rec.cycle, rid=rec.rid, ts=rec.ts)
            return d
        return None

    def who_evicted(self, victim: str) -> "dict | None":
        """Most recent record in which `victim` was an eviction victim:
        the full chain — who bid, what it cost, which auction round —
        plus the evictor's own decision."""
        for rec in reversed(self.records()):
            try:
                m = rec.running_names.index(victim)
            except ValueError:
                continue
            if not rec.evicted[m]:
                continue
            d = victim_decision(rec, m)
            d.update(cycle=rec.cycle, rid=rec.rid, ts=rec.ts,
                     auction=rec.auction, cap_hit=rec.cap_hit)
            ev = int(rec.evictor[m])
            if 0 <= ev < len(rec.pod_names):
                d["evictor_decision"] = pod_decision(rec, ev)
            return d
        return None


# ---------------------------------------------------------------------------
# Text rendering (tools/explainz.py).
# ---------------------------------------------------------------------------


def render_why(d: "dict | None", pod: str) -> str:
    if d is None:
        return f"{pod}: no decision recorded"
    head = f"{pod}: {d['outcome']}"
    if d.get("cycle") is not None:
        head += f" (cycle {d['cycle']}, rid {d.get('rid') or '-'})"
    lines = [head]
    lines.append(
        f"  qos: pressure={d['pressure']} effective_priority="
        f"{d['priority']} (base {d.get('priority_base')} + qos_boost "
        f"{d.get('qos_boost')})"
    )
    if "node" in d:
        lines.append(f"  placed on {d['node']} score={d['score']} "
                     f"commit_key={d['commit_key']}")
    if d.get("pending_reason"):
        lines.append(f"  pending because: {d['pending_reason']}")
    if d["filter_eliminated"]:
        elim = ", ".join(f"{k}={v}" for k, v in d["filter_eliminated"].items())
        lines.append(f"  filter eliminated ({elim}); "
                     f"{d['feasible_nodes']} nodes feasible")
    for c in d["candidates"]:
        terms = " ".join(f"{k}={v}" for k, v in c["terms"].items() if v)
        lines.append(f"  candidate {c['node']}: total={c['total']} ({terms})")
    return "\n".join(lines)


def render_victim(d: "dict | None", victim: str) -> str:
    if d is None:
        return f"{victim}: never evicted in the recorded window"
    lines = [f"{victim}: evicted in auction round {d.get('round')} of "
             f"cycle {d.get('cycle')} (rid {d.get('rid') or '-'})"]
    lines.append(
        f"  victim terms: priority={d['victim_priority']} "
        f"slack={d['victim_slack']} evict_cost={d['evict_cost']}"
    )
    if "evictor" in d:
        lines.append(f"  evicted by {d['evictor']}")
    ed = d.get("evictor_decision")
    if ed:
        lines.append("  evictor decision:")
        lines.extend("  " + ln for ln in
                     render_why(ed, ed["pod"]).splitlines())
    for row in d.get("auction", []):
        lines.append(
            "  auction r{round}: considered={considered:.0f} "
            "bids={bids:.0f} claimed={claimed:.0f} "
            "kept_evict={kept_evict:.0f} evictions={evictions:.0f} "
            "pdb_spent={pdb_spent:.0f}".format(**row)
        )
    if d.get("cap_hit"):
        lines.append("  NOTE: auction round cap hit — later bidders "
                     "deferred to the next cycle")
    return "\n".join(lines)


# Process default (mirrors trace.DEFAULT): IN-PROCESS HostSchedulers
# fall back to this store when not handed their own, so
# set_enabled(True) turns on cycle recording process-wide. The sidecar
# always constructs its own collector (make_server(explain=...)) — its
# Explainz surface is per-server. Disabled by default: the engine only
# runs provenance programs for explained cycles.
DEFAULT = ExplainCollector()


def set_enabled(on: bool) -> None:
    DEFAULT.enabled = bool(on)
