"""Host scheduler shim + fake API server (SURVEY.md C13, §3.3).

Plays the kube-scheduler role for E2E runs (BASELINE.json:"configs"[0]:
100 pods x 10 nodes): watch pending pods, accumulate a batch, call the
engine (in-process or through the gRPC sidecar, C12), issue Binds and
eviction Deletes against the API server, repeat until the queue drains.

The FakeApiServer stands in for kind/a real API server (neither exists
in this image): it holds spec-level node/pod records, enforces
bind-once-while-pending semantics (the idempotency the reference relies
on for safe retries after a scheduler crash, SURVEY.md §5 "Failure
detection"), and is thread-safe.

Cluster state is the source of truth: the shim keeps no cache between
cycles — each batch re-reads the API server (recovery = replay,
SURVEY.md §5 "Checkpoint / resume").
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable

import numpy as np

from tpusched import explain as explaining
from tpusched import ledger as ledgering
from tpusched import metrics as pm
from tpusched import trace as tracing
from tpusched.config import (
    DEFAULT_OBSERVED_AVAIL,
    DEFAULT_SLO_TARGET,
    Buckets,
    EngineConfig,
    clamp01,
)
from tpusched.device_state import DeviceQueue, DeviceSnapshot
from tpusched.engine import Engine
from tpusched.qos import observed_availability, slack_of
from tpusched.rpc.codec import decode_snapshot, snapshot_to_proto


class Conflict(Exception):
    """Bind of a pod that is no longer pending (double-bind guard)."""


# Minimum availability drift before a read re-hints a pod into the
# change accumulator (see FakeApiServer._with_avail): large enough that
# wall-clock unit tests reading milliseconds apart see no hint churn,
# small enough (~0.4%) that a sim tick's worth of waiting registers.
AVAIL_REHINT_EPS = 1.0 / 256.0


class FakeApiServer:
    def __init__(self, clock=None):
        # clock: zero-arg callable for pod timestamps (submitted /
        # bound_at). The simulator injects a VirtualClock so lifecycle
        # accounting runs on virtual time; default is wall time.
        self._clock = clock if clock is not None else time.time
        self._lock = threading.Lock()
        self._nodes: dict[str, dict] = {}
        self._pods: dict[str, dict] = {}      # pending + bound
        self.bind_count = 0
        self.delete_count = 0
        # Change log for delta hints (the fake twin of KubeInformer's
        # event accumulator): every mutation records the object name;
        # drain_changed() empties it. First drain returns None ("no
        # baseline"), matching the informer contract.
        self._changed: set[str] = set()
        self._dirty_all = True
        # Last computed observed_avail each pod was served with — the
        # drift baseline for read-time re-hinting (see _with_avail).
        self._avail_served: dict[str, float] = {}
        # Monotone arrival stamp (ISSUE 20): the device queue's
        # deterministic tie-break. Re-queued pods restamp, matching
        # their new dict-insertion position, so seq order == the dict
        # iteration order the host-sorted path batches in.
        self._arrival_seq = 0

    # -- cluster setup ------------------------------------------------------

    def add_node(self, name: str, **spec) -> None:
        with self._lock:
            self._nodes[name] = dict(spec, name=name)
            self._changed.add(name)

    def delete_node(self, name: str) -> bool:
        """Node removal (sim: a node failure); idempotent. Pods bound
        to it are the CALLER's problem — a real apiserver likewise
        keeps orphaned pods until something evicts them."""
        with self._lock:
            if name not in self._nodes:
                return False
            del self._nodes[name]
            self._changed.add(name)
            return True

    def add_pod(self, name: str, **spec) -> None:
        """`submitted` / `run_seconds` may ride in via spec: the sim
        driver re-queues evicted pods with their lifecycle history
        preserved, so availability keeps decaying across requeues
        instead of resetting."""
        with self._lock:
            rec = dict(spec, name=name, phase="Pending", node=None)
            rec.setdefault("submitted", self._clock())
            rec.setdefault("run_seconds", 0.0)
            rec["arrival_seq"] = self._arrival_seq
            self._arrival_seq += 1
            self._pods[name] = rec
            self._changed.add(name)

    def add_bound_pod(self, name: str, node: str, **spec) -> None:
        """A pod already running on a node (pre-existing workload)."""
        with self._lock:
            now = self._clock()
            rec = dict(spec, name=name, phase="Bound", node=node)
            rec.setdefault("submitted", now)
            rec.setdefault("run_seconds", 0.0)
            rec.setdefault("bound_at", now)
            self._pods[name] = rec
            self._changed.add(name)

    def get_pod(self, name: str) -> "dict | None":
        with self._lock:
            p = self._pods.get(name)
            return dict(p) if p is not None else None

    def set_observed_availability(self, name: str, avail: float) -> bool:
        """Pin a pod's observed availability explicitly (the
        FakeApiServer twin of the kube annotation write-back path,
        KubeApiClient.write_observed_availability). An explicit value
        OVERRIDES lifecycle accounting until cleared."""
        with self._lock:
            p = self._pods.get(name)
            if p is None:
                return False
            p["observed_avail"] = clamp01(
                avail, default=DEFAULT_OBSERVED_AVAIL)
            self._changed.add(name)
            return True

    # -- delta hints --------------------------------------------------------

    def drain_changed(self) -> "set[str] | None":
        with self._lock:
            if self._dirty_all:
                self._dirty_all = False
                self._changed.clear()
                return None
            out = self._changed
            self._changed = set()
            return out

    def restore_changed(self, names: "set[str] | None") -> None:
        """Un-drain hints a caller consumed but never shipped (e.g. a
        cycle that returned early): without this, the next delta would
        trust a stale base for these records."""
        with self._lock:
            if names is None:
                self._dirty_all = True
            else:
                self._changed |= names

    # -- watch/list side ----------------------------------------------------

    def list_nodes(self) -> list[dict]:
        with self._lock:
            return [dict(n) for n in self._nodes.values()]

    def _with_avail(self, p: dict, now: float) -> dict:
        """Record copy with lifecycle-accounted observed_avail (ISSUE 5:
        the closed QoS loop). An explicit spec value PINS it (tests,
        annotation write-back); otherwise availability is computed from
        submitted / run_seconds / bound_at at read time, so every
        cycle's snapshot sees pressure that reflects how long the pod
        has actually waited vs run. Never-observed pods (zero age) fall
        back to 1.0 — see tpusched.qos.observed_availability.

        Read-time computation silently mutates a record no api write
        ever touched, which would break the delta codec's changed-hint
        contract ("name everything you touch": delta_between trusts
        un-hinted records as byte-identical) — a waiting pod would ship
        its arrival-time availability forever and the sidecar's
        pressure signal would freeze. So each read re-hints the pod
        into the change accumulator whenever the computed value drifts
        beyond AVAIL_REHINT_EPS from the last value it was served with;
        the hint drains NEXT cycle, so delta/pipeline transports see
        availability one cycle stale — the same lag the real kube
        annotation write-back path has."""
        q = dict(p)
        if "observed_avail" not in q:
            avail = observed_availability(
                q.get("submitted", now), q.get("run_seconds", 0.0),
                q.get("bound_at") if q["phase"] == "Bound" else None, now,
            )
            q["observed_avail"] = avail
            name = q["name"]
            last = self._avail_served.get(name)
            if last is None:
                # First read: the creation hint (add_pod/add_bound_pod)
                # already covers this cycle's value.
                self._avail_served[name] = avail
            elif abs(avail - last) > AVAIL_REHINT_EPS:
                self._avail_served[name] = avail
                self._changed.add(name)
        return q

    def pending_pods(self) -> list[dict]:
        with self._lock:
            now = self._clock()
            return [self._with_avail(p, now) for p in self._pods.values()
                    if p["phase"] == "Pending"]

    def pods_named(self, names: Iterable[str]) -> list[dict]:
        """O(len(names)) read of specific pending pods, with the same
        availability accounting / re-hint side effects as
        pending_pods(). Skips names that are gone or no longer Pending
        — the device-queue cycle (ISSUE 20) reads ONLY its extracted
        window through this, never the full pending set."""
        with self._lock:
            now = self._clock()
            out = []
            for name in names:
                p = self._pods.get(name)
                if p is not None and p["phase"] == "Pending":
                    out.append(self._with_avail(p, now))
            return out

    def bound_pods(self) -> list[dict]:
        with self._lock:
            now = self._clock()
            return [self._with_avail(p, now) for p in self._pods.values()
                    if p["phase"] == "Bound"]

    # -- write side ---------------------------------------------------------

    def bind(self, pod_name: str, node_name: str) -> None:
        with self._lock:
            pod = self._pods.get(pod_name)
            if pod is None:
                raise Conflict(f"bind: pod {pod_name} does not exist")
            if pod["phase"] != "Pending":
                raise Conflict(
                    f"bind: pod {pod_name} is {pod['phase']} on {pod['node']}"
                )
            if node_name not in self._nodes:
                raise Conflict(f"bind: node {node_name} does not exist")
            pod["phase"] = "Bound"
            pod["node"] = node_name
            pod["bound_at"] = self._clock()
            self.bind_count += 1
            self._changed.add(pod_name)

    def delete_pod(self, pod_name: str) -> bool:
        """Eviction; returns False if already gone (idempotent)."""
        with self._lock:
            if pod_name not in self._pods:
                return False
            del self._pods[pod_name]
            self._avail_served.pop(pod_name, None)
            self.delete_count += 1
            self._changed.add(pod_name)
            return True


# ---------------------------------------------------------------------------
# The scheduler host.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CycleStats:
    batch_size: int
    placed: int
    evicted: int
    build_seconds: float
    solve_seconds: float
    bind_seconds: float

    @property
    def total_seconds(self) -> float:
        return self.build_seconds + self.solve_seconds + self.bind_seconds


class HostScheduler:
    """One scheduling host: batches pending pods, solves, binds.

    backend: an Engine (in-process) or a SchedulerClient (gRPC sidecar)
    — both consume the same wire snapshot via the C12 codec, so the
    in-process path exercises exactly what the sidecar decodes.
    """

    def __init__(
        self,
        api: FakeApiServer,
        config: EngineConfig | None = None,
        client=None,
        batch_size: int = 1024,
        buckets: Buckets | None = None,
        engine: Engine | None = None,
        backoff_initial: float = 1.0,
        backoff_max: float = 10.0,
        clock=None,
        use_delta: bool = True,
        transport: str = "delta",
        explain=None,
        refresh_frac: "float | None" = None,
        tracer=None,
        warm: "bool | str" = False,
        ledger=None,
        device_queue: bool = False,
        queue_capacity: int = 1024,
    ):
        """explain (round 12, ISSUE 8): optional
        tpusched.explain.ExplainCollector; None falls back to the
        process default (tpusched.explain.DEFAULT — disabled unless
        explain.set_enabled(True), mirroring trace.DEFAULT). When the
        collector is enabled, the IN-PROCESS engine path runs every
        cycle explained and appends one DecisionRecord per cycle (the
        sim's miss-attribution input; `ts` rides this host's clock, so
        virtual-time drivers get virtual timestamps). gRPC transports
        ignore it — server-side explain (make_server(explain=...))
        owns provenance there.

        tracer: optional tpusched.trace.TraceCollector for the
        per-cycle host.cycle span; None falls back to the process
        default at emit time (injected-collector discipline, TPL009).

        warm (ROADMAP item 3): in-process engines only — maintain ONE
        device-resident DeviceSnapshot lineage across cycles, feed it
        the api's change hints as record deltas, and warm-start each
        solve from the carried tableau (Engine.solve_warm_async).
        Placements are bitwise-identical to the decode-every-cycle path
        (the twin-parity contract); availability freshness follows the
        delta transports' hint contract (FakeApiServer re-hints only
        past AVAIL_REHINT_EPS drift). Any cycle failure invalidates the
        lineage — the next cycle full-loads and solves cold. While the
        explain collector is enabled, cycles fall back to the explained
        decode path (the warm program is never traced with observers).

        warm="incremental" (ISSUE 12): additionally seed each solve
        with the previous cycle's assignment and run commit rounds only
        over the pending frontier (Engine.solve_warm_async(incremental=
        True)) — bounded divergence under the in-kernel validity
        contract instead of bitwise parity; every cycle failure drops
        the carry with the lineage (the same unwind).

        ledger (round 18, ISSUE 13): optional
        tpusched.ledger.CycleLedger; None falls back to the process
        default at emit time (injected-collector discipline). Every
        successful cycle appends one CycleRecord — batch/placed/
        evicted counts, build/solve/bind stage walls, churn (the
        drained change hints), warm path taken, commit rounds, and
        the XLA cache misses the cycle paid (ledger.COMPILES delta).
        The record's `ts` rides this host's clock, so virtual-time
        drivers emit virtual timestamps; `ledger_source` tags the
        emitter ("host"; the sim driver re-tags its host "sim").

        device_queue (ISSUE 20): keep the pending set in a
        device-resident DeviceQueue instead of re-reading and
        re-filtering `pending_pods()` every cycle. Change hints drive
        O(churn) queue upserts/removals, the top-W solve window is
        extracted on device (availability-decay priority recomputed
        in-kernel), and only the window's W records are read back
        through `pods_named` — per-cycle host work is O(arrivals),
        not O(pending). The queue chooses batch MEMBERSHIP only; the
        window is re-ordered by arrival_seq before the solve, so
        whenever every eligible pod fits the batch the solver sees the
        EXACT batch the host-sorted path would have built (the
        pressure_skew bit-parity contract); under overload the window
        is the highest-pressure W instead of the first W by age."""
        self.api = api
        self.tracer = tracer
        self.config = config or EngineConfig()
        # Transport config accepts ADDRESSES, not just a built client
        # (round 11, ISSUE 6): a str or an ordered list/tuple of
        # replica endpoints builds a failover-capable SchedulerClient
        # owned (and closed) by this host.
        self._owns_client = False
        if isinstance(client, (str, list, tuple)):
            from tpusched.rpc.client import SchedulerClient  # tpl: disable=TPL001(grpc transport is optional; the in-process host must import without grpc)

            client = SchedulerClient(client)
            self._owns_client = True
        self.client = client
        self.batch_size = batch_size
        self.buckets = buckets
        # Engine jit caches live per instance: callers running many hosts
        # (benchmarks, replays) should pass a shared engine so compiles
        # amortize the way the long-lived sidecar's do.
        if client is not None:
            self._engine = None
        else:
            self._engine = engine if engine is not None else Engine(self.config)
        if warm and client is not None:
            raise ValueError(
                "warm=True is the in-process device-resident path; gRPC "
                "transports keep their lineage in the sidecar's "
                "DeviceSession"
            )
        if warm not in (False, True, "bitwise", "incremental"):
            raise ValueError(
                f"warm={warm!r}: want False, True/'bitwise', or "
                "'incremental'"
            )
        self._warm = bool(warm)
        self._warm_incremental = warm == "incremental"
        self._warm_ds: "DeviceSnapshot | None" = None
        # Last cycle's snapshot membership per class (node / pending /
        # running names): the solve input is the FILTERED pending list
        # (backoff windows, batch cap), so membership changes without a
        # change hint and the delta must carry the symmetric difference.
        self._warm_members = None
        # Sidecar transport (chosen by `transport`; use_delta=False is
        # the legacy spelling of "full"):
        #   "delta"    — DeltaSession: each cycle ships only churned
        #                records against the previous cycle's base
        #                (SURVEY.md §7 hard part 6), with changed-name
        #                hints from the api's change log making the
        #                diff O(churn);
        #   "pipeline" — AssignPipeline at depth 1: the pinned-base
        #                cumulative-delta discipline plus its retry /
        #                lineage-resync machinery (ISSUE 5: the sim's
        #                gRPC mode rides this, so long simulated runs
        #                heal through sidecar restarts the way the
        #                robustness suite pins);
        #   "full"     — full snapshot every cycle.
        if transport not in ("delta", "pipeline", "full"):
            raise ValueError(
                f"transport={transport!r}: want delta|pipeline|full"
            )
        if not use_delta and transport == "delta":
            transport = "full"
        self._delta = None
        self._pipeline = None
        if client is not None and transport == "delta":
            from tpusched.rpc.client import DeltaSession  # tpl: disable=TPL001(grpc transport is optional; the in-process host must import without grpc)

            self._delta = DeltaSession(client)
        elif client is not None and transport == "pipeline":
            from tpusched.rpc.client import AssignPipeline  # tpl: disable=TPL001(grpc transport is optional; the in-process host must import without grpc)

            # refresh_frac: pin-refresh churn threshold passthrough
            # (None keeps the client default). The simulator threads
            # SimConfig.pipeline_refresh_frac here so long drifting
            # runs can stay on the delta path deliberately.
            kw = {} if refresh_frac is None else dict(
                refresh_frac=refresh_frac)
            self._pipeline = AssignPipeline(client, depth=1, **kw)
        self.cycles: list[CycleStats] = []
        # Queue semantics (SURVEY.md §1.2 L5: activeQ/backoffQ): a pod
        # that fails to place enters backoff with exponentially growing
        # delay (upstream kube-scheduler: initial 1s, cap 10s) and is
        # excluded from batches until its retry time — so one
        # unschedulable pod cannot spin the cycle loop. Success clears
        # its backoff state. `clock` is injectable for tests.
        # GANG members share ONE backoff entry (keyed by the group):
        # per-pod windows would desynchronize and the all-or-nothing
        # gate could then never see the whole group in one batch.
        self.backoff_initial = backoff_initial
        self.backoff_max = backoff_max
        self._clock = clock if clock is not None else time.monotonic
        self._backoff: dict[str, tuple[float, int]] = {}  # key -> (retry_at, attempts)
        self._io_pool: ThreadPoolExecutor | None = None
        # Cycles that died on a transient sidecar failure and were
        # re-driven by run_until_idle (ISSUE 3: the host survives its
        # scheduler backend's failures the way kube-scheduler survives
        # an apiserver hiccup — state is re-read, the cycle re-runs).
        # Round 9 exports the count as a Prometheus counter in the
        # process-default registry (it was in-memory-only state).
        self.failed_cycles = 0
        self._m_failed_cycles = pm.Counter(
            "tpusched_host_failed_cycles_total",
            "scheduling cycles re-driven after a transient rpc failure")
        self.explain = explain if explain is not None \
            else explaining.DEFAULT
        self.ledger = ledger
        self.ledger_source = "host"
        # Device-resident pending queue (ISSUE 20). The side tables map
        # backoff keys to resident member names so gang parking and
        # backoff-book pruning stay O(churn) — the host-sorted path
        # derives both from the full pending read the queue exists to
        # avoid.
        self._devqueue = None
        self._dq_members: dict[str, set[str]] = {}   # backoff key -> names
        self._dq_key_of: dict[str, str] = {}         # name -> backoff key
        if device_queue:
            self._devqueue = DeviceQueue(
                capacity=queue_capacity,
                qos_gain=float(self.config.qos.qos_gain))

    def _io(self) -> ThreadPoolExecutor:
        """Lazy pool for concurrent API-server writes (binds/deletes)."""
        if self._io_pool is None:
            self._io_pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="tpusched-bind"
            )
        return self._io_pool

    def close(self) -> None:
        """Shut down the bind/delete worker pool (idle workers also
        exit when the host is garbage-collected) and any client this
        host built from addresses; long-lived processes cycling many
        hosts should call this."""
        if self._io_pool is not None:
            self._io_pool.shutdown(wait=False)
            self._io_pool = None
        if self._owns_client and self.client is not None:
            self.client.close()
            self.client = None
            self._owns_client = False

    @staticmethod
    def _backoff_key(p: dict) -> str:
        g = p.get("pod_group")
        return f"gang\x00{g}" if g else f"pod\x00{p['name']}"

    def _restore_hints(self, changed) -> None:
        """Un-drain change hints a cycle consumed but never shipped.
        Device-queue mutations already applied from these hints are
        safe to replay — upsert/remove/park are idempotent."""
        if self._delta is not None or self._pipeline is not None \
                or self._warm or self._devqueue is not None:
            restore = getattr(self.api, "restore_changed", None)
            if restore is not None:
                restore(changed)

    # -- device-resident pending queue (ISSUE 20) ----------------------------

    def _dq_now(self) -> float:
        """The queue's single timebase: the API SERVER's clock (pod
        `submitted` stamps ride it), NOT this host's backoff clock —
        mixing the two in one table would corrupt in-kernel ages. Sim
        drivers inject one VirtualClock into both, so there the bases
        coincide."""
        clk = getattr(self.api, "_clock", None)
        return float(clk()) if callable(clk) else time.time()

    def _dq_upsert(self, p: dict) -> None:
        name = p["name"]
        key = self._backoff_key(p)
        old = self._dq_key_of.get(name)
        if old is not None and old != key:
            self._dq_members.get(old, set()).discard(name)
        self._dq_key_of[name] = key
        self._dq_members.setdefault(key, set()).add(name)
        gain = float(self.config.qos.qos_gain)
        pinned = p.get("observed_avail")
        if pinned is not None:
            # Pinned availability (annotation write-back / tests): no
            # in-kernel decay — fold the whole effective priority into
            # the base and zero the SLO leg so the kernel's pressure
            # term vanishes. Re-pins arrive as change hints.
            base = float(p.get("priority", 0.0)) + gain * clamp01(
                float(p.get("slo_target", 0.0)) - float(pinned))
            slo = 0.0
        else:
            base = float(p.get("priority", 0.0))
            slo = float(p.get("slo_target", 0.0))
        retry_at, _ = self._backoff.get(key, (0.0, 0))
        rem = retry_at - self._clock()
        self._devqueue.upsert(
            name, base_priority=base, slo_target=slo,
            submitted=float(p.get("submitted", 0.0)),
            run_seconds=float(p.get("run_seconds", 0.0)),
            parked_until=self._dq_now() + rem if rem > 0 else 0.0,
            seq=p.get("arrival_seq"))

    def _dq_remove(self, names: list[str]) -> None:
        for name in names:
            key = self._dq_key_of.pop(name, None)
            if key is None:
                continue
            members = self._dq_members.get(key)
            if members is not None:
                members.discard(name)
                if not members:
                    # Last resident member gone: the backoff book entry
                    # is dead too (the host-sorted path prunes these
                    # against the full pending read).
                    del self._dq_members[key]
                    self._backoff.pop(key, None)
        self._devqueue.remove(names)

    def _dq_sync(self, changed: "set[str] | None") -> None:
        """Reconcile the device queue with the api: O(churn) per cycle.
        changed=None (first cycle / informer re-list) is the one full
        O(pending) resync; every other cycle touches only the hinted
        names. Hint names are processed in sorted order so internally
        stamped arrival seqs (records without arrival_seq) stay
        deterministic under set-iteration randomization."""
        if changed is None:
            live = self.api.pending_pods()
            live_names = {p["name"] for p in live}
            self._dq_remove([n for n in list(self._dq_key_of)
                             if n not in live_names])
            for p in live:
                self._dq_upsert(p)
            return
        for name in sorted(changed):
            p = self.api.get_pod(name)
            if p is None or p.get("phase") != "Pending":
                if name in self._dq_key_of:
                    self._dq_remove([name])
                continue
            self._dq_upsert(p)

    def _dq_repark(self, failed_keys: dict) -> None:
        """Mirror this cycle's backoff-book updates into the queue's
        parking bits: failed keys park every resident member until the
        key's retry time, cleared keys unpark them (a gang whose member
        placed re-enters the active window NOW, exactly like the
        host-sorted path's book-driven filter)."""
        dq_now = self._dq_now()
        host_now = self._clock()
        for key, fail in failed_keys.items():
            if fail:
                retry_at, _ = self._backoff.get(key, (0.0, 0))
                until = dq_now + max(retry_at - host_now, 0.0)
            else:
                until = 0.0
            for nm in self._dq_members.get(key, ()):
                self._devqueue.park(nm, until)

    @staticmethod
    def _result_names(meta, res):
        """(assignments, evicted) as name pairs from an in-process
        SolveResult + its SnapshotMeta — shared by the warm and decode
        cycle paths so the bind inputs cannot drift between them."""
        assignments = [
            (meta.pod_names[i], meta.node_names[int(n)])
            for i, n in enumerate(res.assignment[: meta.n_pods])
            if n >= 0
        ]
        evicted = []
        if res.evicted is not None and res.evicted.any():
            names = meta.running_names or []
            evicted = [
                names[m] for m in np.argwhere(res.evicted).ravel()
                if m < len(names)
            ]
        return assignments, evicted

    def _warm_reset(self, reason: str) -> None:
        """Drop the warm lineage (ROADMAP item 3): the carried tableau
        must not survive a failed cycle, drain/restore unwind, or an
        explain-mode detour — the next warm cycle full-loads a fresh
        DeviceSnapshot and solves cold."""
        if self._warm_ds is not None:
            self._warm_ds.invalidate_warm(reason)
        self._warm_ds = None
        self._warm_members = None

    def _warm_cycle_solve(self, nodes_r, pods_r, running_r, changed,
                          backlog: int = 0):
        """One in-process warm cycle: reconcile the device-resident
        lineage with this cycle's record snapshot and warm-solve it.
        Deltas come from the api change hints PLUS the membership diff
        per class — backoff windows and the batch cap move pods in and
        out of the solve input without any hint, and a bind moves a pod
        pending -> running under one hint. changed=None (first cycle, or
        an informer re-list) rebuilds the lineage from scratch.

        backlog: total pending pods (pre-batch-cap); the lineage's
        running bucket is floored to current running + backlog so a
        draining queue does not force a row_bucket rebuild (= a cold
        solve) every cycle as binds land."""
        cur = (
            {r["name"] for r in nodes_r},
            {r["name"] for r in pods_r},
            {r["name"] for r in running_r},
        )
        ds = self._warm_ds
        if ds is None or changed is None or self._warm_members is None:
            buckets = self.buckets
            if buckets is None:
                buckets = Buckets.fit(
                    len(pods_r), len(nodes_r),
                    len(running_r) + backlog,
                )
            # The lineage shards over the engine's mesh (if any) so the
            # warm dispatch reads the device arrays in place.
            ds = DeviceSnapshot(self.config, buckets,
                                mesh=self._engine.mesh)
            ds.full_load(nodes_r, pods_r, running_r)
            self._warm_ds = ds
        else:
            prev_n, prev_p, prev_r = self._warm_members
            touch = set(changed)
            ds.apply(
                upsert_nodes=[r for r in nodes_r
                              if r["name"] in touch
                              or r["name"] not in prev_n],
                remove_nodes=sorted(prev_n - cur[0]),
                upsert_pods=[r for r in pods_r
                             if r["name"] in touch
                             or r["name"] not in prev_p],
                remove_pods=sorted(prev_p - cur[1]),
                upsert_running=[r for r in running_r
                                if r["name"] in touch
                                or r["name"] not in prev_r],
                remove_running=sorted(prev_r - cur[2]),
            )
        self._warm_members = cur
        # Path taken, read off the lineage counters around the solve
        # (commit_warm stamps them at dispatch): the ledger's warm-mix
        # must report what actually served, incl. cold fallbacks.
        marker = ds.warm_marker()
        res = self._engine.solve_warm_async(
            ds, incremental=self._warm_incremental
        ).result()
        return res, ds.meta, ds.warm_path_taken(marker)

    # -- snapshot assembly --------------------------------------------------

    @staticmethod
    def _node_record(n: dict) -> dict:
        return dict(
            name=n["name"], allocatable=n.get("allocatable", {}),
            labels=n.get("labels", {}), taints=n.get("taints", []),
            used=n.get("used", {}),
            unschedulable=n.get("unschedulable", False),
        )

    @staticmethod
    def _pending_record(p: dict) -> dict:
        keep = (
            "name", "requests", "priority", "slo_target", "observed_avail",
            "labels", "node_selector", "required_terms", "preferred_terms",
            "tolerations", "topology_spread", "pod_affinity", "pod_group",
            "pod_group_min_member", "namespace",
        )
        return {k: p[k] for k in keep if k in p}

    @staticmethod
    def _running_record(p: dict) -> dict:
        rec = dict(
            name=p["name"], node=p["node"], requests=p.get("requests", {}),
            priority=p.get("priority", 0.0), labels=p.get("labels", {}),
            pod_affinity=p.get("pod_affinity", []),
            namespace=p.get("namespace", "default"),
        )
        if p.get("pdb_group"):
            rec["pdb_group"] = p["pdb_group"]
            rec["pdb_disruptions_allowed"] = p.get("pdb_disruptions_allowed", 0)
        # QoS slack of a running pod: observed availability minus SLO
        # (SURVEY.md C10); specs carry both or a precomputed slack.
        # Defaults live in ONE place (config.py) shared with the kube
        # annotation parser and the wire codec.
        if "slack" in p:
            rec["slack"] = p["slack"]
        else:
            rec["slack"] = slack_of(
                p.get("slo_target", DEFAULT_SLO_TARGET),
                p.get("observed_avail", DEFAULT_OBSERVED_AVAIL),
            )
        return rec

    def _wire_snapshot(self, pending: list[dict]):
        nodes = [self._node_record(n) for n in self.api.list_nodes()]
        running = [self._running_record(p) for p in self.api.bound_pods()]
        pods = [self._pending_record(p) for p in pending]
        return snapshot_to_proto(nodes, pods, running)

    # -- one cycle ----------------------------------------------------------

    def backlogged(self) -> int:
        """Pods currently waiting out a backoff window."""
        now = self._clock()
        return sum(1 for t, _ in self._backoff.values() if t > now)

    def cycle(self) -> CycleStats | None:
        """One batched scheduling cycle; None when nothing is ACTIVE
        (pods in their backoff window don't count — they re-enter the
        active queue when it expires)."""
        now = self._clock()
        # Flight-ledger context (round 18, ISSUE 13): compile counters
        # snapshot BEFORE any solve work so the record attributes
        # exactly the retraces this cycle paid.
        lg = self.ledger or ledgering.DEFAULT
        comp0 = ledgering.COMPILES.counters() if lg.enabled else (0, 0.0)
        warm_path = "cold"
        rounds = frontier = 0
        n_nodes = n_running = 0
        # Drain change hints BEFORE reading cluster state: an event
        # landing between the drain and the reads stays in the
        # accumulator for next cycle (harmless over-inclusion), whereas
        # draining after the reads could consume a hint whose state the
        # snapshot missed — shipping a stale delta record next cycle.
        changed = None
        epoch_fn = e0 = None
        # Warm cycles suspend while the explain collector is on (the
        # warm program carries no provenance observers); the lineage is
        # dropped so it cannot go hint-stale while bypassed.
        warm_cycle = self._warm and not self.explain.enabled
        if self._warm and not warm_cycle and self._warm_ds is not None:
            self._warm_reset("explain_enabled")
        if self._delta is not None or self._pipeline is not None \
                or warm_cycle or self._devqueue is not None:
            drain = getattr(self.api, "drain_changed", None)
            epoch_fn = getattr(self.api, "relist_epoch", None)
            if epoch_fn is not None:
                e0 = epoch_fn()
            if drain is not None:
                changed = drain()
        # EVERYTHING between the drain and a successful send sits under
        # one try: pending_pods() itself can raise (a malformed pod
        # record parsed by an informer-backed api), and a failure after
        # the drain but before the send would otherwise lose the hints —
        # DeltaSession's base only advances on success, so the next
        # delta would trust a stale base for those records forever.
        window_s = 0.0
        queue_depth = 0
        try:
            if self._devqueue is not None:
                # Device-queue path (ISSUE 20): O(churn) hint-driven
                # sync, in-kernel ranking, O(W) window read-back. The
                # full pending set is never read after the first cycle.
                t0 = time.perf_counter()
                self._dq_sync(changed)
                win_names, _n_elig, queue_depth = self._devqueue.window(
                    self._dq_now(), self.batch_size)
                window_s = time.perf_counter() - t0
                reader = getattr(self.api, "pods_named", None)
                if reader is not None:
                    pending = reader(win_names)
                else:
                    want = set(win_names)
                    pending = [p for p in self.api.pending_pods()
                               if p["name"] in want]
                # The queue chose MEMBERSHIP; arrival order feeds the
                # solver so the batch is byte-identical to the
                # host-sorted path's whenever everything eligible fit.
                pending.sort(key=lambda p: p.get("arrival_seq", 0))
                backlog = queue_depth
            else:
                all_pending = self.api.pending_pods()
                queue_depth = len(all_pending)
                # Prune backoff state for pods that vanished (deleted,
                # or bound by another actor) so the book can't grow
                # unbounded.
                live_keys = {self._backoff_key(p) for p in all_pending}
                for k in [k for k in self._backoff if k not in live_keys]:
                    del self._backoff[k]
                pending = [
                    p for p in all_pending
                    if self._backoff.get(
                        self._backoff_key(p), (0.0, 0))[0] <= now
                ]
                pending = pending[: self.batch_size]
                backlog = len(all_pending)
            if not pending:
                # Nothing ships this cycle: un-drain the hints or the
                # next delta would trust a stale base for those records.
                self._restore_hints(changed)
                return None
            t0 = time.perf_counter()
            if warm_cycle:
                # Record-dialect snapshot (the DeviceSnapshot input);
                # the wire proto is never built on the warm path.
                nodes_r = [self._node_record(n)
                           for n in self.api.list_nodes()]
                running_r = [self._running_record(p)
                             for p in self.api.bound_pods()]
                pods_r = [self._pending_record(p) for p in pending]
            else:
                msg = self._wire_snapshot(pending)
            build_s = time.perf_counter() - t0
            # An informer re-list between the drain and these reads
            # replaced the cache with state the drained hints cannot
            # cover (the missed-event window) — diff everything.
            if epoch_fn is not None and epoch_fn() != e0:
                changed = None

            t0 = time.perf_counter()
            if warm_cycle:
                # Inside the try: a failed apply/solve restores the
                # hints AND invalidates the lineage (the unwind below),
                # so the next cycle full-loads and solves cold instead
                # of trusting half-applied warm state.
                try:
                    res, meta, warm_path = self._warm_cycle_solve(
                        nodes_r, pods_r, running_r, changed,
                        backlog=backlog,
                    )
                except BaseException:
                    self._warm_reset("cycle_error")
                    raise
            elif self.client is not None:
                if self._pipeline is not None:
                    # Depth-1 AssignPipeline: submit drains the pipe
                    # before returning, so exactly one response comes
                    # back per cycle while the pinned-base cumulative
                    # delta + resync/retry machinery stays engaged.
                    resp = self._pipeline.submit(msg, changed=changed,
                                                 packed_ok=True)[-1]
                elif self._delta is not None:
                    resp = self._delta.assign(msg, changed=changed,
                                              packed_ok=True)
                else:
                    resp = self.client.assign(msg, packed_ok=True)
        except BaseException:
            self._restore_hints(changed)
            raise
        if warm_cycle:
            assignments, evicted = self._result_names(meta, res)
            solve_s = time.perf_counter() - t0
            rounds = int(res.rounds)
            if res.inc_info:
                frontier = int(res.inc_info.get("frontier", 0))
            n_nodes, n_running = len(nodes_r), len(running_r)
        elif self.client is not None:
            # Packed parallel-array response: three frombuffer reads
            # instead of P Python proto message traversals (~30 ms per
            # 10k-pod cycle on each side of the wire).
            from tpusched.rpc.client import assign_response_arrays  # tpl: disable=TPL001(grpc transport is optional; the in-process host must import without grpc)

            pod_names, node_names, ni, _, _ = assign_response_arrays(resp)
            assignments = [
                (pod_names[i], node_names[int(n)])
                for i, n in enumerate(ni) if n >= 0
            ]
            evicted = list(resp.evicted)
            solve_s = time.perf_counter() - t0
            rounds = int(resp.rounds)
            n_nodes, n_running = len(msg.nodes), len(msg.running)
        else:
            snap, meta = decode_snapshot(msg, self.config, self.buckets)
            # Async dispatch: the window between dispatch and join is
            # where in-cycle CPU work can hide (pipeline.solve_stream's
            # overlap, in-cycle form — one cluster's consecutive CYCLES
            # cannot pipeline, since cycle k's binds feed cycle k+1's
            # snapshot), and the engine's ordered fetch worker drives
            # the device either way.
            ex_col = self.explain
            explain_on = ex_col.enabled
            if explain_on:
                p_solve, p_probe = self._engine.solve_explained_async(
                    snap, ex_col.topk)
                res, exd = p_solve.result()
                probe = p_probe.result()
                ex_col.record(explaining.build_record(
                    self.config, meta, res, exd, probe,
                    rpc="host.cycle", ts=self._clock(),
                ))
            else:
                pending_solve = self._engine.solve_async(snap)
                res = pending_solve.result()
            assignments, evicted = self._result_names(meta, res)
            solve_s = time.perf_counter() - t0
            rounds = int(res.rounds)
            n_nodes, n_running = meta.n_nodes, meta.n_running

        t0 = time.perf_counter()
        # Deletes before binds: a preemptor's room must exist before its
        # bind (upstream issues evictions first, then re-queues). Each
        # call is one API-server write; issue each class CONCURRENTLY
        # (against a real apiserver these are network round trips —
        # hundreds of serial Binding POSTs dominated bind_seconds; the
        # FakeApiServer is lock-bound and unaffected), with a join
        # between the classes so every delete lands before any bind.
        pool = self._io()
        if evicted:
            list(pool.map(self.api.delete_pod, evicted))

        def _try_bind(a):
            try:
                self.api.bind(*a)
                return a[0]
            except Conflict:
                # Another actor bound/removed it; safe to skip — the
                # next cycle re-reads truth (idempotent-bind story).
                return None

        bound_names = {n for n in pool.map(_try_bind, assignments) if n}
        placed = len(bound_names)
        # Queue maintenance: placed pods (or gangs with any member
        # placed) leave the backoff book; unplaced ones back off
        # exponentially — one shared entry per gang.
        now = self._clock()
        failed_keys: dict[str, bool] = {}
        for p in pending:
            key = self._backoff_key(p)
            if p["name"] in bound_names:
                failed_keys[key] = False
            else:
                failed_keys.setdefault(key, True)
        for key, fail in failed_keys.items():
            if not fail:
                self._backoff.pop(key, None)
                continue
            _, attempts = self._backoff.get(key, (0.0, 0))
            delay = min(
                self.backoff_initial * (2 ** min(attempts, 30)),
                self.backoff_max,
            )
            # Stop counting once the delay is capped: 2**attempts would
            # overflow float for a pod that stays unschedulable for long.
            if delay < self.backoff_max:
                attempts += 1
            self._backoff[key] = (now + delay, attempts)
        if self._devqueue is not None:
            self._dq_repark(failed_keys)
        bind_s = time.perf_counter() - t0
        stats = CycleStats(
            batch_size=len(pending), placed=placed, evicted=len(evicted),
            build_seconds=build_s, solve_seconds=solve_s, bind_seconds=bind_s,
        )
        self.cycles.append(stats)
        # One retroactive span per completed cycle: the host-side roof
        # over the per-request client/server traces (the rpc spans
        # carry their own request_ids; this one carries the batch).
        (self.tracer or tracing.DEFAULT).record(
            "host.cycle", dur_s=stats.total_seconds, cat="host",
            batch=stats.batch_size, placed=placed, evicted=len(evicted),
        )
        # One flight-ledger record per completed cycle (round 18,
        # ISSUE 13): the cycle-sequence join of everything above —
        # sizes, stage walls, churn, warm path, rounds, and the
        # retraces this cycle paid. The sentinel inside observe()
        # flags and attributes p99 spikes.
        if lg.enabled:
            c1, s1 = ledgering.COMPILES.counters()
            lg.observe(ledgering.CycleRecord(
                ts=float(now), source=self.ledger_source,
                pods=len(pending), nodes=int(n_nodes),
                running=int(n_running), placed=placed,
                evicted=len(evicted),
                churn=len(changed) if changed else 0,
                frontier=frontier, rounds=rounds, warm_path=warm_path,
                solve_s=solve_s,
                stages=(dict(build=build_s, solve=solve_s, bind=bind_s,
                             window=window_s)
                        if self._devqueue is not None else
                        dict(build=build_s, solve=solve_s, bind=bind_s)),
                compiles=c1 - comp0[0],
                compile_s=round(s1 - comp0[1], 6),
                queue_depth=int(queue_depth),
            ))
        return stats

    @staticmethod
    def _transient_rpc_error(exc: BaseException) -> bool:
        """A sidecar RpcError the host loop may safely re-drive: the
        failed cycle mutated nothing (binds happen after a successful
        response; change hints were restored by cycle()'s unwind), the
        snapshot is rebuilt from API-server truth next cycle, and a
        retried applied-but-unacked delta is deduped server-side by its
        (lineage_id, seq). Retryable statuses (UNAVAILABLE /
        RESOURCE_EXHAUSTED) were already retried inside the client's
        deadline budget, DEADLINE_EXCEEDED means the watchdog killed
        one dispatch (re-submit as a new cycle, exactly what re-driving
        does), and even INTERNAL is worth bounded re-reads —
        kube-scheduler keeps cycling through apiserver hiccups. NOT
        re-driven: statuses the server taxonomy marks as request bugs
        (INVALID_ARGUMENT, UNIMPLEMENTED) — the identical cycle would
        deterministically fail again, and re-drives each paying an
        O(cluster) rebuild would only mask the bug. The
        consecutive-failure cap is the give-up switch for the rest."""
        try:
            import grpc
        except ImportError:  # in-process host: nothing rpc to tolerate
            return False
        if not isinstance(exc, grpc.RpcError):
            return False
        return exc.code() not in (grpc.StatusCode.INVALID_ARGUMENT,
                                  grpc.StatusCode.UNIMPLEMENTED)

    def run_until_idle(self, max_cycles: int = 100,
                       max_consecutive_failures: int = 8) -> int:
        """Cycle until the ACTIVE queue drains (unschedulable pods land
        in backoff and stop participating — a live host would keep
        polling and retry them as windows expire). Returns the number of
        cycles executed (failed transient attempts count toward
        max_cycles so a dead sidecar cannot spin this loop forever).

        Transient sidecar failures (any grpc RpcError — see
        _transient_rpc_error for why re-driving is safe) are tolerated
        up to max_consecutive_failures in a row; the first success
        resets the streak. Anything else propagates immediately."""
        n = 0
        streak = 0
        while n < max_cycles:
            try:
                stats = self.cycle()
            except BaseException as e:
                if streak >= max_consecutive_failures \
                        or not self._transient_rpc_error(e):
                    raise
                streak += 1
                self.failed_cycles += 1
                self._m_failed_cycles.inc()
                n += 1
                continue
            streak = 0
            n += 1 if stats else 0
            if stats is None:
                break
            if stats.placed == 0 and stats.evicted == 0 and self.backlogged():
                break  # everything still pending is in backoff
        return n


# ---------------------------------------------------------------------------
# E2E benchmark entry (BASELINE.json:"configs"[0]; used by bench.py).
# ---------------------------------------------------------------------------


def build_synthetic_cluster(api: FakeApiServer, rng, n_pods: int, n_nodes: int):
    """configs[0]-shaped cluster: QoS-weighted LeastRequested workload."""
    for i in range(n_nodes):
        api.add_node(
            f"node-{i}",
            allocatable={"cpu": 8000.0, "memory": float(32 << 30)},
            labels={"kubernetes.io/hostname": f"node-{i}",
                    "topology.kubernetes.io/zone": f"zone-{i % 3}"},
        )
    for i in range(n_pods):
        slo = float(rng.choice([0.0, 0.9, 0.99]))
        # No observed_avail pin (ISSUE 5): availability comes from the
        # api's lifecycle accounting at read time — a never-scheduled
        # pod starts at the optimistic 1.0 fallback and decays as it
        # waits, so pressure reflects real queueing instead of the old
        # rng.uniform(0.5, 1.0) demo draw that left the QoS loop open.
        api.add_pod(
            f"pod-{i}",
            requests={"cpu": float(rng.integers(100, 500)),
                      "memory": float(rng.integers(1 << 28, 1 << 30))},
            priority=float(rng.integers(0, 100)),
            slo_target=slo,
            labels={"app": ["web", "db", "cache"][int(rng.integers(3))]},
        )


def synthetic_buckets(n_pods: int, n_nodes: int) -> Buckets:
    """Explicit floor buckets covering a build_synthetic_cluster
    workload through a FULL run: running_pods floors at n_pods (every
    pending pod eventually binds), feature axes at the Buckets defaults
    (the synthetic content's labels fit under them). Pinning these on a
    fleet's servers makes every cycle ONE shape class — the finite set
    a prewarmed replica compiles at boot (PR 18: chaos kill-the-leader
    asserts a promoted standby's compile delta is 0), where
    content-derived buckets would grow as pods bind and recompile
    mid-run."""
    return Buckets.fit(n_pods, n_nodes, n_running=n_pods)


def run_e2e_benchmark(n_pods: int = 100, n_nodes: int = 10, iters: int = 10,
                      use_grpc: bool = True, prewarm: bool = False):
    """Full-boundary E2E: fake API server -> host shim -> gRPC sidecar
    -> engine -> binds. Returns bench.py-style percentile stats of the
    complete cycle latency plus placements/sec. prewarm=True boots the
    sidecar with pinned synthetic_buckets and the full shape-class
    registry traced (and reports the boot cost as cold_start_s /
    prewarm_s), so the "+1 warmup" iteration pays no compile."""
    from tpusched.rpc.client import SchedulerClient  # tpl: disable=TPL001(grpc transport is optional; the in-process host must import without grpc)
    from tpusched.rpc.server import make_server  # tpl: disable=TPL001(grpc transport is optional; the in-process host must import without grpc)

    cfg = EngineConfig(mode="fast")
    server = client = shared_engine = svc = None
    boot = dict(cold_start_s=0.0, prewarm_s=0.0)
    if use_grpc:
        t_boot = time.perf_counter()
        server, port, svc = make_server(
            "127.0.0.1:0", config=cfg,
            buckets=synthetic_buckets(n_pods, n_nodes) if prewarm else None,
            prewarm=prewarm)
        server.start()
        svc.wait_prewarmed()
        boot["cold_start_s"] = round(time.perf_counter() - t_boot, 6)
        boot["prewarm_s"] = svc.prewarm_s
        client = SchedulerClient(f"127.0.0.1:{port}")
    else:
        shared_engine = Engine(cfg)  # one jit cache across iterations
    times, placed_total = [], 0
    try:
        for it in range(iters + 1):  # +1 warmup (compile)
            api = FakeApiServer()
            rng = np.random.default_rng(1000 + it)
            build_synthetic_cluster(api, rng, n_pods, n_nodes)
            host = HostScheduler(api, cfg, client=client, engine=shared_engine)
            t0 = time.perf_counter()
            host.run_until_idle()
            dt = time.perf_counter() - t0
            placed = sum(c.placed for c in host.cycles)
            if it > 0:  # skip compile iteration
                times.append(dt)
                placed_total += placed
    finally:
        if client is not None:
            client.close()
        if server is not None:
            server.stop(0)
        if svc is not None:
            svc.close()
        if shared_engine is not None:
            shared_engine.close()
    times = np.asarray(times)
    return dict(
        p50=float(np.percentile(times, 50)),
        p90=float(np.percentile(times, 90)),
        p99=float(np.percentile(times, 99)),
        max=float(times.max()),
        mean=float(times.mean()),
        iters=len(times),
        placements_per_sec=round(placed_total / times.sum(), 1),
        **boot,
    )
