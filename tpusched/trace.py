"""End-to-end request tracing (round 9, ISSUE 4 tentpole).

A `TraceCollector` is a lock-cheap ring buffer of `Span` records —
named, timed stage intervals attached to a `trace_id` (the wire
`request_id`). Every stage boundary of the serving path emits one:
client send/retry/resync (rpc/client.py), gate queue wait, coalescer
fuse/wait, decode, device delta-apply (+H2D bytes), solve dispatch,
fetch join, reply pack (rpc/server.py), the engine's background fetch
(engine.py), device rebuilds (device_state.py), injected faults
(faults.py), and kube watch reconnects (kube.py).

Design constraints, in order:

  * ZERO overhead when disabled: ``span()`` is one attribute read and
    returns a shared no-op context manager; ``record()`` returns
    immediately. No thread, no allocation, no lock on the disabled
    path — tracing must be safe to leave compiled into every hot path.
  * Lock-cheap when enabled: one short lock around a deque append.
    Spans are immutable-after-finish plain records; readers snapshot
    under the same lock. The collector NEVER spawns threads
    (tests/conftest.py thread_leak_check pins this).
  * Seedable ids: trace ids are ``<seeded-prefix>-<counter>`` so tests
    and chaos twins get reproducible identities; span ids are a
    process-wide monotone counter (itertools.count — atomic in
    CPython).
  * Cross-thread, cross-wire stitching: spans carry an explicit
    trace_id; WITHIN a thread, nested ``span()`` blocks auto-parent
    through a per-collector thread-local stack, and code dispatching
    work to another thread captures ``current()`` and passes it to
    ``record(ctx=...)`` (engine fetch worker). Across the wire the
    client stamps its trace_id into the request's ``request_id`` field
    and its active span id into ``parent_span``; the server roots its
    spans there (absent id => server-minted), so client and server
    rings merge into one causal trace per request.

Export: ``to_chrome(spans)`` renders Chrome/Perfetto trace-event JSON
(``tools/tracez.py``); ``span_dict``/``spans()`` feed the sidecar's
Debugz rpc. The `FlightRecorder` snapshots the ring (plus caller
counters) on failure events — watchdog trips, ladder demotions, resync
storms — so every degradation event carries its causal trace instead
of being a bare counter bump.
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

# Process-wide span id mint: itertools.count.__next__ is atomic in
# CPython, so span ids need no lock and stay unique across collectors.
_SPAN_IDS = itertools.count(1)


@dataclass
class Span:
    trace_id: str       # wire request_id ("" = untraced event)
    span_id: int
    parent_id: int      # 0 = root
    name: str           # stage name ("decode", "gate.wait", ...)
    cat: str            # "client" | "server" | "engine" | "device" | ...
    t_wall: float       # epoch seconds at span start
    dur_s: float
    thread: str
    attrs: "dict[str, Any]" = field(default_factory=dict)

    @property
    def end_wall(self) -> float:
        """Epoch seconds at span end (the wire ledger stitches cycle
        bounds and one-way gaps from span endpoints — round 19)."""
        return self.t_wall + self.dur_s


def span_dict(s: Span) -> "dict[str, Any]":
    return dict(
        trace_id=s.trace_id, span_id=s.span_id, parent_id=s.parent_id,
        name=s.name, cat=s.cat, t_wall=s.t_wall, dur_s=s.dur_s,
        thread=s.thread, attrs=dict(s.attrs),
    )


class _NoopSpan:
    """Shared disabled-path context manager: supports the same surface
    live spans do (attrs mutation, span_id read) so call sites need no
    enabled-check of their own."""

    __slots__ = ()
    span_id = 0
    attrs: "dict[str, Any]" = {}  # writes land here and are discarded; shared is fine

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: object) -> bool:
        return False


_NOOP = _NoopSpan()


class _LiveSpan:
    """One open span; finishing (context exit) appends the immutable
    record to the collector ring."""

    __slots__ = ("_col", "name", "cat", "trace_id", "parent_id",
                 "span_id", "attrs", "_t_wall", "_t0")

    def __init__(self, col: "TraceCollector", name: str, cat: str,
                 trace_id: "str | None", parent_id: "int | None",
                 attrs: "dict[str, Any]"):
        self._col = col
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.span_id = next(_SPAN_IDS)
        self.attrs = attrs

    def __enter__(self) -> "_LiveSpan":
        col = self._col
        stack = col._stack()
        if self.trace_id is None:
            # Inherit identity from the enclosing span on this thread;
            # with no enclosure this is an untraced event stream ("").
            if stack:
                self.trace_id = stack[-1][0]
                if self.parent_id is None:
                    self.parent_id = stack[-1][1]
            else:
                self.trace_id = ""
        elif self.parent_id is None and stack \
                and stack[-1][0] == self.trace_id:
            self.parent_id = stack[-1][1]
        if self.parent_id is None:
            self.parent_id = 0
        stack.append((self.trace_id, self.span_id))
        self._t_wall = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, et: Any, ev: Any, tb: Any) -> bool:
        dur = time.perf_counter() - self._t0
        stack = self._col._stack()
        if stack and stack[-1][1] == self.span_id:
            stack.pop()
        if et is not None:
            self.attrs.setdefault("error", f"{et.__name__}: {ev}")
        self._col._append(Span(
            trace_id=self.trace_id, span_id=self.span_id,
            parent_id=self.parent_id, name=self.name, cat=self.cat,
            t_wall=self._t_wall, dur_s=dur,
            thread=threading.current_thread().name, attrs=self.attrs,
        ))
        return False


class TraceCollector:
    """Ring-buffered span collector (module docstring)."""

    def __init__(self, capacity: int = 4096, seed: "int | None" = None,
                 enabled: bool = True):
        self._lock = threading.Lock()
        self._ring: "deque[Span]" = deque(maxlen=int(capacity))
        self._tls = threading.local()
        self.enabled = enabled
        self._prefix = f"{random.Random(seed).getrandbits(32):08x}"
        self._mint = itertools.count(1)

    # -- id minting ----------------------------------------------------------

    def new_trace_id(self) -> str:
        """Seeded-prefix + counter: unique per collector, reproducible
        under a pinned seed."""
        return f"{self._prefix}-{next(self._mint)}"

    # -- recording -----------------------------------------------------------

    def _stack(self) -> "list[tuple[str, int]]":
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _append(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span)

    def span(self, name: str, cat: str = "server",
             trace_id: "str | None" = None,
             parent_id: "int | None" = None,
             **attrs: Any) -> "_LiveSpan | _NoopSpan":
        """Context manager timing a stage. trace_id=None inherits from
        the enclosing span on this thread (or records untraced)."""
        if not self.enabled:
            return _NOOP
        return _LiveSpan(self, name, cat, trace_id, parent_id, attrs)

    def request(self, trace_id: str, parent_id: int = 0,
                name: str = "request", cat: str = "server",
                **attrs: Any) -> "_LiveSpan | _NoopSpan":
        """Root span with explicit wire identity (server handlers)."""
        if not self.enabled:
            return _NOOP
        return _LiveSpan(self, name, cat, trace_id, int(parent_id), attrs)

    def record(self, name: str, dur_s: float = 0.0, cat: str = "event",
               ctx: "tuple[str, int] | None" = None,
               **attrs: Any) -> None:
        """Retroactive span ending NOW with the given duration — for
        stages whose start wasn't wrapped (gate wait, cross-thread
        fetches). ctx: (trace_id, parent_span_id) captured earlier via
        current(); None inherits from this thread's stack."""
        if not self.enabled:
            return
        if ctx is None:
            stack = self._stack()
            ctx = stack[-1] if stack else ("", 0)
        dur_s = max(float(dur_s), 0.0)
        self._append(Span(
            trace_id=ctx[0], span_id=next(_SPAN_IDS), parent_id=ctx[1],
            name=name, cat=cat, t_wall=time.time() - dur_s, dur_s=dur_s,
            thread=threading.current_thread().name, attrs=attrs,
        ))

    def current(self) -> "tuple[str, int] | None":
        """(trace_id, span_id) of this thread's innermost open span —
        capture before handing work to another thread."""
        stack = self._stack()
        return stack[-1] if stack else None

    # -- reading -------------------------------------------------------------

    def spans(self, trace_id: "str | None" = None) -> "list[Span]":
        """Snapshot of the ring, oldest first; optionally one trace."""
        with self._lock:
            out = list(self._ring)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def durations(self, trace_id: str) -> "dict[str, float]":
        """Total completed-span seconds per stage name for one trace —
        the cycle ledger's stage-timing join (round 18, ISSUE 13): a
        CycleRecord's `stages` dict is this, so a ledger anomaly names
        the same stages a trace shows. Open (unfinished) spans are
        absent by construction; disabled collectors return {}."""
        out: "dict[str, float]" = {}
        for s in self.spans(trace_id):
            out[s.name] = out.get(s.name, 0.0) + s.dur_s
        return out

    def traces(self, last: int = 16) -> "dict[str, list[Span]]":
        """The most recent `last` traces (trace_id -> spans, oldest
        span first within each), by recency of each trace's newest
        span. Untraced events ("") are excluded. last <= 0 returns
        nothing (a negative slice would invert the bound)."""
        if int(last) <= 0:
            return {}
        groups: "dict[str, list[Span]]" = {}
        for s in self.spans():
            if s.trace_id:
                # dict preserves insertion order; re-inserting on every
                # span keeps ids ordered by their NEWEST span.
                groups[s.trace_id] = groups.pop(s.trace_id, [])
                groups[s.trace_id].append(s)
        ids = list(groups)[-int(last):]
        return {t: groups[t] for t in ids}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


def to_chrome(spans: "Iterable[Span | dict[str, Any]]",
              pid: int = 1) -> "list[dict[str, Any]]":
    """Chrome/Perfetto trace-event list ("X" complete events, ts/dur in
    microseconds) from spans or span_dicts. Load via chrome://tracing
    or ui.perfetto.dev."""
    events: "list[dict[str, Any]]" = []
    for s in spans:
        d = span_dict(s) if isinstance(s, Span) else s
        args = dict(d["attrs"])
        args["trace_id"] = d["trace_id"]
        args["span_id"] = d["span_id"]
        if d["parent_id"]:
            args["parent_span"] = d["parent_id"]
        events.append(dict(
            name=d["name"], cat=d["cat"] or "span", ph="X",
            ts=d["t_wall"] * 1e6, dur=max(d["dur_s"], 0.0) * 1e6,
            pid=pid, tid=d["thread"], args=args,
        ))
    return events


class FlightRecorder:
    """Snapshots a collector's ring on failure events (watchdog trip,
    ladder demotion, resync storm) so the operator gets the CAUSAL
    trace of a degradation, not just a counter bump. Keeps the last
    `capacity` dumps; thread-safe; spawns no threads."""

    def __init__(self, capacity: int = 8):
        self._lock = threading.Lock()
        self._dumps: "deque[dict[str, Any]]" = deque(maxlen=int(capacity))
        self.trips = 0
        # Optional tpusched.explain.ExplainCollector (round 12): when
        # attached AND enabled, every dump also carries the last-N
        # decision records, so a watchdog trip / ladder demotion ships
        # the DECISIONS in flight alongside the causal trace.
        self.decisions: Any = None
        self.decisions_last = 4

    def record(self, reason: str, collector: TraceCollector,
               **extra: Any) -> "dict[str, Any]":
        dump: "dict[str, Any]" = dict(
            ts=time.time(), reason=reason, extra=extra,
            spans=[span_dict(s) for s in collector.spans()],
        )
        dec = self.decisions
        if dec is not None and getattr(dec, "enabled", False):
            from tpusched import explain as _explain  # tpl: disable=TPL001(trace must stay stdlib-only at import; explain pulls the jax kernels stack)

            dump["decisions"] = [
                _explain.record_dict(r, include_auction=True)
                for r in dec.last(self.decisions_last)
            ]
        with self._lock:
            self._dumps.append(dump)
            self.trips += 1
        return dump

    def dumps(self) -> "list[dict[str, Any]]":
        with self._lock:
            return list(self._dumps)


class StormDetector:
    """Sliding-window event-rate trigger: hit() returns True when the
    `n`th event lands within `window_s` — and then resets, so one storm
    yields ONE flight-recorder dump, not one per event. Clock-injectable
    for deterministic tests."""

    def __init__(self, n: int = 4, window_s: float = 5.0,
                 clock: "Callable[[], float]" = time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self.n = int(n)
        self.window_s = float(window_s)
        self._times: "deque[float]" = deque(maxlen=self.n)
        self.storms = 0

    def hit(self) -> bool:
        now = self._clock()
        with self._lock:
            self._times.append(now)
            if (len(self._times) == self.n
                    and now - self._times[0] <= self.window_s):
                self._times.clear()
                self.storms += 1
                return True
            return False


# Process default: clients, the sidecar, and the event streams
# (device_state rebuilds, faults, kube reconnects) all share this
# collector unless handed their own, so an in-process client+server run
# yields ONE stitched ring. `set_enabled(False)` is the global off
# switch (bench.py --trace=off measures the disabled path).
DEFAULT = TraceCollector()


def set_enabled(on: bool) -> None:
    DEFAULT.enabled = bool(on)
