"""Device mesh + sharding layout (SURVEY.md C14).

The 2D mesh maps the problem's two big axes onto hardware
(SURVEY.md §2.3): pending pods shard over the 'p' axis (the DP
analogue), candidate nodes over the 'n' axis (the TP analogue). The
[P, N] feasibility/score matrices shard PS('p','n'); per-pod reductions
over nodes (argmax, NormalizeScore max) become cross-'n' XLA collectives
inserted by the SPMD partitioner; nothing is hand-scheduled.

Multi-host: jax.distributed.initialize() before make_mesh() and the same
code spans slices — ICI within a slice, DCN across (SURVEY.md §5
"Distributed communication backend").
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from tpusched.snapshot import ClusterSnapshot

POD_AXIS = "p"
NODE_AXIS = "n"


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host entry (SURVEY.md §5 'Distributed communication
    backend'): initialize jax.distributed so jax.devices() spans every
    host's chips — ICI within a slice, DCN across slices — then build
    meshes as usual; the same solve code runs SPMD with XLA inserting
    the cross-host collectives. With no arguments, relies on the TPU
    environment's auto-detection (GKE/Borg metadata); arguments mirror
    jax.distributed.initialize for manual clusters.

    The reference's analogue is client-go's watch/bind HTTP plumbing —
    its only 'backend' — while compute scaling here rides XLA
    collectives; gRPC stays at the host boundary (SURVEY.md §2.3)."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def make_mesh(shape: tuple[int, int] | None = None, devices=None) -> Mesh:
    """Mesh of shape (p, n). Default: all devices on the 'p' axis (pod
    sharding scales first; node-axis sharding pays collective cost on
    every per-pod reduction)."""
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devices), 1)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, (POD_AXIS, NODE_AXIS))


def _spec_for(path: str, mesh: Mesh) -> NamedSharding:
    p = PS(POD_AXIS)
    n = PS(NODE_AXIS)
    rep = PS()
    table = {"pods": p, "nodes": n}
    return NamedSharding(mesh, table.get(path, rep))


def snapshot_shardings(mesh: Mesh, snap: ClusterSnapshot) -> ClusterSnapshot:
    """Pytree of NamedShardings matching the snapshot's structure:
    pod-major arrays shard on 'p', node-major on 'n', vocab tables
    (atoms, taint effects, groups, running pods) replicate."""

    def build(sub, path):
        return jax.tree.map(lambda _: _spec_for(path, mesh), sub)

    return ClusterSnapshot(
        nodes=build(snap.nodes, "nodes"),
        pods=build(snap.pods, "pods"),
        running=build(snap.running, "rep"),
        atoms=build(snap.atoms, "rep"),
        sigs=build(snap.sigs, "rep"),
        taint_effect=_spec_for("rep", mesh),
        group_min_member=_spec_for("rep", mesh),
        pdb_allowed=_spec_for("rep", mesh),
    )


def shard_snapshot(mesh: Mesh, snap: ClusterSnapshot) -> ClusterSnapshot:
    """device_put the snapshot with the standard layout."""
    return jax.device_put(snap, snapshot_shardings(mesh, snap))


def matrix_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [P, N] result matrices."""
    return NamedSharding(mesh, PS(POD_AXIS, NODE_AXIS))


def pod_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PS(POD_AXIS))
