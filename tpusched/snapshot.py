"""ClusterSnapshot: the cluster state as a struct-of-arrays pytree (C1).

This is the device-side mirror of the reference scheduler's cluster cache
(SURVEY.md §1.2 L2: informer-fed snapshot of nodes + assumed pods). Every
string the scheduler reasons about — label keys, (key,value) pairs, taints,
match-expression atoms, topology keys — is interned on the host into an
integer vocabulary by `SnapshotBuilder`, so the device sees only dense,
padded, statically-shaped int/float arrays. That is what lets the whole
Filter->Score->Commit cycle compile to a single XLA program.

Encoding invariants (relied on by every kernel):
  * -1 is the universal padding id in any id array.
  * `valid` masks mark live rows; padded rows must never win an argmax.
  * A nodeSelectorTerm with zero atoms is invalid (upstream: an empty
    term matches no objects); a pod with zero valid required terms has no
    required node affinity (matches all nodes).
  * A pod/label selector (topology spread, inter-pod affinity) with a set
    valid flag but zero atoms matches ALL pods (upstream: empty label
    selector matches everything).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import numpy as np
from flax import struct

from tpusched.config import (
    Buckets,
    DEFAULT_OBSERVED_AVAIL,
    DEFAULT_SLO_TARGET,
    EngineConfig,
    OPERATORS,
    RESOURCE_PODS,
    TAINT_EFFECTS,
    DO_NOT_SCHEDULE,
    SCHEDULE_ANYWAY,
    _next_bucket,
)


# ---------------------------------------------------------------------------
# Host-side spec structures (the "pod spec" surface a caller fills in).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MatchExpression:
    """One matchExpressions entry: key op values (upstream semantics,
    SURVEY.md C2): In / NotIn / Exists / DoesNotExist / Gt / Lt."""

    key: str
    op: str
    values: tuple[str, ...] = ()

    def __post_init__(self):
        if self.op not in OPERATORS:
            raise ValueError(f"bad operator {self.op!r}; want one of {OPERATORS}")
        if self.op in ("Gt", "Lt") and len(self.values) != 1:
            raise ValueError(f"{self.op} needs exactly one value")


@dataclasses.dataclass(frozen=True)
class NodeSelectorTerm:
    expressions: tuple[MatchExpression, ...]


@dataclasses.dataclass(frozen=True)
class PreferredTerm:
    weight: float
    term: NodeSelectorTerm


@dataclasses.dataclass(frozen=True)
class Toleration:
    key: str = ""           # "" + Exists tolerates everything
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""        # "" matches all effects


@dataclasses.dataclass(frozen=True)
class TopologySpreadConstraint:
    topology_key: str
    max_skew: int
    when_unsatisfiable: str  # DoNotSchedule | ScheduleAnyway
    # Label selector over pods, as match expressions (matchLabels entries
    # become In expressions with a single value).
    selector: tuple[MatchExpression, ...] = ()


@dataclasses.dataclass(frozen=True)
class PodAffinityTerm:
    topology_key: str
    selector: tuple[MatchExpression, ...] = ()
    anti: bool = False
    required: bool = True
    weight: float = 1.0      # only used when required=False
    # Namespace scope (upstream podAffinityTerm.namespaces): the term
    # matches only member pods in these namespaces. Empty = the incoming
    # pod's own namespace (upstream default); ("*",) = all namespaces
    # (the namespaceSelector:{} escape hatch).
    namespaces: tuple[str, ...] = ()


def selector_from_labels(labels: Mapping[str, str]) -> tuple[MatchExpression, ...]:
    """matchLabels -> equivalent In expressions (upstream conversion)."""
    return tuple(MatchExpression(k, "In", (v,)) for k, v in sorted(labels.items()))


# ---------------------------------------------------------------------------
# Device-side pytrees.
# ---------------------------------------------------------------------------


@struct.dataclass
class AtomTable:
    """Distinct match-expression atoms across the snapshot.

    atom_sat[a, n] (computed on device, kernels/atoms.py) answers "does
    node n satisfy atom a"; pods then reference atoms by id. Pod-label
    selectors reuse the same table against pod labels."""

    key: Any        # [A] int32  key id (-1 pad)
    op: Any         # [A] int8   OP_* code
    pairs: Any      # [A, VA] int32  (key,value)-pair ids for In/NotIn
    num: Any        # [A] f32    numeric bound for Gt/Lt
    valid: Any      # [A] bool


@struct.dataclass
class SigTable:
    """Distinct (topology key, namespace scope, pod-label selector)
    signatures across all topology-spread and inter-pod-affinity
    constraints (SURVEY.md C6/C7).

    Domain counting is done once per signature — counts[s, d] = matching
    member pods in domain d of sig s's topology key — instead of once per
    pod, which is what makes pairwise constraints scale: pods reference
    signatures by id (pods.ts_sig / pods.ia_sig) and just gather.

    A member matches sig s iff its labels satisfy the selector atoms AND
    its namespace is in the sig's scope (ns list, or ns_all). Spread
    constraints are always scoped to the incoming pod's own namespace
    (upstream counts same-namespace pods only); affinity terms resolve
    their `namespaces` field at build time."""

    key: Any     # [S] int32 topology-key index
    atoms: Any   # [S, AT] int32 selector atom ids (-1 pad; none = match all)
    ns: Any      # [S, NSV] int32 allowed namespace ids (-1 pad)
    ns_all: Any  # [S] bool: matches every namespace
    valid: Any   # [S] bool


@struct.dataclass
class NodeArrays:
    allocatable: Any   # [N, R] f32
    used: Any          # [N, R] f32 (requests of bound pods)
    label_pairs: Any   # [N, LN] int32 (-1 pad)
    label_keys: Any    # [N, LN] int32 (-1 pad)
    label_nums: Any    # [N, LN] f32 (numeric label value or NaN)
    taint_ids: Any     # [N, TN] int32 into taint vocab (-1 pad)
    domain: Any        # [N, TK] int32 topology-domain id per topo key (-1 none)
    # [N] bool: node accepts NEW pods (false = cordoned, the upstream
    # node.spec.unschedulable flag). A cordoned node stays `valid`: its
    # running pods still count toward capacity, spread domains, and
    # affinity matches — it just takes no new placements.
    schedulable: Any
    valid: Any         # [N] bool


@struct.dataclass
class PodArrays:
    requests: Any        # [P, R] f32
    base_priority: Any   # [P] f32 (pod.spec.priority analogue)
    slo_target: Any      # [P] f32 availability SLO in [0,1]
    observed_avail: Any  # [P] f32 observed availability in [0,1]
    tolerated: Any       # [P, VT] bool (precompiled toleration vs taint vocab)
    label_pairs: Any     # [P, LP] int32
    label_keys: Any      # [P, LP] int32
    # Required node affinity: OR over terms, AND over atoms within a term.
    req_term_atoms: Any  # [P, T, AT] int32 atom ids (-1 pad)
    req_term_valid: Any  # [P, T] bool
    # Preferred node affinity.
    pref_term_atoms: Any  # [P, PT, AT] int32
    pref_term_valid: Any  # [P, PT] bool
    pref_weight: Any      # [P, PT] f32
    # Topology spread constraints.
    ts_key: Any          # [P, C] int32 index into topo keys (-1 pad)
    ts_max_skew: Any     # [P, C] f32
    ts_when: Any         # [P, C] int8 DO_NOT_SCHEDULE | SCHEDULE_ANYWAY
    ts_sel_atoms: Any    # [P, C, AT] int32 selector atoms over pod labels
    ts_sig: Any          # [P, C] int32 signature id (-1 pad)
    ts_valid: Any        # [P, C] bool
    # Inter-pod (anti-)affinity terms.
    ia_key: Any          # [P, IT] int32 topo key index
    ia_sel_atoms: Any    # [P, IT, AT] int32 selector atoms over pod labels
    ia_sig: Any          # [P, IT] int32 signature id (-1 pad)
    ia_anti: Any         # [P, IT] bool
    ia_required: Any     # [P, IT] bool
    ia_weight: Any       # [P, IT] f32
    ia_valid: Any        # [P, IT] bool
    # Gang scheduling.
    group: Any           # [P] int32 pod-group id (-1 = none)
    namespace: Any       # [P] int32 namespace id
    # [P] bool: tolerates the node.kubernetes.io/unschedulable:NoSchedule
    # taint — upstream's NodeUnschedulable plugin admits such pods
    # (DaemonSet pattern) onto cordoned nodes.
    tolerates_unsched: Any
    valid: Any           # [P] bool


@struct.dataclass
class RunningPodArrays:
    node_idx: Any     # [M] int32 (-1 pad)
    requests: Any     # [M, R] f32
    priority: Any     # [M] f32
    slack: Any        # [M] f32 observed_avail - slo (positive = cheap victim)
    label_pairs: Any  # [M, LP] int32
    label_keys: Any   # [M, LP] int32
    # Required ANTI-affinity terms this running pod holds, as signature
    # ids (-1 pad). Upstream inter-pod anti-affinity is SYMMETRIC: an
    # existing pod's required anti-affinity repels incoming pods that
    # match its selector (SURVEY.md C7). Preferred / positive terms of
    # running pods are not symmetric for filtering and are not stored.
    anti_sig: Any     # [M, IT] int32
    namespace: Any    # [M] int32 namespace id
    pdb_group: Any    # [M] int32 PodDisruptionBudget id (-1 = none)
    valid: Any        # [M] bool


@struct.dataclass
class ClusterSnapshot:
    nodes: NodeArrays
    pods: PodArrays
    running: RunningPodArrays
    atoms: AtomTable
    sigs: SigTable
    taint_effect: Any     # [VT] int8
    group_min_member: Any  # [G] int32 (0 for unused slots)
    # [GP] f32 remaining disruptions allowed per PodDisruptionBudget
    # (SURVEY.md C9 "fewest PDB violations"): evicting more than this
    # many members of a budget is a violation, avoided unless no
    # non-violating victim set exists (upstream last-resort semantics).
    pdb_allowed: Any


@dataclasses.dataclass
class SnapshotMeta:
    """Host-side decode tables (index -> name); not shipped to device."""

    node_names: list[str]
    pod_names: list[str]
    n_nodes: int
    n_pods: int
    n_running: int
    buckets: Buckets
    group_names: list[str]
    # Running-pod names (eviction responses); populated by callers that
    # track them (the gRPC codec and host shim).
    running_names: list[str] | None = None


# ---------------------------------------------------------------------------
# Builder: interning + padding.
# ---------------------------------------------------------------------------


def _try_float(s: str) -> float:
    try:
        return float(s)
    except (TypeError, ValueError):
        return float("nan")


class _Interner:
    """The host-side string->id state of one snapshot LINEAGE.

    Extracted from SnapshotBuilder.build()'s closures so it can outlive
    one build: DeviceSnapshot (device_state.py) keeps an interner alive
    across delta cycles and compiles only churned records against it —
    new vocabulary APPENDS, so ids already burned into device arrays
    stay valid. Id assignment order therefore matches a fresh build only
    until the first mid-session vocabulary growth; ids are opaque
    equality tokens everywhere on device, so results are unaffected
    (the delta-vs-rebuild parity tests pin this)."""

    def __init__(self):
        self.key_ids: dict[str, int] = {}
        self.pair_ids: dict[tuple[str, str], int] = {}
        self.taint_ids: dict[tuple[str, str, str], int] = {}
        self.atom_ids: dict[tuple, int] = {}
        self.atoms: list[tuple[int, int, tuple[int, ...], float]] = []
        self.topo_keys: list[str] = []
        self.domain_ids: list[dict[str, int]] = []  # per topo key: value -> id
        self.ns_ids: dict[str, int] = {}
        self.sig_ids: dict[tuple, int] = {}
        # each entry: (key_idx, ns_scope, atoms) where ns_scope is "*"
        # (all namespaces) or a sorted tuple of namespace ids.
        self.sigs: list[tuple[int, Any, tuple[int, ...]]] = []

    # -- primitive id assignment -------------------------------------------

    def kid(self, k: str) -> int:
        return self.key_ids.setdefault(k, len(self.key_ids))

    def pid(self, k: str, v: str) -> int:
        return self.pair_ids.setdefault((k, v), len(self.pair_ids))

    def tid(self, k: str, v: str, effect: str) -> int:
        if effect not in TAINT_EFFECTS:
            raise ValueError(f"bad taint effect {effect!r}")
        return self.taint_ids.setdefault((k, v, effect), len(self.taint_ids))

    def topo_idx(self, k: str) -> int:
        if k not in self.topo_keys:
            self.topo_keys.append(k)
            self.domain_ids.append({})
        return self.topo_keys.index(k)

    def nsid(self, ns: str) -> int:
        return self.ns_ids.setdefault(ns, len(self.ns_ids))

    def aid(self, expr: MatchExpression) -> int:
        op = OPERATORS.index(expr.op)
        k = self.kid(expr.key)
        if expr.op in ("In", "NotIn"):
            pids = tuple(sorted(self.pid(expr.key, v) for v in expr.values))
            num = float("nan")
        elif expr.op in ("Gt", "Lt"):
            pids = ()
            num = float(expr.values[0])
        else:
            pids = ()
            num = float("nan")
        # Dedup key must not contain NaN (nan != nan would make every
        # non-numeric atom "distinct", exploding the atom/signature
        # tables ~Px): key numeric ops by the number, others by None.
        sig = (k, op, pids, num if num == num else None)
        if sig not in self.atom_ids:
            self.atom_ids[sig] = len(self.atoms)
            self.atoms.append((k, op, pids, num))
        return self.atom_ids[sig]

    def sid(self, key_idx: int, atoms_list: list[int], ns_scope) -> int:
        sig = (key_idx, ns_scope, tuple(sorted(atoms_list)))
        if sig not in self.sig_ids:
            self.sig_ids[sig] = len(self.sigs)
            self.sigs.append(sig)
        return self.sig_ids[sig]

    def ns_scope_of(self, namespaces: Sequence[str], own_ns: str):
        """Resolve an affinity term's namespace list against the
        owning pod's namespace (upstream: empty = own namespace).
        Iterate names in sorted order so id ASSIGNMENT order is
        deterministic (set iteration is hash-randomized)."""
        if not namespaces:
            return (self.nsid(own_ns),)
        if "*" in namespaces:
            return "*"
        return tuple(sorted(self.nsid(x) for x in sorted(set(namespaces))))

    # -- record-level interning --------------------------------------------

    def compile_pod(self, p: Mapping) -> dict:
        """Intern everything one pending-pod record references; returns
        the compiled form row fills consume. MUTATES the interner (new
        atoms/sigs/namespaces/topology keys append)."""
        aid = self.aid
        terms = [NodeSelectorTerm(tuple(
            MatchExpression(k, "In", (v,))
            for k, v in sorted(p["node_selector"].items())
        ))] if p["node_selector"] else []
        # nodeSelector ANDs with required affinity: encode nodeSelector
        # as an extra atom set ANDed into every required term (or a
        # standalone single term when no affinity terms exist).
        sel_atoms = [aid(e) for t in terms for e in t.expressions]
        req_terms = []
        for t in p["required_terms"]:
            if not t.expressions:
                continue  # empty term matches no objects -> drop (cannot satisfy)
            req_terms.append([aid(e) for e in t.expressions] + sel_atoms)
        if not req_terms and sel_atoms:
            req_terms = [sel_atoms]
        pref_terms = [
            ([aid(e) for e in pt.term.expressions], float(pt.weight))
            for pt in p["preferred_terms"] if pt.term.expressions
        ]
        own_ns = p["namespace"]
        ts = [
            dict(key=self.topo_idx(c.topology_key), max_skew=float(c.max_skew),
                 when=DO_NOT_SCHEDULE if c.when_unsatisfiable == "DoNotSchedule"
                 else SCHEDULE_ANYWAY,
                 atoms=[aid(e) for e in c.selector])
            for c in p["topology_spread"]
        ]
        for c in ts:
            # Spread counting is always scoped to the incoming pod's
            # own namespace (upstream PodTopologySpread semantics).
            c["sig"] = self.sid(c["key"], c["atoms"], (self.nsid(own_ns),))
        ia = [
            dict(key=self.topo_idx(t.topology_key),
                 atoms=[aid(e) for e in t.selector],
                 anti=t.anti, required=t.required, weight=float(t.weight),
                 ns=self.ns_scope_of(t.namespaces, own_ns))
            for t in p["pod_affinity"]
        ]
        for t in ia:
            t["sig"] = self.sid(t["key"], t["atoms"], t["ns"])
        return dict(req_terms=req_terms, pref_terms=pref_terms, ts=ts, ia=ia)

    def compile_running_anti(self, rrec: Mapping) -> tuple[list[int], int]:
        """Running pods' required anti-affinity terms (symmetric rule):
        interned into the same signature table as pending terms. Returns
        (sig ids, widest selector atom count seen)."""
        sigs_of_pod: list[int] = []
        atom_max = 0
        for t in rrec["pod_affinity"]:
            if not (t.anti and t.required):
                continue
            alist = [self.aid(e) for e in t.selector]
            atom_max = max(atom_max, len(alist))
            sigs_of_pod.append(self.sid(
                self.topo_idx(t.topology_key), alist,
                self.ns_scope_of(t.namespaces, rrec["namespace"]),
            ))
        return sigs_of_pod, atom_max

    def intern_labels(self, labels: Mapping[str, str]) -> None:
        for k, v in labels.items():
            self.kid(k)
            self.pid(k, v)


class SnapshotBuilder:
    """Accumulates node/pod records and emits a padded ClusterSnapshot.

    All interning happens in build() so records may arrive in any order
    and buckets can be auto-fitted to the observed counts."""

    def __init__(self, config: EngineConfig, buckets: Buckets | None = None):
        self.config = config
        self.buckets = buckets
        self._nodes: list[dict] = []
        self._pods: list[dict] = []
        self._running: list[dict] = []
        self._groups: dict[str, int] = {}  # name -> min_member
        self._pdbs: dict[str, int] = {}    # name -> disruptions allowed

    # -- record intake ------------------------------------------------------

    def add_node(
        self,
        name: str,
        allocatable: Mapping[str, float],
        labels: Mapping[str, str] | None = None,
        taints: Sequence[tuple[str, str, str]] = (),
        used: Mapping[str, float] | None = None,
        unschedulable: bool = False,
    ) -> None:
        """unschedulable: the upstream node.spec.unschedulable flag
        (kubectl cordon) — the node takes no new pods but its running
        pods keep counting everywhere."""
        alloc = dict(allocatable)
        alloc.setdefault(RESOURCE_PODS, 110.0)  # upstream kubelet default
        self._nodes.append(
            dict(name=name, allocatable=alloc, labels=dict(labels or {}),
                 taints=list(taints), used=dict(used or {}),
                 unschedulable=bool(unschedulable))
        )

    def add_pod(
        self,
        name: str,
        requests: Mapping[str, float],
        priority: float = 0.0,
        slo_target: float = DEFAULT_SLO_TARGET,
        observed_avail: float = DEFAULT_OBSERVED_AVAIL,
        labels: Mapping[str, str] | None = None,
        node_selector: Mapping[str, str] | None = None,
        required_terms: Sequence[NodeSelectorTerm] = (),
        preferred_terms: Sequence[PreferredTerm] = (),
        tolerations: Sequence[Toleration] = (),
        topology_spread: Sequence[TopologySpreadConstraint] = (),
        pod_affinity: Sequence[PodAffinityTerm] = (),
        pod_group: str | None = None,
        pod_group_min_member: int = 0,
        namespace: str = "default",
    ) -> None:
        req = dict(requests)
        req.setdefault(RESOURCE_PODS, 1.0)
        if pod_group is not None:
            prev = self._groups.get(pod_group, 0)
            self._groups[pod_group] = max(prev, int(pod_group_min_member))
        self._pods.append(
            dict(name=name, requests=req, priority=float(priority),
                 slo_target=float(slo_target), observed_avail=float(observed_avail),
                 labels=dict(labels or {}),
                 node_selector=dict(node_selector or {}),
                 required_terms=list(required_terms),
                 preferred_terms=list(preferred_terms),
                 tolerations=list(tolerations),
                 topology_spread=list(topology_spread),
                 pod_affinity=list(pod_affinity),
                 pod_group=pod_group,
                 namespace=str(namespace) or "default")
        )

    def add_running_pod(
        self,
        node: str,
        requests: Mapping[str, float],
        priority: float = 0.0,
        slack: float = 0.0,
        labels: Mapping[str, str] | None = None,
        count_into_used: bool = True,
        pod_affinity: Sequence[PodAffinityTerm] = (),
        namespace: str = "default",
        pdb_group: str | None = None,
        pdb_disruptions_allowed: int = 0,
    ) -> None:
        """pod_affinity: only required ANTI terms affect scheduling (the
        upstream symmetric anti-affinity rule); other terms are accepted
        and ignored. pdb_group names the PodDisruptionBudget covering
        this pod; pdb_disruptions_allowed is that budget's remaining
        allowed disruptions (the max across members wins, mirroring how
        a PDB is one object its members share). PDBs are NAMESPACED
        objects upstream, so the budget identity is (namespace, name) —
        same-named PDBs in different namespaces stay separate budgets."""
        req = dict(requests)
        req.setdefault(RESOURCE_PODS, 1.0)
        ns = str(namespace) or "default"
        if pdb_group is not None:
            key = (ns, pdb_group)
            prev = self._pdbs.get(key, 0)
            self._pdbs[key] = max(prev, int(pdb_disruptions_allowed))
        self._running.append(
            dict(node=node, requests=req, priority=float(priority),
                 slack=float(slack), labels=dict(labels or {}),
                 count_into_used=count_into_used,
                 pod_affinity=list(pod_affinity),
                 namespace=ns,
                 pdb_group=(ns, pdb_group) if pdb_group is not None else None)
        )

    # -- build --------------------------------------------------------------

    def build(self) -> tuple[ClusterSnapshot, SnapshotMeta]:
        snap, meta, _ = self.build_state()
        return snap, meta

    def build_state(self) -> "tuple[ClusterSnapshot, SnapshotMeta, BuiltState]":
        """build() plus the reusable host state (interner, numpy array
        holders, index maps) that DeviceSnapshot needs to keep applying
        O(churn) delta updates against the arrays this call produced."""
        cfg = self.config
        R = len(cfg.resources)
        n_nodes, n_pods, n_running = len(self._nodes), len(self._pods), len(self._running)

        intr = _Interner()

        # First pass: intern everything referenced by pods so vocab sizes
        # are known before arrays are allocated.
        pod_compiled = [intr.compile_pod(p) for p in self._pods]

        # Running pods' required anti-affinity terms (symmetric rule):
        # interned into the same signature table as pending terms.
        run_anti: list[list[int]] = []
        run_anti_atom_max = 0
        for rrec in self._running:
            sigs_of_pod, am = intr.compile_running_anti(rrec)
            run_anti_atom_max = max(run_anti_atom_max, am)
            run_anti.append(sigs_of_pod)

        # Intern node labels/taints.
        for nrec in self._nodes:
            intr.intern_labels(nrec["labels"])
            for (k, v, e) in nrec["taints"]:
                intr.tid(k, v, e)
        for rrec in self._running:
            intr.intern_labels(rrec["labels"])
            intr.nsid(rrec["namespace"])
        for p in self._pods:
            intr.intern_labels(p["labels"])
            intr.nsid(p["namespace"])
        atoms, sigs, topo_keys = intr.atoms, intr.sigs, intr.topo_keys
        taint_ids = intr.taint_ids

        # Buckets: start minimal (size-0 feature axes, whose kernels the
        # tracer drops entirely) and grow only to observed need, so
        # snapshots without taints/affinity/etc. don't pay those kernels.
        # CAVEAT: a feature appearing for the first time changes bucket
        # shapes and forces a full recompile; serving paths that must not
        # stall mid-cycle should pass explicit Buckets with floors for
        # every feature the cluster might use.
        bk = self.buckets
        if bk is None:
            bk = Buckets.minimal(n_pods, n_nodes, n_running)
        need = dict(
            node_labels=max((len(n["labels"]) for n in self._nodes), default=0),
            pod_labels=max(
                [len(p["labels"]) for p in self._pods]
                + [len(r["labels"]) for r in self._running] or [0]
            ),
            node_taints=max((len(n["taints"]) for n in self._nodes), default=0),
            atoms=len(atoms),
            atom_values=max((len(a[2]) for a in atoms), default=0),
            terms=max((len(pc["req_terms"]) for pc in pod_compiled), default=0),
            term_atoms=max(
                [run_anti_atom_max]
                + [len(t) for pc in pod_compiled for t in pc["req_terms"]]
                + [len(t[0]) for pc in pod_compiled for t in pc["pref_terms"]]
                + [len(c["atoms"]) for pc in pod_compiled for c in pc["ts"]]
                + [len(t["atoms"]) for pc in pod_compiled for t in pc["ia"]]
            ),
            pref_terms=max((len(pc["pref_terms"]) for pc in pod_compiled), default=0),
            topo_keys=len(topo_keys),
            spread_constraints=max((len(pc["ts"]) for pc in pod_compiled), default=0),
            affinity_terms=max(
                [len(pc["ia"]) for pc in pod_compiled]
                + [len(a) for a in run_anti] or [0]
            ),
            pod_groups=len(self._groups),
            taint_vocab=len(taint_ids),
            signatures=len(sigs),
            sig_namespaces=max(
                (len(ns) for _, ns, _ in sigs if ns != "*"), default=0
            ),
            pdb_groups=len(self._pdbs),
        )
        grow = {
            f: max(getattr(bk, f), _ceil_bucket(v))
            for f, v in need.items() if v > getattr(bk, f)
        }
        if grow:
            bk = dataclasses.replace(bk, **grow)
        if n_pods > bk.pods or n_nodes > bk.nodes or n_running > bk.running_pods:
            bk = dataclasses.replace(
                bk,
                pods=max(bk.pods, _ceil_bucket(n_pods)),
                nodes=max(bk.nodes, _ceil_bucket(n_nodes)),
                running_pods=max(bk.running_pods, _ceil_bucket(n_running)),
            )

        P, N, M = bk.pods, bk.nodes, bk.running_pods

        # Atom table.
        tables = _TableArraysNP(bk)
        for i, atom in enumerate(atoms):
            _fill_atom_row(tables, i, atom)

        # Node arrays.
        nodes_np = _NodeArraysNP(bk, R)
        node_index = {}
        for i, nrec in enumerate(self._nodes):
            node_index[nrec["name"]] = i
            _fill_node_row(nodes_np, i, nrec, intr, cfg)

        # Taint effect table.
        for (k, v, e), t in taint_ids.items():
            tables.taint_effect[t] = TAINT_EFFECTS.index(e)

        # Signature table.
        for s, sig in enumerate(sigs):
            _fill_sig_row(tables, s, sig)

        # Pod arrays.
        pods = _PodArraysNP(bk, R)
        group_list = sorted(self._groups)
        group_idx = {g: i for i, g in enumerate(group_list)}
        for i, (p, pc) in enumerate(zip(self._pods, pod_compiled)):
            _fill_pod_row(pods, i, p, pc, intr, cfg, group_idx)

        for g, name in enumerate(group_list):
            tables.group_min[g] = self._groups[name]

        # Running pods.
        run_np = _RunningArraysNP(bk, R)
        pdb_list = sorted(self._pdbs)
        pdb_idx = {g: i for i, g in enumerate(pdb_list)}
        for g, name in enumerate(pdb_list):
            tables.pdb_allowed[g] = float(self._pdbs[name])
        for i, rrec in enumerate(self._running):
            _fill_running_row(run_np, i, rrec, run_anti[i], intr, cfg,
                              node_index, pdb_idx)
            # Fold counted requests into the node's used row HERE, in
            # record order, so incremental re-encodes that re-sum a
            # node's members in the same order stay float-identical.
            if rrec["count_into_used"]:
                ni = node_index[rrec["node"]]
                for r, rn in enumerate(cfg.resources):
                    nodes_np.used[ni, r] += float(rrec["requests"].get(rn, 0.0))

        snap = _snapshot_from_arrays(nodes_np, pods, run_np, tables)
        meta = SnapshotMeta(
            node_names=[n["name"] for n in self._nodes],
            pod_names=[p["name"] for p in self._pods],
            n_nodes=n_nodes, n_pods=n_pods, n_running=n_running,
            buckets=bk, group_names=group_list,
        )
        state = BuiltState(
            interner=intr, nodes_np=nodes_np, pods_np=pods, run_np=run_np,
            tables=tables, buckets=bk, node_index=node_index,
            group_idx=group_idx, pdb_idx=pdb_idx,
        )
        return snap, meta, state


# ---------------------------------------------------------------------------
# Numpy array holders + single-row fills (shared by build and the
# incremental DeviceSnapshot path in device_state.py). Every fill RESETS
# the row to padding first, so re-encoding a churned row in place is
# exactly equivalent to building it fresh.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class BuiltState:
    """Host state of one build, reusable for incremental row updates."""

    interner: _Interner
    nodes_np: "_NodeArraysNP"
    pods_np: "_PodArraysNP"
    run_np: "_RunningArraysNP"
    tables: "_TableArraysNP"
    buckets: Buckets
    node_index: dict
    group_idx: dict
    pdb_idx: dict


class _NodeArraysNP:
    """Scratch numpy buffers for NodeArrays during build."""

    def __init__(self, bk: Buckets, R: int):
        N = bk.nodes
        self.allocatable = np.zeros((N, R), np.float32)
        self.used = np.zeros((N, R), np.float32)
        self.label_pairs = np.full((N, bk.node_labels), -1, np.int32)
        self.label_keys = np.full((N, bk.node_labels), -1, np.int32)
        self.label_nums = np.full((N, bk.node_labels), np.nan, np.float32)
        self.taint_ids = np.full((N, bk.node_taints), -1, np.int32)
        self.domain = np.full((N, bk.topo_keys), -1, np.int32)
        self.schedulable = np.zeros(N, bool)
        self.valid = np.zeros(N, bool)


class _RunningArraysNP:
    """Scratch numpy buffers for RunningPodArrays during build."""

    def __init__(self, bk: Buckets, R: int):
        M = bk.running_pods
        self.node_idx = np.full(M, -1, np.int32)
        self.requests = np.zeros((M, R), np.float32)
        self.priority = np.zeros(M, np.float32)
        self.slack = np.zeros(M, np.float32)
        self.label_pairs = np.full((M, bk.pod_labels), -1, np.int32)
        self.label_keys = np.full((M, bk.pod_labels), -1, np.int32)
        self.anti_sig = np.full((M, bk.affinity_terms), -1, np.int32)
        self.namespace = np.full(M, -1, np.int32)
        self.pdb_group = np.full(M, -1, np.int32)
        self.valid = np.zeros(M, bool)


class _TableArraysNP:
    """Atom/sig/taint/group/PDB table buffers during build."""

    def __init__(self, bk: Buckets):
        self.atom_key = np.full(bk.atoms, -1, np.int32)
        self.atom_op = np.zeros(bk.atoms, np.int8)
        self.atom_pairs = np.full((bk.atoms, bk.atom_values), -1, np.int32)
        self.atom_num = np.full(bk.atoms, np.nan, np.float32)
        self.atom_valid = np.zeros(bk.atoms, bool)
        self.sig_key = np.full(bk.signatures, -1, np.int32)
        self.sig_atoms = np.full((bk.signatures, bk.term_atoms), -1, np.int32)
        self.sig_ns = np.full((bk.signatures, bk.sig_namespaces), -1, np.int32)
        self.sig_ns_all = np.zeros(bk.signatures, bool)
        self.sig_valid = np.zeros(bk.signatures, bool)
        self.taint_effect = np.zeros(bk.taint_vocab, np.int8)
        self.group_min = np.zeros(bk.pod_groups, np.int32)
        self.pdb_allowed = np.zeros(bk.pdb_groups, np.float32)


def _fill_atom_row(tables: _TableArraysNP, i: int, atom) -> None:
    k, op, pids, num = atom
    tables.atom_key[i] = k
    tables.atom_op[i] = op
    tables.atom_pairs[i] = -1
    tables.atom_pairs[i, : len(pids)] = pids
    tables.atom_num[i] = num
    tables.atom_valid[i] = True


def _fill_sig_row(tables: _TableArraysNP, s: int, sig) -> None:
    k, ns_scope, alist = sig
    tables.sig_key[s] = k
    tables.sig_atoms[s] = -1
    tables.sig_atoms[s, : len(alist)] = alist
    tables.sig_ns[s] = -1
    if ns_scope == "*":
        tables.sig_ns_all[s] = True
    else:
        tables.sig_ns_all[s] = False
        tables.sig_ns[s, : len(ns_scope)] = ns_scope
    tables.sig_valid[s] = True


def _fill_node_row(nodes_np: _NodeArraysNP, i: int, nrec: dict,
                   intr: _Interner, cfg: EngineConfig) -> None:
    """Encode one node record into row i. `used` is the record's OWN
    usage only — counted running-pod requests are folded in by the
    caller (build_state / DeviceSnapshot), which owns summation order."""
    nodes_np.valid[i] = True
    nodes_np.schedulable[i] = not nrec["unschedulable"]
    for r, rn in enumerate(cfg.resources):
        nodes_np.allocatable[i, r] = float(nrec["allocatable"].get(rn, 0.0))
        nodes_np.used[i, r] = float(nrec["used"].get(rn, 0.0))
    nodes_np.label_pairs[i] = -1
    nodes_np.label_keys[i] = -1
    nodes_np.label_nums[i] = np.nan
    for j, (k, v) in enumerate(sorted(nrec["labels"].items())):
        nodes_np.label_keys[i, j] = intr.key_ids[k]
        nodes_np.label_pairs[i, j] = intr.pair_ids[(k, v)]
        nodes_np.label_nums[i, j] = _try_float(v)
    nodes_np.taint_ids[i] = -1
    for j, (k, v, e) in enumerate(nrec["taints"]):
        nodes_np.taint_ids[i, j] = intr.taint_ids[(k, v, e)]
    nodes_np.domain[i] = -1
    for ti, tk in enumerate(intr.topo_keys):
        if tk in nrec["labels"]:
            v = nrec["labels"][tk]
            nodes_np.domain[i, ti] = intr.domain_ids[ti].setdefault(
                v, len(intr.domain_ids[ti])
            )


def _fill_pod_row(pods: "_PodArraysNP", i: int, p: dict, pc: dict,
                  intr: _Interner, cfg: EngineConfig, group_idx: dict) -> None:
    pods.valid[i] = True
    for r, rn in enumerate(cfg.resources):
        pods.requests[i, r] = float(p["requests"].get(rn, 0.0))
    pods.base_priority[i] = p["priority"]
    pods.slo_target[i] = p["slo_target"]
    pods.observed_avail[i] = p["observed_avail"]
    pods.label_pairs[i] = -1
    pods.label_keys[i] = -1
    for j, (k, v) in enumerate(sorted(p["labels"].items())):
        pods.label_keys[i, j] = intr.key_ids[k]
        pods.label_pairs[i, j] = intr.pair_ids[(k, v)]
    # Tolerations precompiled against the taint vocab.
    pods.tolerated[i] = False
    for (tk, tv, te), t in intr.taint_ids.items():
        pods.tolerated[i, t] = any(
            _tolerates(tol, tk, tv, te) for tol in p["tolerations"]
        )
    pods.req_term_valid[i] = False
    pods.req_term_atoms[i] = -1
    for t, term in enumerate(pc["req_terms"]):
        pods.req_term_valid[i, t] = True
        pods.req_term_atoms[i, t, : len(term)] = term
    pods.pref_term_valid[i] = False
    pods.pref_term_atoms[i] = -1
    pods.pref_weight[i] = 0.0
    for t, (term, w) in enumerate(pc["pref_terms"]):
        pods.pref_term_valid[i, t] = True
        pods.pref_term_atoms[i, t, : len(term)] = term
        pods.pref_weight[i, t] = w
    pods.ts_valid[i] = False
    pods.ts_key[i] = -1
    pods.ts_max_skew[i] = 0.0
    pods.ts_when[i] = 0
    pods.ts_sel_atoms[i] = -1
    pods.ts_sig[i] = -1
    for c, con in enumerate(pc["ts"]):
        pods.ts_valid[i, c] = True
        pods.ts_key[i, c] = con["key"]
        pods.ts_max_skew[i, c] = con["max_skew"]
        pods.ts_when[i, c] = con["when"]
        pods.ts_sel_atoms[i, c, : len(con["atoms"])] = con["atoms"]
        pods.ts_sig[i, c] = con["sig"]
    pods.ia_valid[i] = False
    pods.ia_key[i] = -1
    pods.ia_sel_atoms[i] = -1
    pods.ia_sig[i] = -1
    pods.ia_anti[i] = False
    pods.ia_required[i] = False
    pods.ia_weight[i] = 0.0
    for t, term in enumerate(pc["ia"]):
        pods.ia_valid[i, t] = True
        pods.ia_key[i, t] = term["key"]
        pods.ia_sel_atoms[i, t, : len(term["atoms"])] = term["atoms"]
        pods.ia_sig[i, t] = term["sig"]
        pods.ia_anti[i, t] = term["anti"]
        pods.ia_required[i, t] = term["required"]
        pods.ia_weight[i, t] = term["weight"]
    pods.group[i] = (
        group_idx[p["pod_group"]] if p["pod_group"] is not None else -1
    )
    pods.namespace[i] = intr.ns_ids[p["namespace"]]
    pods.tolerates_unsched[i] = any(
        _tolerates(tol, "node.kubernetes.io/unschedulable", "", "NoSchedule")
        for tol in p["tolerations"]
    )


def _fill_running_row(run_np: _RunningArraysNP, i: int, rrec: dict,
                      anti_sigs: list, intr: _Interner, cfg: EngineConfig,
                      node_index: dict, pdb_idx: dict) -> None:
    ni = node_index[rrec["node"]]
    run_np.node_idx[i] = ni
    run_np.valid[i] = True
    for r, rn in enumerate(cfg.resources):
        run_np.requests[i, r] = float(rrec["requests"].get(rn, 0.0))
    run_np.priority[i] = rrec["priority"]
    run_np.slack[i] = rrec["slack"]
    run_np.label_pairs[i] = -1
    run_np.label_keys[i] = -1
    for j, (k, v) in enumerate(sorted(rrec["labels"].items())):
        run_np.label_keys[i, j] = intr.key_ids[k]
        run_np.label_pairs[i, j] = intr.pair_ids[(k, v)]
    run_np.anti_sig[i] = -1
    for j, s in enumerate(anti_sigs):
        run_np.anti_sig[i, j] = s
    run_np.namespace[i] = intr.ns_ids[rrec["namespace"]]
    run_np.pdb_group[i] = (
        pdb_idx[rrec["pdb_group"]] if rrec["pdb_group"] is not None else -1
    )


def _pad_node_row(nodes_np: _NodeArraysNP, i: int) -> None:
    """Reset row i to the padding encoding (invalid, masked)."""
    nodes_np.allocatable[i] = 0.0
    nodes_np.used[i] = 0.0
    nodes_np.label_pairs[i] = -1
    nodes_np.label_keys[i] = -1
    nodes_np.label_nums[i] = np.nan
    nodes_np.taint_ids[i] = -1
    nodes_np.domain[i] = -1
    nodes_np.schedulable[i] = False
    nodes_np.valid[i] = False


def _pad_pod_row(pods: "_PodArraysNP", i: int) -> None:
    pods.requests[i] = 0.0
    pods.base_priority[i] = 0.0
    pods.slo_target[i] = 0.0
    pods.observed_avail[i] = 1.0
    pods.tolerated[i] = False
    pods.label_pairs[i] = -1
    pods.label_keys[i] = -1
    pods.req_term_atoms[i] = -1
    pods.req_term_valid[i] = False
    pods.pref_term_atoms[i] = -1
    pods.pref_term_valid[i] = False
    pods.pref_weight[i] = 0.0
    pods.ts_key[i] = -1
    pods.ts_max_skew[i] = 0.0
    pods.ts_when[i] = 0
    pods.ts_sel_atoms[i] = -1
    pods.ts_sig[i] = -1
    pods.ts_valid[i] = False
    pods.ia_key[i] = -1
    pods.ia_sel_atoms[i] = -1
    pods.ia_sig[i] = -1
    pods.ia_anti[i] = False
    pods.ia_required[i] = False
    pods.ia_weight[i] = 0.0
    pods.ia_valid[i] = False
    pods.group[i] = -1
    pods.namespace[i] = -1
    pods.tolerates_unsched[i] = False
    pods.valid[i] = False


def _pad_running_row(run_np: _RunningArraysNP, i: int) -> None:
    run_np.node_idx[i] = -1
    run_np.requests[i] = 0.0
    run_np.priority[i] = 0.0
    run_np.slack[i] = 0.0
    run_np.label_pairs[i] = -1
    run_np.label_keys[i] = -1
    run_np.anti_sig[i] = -1
    run_np.namespace[i] = -1
    run_np.pdb_group[i] = -1
    run_np.valid[i] = False


def _snapshot_from_arrays(
    nodes_np: _NodeArraysNP, pods: "_PodArraysNP",
    run_np: _RunningArraysNP, tables: _TableArraysNP,
) -> ClusterSnapshot:
    """Assemble the device pytree from the host array holders. The
    arrays are SHARED by reference, not copied: device transfer (put /
    jit call) copies host->device, after which the holders stay the
    mutable host mirror."""
    return ClusterSnapshot(
        nodes=NodeArrays(
            allocatable=nodes_np.allocatable, used=nodes_np.used,
            label_pairs=nodes_np.label_pairs, label_keys=nodes_np.label_keys,
            label_nums=nodes_np.label_nums, taint_ids=nodes_np.taint_ids,
            domain=nodes_np.domain, schedulable=nodes_np.schedulable,
            valid=nodes_np.valid,
        ),
        pods=PodArrays(
            requests=pods.requests, base_priority=pods.base_priority,
            slo_target=pods.slo_target, observed_avail=pods.observed_avail,
            tolerated=pods.tolerated, label_pairs=pods.label_pairs,
            label_keys=pods.label_keys, req_term_atoms=pods.req_term_atoms,
            req_term_valid=pods.req_term_valid,
            pref_term_atoms=pods.pref_term_atoms,
            pref_term_valid=pods.pref_term_valid, pref_weight=pods.pref_weight,
            ts_key=pods.ts_key, ts_max_skew=pods.ts_max_skew,
            ts_when=pods.ts_when, ts_sel_atoms=pods.ts_sel_atoms,
            ts_sig=pods.ts_sig, ts_valid=pods.ts_valid,
            ia_key=pods.ia_key, ia_sel_atoms=pods.ia_sel_atoms,
            ia_sig=pods.ia_sig, ia_anti=pods.ia_anti,
            ia_required=pods.ia_required, ia_weight=pods.ia_weight,
            ia_valid=pods.ia_valid, group=pods.group,
            namespace=pods.namespace,
            tolerates_unsched=pods.tolerates_unsched, valid=pods.valid,
        ),
        running=RunningPodArrays(
            node_idx=run_np.node_idx, requests=run_np.requests,
            priority=run_np.priority, slack=run_np.slack,
            label_pairs=run_np.label_pairs, label_keys=run_np.label_keys,
            anti_sig=run_np.anti_sig, namespace=run_np.namespace,
            pdb_group=run_np.pdb_group, valid=run_np.valid,
        ),
        atoms=AtomTable(key=tables.atom_key, op=tables.atom_op,
                        pairs=tables.atom_pairs, num=tables.atom_num,
                        valid=tables.atom_valid),
        sigs=SigTable(key=tables.sig_key, atoms=tables.sig_atoms,
                      ns=tables.sig_ns, ns_all=tables.sig_ns_all,
                      valid=tables.sig_valid),
        taint_effect=tables.taint_effect,
        group_min_member=tables.group_min,
        pdb_allowed=tables.pdb_allowed,
    )


class _PodArraysNP:
    """Scratch numpy buffers for PodArrays during build."""

    def __init__(self, bk: Buckets, R: int):
        P = bk.pods
        self.requests = np.zeros((P, R), np.float32)
        self.base_priority = np.zeros(P, np.float32)
        self.slo_target = np.zeros(P, np.float32)
        self.observed_avail = np.ones(P, np.float32)
        self.tolerated = np.zeros((P, bk.taint_vocab), bool)
        self.label_pairs = np.full((P, bk.pod_labels), -1, np.int32)
        self.label_keys = np.full((P, bk.pod_labels), -1, np.int32)
        self.req_term_atoms = np.full((P, bk.terms, bk.term_atoms), -1, np.int32)
        self.req_term_valid = np.zeros((P, bk.terms), bool)
        self.pref_term_atoms = np.full((P, bk.pref_terms, bk.term_atoms), -1, np.int32)
        self.pref_term_valid = np.zeros((P, bk.pref_terms), bool)
        self.pref_weight = np.zeros((P, bk.pref_terms), np.float32)
        self.ts_key = np.full((P, bk.spread_constraints), -1, np.int32)
        self.ts_max_skew = np.zeros((P, bk.spread_constraints), np.float32)
        self.ts_when = np.zeros((P, bk.spread_constraints), np.int8)
        self.ts_sel_atoms = np.full(
            (P, bk.spread_constraints, bk.term_atoms), -1, np.int32
        )
        self.ts_sig = np.full((P, bk.spread_constraints), -1, np.int32)
        self.ts_valid = np.zeros((P, bk.spread_constraints), bool)
        self.ia_key = np.full((P, bk.affinity_terms), -1, np.int32)
        self.ia_sel_atoms = np.full((P, bk.affinity_terms, bk.term_atoms), -1, np.int32)
        self.ia_sig = np.full((P, bk.affinity_terms), -1, np.int32)
        self.ia_anti = np.zeros((P, bk.affinity_terms), bool)
        self.ia_required = np.zeros((P, bk.affinity_terms), bool)
        self.ia_weight = np.zeros((P, bk.affinity_terms), np.float32)
        self.ia_valid = np.zeros((P, bk.affinity_terms), bool)
        self.group = np.full(P, -1, np.int32)
        self.namespace = np.full(P, -1, np.int32)
        self.tolerates_unsched = np.zeros(P, bool)
        self.valid = np.zeros(P, bool)


def _ceil_bucket(x: int) -> int:
    return _next_bucket(max(x, 1))


def _tolerates(tol: Toleration, tk: str, tv: str, te: str) -> bool:
    """Upstream toleration matching (SURVEY.md C2 TaintToleration):
    empty key + Exists tolerates everything; key must match otherwise;
    Exists ignores value, Equal compares it; empty effect matches all."""
    if tol.operator not in ("Exists", "Equal"):
        raise ValueError(f"bad toleration operator {tol.operator!r}")
    if tol.key == "":
        if tol.operator != "Exists":
            return False
        key_ok = True
    else:
        key_ok = tol.key == tk
    if not key_ok:
        return False
    if tol.operator == "Equal" and tol.value != tv:
        return False
    if tol.effect and tol.effect != te:
        return False
    return True
