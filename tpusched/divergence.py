"""Fast-mode divergence measurement (VERDICT weak #7; SURVEY.md §7 hard
part 1).

The north star demands "placement parity with stock kube-scheduler".
Parity mode delivers it exactly (sequential scan == oracle, fuzz-tested
in tests/test_parity.py). Fast mode trades exact ordering under
contention for bounded rounds; its guarantees are:

  * validity — capacity, static predicates, DoNotSchedule spread,
    required (anti-)affinity all hold against commit-time state
    (audited by oracle.validate_assignment);
  * near-equal throughput — the same NUMBER of pods places to within a
    few percent, but not the same SET: measured on 6 seeds/preset
    (round 5, after the small-cluster fallback-depth fix), the `mixed`
    preset nets about -2% placements for fast mode; run this module
    for the current numbers rather than trusting prose;
  * exact node agreement whenever pods' decisions don't interact — note
    that load-balancing scores couple every pod to all earlier commits,
    so on busy clusters node choices differ by design while remaining
    equally valid and equally balanced. Measured: even the `plain`
    preset (no constraints at all) is only ~11% node-identical, because
    per-node agreement collapses once any commit order diverges.

This module puts NUMBERS on the divergence: run both modes over seeded
snapshots and report how often placements differ and by how much.

CLI:  python -m tpusched.divergence [--preset mixed] [--seeds 10]
"""

from __future__ import annotations

import argparse
import dataclasses
import json

import numpy as np

from tpusched.config import EngineConfig
from tpusched.engine import Engine
from tpusched.oracle import validate_assignment
from tpusched.synth import make_cluster

# Contention presets: fractions chosen so the interesting regimes are
# all covered — no constraints (same placement COUNT; node choices still
# diverge via load-balance coupling), capacity pressure only,
# pairwise-heavy, and everything at once.
PRESETS: dict[str, dict] = {
    "plain": dict(),
    "tight": dict(initial_utilization=0.7, n_running_per_node=4),
    "pairwise": dict(spread_frac=0.6, interpod_frac=0.5, run_anti_frac=0.2),
    "mixed": dict(
        initial_utilization=0.5, n_running_per_node=3, taint_frac=0.2,
        toleration_frac=0.3, selector_frac=0.2, affinity_frac=0.3,
        spread_frac=0.4, interpod_frac=0.4, run_anti_frac=0.15,
        namespace_count=2, cordon_frac=0.15,
    ),
}


@dataclasses.dataclass
class DivergenceStats:
    preset: str
    seeds: int
    pods: int = 0                 # total pods compared
    same_node: int = 0            # identical placement (incl. both -1)
    both_placed_diff_node: int = 0
    fast_only_placed: int = 0
    parity_only_placed: int = 0
    fast_placed: int = 0
    parity_placed: int = 0
    fast_violations: int = 0      # MUST stay 0
    # Worst single-seed fast/parity placed ratio (advisor round 2: track
    # the per-seed worst case as a number so erosion of the fast-mode
    # throughput floor shows up in BENCH output, not just in a loosened
    # test threshold).
    min_placed_ratio: float = 1.0

    @property
    def identical_rate(self) -> float:
        return self.same_node / max(self.pods, 1)

    @property
    def placed_delta(self) -> int:
        """Fast minus parity total placements (0 = same throughput)."""
        return self.fast_placed - self.parity_placed

    def row(self) -> dict:
        return dict(
            preset=self.preset, seeds=self.seeds, pods=self.pods,
            identical_rate=round(self.identical_rate, 4),
            both_placed_diff_node=self.both_placed_diff_node,
            fast_only_placed=self.fast_only_placed,
            parity_only_placed=self.parity_only_placed,
            placed_delta=self.placed_delta,
            fast_violations=self.fast_violations,
            min_placed_ratio=round(self.min_placed_ratio, 4),
        )


def measure(
    preset: str = "mixed",
    seeds: int = 10,
    n_pods: int = 80,
    n_nodes: int = 16,
    base_seed: int = 3000,
    engines: "tuple[Engine, Engine] | None" = None,
) -> DivergenceStats:
    """Run fast and parity over `seeds` random snapshots of a preset and
    accumulate agreement statistics. Every fast assignment is also run
    through the independent validity audit. `engines` = (fast, parity)
    to reuse jit caches across presets (bench.py does)."""
    kw = PRESETS[preset]
    if engines is not None:
        fast, parity = engines
    else:
        fast = Engine(EngineConfig(mode="fast"))
        parity = Engine(EngineConfig(mode="parity"))
    out = DivergenceStats(preset=preset, seeds=seeds)
    for s in range(seeds):
        rng = np.random.default_rng(base_seed + s)
        snap, meta = make_cluster(rng, n_pods, n_nodes, **kw)
        fres = fast.solve(snap)
        pres = parity.solve(snap)
        P = meta.n_pods
        fa = fres.assignment[:P]
        pa = pres.assignment[:P]
        out.pods += P
        out.same_node += int((fa == pa).sum())
        out.both_placed_diff_node += int(((fa >= 0) & (pa >= 0) & (fa != pa)).sum())
        out.fast_only_placed += int(((fa >= 0) & (pa < 0)).sum())
        out.parity_only_placed += int(((fa < 0) & (pa >= 0)).sum())
        seed_fast = int((fa >= 0).sum())
        seed_parity = int((pa >= 0).sum())
        out.fast_placed += seed_fast
        out.parity_placed += seed_parity
        if seed_parity > 0:
            out.min_placed_ratio = min(
                out.min_placed_ratio, seed_fast / seed_parity
            )
        violations = validate_assignment(
            snap, fast.config, fres.assignment,
            commit_key=fres.commit_key, evicted=fres.evicted,
        )
        out.fast_violations += len(violations)
    return out


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", choices=sorted(PRESETS), default=None,
                    help="default: all presets")
    ap.add_argument("--seeds", type=int, default=10)
    ap.add_argument("--pods", type=int, default=80)
    ap.add_argument("--nodes", type=int, default=16)
    args = ap.parse_args(argv)
    presets = [args.preset] if args.preset else sorted(PRESETS)
    for p in presets:
        stats = measure(p, args.seeds, args.pods, args.nodes)
        print(json.dumps(stats.row()), flush=True)


if __name__ == "__main__":
    main()
