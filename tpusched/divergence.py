"""Fast-mode divergence measurement (VERDICT weak #7; SURVEY.md §7 hard
part 1) and the warm-start twin audit (ROADMAP item 3).

The north star demands "placement parity with stock kube-scheduler".
Parity mode delivers it exactly (sequential scan == oracle, fuzz-tested
in tests/test_parity.py). Fast mode trades exact ordering under
contention for bounded rounds; its guarantees are:

  * validity — capacity, static predicates, DoNotSchedule spread,
    required (anti-)affinity all hold against commit-time state
    (audited by oracle.validate_assignment);
  * near-equal throughput — the same NUMBER of pods places to within a
    few percent, but not the same SET: measured on 6 seeds/preset
    (round 5, after the small-cluster fallback-depth fix), the `mixed`
    preset nets about -2% placements for fast mode; run this module
    for the current numbers rather than trusting prose;
  * exact node agreement whenever pods' decisions don't interact — note
    that load-balancing scores couple every pod to all earlier commits,
    so on busy clusters node choices differ by design while remaining
    equally valid and equally balanced. Measured: even the `plain`
    preset (no constraints at all) is only ~11% node-identical, because
    per-node agreement collapses once any commit order diverges.

This module puts NUMBERS on the divergence: run both modes over seeded
snapshots and report how often placements differ and by how much.

CLI:  python -m tpusched.divergence [--preset mixed] [--seeds 10]
      python -m tpusched.divergence --warm-audit 50 [--churn 0.05]
      python -m tpusched.divergence --warm-audit 50 --incremental

--warm-audit N runs N delta cycles TWIN — every cycle solved once warm
(carried tableau, dirty rows only) and once cold (full recompute) on the
same device-resident lineage — and reports the first diverging cycle
with the offending pod rows, plus placement-quality drift (placed-count
and chosen-score deltas vs the cold twin). The bitwise warm contract is
byte equality, so this is the debugging tool for when the twin-parity
tests trip: exit code 1 on any divergence. With --incremental the warm
arm is the BOUNDED-DIVERGENCE path (ISSUE 12): placements may legally
drift, so the audit enforces the validity contract instead — the
in-kernel audit and oracle.validate_assignment must both be clean every
cycle — and exit 1 means a validity violation, not mere divergence.
"""

from __future__ import annotations

import argparse
import dataclasses
import json

import numpy as np

from tpusched.config import EngineConfig
from tpusched.device_state import DeviceSnapshot
from tpusched.engine import Engine
from tpusched.oracle import validate_assignment
from tpusched.synth import make_cluster

# Contention presets: fractions chosen so the interesting regimes are
# all covered — no constraints (same placement COUNT; node choices still
# diverge via load-balance coupling), capacity pressure only,
# pairwise-heavy, and everything at once.
PRESETS: dict[str, dict] = {
    "plain": dict(),
    "tight": dict(initial_utilization=0.7, n_running_per_node=4),
    "pairwise": dict(spread_frac=0.6, interpod_frac=0.5, run_anti_frac=0.2),
    "mixed": dict(
        initial_utilization=0.5, n_running_per_node=3, taint_frac=0.2,
        toleration_frac=0.3, selector_frac=0.2, affinity_frac=0.3,
        spread_frac=0.4, interpod_frac=0.4, run_anti_frac=0.15,
        namespace_count=2, cordon_frac=0.15,
    ),
}


@dataclasses.dataclass
class DivergenceStats:
    preset: str
    seeds: int
    pods: int = 0                 # total pods compared
    same_node: int = 0            # identical placement (incl. both -1)
    both_placed_diff_node: int = 0
    fast_only_placed: int = 0
    parity_only_placed: int = 0
    fast_placed: int = 0
    parity_placed: int = 0
    fast_violations: int = 0      # MUST stay 0
    # Worst single-seed fast/parity placed ratio (advisor round 2: track
    # the per-seed worst case as a number so erosion of the fast-mode
    # throughput floor shows up in BENCH output, not just in a loosened
    # test threshold).
    min_placed_ratio: float = 1.0

    @property
    def identical_rate(self) -> float:
        return self.same_node / max(self.pods, 1)

    @property
    def placed_delta(self) -> int:
        """Fast minus parity total placements (0 = same throughput)."""
        return self.fast_placed - self.parity_placed

    def row(self) -> dict:
        return dict(
            preset=self.preset, seeds=self.seeds, pods=self.pods,
            identical_rate=round(self.identical_rate, 4),
            both_placed_diff_node=self.both_placed_diff_node,
            fast_only_placed=self.fast_only_placed,
            parity_only_placed=self.parity_only_placed,
            placed_delta=self.placed_delta,
            fast_violations=self.fast_violations,
            min_placed_ratio=round(self.min_placed_ratio, 4),
        )


def measure(
    preset: str = "mixed",
    seeds: int = 10,
    n_pods: int = 80,
    n_nodes: int = 16,
    base_seed: int = 3000,
    engines: "tuple[Engine, Engine] | None" = None,
) -> DivergenceStats:
    """Run fast and parity over `seeds` random snapshots of a preset and
    accumulate agreement statistics. Every fast assignment is also run
    through the independent validity audit. `engines` = (fast, parity)
    to reuse jit caches across presets (bench.py does)."""
    kw = PRESETS[preset]
    if engines is not None:
        fast, parity = engines
    else:
        fast = Engine(EngineConfig(mode="fast"))
        parity = Engine(EngineConfig(mode="parity"))
    out = DivergenceStats(preset=preset, seeds=seeds)
    for s in range(seeds):
        rng = np.random.default_rng(base_seed + s)
        snap, meta = make_cluster(rng, n_pods, n_nodes, **kw)
        fres = fast.solve(snap)
        pres = parity.solve(snap)
        P = meta.n_pods
        fa = fres.assignment[:P]
        pa = pres.assignment[:P]
        out.pods += P
        out.same_node += int((fa == pa).sum())
        out.both_placed_diff_node += int(((fa >= 0) & (pa >= 0) & (fa != pa)).sum())
        out.fast_only_placed += int(((fa >= 0) & (pa < 0)).sum())
        out.parity_only_placed += int(((fa < 0) & (pa >= 0)).sum())
        seed_fast = int((fa >= 0).sum())
        seed_parity = int((pa >= 0).sum())
        out.fast_placed += seed_fast
        out.parity_placed += seed_parity
        if seed_parity > 0:
            out.min_placed_ratio = min(
                out.min_placed_ratio, seed_fast / seed_parity
            )
        violations = validate_assignment(
            snap, fast.config, fres.assignment,
            commit_key=fres.commit_key, evicted=fres.evicted,
        )
        out.fast_violations += len(violations)
    return out


def warm_churn_stream(rng, nodes, pods, running, cycles: int,
                      churn_frac: float = 0.05,
                      structural_every: int = 5):
    """Seeded delta-cycle generator for the warm audit (and bench churn
    sweeps): mutates the record lists IN PLACE and yields
    DeviceSnapshot.apply kwargs. Each cycle value-churns ~churn_frac of
    the pending pods (observed availability / priority — the QoS
    temporal-locality signal the warm path bets on) plus one node
    (allocatable drift); every `structural_every`-th cycle additionally
    exercises the structural paths: a pod add + remove (row reorder), a
    running-pod removal (a completion), and a cordon toggle (the
    all-residents column invalidation)."""
    seq = 0
    for cyc in range(cycles):
        n_churn = max(1, int(round(churn_frac * len(pods))))
        picks = rng.choice(len(pods), size=min(n_churn, len(pods)),
                           replace=False)
        up_pods = []
        for i in picks:
            rec = pods[int(i)]
            rec["observed_avail"] = float(rng.uniform(0.3, 1.0))
            if rng.random() < 0.3:
                rec["priority"] = float(rng.integers(0, 1000))
            up_pods.append(rec)
        ni = int(rng.integers(len(nodes)))
        nrec = nodes[ni]
        alloc = dict(nrec.get("allocatable", {}))
        if "cpu" in alloc:
            alloc["cpu"] = float(max(1000.0, alloc["cpu"]
                                     * float(rng.uniform(0.9, 1.1))))
        nrec["allocatable"] = alloc
        delta = dict(upsert_pods=up_pods, upsert_nodes=[nrec])
        if structural_every and cyc % structural_every == structural_every - 1:
            seq += 1
            newp = dict(
                name=f"warm-audit-{seq:04d}",
                requests={"cpu": float(rng.integers(100, 800))},
                priority=float(rng.integers(0, 1000)),
                observed_avail=float(rng.uniform(0.5, 1.0)),
                labels={"app": "web"},
            )
            pods.append(newp)
            gone = pods.pop(int(rng.integers(len(pods) - 1)))
            delta["upsert_pods"] = [
                r for r in delta["upsert_pods"] if r["name"] != gone["name"]
            ] + [newp]
            delta["remove_pods"] = [gone["name"]]
            if running:
                done = running.pop(int(rng.integers(len(running))))
                delta["remove_running"] = [done["name"]]
            cn = int(rng.integers(len(nodes)))
            crec = nodes[cn]
            crec["unschedulable"] = not crec.get("unschedulable", False)
            if crec["name"] != nrec["name"]:
                delta["upsert_nodes"] = delta["upsert_nodes"] + [crec]
        yield delta


def warm_audit(
    cycles: int = 50,
    preset: str = "mixed",
    n_pods: int = 80,
    n_nodes: int = 16,
    seed: int = 4000,
    churn_frac: float = 0.05,
    mode: str = "fast",
    preemption: bool = False,
    engine: "Engine | None" = None,
    incremental: bool = False,
) -> dict:
    """Twin-run N delta cycles warm vs cold on ONE device-resident
    lineage (the --warm-audit debugging tool). Every cycle: apply a
    seeded churn delta, solve once through the engine warm path
    (Engine.solve_warm: carried tableau + dirty rows), once cold
    (Engine.solve: full recompute of the same arrays).

    Bitwise mode (default): byte-compare assignment / chosen_score /
    evicted and report the first divergence — diverged_cycle (-1 =
    clean) + bad_pods [(row, name, warm_node, cold_node)].

    incremental=True (ISSUE 12): the warm solve is the BOUNDED-
    DIVERGENCE path (solve_warm(incremental=True)); placements may
    legally differ from the cold twin, so the audit instead enforces
    the VALIDITY contract — the in-kernel audit (SolveResult.inc_info)
    must be clean AND oracle.validate_assignment must find nothing —
    and diverged_cycle marks the first validity failure.

    Both modes now also report PLACEMENT-QUALITY drift vs the cold
    twin (trivially zero in a clean bitwise run): placed-count totals
    and per-cycle worst delta, plus the mean |chosen_score| drift over
    pods both twins placed (carried placements keep their
    as-of-placement score, so nonzero drift here is expected churn
    aging, not a bug)."""
    cfg = EngineConfig(mode=mode, preemption=preemption)
    rng = np.random.default_rng(seed)
    nodes, pods, running = make_cluster(
        rng, n_pods, n_nodes, as_records=True, **PRESETS[preset]
    )
    nodes, pods, running = list(nodes), list(pods), list(running)
    ds = DeviceSnapshot(cfg)
    ds.full_load(nodes, pods, running)
    eng = engine if engine is not None else Engine(cfg)
    report = dict(cycles=0, diverged_cycle=-1, bad_pods=[],
                  preset=preset, churn_frac=churn_frac, mode=mode,
                  incremental=incremental, validity_violations=0,
                  placed_warm_total=0, placed_cold_total=0,
                  worst_cycle_placed_delta=0)
    drift = []
    try:
        if incremental:
            # Establish the lineage's carry (the seed the bounded-
            # divergence path starts from) before the audited cycles.
            eng.solve_warm(ds)
        for cyc, delta in enumerate(warm_churn_stream(
                rng, nodes, pods, running, cycles, churn_frac)):
            ds.apply(**delta)
            warm = eng.solve_warm(ds, incremental=incremental)
            cold = eng.solve(ds.snap)
            report["cycles"] = cyc + 1
            pw = int((warm.assignment >= 0).sum())
            pc = int((cold.assignment >= 0).sum())
            report["placed_warm_total"] += pw
            report["placed_cold_total"] += pc
            if abs(pw - pc) > abs(report["worst_cycle_placed_delta"]):
                report["worst_cycle_placed_delta"] = pw - pc
            both = (warm.assignment >= 0) & (cold.assignment >= 0)
            if both.any():
                wsc = np.asarray(warm.chosen_score)[both]
                csc = np.asarray(cold.chosen_score)[both]
                drift.append(float(np.mean(np.abs(wsc - csc))))
            if incremental:
                viol = list(validate_assignment(
                    ds.snap, cfg, warm.assignment,
                    commit_key=warm.commit_key, evicted=warm.evicted,
                ))
                inc_bad = (warm.inc_info or {}).get("audit_violations", 0)
                if viol or inc_bad:
                    report["validity_violations"] += len(viol) + inc_bad
                    if report["diverged_cycle"] < 0:
                        report["diverged_cycle"] = cyc
                        report["bad_pods"] = [
                            (-1, f"<validity: {v}>", -1, -1)
                            for v in viol[:16]
                        ] + ([(-1, f"<in-kernel audit: "
                                   f"{warm.inc_info}>", -1, -1)]
                             if inc_bad else [])
                    break
                continue
            same = (
                np.array_equal(warm.assignment, cold.assignment)
                and np.array_equal(np.asarray(warm.chosen_score),
                                   np.asarray(cold.chosen_score))
                and np.array_equal(warm.evicted, cold.evicted)
            )
            if not same:
                bad = np.nonzero(warm.assignment != cold.assignment)[0]
                names = ds.meta.pod_names
                report["diverged_cycle"] = cyc
                report["bad_pods"] = [
                    (int(i), names[int(i)] if int(i) < len(names) else "<pad>",
                     int(warm.assignment[int(i)]),
                     int(cold.assignment[int(i)]))
                    for i in bad[:32]
                ]
                if not len(bad):
                    report["bad_pods"] = [
                        (-1, "<score-or-eviction-divergence>", -1, -1)
                    ]
                break
    finally:
        if engine is None:
            eng.close()
    report.update(
        warm_solves=ds.warm_solves, cold_solves=ds.cold_solves,
        incremental_solves=ds.incremental_solves,
        cold_reasons=ds.warm_cold_reasons,
        placed_delta_total=(report["placed_warm_total"]
                            - report["placed_cold_total"]),
        mean_abs_score_drift=(round(float(np.mean(drift)), 6)
                              if drift else 0.0),
    )
    return report


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", choices=sorted(PRESETS), default=None,
                    help="default: all presets")
    ap.add_argument("--seeds", type=int, default=10)
    ap.add_argument("--pods", type=int, default=80)
    ap.add_argument("--nodes", type=int, default=16)
    ap.add_argument("--warm-audit", type=int, default=0, metavar="N",
                    help="run N warm-vs-cold twin delta cycles and "
                         "report the first divergence (exit 1)")
    ap.add_argument("--churn", type=float, default=0.05,
                    help="warm-audit per-cycle churned-pod fraction")
    ap.add_argument("--seed", type=int, default=4000)
    ap.add_argument("--preemption", action="store_true",
                    help="warm-audit with the preemption program")
    ap.add_argument("--incremental", action="store_true",
                    help="warm-audit the bounded-divergence incremental "
                         "path: validity contract + quality drift "
                         "instead of bitwise parity")
    args = ap.parse_args(argv)
    if args.incremental and not args.warm_audit:
        ap.error("--incremental requires --warm-audit N")
    if args.warm_audit:
        report = warm_audit(
            cycles=args.warm_audit, preset=args.preset or "mixed",
            n_pods=args.pods, n_nodes=args.nodes, seed=args.seed,
            churn_frac=args.churn, preemption=args.preemption,
            incremental=args.incremental,
        )
        print(json.dumps(report), flush=True)
        if report["diverged_cycle"] >= 0:
            raise SystemExit(1)
        return
    presets = [args.preset] if args.preset else sorted(PRESETS)
    for p in presets:
        stats = measure(p, args.seeds, args.pods, args.nodes)
        print(json.dumps(stats.row()), flush=True)


if __name__ == "__main__":
    main()
