"""Deterministic fault injection (ISSUE 3 tentpole part 4).

A `FaultPlan` is a seedable, fully deterministic schedule of faults at
named SITES threaded through the serving stack. Code under test calls
``plan.fire(site)`` at each injection point; the plan counts invocations
per site and, when a rule matches the current invocation index, fires:

  * ``error``  — raise :class:`FaultError` (the caller's normal
    exception handling converts it: the sidecar heals through the
    decode path / aborts the RPC, the informer takes its relist path);
  * ``delay``  — sleep ``delay_s`` (a hung solve, a slow fetch);
  * ``drop``   — return ``"drop"`` so the CALLER discards state (a
    DeviceSession eviction, a lost watch event).

Determinism is the point: a chaos run and its fault-free twin must be
comparable placement-for-placement, so rules fire at exact invocation
indices — either given explicitly (tests) or drawn once from a seeded
RNG (`FaultPlan.seeded`), never from wall-clock randomness.

Wired injection sites (callers document theirs; this list is the
contract the chaos harness and tests rely on):

  ``server.decode``    before a snapshot/delta decodes (rpc/server.py)
  ``server.session``   before a device-session delta apply; ``drop``
                       evicts the lineage's DeviceSession first
  ``server.reply``     after every server stage completed, before the
                       reply leaves (rpc/server.py _serve) — ``delay``
                       is an injected WIRE stall the wire sentinel
                       must attribute to "transfer" (round 19)
  ``engine.fetch``     inside the engine's background fetch worker —
                       ``delay`` is a hung solve (the watchdog's prey)
  ``kube.watch``       top of each informer watch-stream attempt
                       (kube.py) — ``error`` forces the relist/backoff
                       path, a flapping apiserver
  ``replica.stream``   top of each standby replication poll
                       (replicate.py StandbyFollower) — ``error`` is a
                       failed poll (retried next tick), ``delay``
                       builds replication lag, so kill-the-leader and
                       stale-standby scenarios are seeded like every
                       other fault
  ``replica.takeover`` inside a standby's promotion to leader
                       (rpc/server.py _maybe_takeover) — ``error``
                       refuses the takeover with UNAVAILABLE, the
                       split-brain-attempt guard scenario: the client
                       rotates to the next endpoint and retries
  ``ingest.enqueue``   top of every IngestGate.offer (ingest.py) —
                       ``drop`` sheds the whole batch (the caller
                       retries, the chaos arm proves exactly-once
                       convergence), ``delay`` stalls admission (the
                       latency quantiles see it), ``error`` raises
                       out of the gate; the Enqueue rpc maps it to
                       UNAVAILABLE so the PR 3 client retry contract
                       re-drives it

One plan instance may be shared across components (server + engine +
informer): counters are per-site and thread-safe, and ``fired`` records
every shot for the chaos report.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Iterable

import numpy as np

from tpusched import trace as tracing


class FaultError(RuntimeError):
    """An injected failure (kind="error"). Deliberately a RuntimeError:
    injection points sit inside code whose real failure modes are
    unexpected exceptions, and the handlers under test must take the
    same path for both."""

    def __init__(self, site: str, index: int, message: str = ""):
        super().__init__(
            message or f"injected fault at {site}[{index}]"
        )
        self.site = site
        self.index = index


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """Fire `kind` at `site` on the given 0-based invocation indices."""

    site: str
    kind: str                      # "error" | "delay" | "drop"
    at: frozenset
    delay_s: float = 0.0
    message: str = ""

    def __post_init__(self):
        if self.kind not in ("error", "delay", "drop"):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        object.__setattr__(self, "at", frozenset(int(i) for i in self.at))


class FaultPlan:
    """A deterministic set of FaultRules plus per-site invocation
    counters. The no-rule fast path is one dict lookup, so production
    code can call fire() unconditionally with a shared NO_FAULTS."""

    def __init__(self, rules: Iterable[FaultRule] = ()):
        self._rules: dict[str, list[FaultRule]] = {}
        for r in rules:
            self._rules.setdefault(r.site, []).append(r)
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self.fired: list[tuple[str, int, str]] = []  # (site, index, kind)
        # Span collector for fault.* events; None = the process default
        # at emit time (SchedulerService points this at its own
        # collector so shots land in its flight dumps).
        self.tracer = None

    @classmethod
    def seeded(cls, seed: int, spec: dict) -> "FaultPlan":
        """Draw rule indices deterministically from `seed`.

        spec: site -> dict(kind=..., n=shots, window=index range the
        shots are drawn from [0, window), delay_s=..., message=...).
        A site may also map to a LIST of such dicts. Same (seed, spec)
        always yields the same plan.
        """
        rng = np.random.default_rng(seed)
        rules = []
        for site in sorted(spec):
            entries = spec[site]
            if isinstance(entries, dict):
                entries = [entries]
            for e in entries:
                window = int(e.get("window", 16))
                n = min(int(e.get("n", 1)), window)
                at = rng.choice(window, size=n, replace=False)
                rules.append(FaultRule(
                    site=site, kind=e["kind"],
                    at=frozenset(int(i) for i in at),
                    delay_s=float(e.get("delay_s", 0.0)),
                    message=e.get("message", ""),
                ))
        return cls(rules)

    def count(self, site: str) -> int:
        with self._lock:
            return self._counts.get(site, 0)

    def fire(self, site: str) -> str | None:
        """Count one invocation of `site`; apply any matching rule.
        Returns "drop" when a drop-rule fires, else None. Raises
        FaultError for error rules; sleeps for delay rules (the sleep
        happens OUTSIDE the lock — a hung site must not wedge counting
        at other sites).

        A rule-less plan (NO_FAULTS, shared process-wide) returns
        immediately without touching the lock or counters: fire() sits
        on per-request hot paths across every server/engine in the
        process, and invocation counts are only consumed by chaos
        reports, which always use a rule-bearing plan."""
        if not self._rules:
            return None
        rules = self._rules.get(site)
        with self._lock:
            index = self._counts.get(site, 0)
            self._counts[site] = index + 1
            hit = None
            if rules:
                for r in rules:
                    if index in r.at:
                        hit = r
                        break
                if hit is not None:
                    self.fired.append((site, index, hit.kind))
        if hit is None:
            return None
        # Fault events are SPANS (round 9, ISSUE 4): every injected
        # shot lands in the process trace ring (cat="fault"), so a
        # chaos run's flight-recorder dumps and Chrome export show the
        # injection alongside the stages it broke. Inherits the firing
        # thread's active trace (a server.decode shot lands inside its
        # request's stitched trace); delay shots carry their duration.
        tr = self.tracer or tracing.DEFAULT
        if hit.kind == "delay":
            time.sleep(hit.delay_s)
            tr.record("fault.delay", dur_s=hit.delay_s,
                      cat="fault", site=site, index=index)
            return None
        tr.record(f"fault.{hit.kind}", cat="fault",
                  site=site, index=index)
        if hit.kind == "drop":
            return "drop"
        raise FaultError(site, index, hit.message)

    def report(self) -> dict:
        """Chaos-harness summary: what fired, and how often each site
        was exercised (a site with count 0 means the plan never reached
        that code path — a silent no-op chaos run)."""
        with self._lock:
            return dict(
                fired=[
                    dict(site=s, index=i, kind=k) for s, i, k in self.fired
                ],
                site_counts=dict(self._counts),
            )


# Shared no-op plan: the default `faults=None` resolves here so hot
# paths skip the None-check dance and fire() stays one dict miss.
NO_FAULTS = FaultPlan()
