"""Tiny labeled Prometheus registry (round 9, ISSUE 4).

Replaces the sidecar's hand-rolled `_Metrics` lines and gives host-side
components (kube informer reconnects, HostScheduler failed cycles) a
real exposition surface. Deliberately a subset of prometheus_client —
this image must not grow dependencies — but a STRICT one: the render
always emits `# TYPE` lines, escapes label values, keeps histogram
bucket cumulative counts monotone, and emits `_sum`/`_count` per
histogram series (tests/test_metrics.py parses the full render with a
line-format checker).

Counters/Gauges/Histograms are name-keyed in a Registry; constructing
a metric whose name already exists in the registry RETURNS the
existing metric (labelnames must match) — prometheus_client's
get-or-create discipline, so K informers in one process share one
`tpusched_kube_watch_reconnects_total` family instead of colliding.

Bucket helpers replace the old 5s-capped linear BUCKETS: log-scale
duration buckets span 100 µs .. 600 s+ (a 10k x 5k CPU solve runs far
past 5 s — the round-8 histogram put every real solve in +Inf),
power-of-4 byte buckets span 1 KiB .. 1 GiB for H2D accounting.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Callable, Iterable, Mapping, cast

from tpusched.config import clamp01


def escape_label_value(v: str) -> str:
    """Prometheus text exposition escaping for label values."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def format_value(v: Any) -> str:
    """Canonical sample value: integers render bare, floats repr-exact,
    infinities as +Inf/-Inf."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def log_buckets(lo: float, hi: float, per_decade: int = 3) -> "tuple[float, ...]":
    """Log-spaced upper bounds from `lo` up to and including the first
    bound >= `hi` (e.g. 1e-4 .. 600 at 3/decade: 0.0001, 0.000215,
    0.000464, 0.001, ... 464.2, 1000)."""
    out: list[float] = []
    step = 10.0 ** (1.0 / per_decade)
    b = float(lo)
    while True:
        out.append(round(b, 10))
        if b >= hi:
            break
        b *= step
    return tuple(out)


def pow_buckets(lo: int, hi: int, factor: int = 4) -> "tuple[int, ...]":
    """Geometric integer bounds (bytes): lo, lo*factor, ... >= hi."""
    out: list[int] = []
    b = int(lo)
    while True:
        out.append(b)
        if b >= hi:
            break
        b *= factor
    return tuple(out)


# Serving-stage durations: 100 µs (a gate pass-through) .. 600 s (a
# watchdog-scale hung solve) — the fix for the 5.0s truncation.
DURATION_BUCKETS = log_buckets(1e-4, 600.0, per_decade=3)
BYTE_BUCKETS = pow_buckets(1 << 10, 1 << 30, factor=4)


def bucket_quantile(buckets: "tuple[float, ...]", counts: "list[int]",
                    q: float, interpolate: bool = True) -> float:
    """Quantile estimate from histogram bucket counts (round 18,
    ISSUE 13: shared by Histogram.quantile, the cycle-ledger sentinel,
    and tools/statusz.py's cross-replica merge, so one interpolation
    rule serves them all).

    `buckets` are the finite upper bounds; `counts` are the PER-BUCKET
    (non-cumulative) counts with the +Inf overflow count as the final
    element (len(buckets) + 1 entries). Returns NaN for an empty
    histogram. A quantile landing in the overflow bucket returns the
    last finite bound (the prometheus histogram_quantile convention:
    beyond the layout's resolution, the floor is the honest answer).
    interpolate=False returns the covering bucket's upper bound
    instead of interpolating within it — the conservative form for
    DISCRETE quantities (round counts, churn sizes), where a linear
    split inside a bucket would manufacture fractional thresholds no
    observation ever had."""
    total = sum(counts)
    if total <= 0:
        return math.nan
    rank = max(float(q), 0.0) * total
    cum = 0.0
    for i, b in enumerate(buckets):
        prev_cum = cum
        cum += counts[i]
        if cum >= rank:
            if not interpolate or counts[i] <= 0:
                return float(b)
            lo = float(buckets[i - 1]) if i > 0 else 0.0
            frac = clamp01((rank - prev_cum) / counts[i], default=1.0)
            return lo + (float(b) - lo) * frac
    # Overflow bucket: the layout can't resolve past its last bound.
    return float(buckets[-1]) if buckets else math.nan


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, "_Metric"] = {}  # insertion-ordered

    def _get_or_register(self, name: str, factory: "Callable[[], _Metric]",
                         kind: str,
                         labelnames: "tuple[str, ...]") -> "_Metric":
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind or m.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} re-registered as {kind}"
                        f"{labelnames} but exists as {m.kind}"
                        f"{m.labelnames}"
                    )
                return m
            m = factory()
            self._metrics[name] = m
            return m

    def render(self) -> str:
        """Full text exposition: one `# TYPE` line then the samples of
        each metric family, in registration order."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render_lines())
        return "\n".join(lines) + ("\n" if lines else "")


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str,
                 labelnames: "tuple[str, ...]") -> None:
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: "dict[tuple[str, ...], Any]" = {}

    def render_lines(self) -> "list[str]":
        raise NotImplementedError

    def _new_child(self) -> Any:
        raise NotImplementedError

    def labels(self, *values: Any, **kv: Any) -> Any:
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            values = tuple(kv[n] for n in self.labelnames)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: want labels {self.labelnames}, got {values!r}"
            )
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
            return child
        # (children are never removed: bounded by real label use)

    def _series(self) -> "list[tuple[tuple[str, ...], Any]]":
        with self._lock:
            return list(self._children.items())

    def _label_str(self, key: "tuple[str, ...]", extra: str = "") -> str:
        parts = [
            f'{n}="{escape_label_value(v)}"'
            for n, v in zip(self.labelnames, key)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value: float = 0

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self.value += n


class Counter(_Metric):
    kind = "counter"

    # The factory passed to _get_or_register FULLY initializes the
    # instance before it is published under the registry lock — a
    # concurrent constructor of the same family must never see a
    # half-built metric (__init__ runs after __new__ returns, outside
    # the lock, so it must not be what builds the object).

    def __new__(cls, name: str, help: str = "",
                labelnames: "Iterable[str]" = (),
                registry: "Registry | None" = None) -> "Counter":
        registry = registry if registry is not None else DEFAULT

        def make() -> "Counter":
            m = super(Counter, cls).__new__(cls)
            _Metric.__init__(m, name, help, tuple(labelnames))
            return m

        return cast("Counter", registry._get_or_register(
            name, make, "counter", tuple(labelnames),
        ))

    def __init__(self, name: str, help: str = "",
                 labelnames: "Iterable[str]" = (),
                 registry: "Registry | None" = None) -> None:
        pass  # built by the __new__ factory (comment above)

    def _new_child(self) -> _CounterChild:
        return _CounterChild()

    def inc(self, n: float = 1) -> None:
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; use .labels()"
            )
        self.labels().inc(n)

    def value(self, *label_values: Any) -> float:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            child = self._children.get(key)
        return float(child.value) if child is not None else 0.0

    def render_lines(self) -> "list[str]":
        lines = [f"# TYPE {self.name} counter"]
        series = self._series()
        if not series and not self.labelnames:
            series = [((), _CounterChild())]
        for key, child in series:
            lines.append(
                f"{self.name}{self._label_str(key)} "
                f"{format_value(child.value)}"
            )
        return lines


class _GaugeChild:
    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value: float = 0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = v

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self.value += n


class Gauge(_Metric):
    kind = "gauge"

    def __new__(cls, name: str, help: str = "",
                labelnames: "Iterable[str]" = (),
                registry: "Registry | None" = None) -> "Gauge":
        registry = registry if registry is not None else DEFAULT

        def make() -> "Gauge":
            m = super(Gauge, cls).__new__(cls)
            _Metric.__init__(m, name, help, tuple(labelnames))
            return m

        return cast("Gauge", registry._get_or_register(
            name, make, "gauge", tuple(labelnames),
        ))

    def __init__(self, name: str, help: str = "",
                 labelnames: "Iterable[str]" = (),
                 registry: "Registry | None" = None) -> None:
        pass  # built by the __new__ factory (see Counter)

    def _new_child(self) -> _GaugeChild:
        return _GaugeChild()

    def set(self, v: float) -> None:
        if self.labelnames:
            raise ValueError(f"{self.name} has labels; use .labels().set()")
        self.labels().set(v)

    def render_lines(self) -> "list[str]":
        lines = [f"# TYPE {self.name} gauge"]
        series = self._series()
        if not series and not self.labelnames:
            series = [((), _GaugeChild())]
        for key, child in series:
            lines.append(
                f"{self.name}{self._label_str(key)} "
                f"{format_value(child.value)}"
            )
        return lines


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, buckets: "tuple[float, ...]") -> None:
        self._lock = threading.Lock()
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # last = overflow (+Inf)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.sum += v
            self.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1


class Histogram(_Metric):
    kind = "histogram"

    def __new__(cls, name: str, help: str = "",
                buckets: "Iterable[float]" = DURATION_BUCKETS,
                labelnames: "Iterable[str]" = (),
                registry: "Registry | None" = None) -> "Histogram":
        registry = registry if registry is not None else DEFAULT

        def make() -> "Histogram":
            m = super(Histogram, cls).__new__(cls)
            _Metric.__init__(m, name, help, tuple(labelnames))
            m.buckets = tuple(float(b) for b in buckets)
            return m

        return cast("Histogram", registry._get_or_register(
            name, make, "histogram", tuple(labelnames),
        ))

    def __init__(self, name: str, help: str = "",
                 buckets: "Iterable[float]" = DURATION_BUCKETS,
                 labelnames: "Iterable[str]" = (),
                 registry: "Registry | None" = None) -> None:
        # Built by the __new__ factory (see Counter); only the
        # get-or-create layout check remains: a silently-different
        # bucket layout would mis-bucket this caller's observations —
        # the exact failure mode this module fixes.
        if tuple(float(b) for b in buckets) != self.buckets:
            raise ValueError(
                f"metric {name!r} re-registered with buckets "
                f"{tuple(buckets)!r} but exists with {self.buckets!r}"
            )

    def _new_child(self) -> _HistogramChild:
        return _HistogramChild(self.buckets)

    def observe(self, v: float) -> None:
        if self.labelnames:
            raise ValueError(f"{self.name} has labels; use .labels().observe()")
        self.labels().observe(v)

    def quantile(self, q: float, *label_values: Any,
                 interpolate: bool = True) -> float:
        """Bucket-interpolated quantile estimate for one series
        (label-less histograms pass no label values). NaN when the
        series has no observations (or was never created) — see
        bucket_quantile for the interpolation/overflow rules."""
        key = tuple(str(v) for v in label_values)
        with self._lock:
            child = self._children.get(key)
        if child is None:
            return math.nan
        with child._lock:
            counts = list(child.counts)
        return bucket_quantile(self.buckets, counts, q,
                               interpolate=interpolate)

    def series_counts(self, *label_values: Any) -> "list[int]":
        """Per-bucket counts (overflow last) of one series — the raw
        export tools/statusz.py ships across replicas so a fleet-level
        quantile can merge counts instead of averaging quantiles.
        Empty list when the series does not exist."""
        key = tuple(str(v) for v in label_values)
        with self._lock:
            child = self._children.get(key)
        if child is None:
            return []
        with child._lock:
            return list(child.counts)

    def render_lines(self) -> "list[str]":
        lines = [f"# TYPE {self.name} histogram"]
        for key, child in self._series():
            with child._lock:
                counts = list(child.counts)
                total, ssum = child.count, child.sum
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                le = self._label_str(key, f'le="{format_value(b)}"')
                lines.append(f"{self.name}_bucket{le} {cum}")
            le = self._label_str(key, 'le="+Inf"')
            lines.append(f"{self.name}_bucket{le} {total}")
            lines.append(
                f"{self.name}_sum{self._label_str(key)} {ssum:.6f}"
            )
            lines.append(
                f"{self.name}_count{self._label_str(key)} {total}"
            )
        return lines


class CallbackGauge(_Metric):
    """Gauge whose samples are computed at RENDER time from a callback
    (round 12: `scheduler_device_bytes{kind}` reads the live device-
    resident session/store sizes) — live state without a mutation hook
    on every change. The callback returns either a scalar (label-less
    gauge) or a mapping {label-values-tuple: value}. A callback error
    renders as NO samples for this family (a scrape must never take
    the server down); the TYPE line still renders so the family stays
    discoverable."""

    kind = "gauge"

    def __new__(cls, name: str, help: str = "",
                labelnames: "Iterable[str]" = (),
                callback: "Callable[[], Any] | None" = None,
                registry: "Registry | None" = None) -> "CallbackGauge":
        registry = registry if registry is not None else DEFAULT

        def make() -> "CallbackGauge":
            m = super(CallbackGauge, cls).__new__(cls)
            _Metric.__init__(m, name, help, tuple(labelnames))
            m.callback = callback
            return m

        return cast("CallbackGauge", registry._get_or_register(
            name, make, "gauge", tuple(labelnames),
        ))

    def __init__(self, name: str, help: str = "",
                 labelnames: "Iterable[str]" = (),
                 callback: "Callable[[], Any] | None" = None,
                 registry: "Registry | None" = None) -> None:
        # Built by the __new__ factory (see Counter). Re-registration
        # with a fresh callback re-points the family (the latest owner
        # of the live state wins — mirrors get-or-create semantics).
        if callback is not None:
            self.callback = callback

    def render_lines(self) -> "list[str]":
        lines = [f"# TYPE {self.name} gauge"]
        cb = self.callback
        if cb is None:
            return lines
        try:
            samples = cb()
        except Exception:
            return lines
        if not isinstance(samples, Mapping):
            samples = {(): samples}
        for key, v in samples.items():
            key = tuple(str(k) for k in (
                key if isinstance(key, tuple) else (key,)
            )) if self.labelnames else ()
            lines.append(
                f"{self.name}{self._label_str(key)} {format_value(v)}"
            )
        return lines


# Process-default registry: host-side components (kube informer,
# HostScheduler) register here so one process-wide render_default()
# exposes them; the sidecar's _Metrics uses its OWN Registry (its
# Metrics rpc is per-server).
DEFAULT = Registry()


def render_default() -> str:
    return DEFAULT.render()
