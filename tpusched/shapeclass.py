"""Shape-class registry and persistent-cache wiring (ROADMAP item 3).

XLA compiles one program per (jit family, argument shape tuple), and every
shape in this codebase is a pure function of the static ``Buckets`` plus a
handful of pow2-bucketed request parameters (top-k, explain-k, the warm
incremental frontier cap). That makes "every program this server will ever
trace" a FINITE, LISTABLE set — which this module formalizes:

* ``ShapeClass`` — one jit family Engine labels through ``_traced_jit``
  (engine.py), with the pow2 parameter that keys it (k / cap) when one
  exists.
* ``ShapeClassRegistry`` — the enumerable, JSON-round-trippable set of
  classes derived from an ``EngineConfig`` + explicit ``Buckets`` + the
  serving toggles (explain on/off, warm bitwise/incremental). The families
  here are exactly the bounded families tpuschedlint TPL104 proves at the
  engine's call sites; ``tools/check.py``'s ``prewarm`` stage cross-checks
  the two by AST.
* ``Engine.prewarm(registry)`` (tpusched/engine.py) traces every class at
  boot, so a promoted standby serves its first request with zero new
  compiles; the canonical per-family workloads live in
  ``prewarm_records`` / ``incremental_unassignable`` here.
* ``enable_persistent_cache`` — jax's persistent compilation cache, so a
  fresh PROCESS (bench round N+1, a restarted sidecar) reuses round N's
  XLA instead of recompiling it.

This module must import without jax (tools/check.py runs its registry
smoke in jax-less environments): everything jax-touching is behind a lazy
import inside ``enable_persistent_cache``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Iterator

from tpusched.config import Buckets, EngineConfig

# Compile-event attribution causes (ledger.CompileWatcher events carry
# one): boot-time prewarm work must never read as a serving regression —
# the PR 13 cycle sentinel keys "compile" anomalies off per-cycle counter
# deltas, and a prewarm runs before any cycle, but the timeline still
# needs the split for forensics.
CAUSE_SERVE = "serve"
CAUSE_PREWARM = "prewarm"

# Env var honored by enable_persistent_cache(): point it at a directory
# shared between bench/CI rounds and round N+1 skips round N's compiles.
CACHE_ENV = "TPUSCHED_COMPILE_CACHE"

REGISTRY_VERSION = 1


def k_bucket(k: int, n: int) -> int:
    """Pow2 compile bucket for a top-k request — MUST mirror
    Engine._k_bucket (pinned by tests/test_prewarm.py): O(log N) programs,
    callers slice the first k columns of the bucketed result."""
    kb = 1 << (max(int(k), 1) - 1).bit_length()
    return min(kb, int(n))


def frontier_caps(pods_bucket: int) -> tuple[int, ...]:
    """Every frontier-compaction width Engine._frontier_bucket can emit
    for a pod bucket of P (pinned against the engine formula by
    tests/test_prewarm.py): pow2 caps from the 64 floor up to (but not
    reaching) P, plus 0 = full-width rounds once the cap would cover the
    pod axis anyway. P <= 64 therefore has exactly one class: cap 0."""
    caps = []
    c = 64
    while c < int(pods_bucket):
        caps.append(c)
        c *= 2
    caps.append(0)
    return tuple(caps)


def topk_buckets(nodes_bucket: int) -> tuple[int, ...]:
    """All pow2 top-k buckets a ScoreBatch request can key (k is
    client-chosen in [1, N], so the reachable set is every pow2 <= N)."""
    out = []
    kb = 1
    while kb <= int(nodes_bucket):
        out.append(kb)
        kb *= 2
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class ShapeClass:
    """One jit family the engine will trace: `family` is the exact label
    Engine._traced_jit attaches (and ledger.COMPILES records), `kind`
    groups it for reporting, `params` carries the pow2 parameter baked
    into parameterized families (k for top-k/probe, cap for incremental)."""

    family: str
    kind: str  # "solve" | "score" | "explain" | "warm"
    params: tuple[tuple[str, int], ...] = ()

    def to_dict(self) -> dict:
        return {"family": self.family, "kind": self.kind,
                "params": dict(self.params)}

    @staticmethod
    def from_dict(d: dict) -> "ShapeClass":
        return ShapeClass(
            family=str(d["family"]), kind=str(d["kind"]),
            params=tuple(sorted(
                (str(k), int(v)) for k, v in dict(d.get("params", {})).items()
            )),
        )


def _config_fingerprint(config: EngineConfig, buckets: Buckets) -> str:
    """Stable digest of everything that keys compiled programs: two
    registries agree iff their engines trace the same program set."""
    blob = json.dumps(
        {"config": dataclasses.asdict(config),
         "buckets": dataclasses.asdict(buckets)},
        sort_keys=True, default=str,
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class ShapeClassRegistry:
    """The finite program set of one serving configuration. Frozen and
    JSON-round-trippable so a leader can publish it and a standby can
    prewarm the mirrored set (tpusched/replicate.py)."""

    classes: tuple[ShapeClass, ...]
    buckets: Buckets
    mode: str
    mesh_shape: tuple[int, int]
    explain: bool
    explain_k: int
    warm: str | None
    config_fingerprint: str

    def families(self) -> tuple[str, ...]:
        return tuple(c.family for c in self.classes)

    def __len__(self) -> int:
        return len(self.classes)

    def __iter__(self) -> Iterator[ShapeClass]:
        return iter(self.classes)

    def __contains__(self, family: object) -> bool:
        if isinstance(family, ShapeClass):
            family = family.family
        return any(c.family == family for c in self.classes)

    def to_json(self) -> str:
        return json.dumps({
            "version": REGISTRY_VERSION,
            "config_fingerprint": self.config_fingerprint,
            "buckets": dataclasses.asdict(self.buckets),
            "mode": self.mode,
            "mesh_shape": list(self.mesh_shape),
            "explain": self.explain,
            "explain_k": self.explain_k,
            "warm": self.warm,
            "classes": [c.to_dict() for c in self.classes],
        }, sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "ShapeClassRegistry":
        d = json.loads(s)
        ver = int(d.get("version", 0))
        if ver != REGISTRY_VERSION:
            raise ValueError(
                f"shape-class registry version {ver}: this build reads "
                f"version {REGISTRY_VERSION}"
            )
        return ShapeClassRegistry(
            classes=tuple(ShapeClass.from_dict(c) for c in d["classes"]),
            buckets=Buckets.from_dict(d["buckets"]),
            mode=str(d["mode"]),
            mesh_shape=tuple(int(x) for x in d["mesh_shape"]),  # type: ignore[arg-type]
            explain=bool(d["explain"]),
            explain_k=int(d["explain_k"]),
            warm=(None if d["warm"] is None else str(d["warm"])),
            config_fingerprint=str(d["config_fingerprint"]),
        )


def build_registry(
    config: EngineConfig | None = None,
    buckets: Buckets | None = None,
    *,
    explain: bool = False,
    explain_k: int = 3,
    warm: str | None = None,
    topk: tuple[int, ...] | None = None,
) -> ShapeClassRegistry:
    """Enumerate every jit family a server with this configuration will
    dispatch. `buckets` must be EXPLICIT: without pinned buckets, shapes
    float with content and no finite registry exists (the same caveat
    SnapshotBuilder documents for serving paths).

    topk narrows the score_topk_k{kb} classes to the pow2 buckets of the
    given k values (default: every pow2 <= the node bucket, the full
    client-reachable set).

    The eager "solve" wrapper (Engine._solve_jit) is deliberately ABSENT:
    no public entry point dispatches it, so prewarming it would trace a
    program serving never runs."""
    config = config or EngineConfig()
    if buckets is None:
        raise ValueError(
            "build_registry needs explicit Buckets: shape classes are a "
            "function of pinned bucket sizes (pass Buckets.fit(...) with "
            "floors for everything the cluster might hold)"
        )
    if warm not in (None, "bitwise", "incremental"):
        raise ValueError(
            f"warm={warm!r}: want None, 'bitwise', or 'incremental'"
        )
    N, P = int(buckets.nodes), int(buckets.pods)
    classes: list[ShapeClass] = [
        ShapeClass("solve_packed", "solve"),
        ShapeClass("score", "score"),
        ShapeClass("score_top1", "score"),
    ]
    if topk is None:
        kbs: tuple[int, ...] = topk_buckets(N)
    else:
        kbs = tuple(sorted({k_bucket(k, N) for k in topk}))
    classes.extend(
        ShapeClass(f"score_topk_k{kb}", "score", (("k", kb),)) for kb in kbs
    )
    if explain:
        classes.append(ShapeClass("solve_explained", "explain"))
        kb = k_bucket(min(max(int(explain_k), 1), max(N, 1)), max(N, 1))
        classes.append(
            ShapeClass(f"explain_probe_k{kb}", "explain", (("k", kb),))
        )
    if warm is not None:
        classes.append(ShapeClass("warm_cold_refresh", "warm"))
        classes.append(ShapeClass("warm_refresh", "warm"))
        if warm == "incremental":
            classes.extend(
                ShapeClass(f"warm_incremental_cap{c}", "warm", (("cap", c),))
                for c in frontier_caps(P)
            )
    return ShapeClassRegistry(
        classes=tuple(classes),
        buckets=buckets,
        mode=config.mode,
        mesh_shape=tuple(config.mesh_shape),  # type: ignore[arg-type]
        explain=bool(explain),
        explain_k=int(explain_k),
        warm=warm,
        config_fingerprint=_config_fingerprint(config, buckets),
    )


# ---------------------------------------------------------------------------
# Canonical prewarm workloads.
#
# Leaf shapes are a pure function of Buckets (SnapshotBuilder pads content
# up to explicit buckets), so a TINY synthetic cluster built at the
# registry's buckets compiles exactly the programs real traffic at those
# buckets dispatches. The warm families additionally shape-key on the
# pow2-padded dirty-row lists: the canonical delta is the smallest one
# serving produces — one upserted existing pod (pad (1,), no perms) —
# matching a session delta that touches one pod.
# ---------------------------------------------------------------------------


def incremental_unassignable(cap: int, pods_bucket: int) -> int:
    """How many unassignable filler pods the cap-`cap` representative
    needs: Engine._frontier_bucket picks the cap from
    est = |frontier| + |unassigned carry|, the canonical delta contributes
    1 frontier pod, so `cap//2 - 1` unassigned pods land est exactly at
    cap/2 (-> want == cap). cap 0 means full-width: trivial when the 64
    floor already covers the pod axis (P <= 64), otherwise est must reach
    P/2 so the pow2 bucket meets the axis."""
    P = int(pods_bucket)
    if cap == 0:
        return 0 if P <= 64 else P // 2 - 1
    return max(0, int(cap) // 2 - 1)


def prewarm_records(
    config: EngineConfig, unassignable: int = 0,
) -> tuple[list[dict], list[dict], list[dict]]:
    """Builder-style (nodes, pods, running) record lists for a prewarm
    snapshot: two schedulable nodes, one placeable pod, one running pod,
    plus `unassignable` filler pods whose requests no node can hold
    (their carry stays -1, which is what steers the incremental frontier
    estimate — see incremental_unassignable)."""
    res = config.resources[0]
    nodes = [
        {"name": f"prewarm-n{i}", "allocatable": {res: 1000.0}}
        for i in range(2)
    ]
    pods = [{"name": "prewarm-p0", "requests": {res: 100.0},
             "priority": 1.0}]
    pods.extend(
        {"name": f"prewarm-x{i}", "requests": {res: 1e9}, "priority": 0.0}
        for i in range(int(unassignable))
    )
    running = [{"name": "prewarm-r0", "node": "prewarm-n0",
                "requests": {res: 50.0}}]
    return nodes, pods, running


def enable_persistent_cache(path: str | None = None) -> str | None:
    """Point jax's persistent compilation cache at `path` (or the
    TPUSCHED_COMPILE_CACHE env var when unset). Returns the directory in
    effect, or None when neither is set (no-op — in-process jit caches
    are unaffected either way). The thresholds are dropped to zero so
    even sub-second CPU compiles persist: this repo's round-over-round
    CI diffing wants round N+1's compile_count_total at ~0, not just the
    big kernels cached."""
    path = path if path is not None else os.environ.get(CACHE_ENV)
    if not path:
        return None
    import jax  # tpl: disable=TPL001(optional dep: this module is stdlib-only so tools/check.py can reason about registries without jax; only cache wiring needs it)

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(path))
    for flag, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(flag, val)  # type: ignore[arg-type]
        except Exception:
            # Older jax spells the thresholds differently; the cache dir
            # alone still persists the expensive programs.
            pass
    return str(path)
