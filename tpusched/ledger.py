"""Cycle flight ledger (round 18, ISSUE 13).

Every serving cycle — a HostScheduler batch, a sidecar Assign, a
`pipeline.warm_cycle_stream` delta cycle, a sim-driver tick's cycle —
emits ONE structured `CycleRecord` into a bounded in-memory ring with
rolling aggregation. The record joins what was previously scattered
across three point-in-time surfaces: per-request spans (trace.py),
per-process counters/histograms (metrics.py), and per-decision explain
records — so a p99 spike finally answers "was that a retrace, a round
blow-up, a churn burst, or a preemption storm?" instead of being a
bare histogram bucket.

Three pieces:

  * `CompileWatcher` — counts XLA cache misses per (engine, program,
    shape-class) with compile wall time. Engine wraps its jit entry
    points (`Engine._traced_jit`): the FIRST dispatch of a new shape
    class runs trace+lower+compile synchronously, so its wall time IS
    the compile cost; later dispatches are one set-membership check.
    Cycle emitters read `COMPILES.counters()` before/after a cycle to
    attribute retraces to the cycle that paid them. Events carry a
    `cause` ("serve" by default; `Engine.prewarm` tags its boot-time
    traces "prewarm" via tpusched.shapeclass.CAUSE_PREWARM) so the
    shape-class prewarm + persistent-cache layer (ROADMAP item 3) never
    reads as a serving regression — a prewarm runs before any cycle, and
    the timeline still shows the split for forensics.
  * `CycleLedger` — the ring + rolling-window aggregation, reusing
    metrics.Histogram buckets plus the bucket-interpolated
    `Histogram.quantile()` for the rolling p50/p99 per stage, churn
    p95, and round median. Optionally persists every record as one
    JSONL line (the black box a postmortem replays).
  * the regression sentinel — a cycle whose solve time exceeds the
    rolling p99 (non-interpolated: the covering bucket bound, so a
    flag means "above everything the layout resolved so far") is
    attributed by correlating the record's OWN fields, in order:
    retrace present -> "compile"; rounds above the rolling median ->
    "round_growth"; churn above its p95 -> "churn_burst"; a
    preemption tranche active -> "preemption"; else "unknown". Each
    anomaly bumps `scheduler_cycle_anomalies_total{cause}` and fires
    the attached FlightRecorder, so the anomaly carries its causal
    trace, not just a counter bump.

Schema discipline: `SCHEMA` is the single authority on a record's
fields; `validate_record` is the twin contract between live serving
and virtual-time sim replays (tests/test_ledger.py pins schema
equality), and what tools/check.py's `statusz` smoke validates against
a real sidecar. Record timestamps ride the EMITTER's clock — wall time
on the sidecar, the host's injected clock in-process, so sim replays
carry virtual timestamps.

Stdlib-only on purpose (like trace.py): the ledger must be importable
from every layer, including ones that never touch jax.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import math
import threading
import time
from collections import deque
from typing import Any, TextIO

from tpusched import metrics as pm
from tpusched import trace as tracing

# Churn (records per cycle) and commit-round bucket layouts: discrete
# pow2-ish bounds so the sentinel's non-interpolated quantiles land on
# values a real cycle can actually have.
CHURN_BUCKETS = tuple(float(1 << i) for i in range(17))      # 1 .. 65536
ROUND_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)

ANOMALY_CAUSES = ("compile", "round_growth", "churn_burst",
                  "preemption", "unknown")


@dataclasses.dataclass
class CycleRecord:
    """One scheduling cycle's flight-ledger entry (module docstring).
    `cycle` is assigned by the ledger at observe() time; `anomaly` is
    written by the sentinel ("" = none). `stages` holds per-stage wall
    seconds joined from the cycle's spans (decode, delta.apply,
    dispatch, fetch.join, reply.*, engine.fetch on the sidecar;
    build/solve/bind on the host) — stage NAMES follow the trace span
    names so a ledger anomaly points at the same name a trace shows."""

    ts: float = 0.0            # emitter clock (virtual under the sim)
    source: str = ""           # host | sidecar | pipeline | sim | bench
    pods: int = 0              # batch size offered to the solver
    nodes: int = 0
    running: int = 0
    placed: int = 0
    evicted: int = 0
    churn: int = 0             # changed records feeding this cycle
    frontier: int = 0          # incremental warm solves; 0 otherwise
    rounds: int = 0            # commit rounds
    warm_path: str = "cold"    # cold | warm | incremental
    solve_s: float = 0.0       # the quantity the sentinel judges
    stages: "dict[str, float]" = dataclasses.field(default_factory=dict)
    compiles: int = 0          # XLA cache misses paid inside the cycle
    compile_s: float = 0.0     # their compile wall time
    queue_depth: int = 0       # pending-queue depth at cycle start
    cycle: int = 0
    anomaly: str = ""


# Field name -> accepted types; THE schema authority (docstring).
SCHEMA: "dict[str, tuple[type, ...]]" = {
    "cycle": (int,),
    "ts": (int, float),
    "source": (str,),
    "pods": (int,),
    "nodes": (int,),
    "running": (int,),
    "placed": (int,),
    "evicted": (int,),
    "churn": (int,),
    "frontier": (int,),
    "rounds": (int,),
    "warm_path": (str,),
    "solve_s": (int, float),
    "stages": (dict,),
    "compiles": (int,),
    "compile_s": (int, float),
    "queue_depth": (int,),
    "anomaly": (str,),
}


def record_dict(rec: CycleRecord) -> "dict[str, Any]":
    """Plain dict in SCHEMA key order (JSONL lines, Statusz payloads)."""
    d = dataclasses.asdict(rec)
    return {k: d[k] for k in SCHEMA}


def validate_record(d: "dict[str, Any]") -> "dict[str, Any]":
    """Schema check for one record dict (the sim-vs-live twin contract
    and the check.py statusz smoke). Raises ValueError on any drift:
    missing/extra keys, wrong field types, non-numeric stage values."""
    missing = [k for k in SCHEMA if k not in d]
    extra = [k for k in d if k not in SCHEMA]
    if missing or extra:
        raise ValueError(
            f"CycleRecord schema drift: missing={missing} extra={extra}"
        )
    for k, types in SCHEMA.items():
        if not isinstance(d[k], types) or isinstance(d[k], bool):
            raise ValueError(
                f"CycleRecord field {k!r}: {type(d[k]).__name__} is not "
                f"{'/'.join(t.__name__ for t in types)}"
            )
    for st, v in d["stages"].items():
        if not isinstance(st, str) or isinstance(v, bool) \
                or not isinstance(v, (int, float)):
            raise ValueError(
                f"CycleRecord stages entry {st!r}: {v!r} is not a "
                "str -> seconds pair"
            )
    if d["warm_path"] not in ("cold", "warm", "incremental"):
        raise ValueError(
            f"CycleRecord warm_path {d['warm_path']!r}: want "
            "cold|warm|incremental"
        )
    return d


class CompileWatcher:
    """Process-wide XLA cache-miss ledger (module docstring). Keys are
    opaque (the engine builds (engine-nonce, program, shape-tuple));
    `shape` is the human label the Statusz compile timeline shows.
    Lock bodies are O(set-op) only; BOTH stores are bounded — the
    event deque caps the timeline, and the seen-key set evicts
    oldest-first past `seen_cap` so a process that churns through
    engines (chaos fleets, promotion cycles, long test runs) cannot
    leak one key per engine forever. An evicted key's shape re-counts
    as a compile if it ever recurs — at 4096 keys that is far beyond
    any live engine's real shape set."""

    def __init__(self, capacity: int = 256, seen_cap: int = 4096):
        self._lock = threading.Lock()
        self._seen: "dict[Any, None]" = {}  # insertion-ordered key set
        self._seen_cap = int(seen_cap)
        self._events: "deque[dict[str, Any]]" = deque(maxlen=int(capacity))
        self._by_cause: "dict[str, int]" = {}
        self.total = 0
        self.compile_s_total = 0.0
        self.enabled = True

    def known(self, key: Any) -> bool:
        with self._lock:
            return key in self._seen

    def note(self, key: Any, fn: str, shape: str, dur_s: float,
             cause: str = "serve") -> bool:
        """Record one first-dispatch (compile) event; False when a
        racing first caller already recorded this key. `cause` labels
        WHY the program was traced ("serve" for a request-path cache
        miss, "prewarm" for Engine.prewarm boot work) — the split the
        cycle sentinel's "compile" attribution and the prewarm tests
        read back through cause_counts()/timeline()."""
        ev = dict(ts=time.time(), fn=fn, shape=shape,
                  compile_s=round(float(dur_s), 6), cause=str(cause))
        with self._lock:
            if key in self._seen:
                return False
            self._seen[key] = None
            while len(self._seen) > self._seen_cap:
                self._seen.pop(next(iter(self._seen)))
            self.total += 1
            self.compile_s_total += float(dur_s)
            self._by_cause[str(cause)] = self._by_cause.get(str(cause), 0) + 1
            self._events.append(ev)
            return True

    def counters(self) -> "tuple[int, float]":
        """(total compiles, total compile seconds) — cycle emitters
        read this before/after a cycle to attribute retraces."""
        with self._lock:
            return self.total, self.compile_s_total

    def cause_counts(self) -> "dict[str, int]":
        """Monotonic per-cause compile totals (unlike the capped event
        timeline): {"prewarm": boot traces, "serve": request-path
        cache misses, ...}."""
        with self._lock:
            return dict(self._by_cause)

    def timeline(self) -> "list[dict[str, Any]]":
        with self._lock:
            return list(self._events)


class CycleLedger:
    """Bounded ring of CycleRecords + rolling aggregation + the
    regression sentinel (module docstring).

    registry: where the ledger's metric families live (the sidecar
    passes its per-server registry so anomalies render in its Metrics
    rpc; None = the process-default registry). flight/tracer: the
    FlightRecorder the sentinel fires and the span ring it snapshots
    (tracer None = the process default at fire time). min_cycles: how
    many cycles the rolling windows need before the sentinel arms.
    jsonl: optional path — every record appends one JSON line (the
    black box); close() releases the file."""

    def __init__(self, capacity: int = 1024,
                 registry: "pm.Registry | None" = None,
                 flight: "tracing.FlightRecorder | None" = None,
                 tracer: "tracing.TraceCollector | None" = None,
                 min_cycles: int = 32,
                 jsonl: "str | None" = None,
                 watcher: "CompileWatcher | None" = None,
                 enabled: bool = True):
        self._lock = threading.Lock()
        self._ring: "deque[CycleRecord]" = deque(maxlen=int(capacity))
        self._mint = itertools.count(1)
        self.enabled = enabled
        self.min_cycles = int(min_cycles)
        self.flight = flight
        self.tracer = tracer
        self.watcher = watcher if watcher is not None else COMPILES
        self._jsonl_path = jsonl
        self._jsonl: "TextIO | None" = None
        self._jsonl_closed = False
        # Serializes black-box writes (a TextIOWrapper is not safe for
        # concurrent multi-chunk writes) and the close() handoff.
        self._io_lock = threading.Lock()
        self._stage_names: "set[str]" = set()
        self.anomalies = 0
        reg = registry if registry is not None else pm.DEFAULT
        self._h_solve = pm.Histogram(
            "scheduler_cycle_solve_seconds",
            "per-cycle solve wall (the sentinel's judged quantity)",
            buckets=pm.DURATION_BUCKETS, registry=reg)
        self._h_stage = pm.Histogram(
            "scheduler_cycle_stage_seconds",
            "per-cycle stage wall by trace span name",
            buckets=pm.DURATION_BUCKETS, labelnames=("stage",),
            registry=reg)
        self._h_churn = pm.Histogram(
            "scheduler_cycle_churn_records",
            "changed records feeding each cycle",
            buckets=CHURN_BUCKETS, registry=reg)
        self._h_rounds = pm.Histogram(
            "scheduler_cycle_rounds",
            "commit rounds per ledgered cycle",
            buckets=ROUND_BUCKETS, registry=reg)
        self._c_cycles = pm.Counter(
            "scheduler_cycles_total",
            "ledgered scheduling cycles", ("source", "warm_path"),
            registry=reg)
        self._c_anomalies = pm.Counter(
            "scheduler_cycle_anomalies_total",
            "sentinel-flagged cycles by attributed cause", ("cause",),
            registry=reg)
        self._c_compiles = pm.Counter(
            "scheduler_cycle_compiles_total",
            "XLA cache misses attributed to ledgered cycles",
            registry=reg)

    # -- recording -----------------------------------------------------------

    def observe(self, rec: CycleRecord) -> "CycleRecord | None":
        """Append one cycle: sentinel check against PRIOR cycles'
        rolling windows, then fold the record into them. Returns the
        (cycle-stamped, anomaly-stamped) record, or None when the
        ledger is disabled."""
        if not self.enabled:
            return None
        cause = self._sentinel(rec)
        rec.anomaly = cause or ""
        rec.cycle = next(self._mint)
        with self._lock:
            self._ring.append(rec)
        self._h_solve.observe(rec.solve_s)
        for stage, dur in rec.stages.items():
            with self._lock:
                self._stage_names.add(stage)
            self._h_stage.labels(stage).observe(float(dur))
        self._h_churn.observe(rec.churn)
        self._h_rounds.observe(rec.rounds)
        self._c_cycles.labels(rec.source, rec.warm_path).inc()
        if rec.compiles:
            self._c_compiles.inc(rec.compiles)
        if cause:
            self.anomalies += 1
            self._c_anomalies.labels(cause).inc()
            flight = self.flight
            if flight is not None:
                flight.record("cycle_anomaly",
                              self.tracer or tracing.DEFAULT,
                              cause=cause, cycle=record_dict(rec))
        self._write_jsonl(rec)
        return rec

    def _solve_count(self) -> int:
        child = self._h_solve.labels()
        return int(child.count)

    def _sentinel(self, rec: CycleRecord) -> "str | None":
        """The regression sentinel (module docstring): None = normal.
        All thresholds are NON-interpolated bucket bounds — exceeding
        one means exceeding everything the layout resolved so far, so
        a flag is never an interpolation artifact."""
        if self._solve_count() < self.min_cycles:
            return None
        p99 = self._h_solve.quantile(0.99, interpolate=False)
        if math.isnan(p99) or not rec.solve_s > p99:
            return None
        if rec.compiles > 0:
            return "compile"
        med_rounds = self._h_rounds.quantile(0.5, interpolate=False)
        if not math.isnan(med_rounds) and rec.rounds > med_rounds:
            return "round_growth"
        churn_p95 = self._h_churn.quantile(0.95, interpolate=False)
        if not math.isnan(churn_p95) and rec.churn > churn_p95:
            return "churn_burst"
        if rec.evicted > 0:
            return "preemption"
        return "unknown"

    def _write_jsonl(self, rec: CycleRecord) -> None:
        if self._jsonl_path is None:
            return
        line = json.dumps(record_dict(rec)) + "\n"
        if self._jsonl is None:
            # Lazy open OUTSIDE the lock (file open must not serialize
            # observers); the tiny publish race double-opens at worst,
            # and the loser's handle is closed immediately. A closed
            # ledger never reopens — late observers drop the line.
            f: "TextIO | None" = open(self._jsonl_path, "a")
            with self._io_lock:
                if self._jsonl is None and not self._jsonl_closed:
                    self._jsonl, f = f, None
            if f is not None:
                f.close()
        # Write under the io lock: concurrent handlers must not
        # interleave partial lines into the black box, and a racing
        # close() must not yank the handle mid-write.
        with self._io_lock:
            f = self._jsonl
            if f is not None:
                f.write(line)
                f.flush()

    # -- reading -------------------------------------------------------------

    def records(self, last: "int | None" = None) -> "list[CycleRecord]":
        with self._lock:
            out = list(self._ring)
        if last is not None and last >= 0:
            out = out[len(out) - min(last, len(out)):]
        return out

    def _hist_export(self, hist: pm.Histogram, *labels: Any) -> "dict[str, Any]":
        counts = hist.series_counts(*labels)
        return dict(le=list(hist.buckets), counts=counts)

    def statusz(self, last: int = 32) -> "dict[str, Any]":
        """The Statusz payload: rolling p50/p99 per stage, warm-path
        mix, churn/round aggregates, the compile timeline, anomaly
        counts, the last-N records, and the RAW bucket counts
        (tools/statusz.py merges counts across replicas and
        re-derives fleet quantiles via metrics.bucket_quantile)."""
        recs = self.records(last)
        all_recs = self.records()
        warm_mix: "dict[str, int]" = {}
        anomalies: "dict[str, int]" = {}
        sources: "dict[str, int]" = {}
        for r in all_recs:
            warm_mix[r.warm_path] = warm_mix.get(r.warm_path, 0) + 1
            sources[r.source] = sources.get(r.source, 0) + 1
            if r.anomaly:
                anomalies[r.anomaly] = anomalies.get(r.anomaly, 0) + 1
        with self._lock:
            stage_names = sorted(self._stage_names)
        stages: "dict[str, Any]" = {}
        for stage in stage_names:
            stages[stage] = dict(
                p50_ms=_ms(self._h_stage.quantile(0.50, stage)),
                p99_ms=_ms(self._h_stage.quantile(0.99, stage)),
                hist=self._hist_export(self._h_stage, stage),
            )
        total, compile_s = self.watcher.counters()
        return dict(
            cycles=self._solve_count(),
            anomalies=anomalies,
            anomalies_total=self.anomalies,
            warm_mix=warm_mix,
            sources=sources,
            solve=dict(
                p50_ms=_ms(self._h_solve.quantile(0.50)),
                p99_ms=_ms(self._h_solve.quantile(0.99)),
                hist=self._hist_export(self._h_solve),
            ),
            churn=dict(
                p50=_r(self._h_churn.quantile(0.50)),
                p95=_r(self._h_churn.quantile(0.95)),
                hist=self._hist_export(self._h_churn),
            ),
            rounds=dict(
                p50=_r(self._h_rounds.quantile(0.50)),
                hist=self._hist_export(self._h_rounds),
            ),
            compiles=dict(total=total,
                          compile_s_total=round(compile_s, 6),
                          timeline=self.watcher.timeline()),
            records=[record_dict(r) for r in recs],
        )

    def close(self) -> None:
        """Release the JSONL black box (idempotent; later observers
        drop their lines instead of reopening)."""
        with self._io_lock:
            f, self._jsonl = self._jsonl, None
            self._jsonl_closed = True
        if f is not None:
            f.close()


def _ms(v: float) -> "float | None":
    return None if math.isnan(v) else round(v * 1e3, 3)


def _r(v: float) -> "float | None":
    return None if math.isnan(v) else round(v, 3)


# Process defaults: the engine's jit wrappers feed COMPILES; host /
# pipeline / sim emitters fall back to DEFAULT unless handed their own
# ledger (the sidecar builds one per service so its anomalies render in
# its own Metrics rpc). `set_enabled(False)` is the global off switch —
# bench.py's ledger-off arm measures exactly this path.
COMPILES = CompileWatcher()
DEFAULT = CycleLedger()


def set_enabled(on: bool) -> None:
    DEFAULT.enabled = bool(on)
    COMPILES.enabled = bool(on)
