"""The ten tpuschedlint rules (round 15, ISSUE 10).

Each rule is a small pass over one file's AST producing Findings; the
incident each rule descends from is catalogued in tools/README.md
"Static analysis". Rules are HEURISTIC on purpose: they prove the
cheap lexical property (no `.result()` token under a `with ...lock:`)
rather than the deep semantic one, and every legitimate exception is a
per-line suppression whose mandatory reason documents WHY the line is
exempt — the suppression text is the living review checklist.

Applicability is path-based (repo-relative POSIX paths): most rules
cover product code (tpusched/, tools/, bench.py) and skip tests;
TPL010 covers ONLY test files. Passing any mix of paths to the engine
is safe — each rule selects its own territory.
"""

from __future__ import annotations

import ast
from typing import TYPE_CHECKING, Iterator

from tpusched.lint import interproc
from tpusched.lint.engine import Finding
from tpusched.lint.kernelflow import KERNEL_RULES

if TYPE_CHECKING:
    from tpusched.lint.engine import LintContext

__all__ = ["RULES", "default_rules", "Rule"]


# ---------------------------------------------------------------------------
# Shared AST helpers.
# ---------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> "str | None":
    """Render a Name/Attribute chain as ``a.b.c``; None for anything
    whose base is not a plain name (calls, subscripts, ...)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def terminal_name(node: ast.AST) -> "str | None":
    """The rightmost identifier of a call target: ``x.y.z() -> z``,
    ``f() -> f``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def is_num(node: ast.AST, value: float) -> bool:
    return (isinstance(node, ast.Constant)
            and type(node.value) in (int, float)
            and float(node.value) == value)


def import_aliases(tree: ast.AST) -> "dict[str, str]":
    """local name -> fully dotted module/object it refers to, from the
    MODULE-LEVEL and function-level import statements of one file."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.level == 0 and node.module:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def normalize_call(func: ast.AST, aliases: "dict[str, str]") -> "str | None":
    """Dotted call target with its leading alias expanded:
    ``np.random.rand`` -> ``numpy.random.rand`` under
    ``import numpy as np``."""
    d = dotted_name(func)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    if head in aliases:
        d = aliases[head] + ("." + rest if rest else "")
    return d


def is_test_path(relpath: str) -> bool:
    return (relpath.startswith("tests/")
            or relpath.rsplit("/", 1)[-1].startswith("test_"))


def product_path(relpath: str) -> bool:
    """tpusched/, tools/, or bench.py — the non-test gate surface."""
    if is_test_path(relpath):
        return False
    return (relpath.startswith("tpusched/")
            or relpath.startswith("tools/")
            or relpath.rsplit("/", 1)[-1] == "bench.py")


class Rule:
    rule_id = "TPL999"
    title = ""
    incident = ""  # the CHANGES.md defect class this rule encodes

    def applies(self, relpath: str) -> bool:
        return product_path(relpath)

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        raise NotImplementedError

    def finding(self, relpath: str, node: ast.AST, message: str) -> Finding:
        return Finding(relpath, getattr(node, "lineno", 1),
                       self.rule_id, message)


# ---------------------------------------------------------------------------
# TPL001 — function-level imports in tpusched/.
# ---------------------------------------------------------------------------

class FunctionLevelImport(Rule):
    """Imports belong at module top. Function-level imports put a
    sys.modules dict probe (or worse, a first-call module init) on
    whatever path calls the function — the exact per-record /
    per-cycle cost PR 5 and PR 7 review passes kept hoisting. Optional
    heavy deps (grpc, yaml: a host-only install must import without
    them) are allowlisted; a deliberate lazy import (cycle break,
    CLI-only dependency) takes a suppression whose reason says so.
    """

    rule_id = "TPL001"
    title = "function-level import in tpusched/"
    incident = ("PR 5/PR 7 review passes: per-cycle `from tpusched import "
                "...` inside host/server hot paths")

    #: Top-level modules a deployment may legitimately lack: importing
    #: them at module top would make the whole package require them.
    OPTIONAL_DEPS = frozenset({"grpc", "yaml"})

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("tpusched/") and not is_test_path(relpath)

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        findings = []
        for node in ast.walk(tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            if not self._inside_function(node, parents):
                continue
            mods = self._top_modules(node)
            if mods and mods <= self.OPTIONAL_DEPS:
                continue
            findings.append(self.finding(
                relpath, node,
                f"function-level import of {', '.join(sorted(mods)) or '?'}"
                " — move to module top (or suppress with the cycle/"
                "optional-dep reason)",
            ))
        return findings

    @staticmethod
    def _inside_function(node: ast.AST,
                         parents: "dict[ast.AST, ast.AST]") -> bool:
        p = parents.get(node)
        while p is not None:
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return True
            p = parents.get(p)
        return False

    @staticmethod
    def _top_modules(node: "ast.Import | ast.ImportFrom") -> "set[str]":
        if isinstance(node, ast.Import):
            return {a.name.split(".")[0] for a in node.names}
        if node.module is None or node.level:  # relative import
            return {"."}
        return {node.module.split(".")[0]}


# ---------------------------------------------------------------------------
# TPL002 — unseeded randomness / wall-clock in the hash-pinned sim.
# ---------------------------------------------------------------------------

class UnseededRandomness(Rule):
    """tpusched/sim/, tpusched/kernels/, and faults.py are under the
    determinism contract: same seed -> byte-identical event-log hash
    (PR 5/PR 8 twin harness). Module-level RNG draws (`random.random`,
    `np.random.rand`), zero-arg generator constructions, and wall-clock
    reads (`time.time`, `datetime.now`) all smuggle ambient entropy
    into that hash. Seeded constructions (`random.Random(seed)`,
    `np.random.default_rng(seed)`) and monotonic timers
    (`time.monotonic`, `time.perf_counter`: measurement, not
    timestamps) stay legal.
    """

    rule_id = "TPL002"
    title = "unseeded randomness / wall-clock in deterministic code"
    incident = ("PR 5/PR 8 determinism contract: the event-log hash is "
                "the twin-run equality witness; host.py's demo "
                "rng.uniform() leak took a PR to excise")

    SCOPES = ("tpusched/sim/", "tpusched/kernels/")
    FILES = ("tpusched/faults.py",)
    SEEDED_CTORS = frozenset({
        "Random", "SystemRandom", "default_rng", "RandomState",
        "SeedSequence", "Generator", "PCG64", "Philox",
    })
    WALL_CLOCK = frozenset({"time.time", "time.time_ns"})

    def applies(self, relpath: str) -> bool:
        return (relpath.startswith(self.SCOPES) or relpath in self.FILES)

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        aliases = import_aliases(tree)
        findings = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = normalize_call(node.func, aliases)
            if name is None:
                continue
            msg = self._classify(name, node)
            if msg:
                findings.append(self.finding(relpath, node, msg))
        return findings

    def _classify(self, name: str, call: ast.Call) -> "str | None":
        parts = name.split(".")
        last = parts[-1]
        if name in self.WALL_CLOCK:
            return (f"wall-clock read {name}() in hash-pinned code — "
                    "use the VirtualClock / injected now")
        if parts[0] == "datetime" and last in ("now", "utcnow", "today"):
            return (f"wall-clock read {name}() in hash-pinned code — "
                    "use the VirtualClock / injected now")
        if parts[0] == "random" and len(parts) == 2:
            if last in self.SEEDED_CTORS:
                return self._unseeded_ctor(name, call)
            return (f"global-RNG draw {name}() — construct a seeded "
                    "random.Random / np.random.default_rng(seed)")
        if name.startswith("numpy.random."):
            if last in self.SEEDED_CTORS:
                return self._unseeded_ctor(name, call)
            return (f"module-level numpy RNG draw {name}() — draw from "
                    "a seeded np.random.default_rng(seed) instance")
        return None

    @staticmethod
    def _unseeded_ctor(name: str, call: ast.Call) -> "str | None":
        args = list(call.args) + [k.value for k in call.keywords]
        seedful = [a for a in args
                   if not (isinstance(a, ast.Constant) and a.value is None)]
        if seedful:
            return None
        return (f"{name}() without a seed (or with seed=None) draws OS "
                "entropy — pass an explicit seed")


# ---------------------------------------------------------------------------
# TPL003 — known-cost calls lexically under a lock.
# ---------------------------------------------------------------------------

class WorkUnderLock(Rule):
    """`with <lock>:` bodies must be O(bookkeeping). A call with known
    cost — a fetch join (`.result()`), jit dispatch /
    `block_until_ready`, H2D (`device_put`), byte-store composition,
    sleeps, file/socket I/O — serializes every contender behind work
    that never needed the lock. Lexical heuristic: the call token
    appears inside the with-body (nested `def`/`lambda` bodies are
    excluded — defining a function under a lock is free).
    """

    rule_id = "TPL003"
    title = "known-cost call inside a lock body"
    incident = ("PR 7 review: scheduler_device_bytes scrape summed "
                "store nbytes under _store_lock, stalling Assign "
                "registration behind every Metrics scrape")

    # Shared authority with the whole-program analyses (ISSUE 14):
    # TPL102 propagates the same cost model through the call graph.
    COSTLY = interproc.COSTLY
    COSTLY_BARE = interproc.COSTLY_BARE

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        findings = []
        for node in ast.walk(tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lock_expr = self._lock_expr(node)
            if lock_expr is None:
                continue
            for call, name in self._costly_calls(node.body):
                findings.append(self.finding(
                    relpath, call,
                    f"{name}() under `with {lock_expr}:` — hoist the "
                    "work out of the critical section",
                ))
        return findings

    @staticmethod
    def _lock_expr(node: "ast.With | ast.AsyncWith") -> "str | None":
        for item in node.items:
            for sub in ast.walk(item.context_expr):
                t = terminal_name(sub)
                if t and "lock" in t.lower():
                    return dotted_name(item.context_expr) or t
        return None

    def _costly_calls(
            self, body: "list[ast.stmt]",
    ) -> "Iterator[tuple[ast.Call, str]]":
        stack = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue  # defined, not executed, under the lock
            if isinstance(node, ast.Call):
                t = terminal_name(node.func)
                if t and (
                    (isinstance(node.func, ast.Attribute) and t in self.COSTLY)
                    or (isinstance(node.func, ast.Name)
                        and t in (self.COSTLY | self.COSTLY_BARE))
                ):
                    yield node, t
            stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# TPL004 — inline [0,1] clamps.
# ---------------------------------------------------------------------------

class InlineUnitClamp(Rule):
    """`min(max(v, 0.0), 1.0)` passes NaN straight through (Python
    min/max return the first argument on NaN comparisons), which is
    exactly how a garbage availability annotation once poisoned the
    pressure math — config.clamp01 is the ONE NaN-safe unit-interval
    clamp. Only [0,1]-bounded nestings fire; other min/max range
    clamps (bucket caps, k clamps) are not this bug class.
    """

    rule_id = "TPL004"
    title = "inline [0,1] clamp bypassing config.clamp01"
    incident = ("PR 5 review: NaN slo-target annotations sailed "
                "through naive min/max clamps in kube.py parse paths")

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        findings = []
        for node in ast.walk(tree):
            if self._is_unit_clamp(node):
                findings.append(self.finding(
                    relpath, node,
                    "inline [0,1] clamp — use config.clamp01 "
                    "(NaN-safe, one shared domain contract)",
                ))
        return findings

    @classmethod
    def _is_unit_clamp(cls, node: ast.AST) -> bool:
        outer = cls._minmax(node)
        if outer is None:
            return False
        kind, args = outer
        outer_bound = 1.0 if kind == "min" else 0.0
        inner_kind = "max" if kind == "min" else "min"
        inner_bound = 0.0 if kind == "min" else 1.0
        has_bound = any(is_num(a, outer_bound) for a in args)
        for a in args:
            inner = cls._minmax(a)
            if (inner and inner[0] == inner_kind
                    and any(is_num(ia, inner_bound) for ia in inner[1])
                    and has_bound):
                return True
        return False

    @staticmethod
    def _minmax(node: ast.AST) -> "tuple[str, list[ast.expr]] | None":
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id in ("min", "max") and len(node.args) >= 2
                and not node.keywords):
            return node.func.id, node.args
        return None


# ---------------------------------------------------------------------------
# TPL005 — unnamed threads.
# ---------------------------------------------------------------------------

class UnnamedThread(Rule):
    """tests/conftest.py's thread_leak_check finds leaked workers BY
    NAME ("tpusched" substring): a thread constructed without
    `name="tpusched-..."` is invisible to the leak gate and shows up
    in dumps as `Thread-17 (drive)`. Literal and f-string names must
    prove the prefix; a fully dynamic name expression is accepted
    (can't be proven lexically — the conftest session assertion
    backstops it at runtime).
    """

    rule_id = "TPL005"
    title = "threading.Thread without a tpusched- name"
    incident = ("PR 2/PR 3 thread_leak_check matches by name; unnamed "
                "bench/tool driver threads slipped every leak audit")

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        aliases = import_aliases(tree)
        findings = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = normalize_call(node.func, aliases)
            if name not in ("threading.Thread", "Thread"):
                continue
            if name == "Thread" and aliases.get("Thread") != "threading.Thread":
                continue
            msg = self._check_name_kwarg(node)
            if msg:
                findings.append(self.finding(relpath, node, msg))
        return findings

    @staticmethod
    def _check_name_kwarg(call: ast.Call) -> "str | None":
        kw = next((k for k in call.keywords if k.arg == "name"), None)
        if kw is None:
            return ('threading.Thread(...) without name="tpusched-..." '
                    "— unnamed threads are invisible to thread_leak_check")
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            if not v.value.startswith("tpusched-"):
                return (f'thread name {v.value!r} lacks the "tpusched-" '
                        "prefix thread_leak_check keys on")
            return None
        if isinstance(v, ast.JoinedStr):
            first = v.values[0] if v.values else None
            if (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)
                    and first.value.startswith("tpusched-")):
                return None
            return ('f-string thread name must start with a literal '
                    '"tpusched-" prefix')
        return None  # dynamic expression: runtime backstop applies


# ---------------------------------------------------------------------------
# TPL006 — bench metric direction resolution.
# ---------------------------------------------------------------------------

class BenchMetricDirection(Rule):
    """Every JSON metric line bench.py prints must resolve to a
    better-direction under tools/benchdiff.py's rules — explicit
    `"direction"` key, lower-better unit, or a name pattern — or
    benchdiff silently trends it higher-better and a regression reads
    as an improvement. Checked at the dict-literal level (the shape
    benchdiff parses); a dynamic metric name requires the explicit
    direction key because no pattern can be proven against it.
    """

    rule_id = "TPL006"
    title = "bench metric without a resolvable direction"
    incident = ("PR 8: the *_frac/*_churn families trended as "
                "higher-better until benchdiff grew explicit "
                "direction annotations")

    def applies(self, relpath: str) -> bool:
        return relpath.rsplit("/", 1)[-1] == "bench.py"

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        bd = ctx.benchdiff
        if bd is None:  # no benchdiff in this tree: nothing to resolve against
            return []
        findings = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Dict):
                continue
            fields = self._fields(node)
            if fields is None:
                continue
            name_node, unit, direction_kw = fields
            if direction_kw is not None:
                if (isinstance(direction_kw, ast.Constant)
                        and direction_kw.value not in ("higher", "lower")):
                    findings.append(self.finding(
                        relpath, node,
                        f"direction {direction_kw.value!r} is not "
                        "'higher'|'lower'",
                    ))
                continue
            name = self._static_name(name_node)
            if name is None:
                if unit is not None and unit in bd._LOWER_BETTER_UNITS:
                    continue
                findings.append(self.finding(
                    relpath, node,
                    "dynamic metric name without an explicit "
                    '"direction" key — benchdiff cannot infer its '
                    "better-direction",
                ))
                continue
            if unit is not None and unit in bd._LOWER_BETTER_UNITS:
                continue
            if (bd._HIGHER_BETTER_NAME.search(name)
                    or bd._LOWER_BETTER_NAME.search(name)):
                continue
            findings.append(self.finding(
                relpath, node,
                f"metric {name!r} (unit {unit!r}) resolves to no "
                "benchdiff direction — add \"direction\": "
                "\"higher\"|\"lower\"",
            ))
        return findings

    @staticmethod
    def _fields(node: ast.Dict) -> (
            "tuple[ast.expr | None, str | None, ast.expr | None] | None"):
        """(metric value node, static unit or None, direction value
        node or None) for dicts carrying a "metric" key; None for
        other dicts."""
        name_node = unit = direction = None
        seen_metric = False
        for k, v in zip(node.keys, node.values):
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                continue
            if k.value == "metric":
                seen_metric, name_node = True, v
            elif k.value == "unit":
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    unit = v.value
            elif k.value == "direction":
                direction = v
        if not seen_metric:
            return None
        return name_node, unit, direction

    @staticmethod
    def _static_name(node: "ast.AST | None") -> "str | None":
        """Literal or f-string metric name, formatted values rendered
        as '0' so shape suffixes still pattern-match."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.JoinedStr):
            parts = []
            for v in node.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                else:
                    parts.append("0")
            return "".join(parts)
        return None


# ---------------------------------------------------------------------------
# TPL007 — dict-order-dependent selection.
# ---------------------------------------------------------------------------

class DictOrderSelection(Rule):
    """`next(reversed(d))` reads "newest entry" but actually reads
    "most recently INSERTED OR MOVED" — an LRU hit-touch reorders the
    dict and the selection silently changes meaning. Select by an
    explicit recency field instead; a genuinely-correct use (any
    element acceptable) takes a suppression saying so.
    """

    rule_id = "TPL007"
    title = "next(reversed(...)) dict-order selection"
    incident = ("PR 6 review: the stale-rebase op picked "
                "next(reversed(_stores)) = most-recently-TOUCHED "
                "store, not the newest registered one")

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        findings = []
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "next" and node.args
                    and isinstance(node.args[0], ast.Call)
                    and isinstance(node.args[0].func, ast.Name)
                    and node.args[0].func.id == "reversed"):
                findings.append(self.finding(
                    relpath, node,
                    "next(reversed(...)) selects by dict/sequence "
                    "order — track the intended element explicitly",
                ))
        return findings


# ---------------------------------------------------------------------------
# TPL008 — string-sorting round/seq-shaped keys.
# ---------------------------------------------------------------------------

class StringSortedRounds(Rule):
    """String order puts r100 before r99: any sorted()/.sort() over a
    collection whose name says round/seq/cycle must pass a numeric
    key. Name-token heuristic — `sorted(rounds)` fires,
    `sorted(rounds, key=round_sort_key)` and `sorted(node_names)`
    don't.
    """

    rule_id = "TPL008"
    title = "sorted() on round/seq-shaped keys without a numeric key"
    incident = ("PR 7 review: benchdiff string-sorted round labels, "
                "diffing r100 against r99's predecessor")

    TOKENS = frozenset({"round", "rounds", "seq", "seqs", "rid",
                        "rids", "cycle", "cycles"})

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        findings = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if any(k.arg == "key" for k in node.keywords):
                continue
            target = None
            if (isinstance(node.func, ast.Name) and node.func.id == "sorted"
                    and node.args):
                target = node.args[0]
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "sort" and not node.args):
                target = node.func.value
            if target is None:
                continue
            t = terminal_name(target)
            if t and self.TOKENS & set(t.lower().split("_")):
                findings.append(self.finding(
                    relpath, node,
                    f"sorting {t!r} without key= — string order puts "
                    "r100 < r99; pass a numeric key",
                ))
        return findings


# ---------------------------------------------------------------------------
# TPL009 — trace.DEFAULT / explain.DEFAULT discipline.
# ---------------------------------------------------------------------------

class CollectorDefaultDiscipline(Rule):
    """Injected-collector discipline (PR 4/PR 7 review fixes): spans
    and decision records must land in the collector the caller
    injected, never silently in the process-wide default. The global
    is referenced only (a) in its owning module, (b) as the right arm
    of the documented fallback idiom `injected or MOD.DEFAULT` /
    `x if x is not None else MOD.DEFAULT`, or (c) in the CLI entry
    points that deliberately drive the process default
    (tools/tracez.py, tools/explainz.py).
    """

    rule_id = "TPL009"
    title = "trace/explain DEFAULT outside the fallback idiom"
    incident = ("PR 4 review: make_server(tracer=) spans landed in "
                "trace.DEFAULT instead of the injected ring; PR 7 "
                "mirrored the fix for explain")

    OWNERS = ("tpusched/trace.py", "tpusched/explain.py")
    ENTRY_POINTS = ("tools/tracez.py", "tools/explainz.py")
    MODULES = ("tpusched.trace", "tpusched.explain")

    def applies(self, relpath: str) -> bool:
        return (product_path(relpath)
                and relpath not in self.OWNERS
                and relpath not in self.ENTRY_POINTS)

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        aliases = import_aliases(tree)
        collector_aliases = {
            local for local, full in aliases.items() if full in self.MODULES
        }
        findings = []
        for node in ast.walk(tree):
            if (isinstance(node, ast.ImportFrom) and node.level == 0
                    and node.module in self.MODULES
                    and any(a.name == "DEFAULT" for a in node.names)):
                findings.append(self.finding(
                    relpath, node,
                    f"importing DEFAULT from {node.module} — accept an "
                    "injected collector and fall back with "
                    "`injected or MOD.DEFAULT`",
                ))
                continue
            if not (isinstance(node, ast.Attribute)
                    and node.attr == "DEFAULT"):
                continue
            base = dotted_name(node.value)
            if base is None:
                continue
            head = base.split(".")[0]
            resolved = (base if base in self.MODULES
                        else aliases.get(head) if base == head else None)
            if resolved not in self.MODULES:
                continue
            if self._is_fallback(node, parents):
                continue
            mod = resolved.rsplit(".", 1)[-1]
            findings.append(self.finding(
                relpath, node,
                f"direct {mod}.DEFAULT use — record into the injected "
                "collector (fallback idiom: `injected or "
                f"{mod}.DEFAULT`)",
            ))
        return findings

    @staticmethod
    def _is_fallback(node: ast.AST,
                     parents: "dict[ast.AST, ast.AST]") -> bool:
        p = parents.get(node)
        if isinstance(p, ast.BoolOp) and isinstance(p.op, ast.Or):
            return node in p.values[1:]
        if isinstance(p, ast.IfExp):
            return node is p.orelse
        return False


# ---------------------------------------------------------------------------
# TPL010 — closeable classes must be closed in tests.
# ---------------------------------------------------------------------------

class TestCloseDiscipline(Rule):
    """A test that constructs a closeable tpusched object (Engine,
    HostScheduler, SchedulerClient, ...) and drops it leaks its worker
    threads/channels past the test — the population thread_leak_check
    exists to catch. Heuristic: the bound variable must be close()d,
    enter a `with`, or be handed off to another call in the same test
    function. Tests only; direct-construction assignments only.
    """

    rule_id = "TPL010"
    title = "closeable class never closed in test function"
    incident = ("PR 2 conftest thread_leak_check: leaked fetch "
                "workers from unclosed Engines were the founding "
                "leak class")

    def applies(self, relpath: str) -> bool:
        return (relpath.startswith("tests/")
                and relpath.rsplit("/", 1)[-1].startswith("test_"))

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        closeable = ctx.closeable_classes
        if not closeable:
            return []
        findings = []
        for fn in ast.walk(tree):
            if (isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and fn.name.startswith("test_")):
                findings.extend(self._check_fn(fn, relpath, closeable))
        return findings

    def _check_fn(self, fn: "ast.FunctionDef | ast.AsyncFunctionDef",
                  relpath: str,
                  closeable: "set[str]") -> "list[Finding]":
        candidates = []  # (varname, assign node, class name)
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                cls = terminal_name(node.value.func)
                if cls in closeable:
                    candidates.append((node.targets[0].id, node, cls))
        out = []
        for var, node, cls in candidates:
            if not self._satisfied(fn, var):
                out.append(self.finding(
                    relpath, node,
                    f"{cls}(...) bound to {var!r} is never closed in "
                    "this test — close() it (try/finally), use a "
                    "context manager, or hand it off",
                ))
        return out

    @staticmethod
    def _satisfied(fn: ast.AST, var: str) -> bool:
        for node in ast.walk(fn):
            # x.close / x.stop referenced anywhere (call, addfinalizer,
            # ExitStack.callback, ...).
            if (isinstance(node, ast.Attribute)
                    and node.attr in ("close", "stop", "shutdown")
                    and isinstance(node.value, ast.Name)
                    and node.value.id == var):
                return True
            # `with x`, `with closing(x)`, `with x.something()` ...
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Name) and sub.id == var:
                            return True
            # handed off as an argument: ownership transferred.
            if isinstance(node, ast.Call):
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id == var:
                        return True
        return False


# ---------------------------------------------------------------------------
# TPL011 — carried warm-tableau access discipline.
# ---------------------------------------------------------------------------

class CarriedTableauDiscipline(Rule):
    """The warm-start tableau (kernels.assign.WarmTableau, carried
    across delta cycles as WarmState.tableau inside a DeviceSnapshot
    lineage) is only coherent with the cluster snapshot straight after
    the engine warm path has refreshed its dirty rows — anywhere else
    it is LAST cycle's Filter/Score tables wearing this cycle's shapes,
    and reading it is the stale-state hazard class ISSUE 11 introduces
    (the warm analogue of the TPL007 dict-order bug: silently valid-
    looking, wrong under churn). `.tableau` reads are allowed only in
    the engine warm path and the residency layer; everything else
    consumes SolveResults or the DeviceSnapshot warm counters. A
    deliberate read elsewhere (a debugging tool that accepts staleness)
    takes a suppression whose reason says so.
    """

    rule_id = "TPL011"
    title = "carried warm tableau read outside the engine warm path"
    incident = ("ISSUE 11 (warm-start): tableau cells are only valid "
                "straight after the engine's dirty-row refresh; a "
                "stale read elsewhere solves against last cycle's "
                "Filter/Score tables")

    ALLOWED = frozenset({
        "tpusched/engine.py",
        "tpusched/device_state.py",
        "tpusched/kernels/assign.py",
    })
    ATTRS = frozenset({"tableau"})

    def applies(self, relpath: str) -> bool:
        if relpath in self.ALLOWED:
            return False
        return product_path(relpath) or is_test_path(relpath)

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        findings = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and node.attr in self.ATTRS:
                findings.append(self.finding(
                    relpath, node,
                    f".{node.attr} (the carried warm tableau) read "
                    "outside the engine warm path — consume the "
                    "SolveResult / DeviceSnapshot warm counters "
                    "instead, or suppress with the staleness rationale",
                ))
        return findings


# ---------------------------------------------------------------------------
# TPL1xx — whole-program analyses (round 19, ISSUE 14). These rules run
# over the interprocedural Program index (tpusched/lint/interproc.py):
# per-function summaries + a heuristic call graph with held-lock
# propagation. Each rule reports only findings anchored in the CURRENT
# file, so the engine's per-line suppression/baseline machinery applies
# unchanged, and a cross-module hazard is reported once per involved
# acquisition site.
# ---------------------------------------------------------------------------

class LockOrderCycle(Rule):
    """A cycle in the static lock-order graph is a potential deadlock:
    thread 1 holds A wanting B while thread 2 holds B wanting A — no
    single file shows it, which is why it survives review. Edges come
    from held-lock propagation (a lock acquired anywhere in a function
    transitively callable from a `with`-lock body), so a two-module
    cycle is caught even when neither file nests `with` statements.
    A provably same-instance re-acquisition of a non-reentrant Lock
    (all-self-call chain) is the degenerate one-lock cycle and flags
    too. The checked-in tools/lock_hierarchy.json carries the full
    order; the runtime witness (tpusched/lint/witness.py) cross-checks
    it against observed acquisition orders under tier-1.
    """

    rule_id = "TPL101"
    title = "lock-order cycle (potential deadlock)"
    incident = ("ISSUE 14: ~33 locks across 15 modules; the "
                "_role_lock->_store_lock and session.lock->engine "
                "edges span files no single review pass reads together")

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        prog = ctx.program_view(relpath, src)
        findings = []
        for e in prog.cyclic_edges():
            if e.src_path != relpath:
                continue
            if e.src == e.dst:
                msg = (f"same-instance re-acquisition of non-reentrant "
                       f"{e.src} (via {e.render_chain()}) — guaranteed "
                       "deadlock; split a _locked variant out")
            else:
                cyc = next((c for c in prog.lock_cycles()
                            if e.src in c and e.dst in c), ())
                msg = (f"lock-order cycle: {e.src} -> {e.dst} "
                       f"(via {e.render_chain()}); cycle members: "
                       f"{', '.join(cyc)} — acquire in one global order")
            findings.append(Finding(relpath, e.src_line, self.rule_id, msg))
        return findings


class TransitiveWorkUnderLock(Rule):
    """TPL003 generalized from lexical to whole-program: a known-cost
    call (fetch join, H2D, sleep, I/O, full solve) reached THROUGH a
    function called under a lock serializes every contender exactly
    like a lexical one — it is just invisible to a per-file pass. One
    finding per (rooting call, cost kind), anchored at the call inside
    the `with` body so the suppression (and its mandatory reason)
    lands where the next reader looks.
    """

    rule_id = "TPL102"
    title = "transitive known-cost call under a lock"
    incident = ("ISSUE 14: session.lock delta applies reach device_put "
                "through DeviceSnapshot.apply; PR 7's TPL003 scrape "
                "incident, one call deeper")

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        prog = ctx.program_view(relpath, src)
        findings = []
        seen: "set[tuple[int, str]]" = set()
        for fid in sorted(prog.functions):
            fn = prog.functions[fid]
            if fn.path != relpath:
                continue
            for region in fn.regions:
                lexical = {name for name, _ in region.costly}
                for tfid, (chain, _pure, line) in sorted(
                        prog.region_reach(region).items()):
                    tfn = prog.functions.get(tfid)
                    if tfn is None or len(chain) < 1:
                        continue
                    for cname, _cline in tfn.costly:
                        key = (line, cname)
                        if key in seen or cname in lexical:
                            continue
                        seen.add(key)
                        via = " -> ".join(
                            c.split("::", 1)[-1] for c in chain)
                        findings.append(Finding(
                            relpath, line, self.rule_id,
                            f"call under `with {region.acq.raw}:` "
                            f"transitively reaches {cname}() via {via} "
                            "— hoist the work out of the critical "
                            "section (or suppress with the rationale "
                            "for why the section must cover it)",
                        ))
        return findings


class PerCallJitConstruction(Rule):
    """`jax.jit(...)` constructed inside a per-call function and not
    memoized (module constant, self-attribute, or a memo dict) builds a
    FRESH jit object per invocation: jax's shape-keyed compile cache
    hangs off the jit object, so every call retraces and recompiles —
    the exact compile anomalies ledger.COMPILES attributes
    (`scheduler_cycle_anomalies_total{cause="compile"}`, ROADMAP item
    4). tpusched/ only: bench/profiler scripts construct jits per run
    deliberately.
    """

    rule_id = "TPL103"
    title = "per-call jax.jit construction (retrace hazard)"
    incident = ("ROADMAP item 4 / PR 13 sentinel: p99 spikes traced to "
                "retraces; ring_sig_counts_host recompiled per call")

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("tpusched/") and not is_test_path(relpath)

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        prog = ctx.program_view(relpath, src)
        return [
            Finding(relpath, s.line, self.rule_id,
                    "jax.jit constructed per call — memoize it (module "
                    "constant, self-attribute, or a BOUNDED memo dict) "
                    "so the shape-keyed compile cache survives the call")
            for s in prog.jit_sites
            if s.path == relpath and s.kind == "per_call"
        ]


class UnboundedJitFamily(Rule):
    """A memo-dict jit family (`self._topk_jits[k] = jit(...)`) keyed
    by an unbounded value compiles one XLA program PER DISTINCT KEY —
    an adversarial (or merely diverse) request stream turns the cache
    into a compile treadmill and an executable-memory leak. The key
    must provably flow through a bounding helper (pow2/bucket/cap/
    clamp — directly, or one call-hop up like `_warm_inc_fn(cap)`'s
    callers passing `_frontier_bucket(...)`), or the memo must carry an
    explicit size-cap guard (`len(cache) >= N` eviction).
    """

    rule_id = "TPL104"
    title = "unbounded jit family (no bounding bucket on the memo key)"
    incident = ("ISSUE 14 / ROADMAP item 4: _warm_inc_jits' pow2 caps "
                "are the pattern; _topk_jits keyed by raw k was the "
                "counterexample")

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("tpusched/") and not is_test_path(relpath)

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        prog = ctx.program_view(relpath, src)
        return [
            Finding(relpath, s.line, self.rule_id,
                    f"jit family {s.family} keyed by an unbounded value "
                    "— route the key through a pow2/bucket/cap helper "
                    "or add a size-cap eviction to the memo")
            for s in prog.jit_sites
            if s.path == relpath and s.kind == "family"
            and s.bounded is False
        ]


class JitClosureOverMutableState(Rule):
    """A function handed to jax.jit that reads `self.<attr>` bakes the
    attribute's VALUE in at trace time: later mutation of the engine
    state is silently ignored (stale compile) or, worse, flips the
    traced branch and retraces per call. The repo's discipline is to
    hoist instance state into locals at jit-construction time
    (`cfg = self.config`) so the closure is immutable by construction
    — this rule pins that discipline.
    """

    rule_id = "TPL105"
    title = "jit-wrapped closure reads mutable self state"
    incident = ("ISSUE 14: Engine's local-binding discipline (cfg/mesh "
                "hoisted before the jit'd defs) encoded as a rule")

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("tpusched/") and not is_test_path(relpath)

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: "LintContext",
              parents: "dict[ast.AST, ast.AST]") -> "list[Finding]":
        aliases = import_aliases(tree)
        local_defs: "dict[str, list[ast.AST]]" = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs.setdefault(node.name, []).append(node)
        findings = []
        for call, arg_idx in interproc.iter_jit_calls(tree, aliases):
            if len(call.args) <= arg_idx:
                continue
            fn_arg = call.args[arg_idx]
            bodies: "list[ast.AST]" = []
            if isinstance(fn_arg, ast.Lambda):
                bodies = [fn_arg]
            elif isinstance(fn_arg, ast.Name):
                bodies = local_defs.get(fn_arg.id, [])
            for body in bodies:
                hit = self._self_read(body)
                if hit is not None:
                    findings.append(self.finding(
                        relpath, call,
                        f"jit-wrapped {getattr(fn_arg, 'id', 'lambda')} "
                        f"reads self.{hit} — bind it to a local before "
                        "constructing the jit (trace-time snapshot, "
                        "documented)",
                    ))
                    break
        return findings

    @staticmethod
    def _self_read(fn: ast.AST) -> "str | None":
        for node in ast.walk(fn):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                return node.attr
        return None


RULES = (
    FunctionLevelImport,
    UnseededRandomness,
    WorkUnderLock,
    InlineUnitClamp,
    UnnamedThread,
    BenchMetricDirection,
    DictOrderSelection,
    StringSortedRounds,
    CollectorDefaultDiscipline,
    TestCloseDiscipline,
    CarriedTableauDiscipline,
    LockOrderCycle,
    TransitiveWorkUnderLock,
    PerCallJitConstruction,
    UnboundedJitFamily,
    JitClosureOverMutableState,
    # Kernel dataflow analysis (round 20, ISSUE 15) — defined in
    # kernelflow.py next to the abstract interpreter they read.
    *KERNEL_RULES,
)


def default_rules() -> "list[Rule]":
    return [cls() for cls in RULES]
