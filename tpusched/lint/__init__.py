"""tpuschedlint: the repo's hard-won invariants as enforced AST analysis.

Every rule here descends from a defect class this codebase has already
paid review passes for (round 15, ISSUE 10; incident lineage in
tools/README.md "Static analysis"):

    TPL001  function-level imports in tpusched/ (hot-path import cost)
    TPL002  unseeded randomness / wall-clock in the hash-pinned sim
    TPL003  known-cost calls lexically under a lock
    TPL004  inline [0,1] clamps bypassing config.clamp01
    TPL005  threading.Thread without a tpusched- name
    TPL006  bench.py metric emitted without a resolvable direction
    TPL007  next(reversed(...)) dict-order-dependent selection
    TPL008  sorted() on round/seq-shaped keys without a numeric key
    TPL009  trace.DEFAULT/explain.DEFAULT outside the fallback idiom
    TPL010  closeable class never closed in a test function
    TPL011  carried warm-tableau read outside the engine warm path

Whole-program analyses (round 19, ISSUE 14; call graph + per-function
summaries in tpusched/lint/interproc.py, runtime cross-check in
tpusched/lint/witness.py):

    TPL101  lock-order cycle (potential deadlock)
    TPL102  transitive known-cost call under a lock (TPL003, deep)
    TPL103  per-call jax.jit construction (retrace hazard)
    TPL104  unbounded jit family (no bounding bucket on the memo key)
    TPL105  jit-wrapped closure reads mutable self state

Kernel dataflow analysis (round 20, ISSUE 15; abstract interpreter in
tpusched/lint/kernelflow.py, runtime refuter in tools/padcheck.py):

    TPL201  f32 order-sensitive reduction feeds a commit/compare
            decision (tree shape = width/layout/sharding dependence)
    TPL202  padding-hazardous reduction reachable from a compacted-view
            (_pods_view/frontier) path
    TPL203  scatter-add with non-unique indices and f32 values
            (duplicates apply in unspecified order)
    TPL204  int32 fixed-point sum without a provable overflow bound

Every cross-pod/cross-node reduction site is inventoried in
tools/reduction_ledger.json (exactness class, padding verdict,
sharding-safety note — the artifact ROADMAP item 1 consumes;
regenerate: ``python tools/lint.py --write-ledger``; staleness is a
``tools/check.py`` kernelflow failure, and tools/padcheck.py
differentially executes the sites' enclosing kernels at two bucket
widths to refute bad exactness claims at runtime).

The static lock order is checked in as tools/lock_hierarchy.json
(regenerate: ``python tools/lint.py --write-hierarchy``; staleness is a
``tools/check.py`` lockgraph failure) and validated at runtime by the
lock-order witness tier-1 installs via tests/conftest.py.

Run via ``python tools/lint.py tpusched tools bench.py tests`` (the
tier-1 gate, tests/test_lint.py::test_tree_is_clean) or through
``tools/check.py``. Per-line suppressions:

    expr  # tpl: disable=TPL003(reason is mandatory)

and a JSON baseline file (tools/lint_baseline.json) for grandfathered
findings — kept EMPTY at HEAD; the engine reports TPL000 for a
suppression without a reason so the escape hatch stays documented.
"""

from tpusched.lint.engine import (  # noqa: F401
    Finding,
    LintContext,
    LintEngine,
    load_baseline,
    parse_suppressions,
    write_baseline,
)
from tpusched.lint.rules import RULES, default_rules  # noqa: F401
