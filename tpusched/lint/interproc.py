"""Whole-program analysis substrate for tpuschedlint (round 19, ISSUE 14).

PR 9's rules are lexical and per-file: they prove properties a single
AST shows (a `.result()` token inside a `with ...lock:` body). The
serving stack is now a genuinely concurrent system — ~33 locks across
15 modules — and its real hazards are INTERPROCEDURAL: a blocking call
reached through a function called under a lock, a lock-order cycle
spanning two modules, a jit entry point that silently retraces per
request. This module builds the shared substrate those analyses run on:

  * a per-function summary index over every product file (functions,
    methods, nested defs; the calls they make; the locks they acquire;
    their known-cost calls; their jit construction sites);
  * a heuristic call graph: precise resolution for module functions,
    imports, `self.`/`cls.` methods (through program base classes) and
    locally-inferred receiver types, with a bounded DYNAMIC-DISPATCH
    FALLBACK (an attribute call on an unknown receiver resolves to
    every program function of that name, unless the name is so common
    the resolution would be noise — `_DISPATCH_CAP`);
  * lock identity: every `threading.Lock()`/`Condition()` creation
    site becomes a LockDecl (`path::Class.attr`), and acquisition
    expressions resolve against those decls (self-attr, module global,
    one-hop attribute-type inference, unique-attr fallback);
  * held-lock propagation: for each `with <lock>:` region, the set of
    lock acquisitions and known-cost calls reachable through the call
    graph, each with a shortest witness chain;
  * the lock-order graph (edges + cycles) serialized as the checked-in
    artifact tools/lock_hierarchy.json, which the RUNTIME witness
    (tpusched/lint/witness.py) cross-checks against observed
    acquisition orders under tier-1;
  * jit-boundary analysis: every `jax.jit`/`_traced_jit` site
    classified (module-level / cached attribute / memoized family /
    per-call), with family BOUNDEDNESS proven via bounding-helper key
    flow (pow2/bucket/clamp helpers, one call hop) or an explicit
    size-cap guard on the memo.

Everything is stdlib `ast`, deterministic (sorted outputs, stable
ids), and pure — rules in rules.py turn the results into Findings so
the engine's suppression/baseline machinery applies unchanged.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Any, Iterator, Optional

__all__ = [
    "CallSite", "FunctionInfo", "JitSite", "LockAcq", "LockDecl",
    "LockEdge", "LockRegion", "Program", "scan_product_sources",
    "COSTLY", "COSTLY_BARE",
]

# Known-cost call names (shared authority with TPL003 in rules.py): a
# fetch join, jit dispatch/sync, H2D, byte-store composition, sleeps,
# file/socket I/O, a full solve. Attribute calls match COSTLY; bare
# names additionally match COSTLY_BARE.
COSTLY = frozenset({
    "result", "block_until_ready", "device_put", "sleep",
    "urlopen", "compose_bytes", "serve_forever", "exec_module",
    "solve", "solve_async", "solve_explained", "score_topk",
    "run_until_idle",
})
COSTLY_BARE = frozenset({"open", "sleep"})

#: Dynamic-dispatch fallback cap: an attribute call on an unknown
#: receiver resolves to every program function of that name — unless
#: more than this many share it, in which case the name is too common
#: to carry signal (`close`, `get`, ...) and the call stays unresolved.
_DISPATCH_CAP = 6

#: Methods of the builtin container/scalar types are excluded from the
#: dynamic-dispatch fallback: `ring.append(...)` on a deque must not
#: resolve to ReplicationLog.append — the analysis cannot distinguish
#: builtin receivers, and these names carry no dispatch signal.
_BUILTIN_METHODS = frozenset(
    name
    for t in (list, dict, set, frozenset, tuple, str, bytes, bytearray)
    for name in dir(t) if not name.startswith("_")
) | {
    # deque / queue / lock / thread / file-protocol names: same
    # reasoning — the receiver is overwhelmingly a stdlib primitive
    # the program cannot shadow meaningfully at a dynamic call site.
    "appendleft", "popleft", "rotate", "extendleft",
    "put", "put_nowait", "get_nowait", "task_done", "qsize",
    "acquire", "release", "locked", "notify", "notify_all", "wait",
    "start", "is_alive", "cancel", "set", "is_set",
    "read", "write", "flush", "seek", "readline", "readlines",
    "writelines", "fileno", "tell",
}

#: Functions whose NAME proves their result is a bounded jit-family
#: key (pow2 buckets, caps, clamps). Used by the TPL104 boundedness
#: proof: a memo key produced by one of these (directly, via a local,
#: or one call-hop up through the family function's parameter) keeps
#: the family's compile set finite.
_BOUNDING_NAME = re.compile(r"(bucket|pow2|cap|clamp)", re.IGNORECASE)

_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition"})

#: CapWords (with optional leading underscores): the class-name
#: convention `_ctor_class_name` keys on — `_OrderedFetchWorker(...)`
#: is a constructor call, `make_server(...)` is not.
_CLASS_LIKE = re.compile(r"^_*[A-Z]")


# ---------------------------------------------------------------------------
# Small AST helpers (kept local: this module must not import rules.py,
# which imports it).
# ---------------------------------------------------------------------------

def _dotted(node: ast.AST) -> Optional[str]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _terminal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _module_to_relpath(dotted: str) -> str:
    return dotted.replace(".", "/") + ".py"


def _is_lock_ctor(call: ast.Call, aliases: dict[str, str]) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' when `call` constructs a threading
    primitive (threading.Lock(), Lock() imported from threading, or the
    __import__("threading").Lock() spelling) — else None."""
    func = call.func
    t = _terminal(func)
    if t not in _LOCK_CTORS:
        return None
    if isinstance(func, ast.Name):
        return t if aliases.get(t) == f"threading.{t}" else None
    assert isinstance(func, ast.Attribute)
    base = func.value
    d = _dotted(base)
    if d is not None:
        head = d.split(".")[0]
        if d == "threading" or aliases.get(head, "").startswith("threading"):
            return t
        return None
    # __import__("threading").Lock()
    if (isinstance(base, ast.Call) and isinstance(base.func, ast.Name)
            and base.func.id == "__import__" and base.args
            and isinstance(base.args[0], ast.Constant)
            and base.args[0].value == "threading"):
        return t
    return None


def _file_aliases(tree: ast.Module) -> dict[str, str]:
    """local name -> dotted module/object, module-wide (same contract
    as rules.import_aliases but owned here to avoid an import cycle)."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.level == 0 and node.module:
            for a in node.names:
                if a.name != "*":
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _import_module_names(tree: ast.Module) -> set[str]:
    """Local names that are PROVABLY modules: bound by an `import X`
    / `import X.Y as Z` statement. An attribute chain rooted at one of
    these that does not resolve inside the program is a FOREIGN module
    call (`jnp.linalg.solve`, `subprocess.run`) and must never fall
    through to method dispatch."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out.add(a.asname or a.name.split(".")[0])
    return out


# ---------------------------------------------------------------------------
# Summary records.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True, order=True)
class LockDecl:
    """One `<target> = threading.Lock()` creation site."""

    lock_id: str    # "tpusched/rpc/server.py::DeviceSession.lock"
    path: str       # repo-relative POSIX path
    line: int       # line of the Lock() call (the witness keys on this)
    attr: str       # attribute / global name
    owner: str      # owning class name, "" for module-level
    kind: str       # "Lock" | "RLock" | "Condition"


@dataclasses.dataclass(frozen=True)
class LockAcq:
    """One resolved `with <lock>:` acquisition."""

    decl: LockDecl
    line: int
    raw: str        # source spelling ("self._store_lock")
    via_self: bool  # receiver is `self` (same-instance provable)


@dataclasses.dataclass(frozen=True)
class CallSite:
    line: int
    raw: str                  # rendered target ("self._engine.solve")
    targets: tuple[str, ...]  # resolved function ids (empty: unresolved)
    kind: str                 # "local"|"module"|"import"|"self"|"class"|
    #                           "typed"|"dynamic"|"unresolved"


@dataclasses.dataclass
class LockRegion:
    """One `with <lock>:` body and what happens inside it (nested defs
    excluded — defining a function under a lock is free)."""

    acq: LockAcq
    calls: list[CallSite]
    inner_acqs: list[LockAcq]           # lexically nested acquisitions
    costly: list[tuple[str, int]]       # lexical known-cost calls


@dataclasses.dataclass
class FunctionInfo:
    fid: str                  # "tpusched/engine.py::Engine.solve"
    path: str
    line: int
    cls: Optional[str]
    name: str
    calls: list[CallSite] = dataclasses.field(default_factory=list)
    regions: list[LockRegion] = dataclasses.field(default_factory=list)
    acquires: list[LockAcq] = dataclasses.field(default_factory=list)
    costly: list[tuple[str, int]] = dataclasses.field(default_factory=list)
    #: lock-ish `with` context exprs the analysis could not name —
    #: invisible to TPL101/TPL102 by construction, so they surface in
    #: graph_doc() as the model's known blind spots (the unmodeled-
    #: edge workflow's static counterpart).
    unresolved_locks: list[tuple[str, int]] = dataclasses.field(
        default_factory=list)


@dataclasses.dataclass(frozen=True)
class LockEdge:
    """src is held when dst is acquired. `chain` is the shortest
    witness call chain (function ids) from the holding region to the
    acquiring function; empty = lexically nested in the same region."""

    src: str
    dst: str
    src_path: str
    src_line: int   # line of the call (or inner with) inside the region
    dst_path: str
    dst_line: int   # line of the dst acquisition
    chain: tuple[str, ...]
    self_pure: bool  # every hop a self-call AND both acqs on `self`

    def render_chain(self) -> str:
        if not self.chain:
            return "nested with"
        return " -> ".join(c.split("::", 1)[-1] for c in self.chain)


@dataclasses.dataclass
class JitSite:
    path: str
    line: int
    func: Optional[str]       # enclosing function id (None: module level)
    kind: str                 # "module"|"decorator"|"attr_cache"|
    #                           "family"|"per_call"
    family: Optional[str] = None      # "Engine._topk_jits"
    bounded: Optional[bool] = None    # families only
    bound_via: str = ""


# ---------------------------------------------------------------------------
# Per-module index (pass 1).
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _ClassInfo:
    name: str
    path: str
    bases: tuple[str, ...]
    methods: dict[str, ast.AST]
    attr_types: dict[str, str]   # self.attr -> program class name
    lock_attrs: dict[str, LockDecl]


@dataclasses.dataclass
class _ModuleInfo:
    path: str
    tree: ast.Module
    aliases: dict[str, str]
    module_aliases: set[str]           # names bound by `import X [as Y]`
    classes: dict[str, _ClassInfo]
    functions: dict[str, ast.AST]      # module-level defs
    global_locks: dict[str, LockDecl]


def scan_product_sources(root: Path) -> dict[str, str]:
    """The whole-program file set: tpusched/**, tools/*, bench.py —
    the same non-test product surface the per-file rules gate."""
    out: dict[str, str] = {}
    for sub in ("tpusched", "tools"):
        base = root / sub
        if base.is_dir():
            for p in sorted(base.rglob("*.py")):
                out[p.relative_to(root).as_posix()] = p.read_text()
    bench = root / "bench.py"
    if bench.is_file():
        out["bench.py"] = bench.read_text()
    return out


class Program:
    """The whole-program index + analyses (module docstring)."""

    def __init__(self, sources: dict[str, str]):
        self.sources = dict(sources)
        self.modules: dict[str, _ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.locks: dict[str, LockDecl] = {}
        #: attr/global name -> decls sharing it (unique-attr fallback)
        self._locks_by_attr: dict[str, list[LockDecl]] = {}
        #: function name -> fids (any kind; debugging/report surface)
        self._by_name: dict[str, list[str]] = {}
        #: method name -> fids (the dynamic-dispatch fallback index)
        self._methods_by_name: dict[str, list[str]] = {}
        #: fid -> its AST node (return-type inference)
        self._fn_nodes: dict[str, ast.AST] = {}
        #: class name -> _ClassInfo (assumed unique program-wide)
        self._classes: dict[str, _ClassInfo] = {}
        self.jit_sites: list[JitSite] = []
        self._edges: Optional[list[LockEdge]] = None
        for path in sorted(self.sources):
            self._index_module(path, self.sources[path])
        # Name registration is a PRE-pass: dynamic dispatch during
        # summarization must see every program function, not just the
        # alphabetically-earlier modules'.
        for path in sorted(self.modules):
            self._register_names(self.modules[path])
        for path in sorted(self.modules):
            self._summarize_module(self.modules[path])
        self._jit_pass()

    def has(self, relpath: str, src: str) -> bool:
        return self.sources.get(relpath) == src

    # -- pass 1: declarations -------------------------------------------

    def _index_module(self, path: str, src: str) -> None:
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            return
        aliases = _file_aliases(tree)
        mod = _ModuleInfo(path=path, tree=tree, aliases=aliases,
                          module_aliases=_import_module_names(tree),
                          classes={}, functions={}, global_locks={})
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                mod.classes[node.name] = self._index_class(path, node, aliases)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
                if (isinstance(tgt, ast.Name) and isinstance(val, ast.Call)):
                    kind = _is_lock_ctor(val, aliases)
                    if kind:
                        decl = LockDecl(
                            lock_id=f"{path}::{tgt.id}", path=path,
                            line=val.lineno, attr=tgt.id, owner="",
                            kind=kind,
                        )
                        mod.global_locks[tgt.id] = decl
                        self._add_lock(decl)
        self.modules[path] = mod
        for cname, cinfo in mod.classes.items():
            # First definition wins; program class names are unique in
            # practice and determinism beats cleverness here.
            self._classes.setdefault(cname, cinfo)

    def _index_class(self, path: str, node: ast.ClassDef,
                     aliases: dict[str, str]) -> _ClassInfo:
        bases = tuple(b for b in (_terminal(x) for x in node.bases)
                      if b is not None)
        info = _ClassInfo(name=node.name, path=path, bases=bases,
                          methods={}, attr_types={}, lock_attrs={})
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods[item.name] = item
                self._scan_self_assigns(path, info, item, aliases)
        return info

    def _scan_self_assigns(self, path: str, info: _ClassInfo,
                           fn: ast.AST, aliases: dict[str, str]) -> None:
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt, val = node.targets[0], node.value
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            ctor = self._value_ctor(val)
            if ctor is None:
                continue
            val = ctor
            kind = _is_lock_ctor(val, aliases)
            if kind:
                decl = LockDecl(
                    lock_id=f"{path}::{info.name}.{tgt.attr}", path=path,
                    line=val.lineno, attr=tgt.attr, owner=info.name,
                    kind=kind,
                )
                info.lock_attrs[tgt.attr] = decl
                self._add_lock(decl)
                continue
            cls = self._ctor_class_name(val)
            if cls is not None:
                prev = info.attr_types.get(tgt.attr)
                if prev is None:
                    info.attr_types[tgt.attr] = cls
                elif prev != cls:
                    info.attr_types[tgt.attr] = "?"  # conflicting: drop

    @staticmethod
    def _value_ctor(val: ast.AST) -> Optional[ast.Call]:
        """The constructor call inside an assignment value, seeing
        through the injected-or-default idioms: `D(...)`,
        `injected or D(...)`, `x if x is not None else D(...)` — the
        fallback arm pins the type the injected object must share."""
        if isinstance(val, ast.Call):
            return val
        if (isinstance(val, ast.BoolOp) and isinstance(val.op, ast.Or)
                and isinstance(val.values[-1], ast.Call)):
            return val.values[-1]
        if isinstance(val, ast.IfExp):
            arms = [a for a in (val.body, val.orelse)
                    if isinstance(a, ast.Call)]
            if len(arms) == 1:
                return arms[0]
        return None

    @staticmethod
    def _ctor_class_name(call: ast.Call) -> Optional[str]:
        """`D(...)` -> D; `D.from_x(...)` -> D (alternate-constructor
        idiom). Resolution against program classes happens at use."""
        f = call.func
        if isinstance(f, ast.Name) and _CLASS_LIKE.match(f.id):
            return f.id
        if (isinstance(f, ast.Attribute) and f.attr.startswith("from_")
                and isinstance(f.value, ast.Name)
                and _CLASS_LIKE.match(f.value.id)):
            return f.value.id
        return None

    def _add_lock(self, decl: LockDecl) -> None:
        self.locks[decl.lock_id] = decl
        self._locks_by_attr.setdefault(decl.attr, []).append(decl)

    # -- pass 1.5: function-name index ----------------------------------

    def _register_names(self, mod: _ModuleInfo) -> None:
        def reg_tree(fid: str, fn: ast.AST) -> None:
            self._by_name.setdefault(getattr(fn, "name", "?"), []).append(fid)
            self._fn_nodes[fid] = fn
            for n in ast.walk(fn):
                if (isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and n is not fn):
                    self._by_name.setdefault(n.name, []).append(
                        f"{fid}.{n.name}")
                    self._fn_nodes.setdefault(f"{fid}.{n.name}", n)

        for name, fn in sorted(mod.functions.items()):
            reg_tree(f"{mod.path}::{name}", fn)
        for cname, cinfo in sorted(mod.classes.items()):
            for mname, meth in sorted(cinfo.methods.items()):
                reg_tree(f"{mod.path}::{cname}.{mname}", meth)
                # Attribute calls can only land on METHODS: the
                # dynamic-dispatch fallback must not resolve `x.f()` to
                # a module function or a nested def.
                self._methods_by_name.setdefault(mname, []).append(
                    f"{mod.path}::{cname}.{mname}")

    # -- pass 2: per-function summaries ---------------------------------

    def _summarize_module(self, mod: _ModuleInfo) -> None:
        for name, fn in sorted(mod.functions.items()):
            self._summarize_function(mod, None, f"{mod.path}::{name}", fn)
        for cname, cinfo in sorted(mod.classes.items()):
            for mname, meth in sorted(cinfo.methods.items()):
                self._summarize_function(
                    mod, cinfo, f"{mod.path}::{cname}.{mname}", meth)

    def _summarize_function(self, mod: _ModuleInfo,
                            cinfo: Optional[_ClassInfo], fid: str,
                            fn: ast.AST) -> None:
        info = FunctionInfo(fid=fid, path=mod.path,
                            line=getattr(fn, "lineno", 1),
                            cls=cinfo.name if cinfo else None,
                            name=getattr(fn, "name", "?"))
        local_types = self._infer_local_types(fn)
        nested = {n.name: f"{fid}.{n.name}"
                  for n in ast.walk(fn)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and n is not fn}
        env = _ResolveEnv(self, mod, cinfo, local_types, nested)

        body: list[ast.AST] = list(ast.iter_child_nodes(fn))
        self._walk_body(body, info, env, region_stack=[])
        self.functions[fid] = info
        # Nested defs become their own (callable-by-name) functions.
        for n in ast.walk(fn):
            if (isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and n is not fn and "." not in getattr(n, "name", "")):
                nfid = nested[n.name]
                if nfid not in self.functions:
                    self._summarize_function(mod, cinfo, nfid, n)

    def _infer_local_types(self, fn: ast.AST) -> dict[str, str]:
        """Single-assignment local var -> program class name (from
        `v = D(...)` / `v = D.from_x(...)`); conflicts drop out."""
        types: dict[str, str] = {}
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                ctor = self._value_ctor(node.value)
                if ctor is None:
                    continue
                cls = self._ctor_class_name(ctor)
                name = node.targets[0].id
                if cls is not None:
                    types[name] = "?" if types.get(name, cls) != cls else cls
        return {k: v for k, v in types.items() if v != "?"}

    def _walk_body(self, nodes: list[ast.AST], info: FunctionInfo,
                   env: "_ResolveEnv",
                   region_stack: list[LockRegion]) -> None:
        """Collect calls / acquisitions / costly calls, attributing them
        to every enclosing lock region. Nested function/class bodies are
        NOT executed here (their own summaries cover them)."""
        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                self._walk_with(node, info, env, region_stack)
                continue
            if isinstance(node, ast.Call):
                self._note_call(node, info, env, region_stack)
            self._walk_body(list(ast.iter_child_nodes(node)), info, env,
                            region_stack)

    def _walk_with(self, node: ast.AST, info: FunctionInfo,
                   env: "_ResolveEnv",
                   region_stack: list[LockRegion]) -> None:
        opened: list[LockRegion] = []
        for item in node.items:  # type: ignore[attr-defined]
            # The context expression itself runs under the OUTER locks.
            if isinstance(item.context_expr, ast.Call):
                self._note_call(item.context_expr, info, env, region_stack)
            else:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call):
                        self._note_call(sub, info, env, region_stack)
            acq, raw = env.resolve_lock(item.context_expr)
            if acq is not None:
                info.acquires.append(acq)
                for r in region_stack:
                    r.inner_acqs.append(acq)
                region = LockRegion(acq=acq, calls=[], inner_acqs=[],
                                    costly=[])
                info.regions.append(region)
                region_stack.append(region)
                opened.append(region)
            elif raw is not None:
                info.unresolved_locks.append(
                    (raw, item.context_expr.lineno))
        self._walk_body(list(node.body), info, env,  # type: ignore[attr-defined]
                        region_stack)
        for region in opened:
            region_stack.remove(region)

    def _note_call(self, call: ast.Call, info: FunctionInfo,
                   env: "_ResolveEnv",
                   region_stack: list[LockRegion]) -> None:
        cs = env.resolve_call(call)
        if cs is not None:
            info.calls.append(cs)
            for r in region_stack:
                r.calls.append(cs)
        t = _terminal(call.func)
        if t and ((isinstance(call.func, ast.Attribute) and t in COSTLY)
                  or (isinstance(call.func, ast.Name)
                      and t in (COSTLY | COSTLY_BARE))):
            info.costly.append((t, call.lineno))
            for r in region_stack:
                r.costly.append((t, call.lineno))

    # -- dynamic dispatch -----------------------------------------------

    def dispatch(self, name: str) -> tuple[str, ...]:
        """Dynamic-dispatch fallback: every program METHOD named
        `name`, or () when the name is a builtin/stdlib-protocol method
        or more than _DISPATCH_CAP program methods share it (too common
        to carry signal) or none do."""
        if name in _BUILTIN_METHODS:
            return ()
        fids = self._methods_by_name.get(name, ())
        if 0 < len(fids) <= _DISPATCH_CAP:
            return tuple(sorted(fids))
        return ()

    def class_info(self, name: str) -> Optional[_ClassInfo]:
        return self._classes.get(name)

    def method_of(self, cls: str, name: str) -> Optional[str]:
        """Resolve cls.name through the program base-class chain."""
        seen: set[str] = set()
        stack = [cls]
        while stack:
            c = stack.pop(0)
            if c in seen:
                continue
            seen.add(c)
            ci = self._classes.get(c)
            if ci is None:
                continue
            if name in ci.methods:
                return f"{ci.path}::{ci.name}.{name}"
            stack.extend(ci.bases)
        return None

    def lock_attr_of(self, cls: str, attr: str) -> Optional[LockDecl]:
        seen: set[str] = set()
        stack = [cls]
        while stack:
            c = stack.pop(0)
            if c in seen:
                continue
            seen.add(c)
            ci = self._classes.get(c)
            if ci is None:
                continue
            if attr in ci.lock_attrs:
                return ci.lock_attrs[attr]
            stack.extend(ci.bases)
        return None

    def unique_lock_attr(self, attr: str) -> Optional[LockDecl]:
        decls = self._locks_by_attr.get(attr, [])
        return decls[0] if len(decls) == 1 else None

    # -- held-lock reachability -----------------------------------------

    def _reach(self, roots: tuple[str, ...]) -> dict[
            str, tuple[tuple[str, ...], bool]]:
        """BFS over the call graph from `roots`: fid -> (shortest chain
        of fids ending at fid, chain is all-self-calls). Deterministic:
        sorted expansion, first (shortest) chain wins."""
        out: dict[str, tuple[tuple[str, ...], bool]] = {}
        frontier: list[tuple[str, tuple[str, ...], bool]] = [
            (r, (r,), True) for r in sorted(roots)
        ]
        while frontier:
            nxt: list[tuple[str, tuple[str, ...], bool]] = []
            for fid, chain, pure in frontier:
                if fid in out:
                    continue
                out[fid] = (chain, pure)
                fn = self.functions.get(fid)
                if fn is None:
                    continue
                for cs in fn.calls:
                    hop_pure = pure and cs.kind == "self"
                    for tgt in cs.targets:
                        if tgt not in out:
                            nxt.append((tgt, chain + (tgt,), hop_pure))
            frontier = sorted(nxt)
        return out

    def region_reach(self, region: LockRegion) -> dict[
            str, tuple[tuple[str, ...], bool, int]]:
        """Functions reachable from the region's calls: fid ->
        (chain, self_pure, line of the region call that roots it)."""
        out: dict[str, tuple[tuple[str, ...], bool, int]] = {}
        for cs in sorted(region.calls, key=lambda c: c.line):
            if not cs.targets:
                continue
            reach = self._reach(cs.targets)
            for fid, (chain, pure) in reach.items():
                if fid not in out or len(chain) < len(out[fid][0]):
                    out[fid] = (chain, pure and cs.kind == "self", cs.line)
        return out

    # -- lock-order edges -----------------------------------------------

    def lock_edges(self) -> list[LockEdge]:
        if self._edges is not None:
            return self._edges
        edges: dict[tuple[str, str], LockEdge] = {}

        def consider(e: LockEdge) -> None:
            k = (e.src, e.dst)
            old = edges.get(k)
            if (old is None or len(e.chain) < len(old.chain)
                    or (len(e.chain) == len(old.chain)
                        and (e.src_path, e.src_line)
                        < (old.src_path, old.src_line))):
                edges[k] = e

        for fid in sorted(self.functions):
            fn = self.functions[fid]
            for region in fn.regions:
                src = region.acq.decl
                for inner in region.inner_acqs:
                    consider(LockEdge(
                        src=src.lock_id, dst=inner.decl.lock_id,
                        src_path=fn.path, src_line=inner.line,
                        dst_path=fn.path, dst_line=inner.line,
                        chain=(),
                        self_pure=(region.acq.via_self and inner.via_self
                                   and src.owner == inner.decl.owner),
                    ))
                for tfid, (chain, pure, call_line) in sorted(
                        self.region_reach(region).items()):
                    tfn = self.functions.get(tfid)
                    if tfn is None:
                        continue
                    for acq in tfn.acquires:
                        consider(LockEdge(
                            src=src.lock_id, dst=acq.decl.lock_id,
                            src_path=fn.path, src_line=call_line,
                            dst_path=tfn.path, dst_line=acq.line,
                            chain=chain,
                            self_pure=(pure and region.acq.via_self
                                       and acq.via_self
                                       and src.owner == acq.decl.owner),
                        ))
        self._edges = sorted(
            edges.values(), key=lambda e: (e.src, e.dst))
        return self._edges

    def lock_cycles(self) -> list[tuple[str, ...]]:
        """Cycles in the lock-order graph, as sorted lock-id tuples:
        multi-lock strongly connected components, plus self-edges whose
        witness path proves the SAME instance re-acquires (all-self
        chains on a non-reentrant Lock)."""
        adj: dict[str, set[str]] = {}
        for e in self.lock_edges():
            if e.src != e.dst:
                adj.setdefault(e.src, set()).add(e.dst)
        sccs = _tarjan(adj)
        out = [tuple(sorted(c)) for c in sccs if len(c) > 1]
        for e in self.lock_edges():
            if (e.src == e.dst and e.self_pure
                    and self.locks[e.src].kind == "Lock"):
                out.append((e.src,))
        return sorted(set(out))

    def cyclic_edges(self) -> list[LockEdge]:
        """Edges participating in a cycle (both endpoints in one SCC,
        or a proven self-edge)."""
        in_cycle = {c for cyc in self.lock_cycles() for c in cyc
                    if len(cyc) > 1}
        selfs = {cyc[0] for cyc in self.lock_cycles() if len(cyc) == 1}
        out = []
        for e in self.lock_edges():
            if e.src in in_cycle and e.dst in in_cycle and e.src != e.dst:
                out.append(e)
            elif e.src == e.dst and e.src in selfs and e.self_pure:
                out.append(e)
        return out

    def hierarchy_doc(self) -> dict[str, Any]:
        """The checked-in tools/lock_hierarchy.json payload: every lock
        creation site + every static order edge (with witness chains),
        and any cycles. The runtime witness keys locks by (path, line)
        and checks observed orders against `edges`."""
        return {
            "version": 1,
            "locks": [
                dataclasses.asdict(self.locks[k])
                for k in sorted(self.locks)
            ],
            "edges": [
                {
                    "src": e.src, "dst": e.dst,
                    "via": e.render_chain(),
                    "site": f"{e.src_path}:{e.src_line}",
                    "acquired_at": f"{e.dst_path}:{e.dst_line}",
                }
                for e in self.lock_edges()
            ],
            "cycles": [list(c) for c in self.lock_cycles()],
        }

    # -- jit-boundary analysis ------------------------------------------

    def _jit_pass(self) -> None:
        for path in sorted(self.modules):
            mod = self.modules[path]
            self.jit_sites.extend(_JitScanner(self, mod).scan())
        self.jit_sites.sort(key=lambda s: (s.path, s.line))

    def unbounded_families(self) -> list[JitSite]:
        return [s for s in self.jit_sites
                if s.kind == "family" and s.bounded is False]

    def graph_doc(self) -> dict[str, Any]:
        """`tools/lint.py --graph` payload: per-function call targets +
        held-lock regions, for debugging the analyses."""
        funcs = {}
        for fid in sorted(self.functions):
            fn = self.functions[fid]
            funcs[fid] = {
                "calls": [
                    {"line": c.line, "raw": c.raw, "kind": c.kind,
                     "targets": list(c.targets)}
                    for c in sorted(fn.calls, key=lambda c: c.line)
                ],
                "acquires": [
                    {"line": a.line, "lock": a.decl.lock_id}
                    for a in fn.acquires
                ],
                "regions": [
                    {"lock": r.acq.decl.lock_id, "line": r.acq.line,
                     "reaches": sorted(
                         lk.lock_id for lk in self._region_lock_set(r))}
                    for r in fn.regions
                ],
            }
            if fn.unresolved_locks:
                funcs[fid]["unresolved_locks"] = [
                    {"raw": raw, "line": line}
                    for raw, line in fn.unresolved_locks
                ]
        return {"functions": funcs, "locks": sorted(self.locks),
                "jit_sites": [dataclasses.asdict(s) for s in self.jit_sites]}

    def _region_lock_set(self, region: LockRegion) -> list[LockDecl]:
        out = {a.decl.lock_id: a.decl for a in region.inner_acqs}
        for tfid in self.region_reach(region):
            tfn = self.functions.get(tfid)
            if tfn:
                for a in tfn.acquires:
                    out[a.decl.lock_id] = a.decl
        return [out[k] for k in sorted(out)]


# ---------------------------------------------------------------------------
# Resolution environment (one function's scope).
# ---------------------------------------------------------------------------

class _ResolveEnv:
    def __init__(self, program: Program, mod: _ModuleInfo,
                 cinfo: Optional[_ClassInfo],
                 local_types: dict[str, str],
                 nested: dict[str, str]):
        self.program = program
        self.mod = mod
        self.cinfo = cinfo
        self.local_types = local_types
        self.nested = nested

    # -- calls ----------------------------------------------------------

    def resolve_call(self, call: ast.Call) -> Optional[CallSite]:
        p = self.program
        raw = _dotted(call.func)
        if raw is None:
            # `self._pool().submit(...)`: the receiver is itself a call
            # — try return-type inference, then dynamic dispatch.
            t = _terminal(call.func)
            if t is None or not isinstance(call.func, ast.Attribute):
                return None
            rc = self._receiver_class(call.func.value)
            if rc is not None:
                tgt0 = p.method_of(rc, t)
                if tgt0 is not None:
                    return CallSite(call.lineno, f"(...).{t}", (tgt0,),
                                    "typed")
            dyn0 = p.dispatch(t)
            return CallSite(call.lineno, f"(...).{t}", dyn0,
                            "dynamic" if dyn0 else "unresolved")
        line = call.lineno
        # bare name: nested def, module function, imported object,
        # program class constructor
        if isinstance(call.func, ast.Name):
            name = call.func.id
            if name in self.nested:
                return CallSite(line, raw, (self.nested[name],), "local")
            if name in self.mod.functions:
                return CallSite(line, raw, (f"{self.mod.path}::{name}",),
                                "module")
            if name in self.mod.classes:
                init = p.method_of(name, "__init__")
                return CallSite(line, raw, (init,) if init else (), "class")
            full = self.mod.aliases.get(name)
            if full is not None:
                tgt = self._resolve_imported(full)
                if tgt is not None:
                    return CallSite(line, raw, tgt, "import")
            ci = p.class_info(name)
            if ci is not None:
                init = p.method_of(name, "__init__")
                return CallSite(line, raw, (init,) if init else (), "class")
            return CallSite(line, raw, (), "unresolved")
        # attribute call
        assert isinstance(call.func, ast.Attribute)
        meth = call.func.attr
        recv = call.func.value
        recv_cls = self._receiver_class(recv)
        if recv_cls is not None:
            kind = ("self" if isinstance(recv, ast.Name)
                    and recv.id in ("self", "cls") else "typed")
            tgt2 = p.method_of(recv_cls, meth)
            if tgt2 is not None:
                return CallSite(line, raw, (tgt2,), kind)
            # fall through: a method the class gets dynamically
        d = _dotted(recv)
        if d is not None:
            # module attribute: tpusched.engine.solve_core style
            head = d.split(".")[0]
            full = self.mod.aliases.get(head)
            base = d if head == d else None
            dotted_mod = (full + d[len(head):]) if full else (base or d)
            relpath = _module_to_relpath(dotted_mod)
            m = p.modules.get(relpath)
            if m is not None:
                if meth in m.functions:
                    return CallSite(line, raw, (f"{relpath}::{meth}",),
                                    "import")
                if meth in m.classes:
                    init = p.method_of(meth, "__init__")
                    return CallSite(line, raw, (init,) if init else (),
                                    "class")
                # The receiver IS a module: `tracing.frob(...)` names a
                # module function we don't know — method dispatch must
                # not guess (`subprocess.run` -> SimDriver.run).
                return CallSite(line, raw, (), "unresolved")
            if head in self.mod.module_aliases:
                # The chain is rooted at an `import X`-bound name and
                # did not resolve to a program module above, so the
                # whole receiver subtree is FOREIGN (`jnp.linalg`,
                # `subprocess`) — never method dispatch. Program-module
                # ATTRIBUTES (`tracing.DEFAULT.record` via `from
                # tpusched import trace as tracing`) keep the fallback:
                # their head is not an `import X` binding.
                return CallSite(line, raw, (), "unresolved")
        dyn = p.dispatch(meth)
        if dyn:
            return CallSite(line, raw, dyn, "dynamic")
        return CallSite(line, raw, (), "unresolved")

    def _return_class(self, call: ast.Call) -> Optional[str]:
        """Return type of a single-target program call, when every
        `return` provably yields one program class (`self._pool()` ->
        _OrderedFetchWorker via `return self._fetch_pool`)."""
        cs = self.resolve_call(call)
        if cs is None or len(cs.targets) != 1:
            return None
        fid = cs.targets[0]
        node = self.program._fn_nodes.get(fid)
        if node is None:
            return None
        owner_ci = None
        tail = fid.split("::", 1)[-1]
        if "." in tail:
            owner_ci = self.program.class_info(tail.split(".")[0])
        local_types = self.program._infer_local_types(node)
        classes: set[str] = set()
        for n in ast.walk(node):
            if not isinstance(n, ast.Return) or n.value is None:
                continue
            v = n.value
            if (isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "self" and owner_ci is not None):
                t = owner_ci.attr_types.get(v.attr)
                if t and t != "?":
                    classes.add(t)
                    continue
            elif isinstance(v, ast.Call):
                t2 = Program._ctor_class_name(v)
                if t2 is not None:
                    classes.add(t2)
                    continue
            elif isinstance(v, ast.Name) and v.id in local_types:
                classes.add(local_types[v.id])
                continue
            return None  # a return we can't type: give up
        if len(classes) == 1:
            cls = classes.pop()
            return cls if self.program.class_info(cls) else None
        return None

    def _resolve_imported(self, full: str) -> Optional[tuple[str, ...]]:
        """'tpusched.engine.solve_core' -> the program function, or a
        class -> its __init__."""
        if "." not in full:
            return None
        modpart, _, name = full.rpartition(".")
        relpath = _module_to_relpath(modpart)
        m = self.program.modules.get(relpath)
        if m is None:
            return None
        if name in m.functions:
            return (f"{relpath}::{name}",)
        if name in m.classes:
            init = self.program.method_of(name, "__init__")
            return (init,) if init else ()
        return None

    def _receiver_class(self, recv: ast.AST) -> Optional[str]:
        """Program class of a call/lock receiver expression, when
        inferable: self/cls, a typed local, a class reference, a typed
        self-attribute, or the return type of a typed-returning
        program method (`self._pool().submit`)."""
        if isinstance(recv, ast.Name):
            if recv.id in ("self", "cls") and self.cinfo is not None:
                return self.cinfo.name
            lt = self.local_types.get(recv.id)
            if lt is not None:
                return lt
            # `TraceCollector.record(...)`-style class-attr calls.
            if _CLASS_LIKE.match(recv.id) and (
                    self.program.class_info(recv.id) is not None):
                return recv.id
            return None
        if isinstance(recv, ast.Call):
            return self._return_class(recv)
        if (isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)):
            if recv.value.id == "self" and self.cinfo is not None:
                cls = self.cinfo.attr_types.get(recv.attr)
                if cls is not None and cls != "?":
                    return cls if self.program.class_info(cls) else None
            v = self.local_types.get(recv.value.id)
            if v is not None:
                ci = self.program.class_info(v)
                if ci is not None:
                    cls2 = ci.attr_types.get(recv.attr)
                    if cls2 and cls2 != "?":
                        return cls2
        return None

    # -- locks ----------------------------------------------------------

    def resolve_lock(self, expr: ast.AST) -> tuple[
            Optional[LockAcq], Optional[str]]:
        """(resolved acquisition, raw lock-ish spelling). (None, raw)
        for a lock-looking context expr we cannot name; (None, None)
        for non-lock context managers."""
        t = _terminal(expr)
        raw = _dotted(expr) or (t or "?")
        p = self.program
        looks_lockish = t is not None and (
            "lock" in t.lower() or t in ("_cv",)
            or any(d.attr == t for d in p.locks.values()))
        if t is None or not looks_lockish:
            return None, None
        # bare global
        if isinstance(expr, ast.Name):
            decl = self.mod.global_locks.get(t)
            if decl is None:
                decl = p.unique_lock_attr(t)
            if decl is not None:
                return LockAcq(decl, expr.lineno, raw, False), None
            return None, raw
        if not isinstance(expr, ast.Attribute):
            return None, raw
        recv = expr.value
        via_self = isinstance(recv, ast.Name) and recv.id == "self"
        recv_cls = self._receiver_class(recv)
        if recv_cls is not None:
            decl = p.lock_attr_of(recv_cls, t)
            if decl is not None:
                return LockAcq(decl, expr.lineno, raw, via_self), None
        decl = p.unique_lock_attr(t)
        if decl is not None:
            return LockAcq(decl, expr.lineno, raw,
                           via_self and decl.owner != ""
                           and self.cinfo is not None
                           and decl.owner == self.cinfo.name), None
        return None, raw


# ---------------------------------------------------------------------------
# Jit-boundary scanner.
# ---------------------------------------------------------------------------

class _JitScanner:
    """Classify every jax.jit / Engine._traced_jit construction site in
    one module (class docstring of Program; consumed by TPL103/104/105)."""

    def __init__(self, program: Program, mod: _ModuleInfo):
        self.program = program
        self.mod = mod
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def _is_jit_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        d = _dotted(node.func)
        if d is None:
            return False
        head = d.split(".")[0]
        norm = d
        if head in self.mod.aliases:
            rest = d[len(head):]
            norm = self.mod.aliases[head] + rest
        return (norm == "jax.jit" or norm.endswith("._traced_jit")
                or d.endswith("._traced_jit"))

    def scan(self) -> list[JitSite]:
        out: list[JitSite] = []
        for node in ast.walk(self.mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._is_jit_decorator(dec):
                        out.append(JitSite(
                            path=self.mod.path, line=node.lineno,
                            func=None, kind="decorator"))
            if self._is_jit_call(node):
                out.append(self._classify(node))  # type: ignore[arg-type]
        return out

    def _is_jit_decorator(self, dec: ast.AST) -> bool:
        d = _dotted(dec) or (
            _dotted(dec.func) if isinstance(dec, ast.Call) else None)
        if d is None:
            return False
        head = d.split(".")[0]
        if head in self.mod.aliases:
            d = self.mod.aliases[head] + d[len(head):]
        return d == "jax.jit"

    def _enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        p = self.parents.get(node)
        while p is not None:
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return p
            p = self.parents.get(p)
        return None

    def _enclosing_fid(self, fn: ast.AST) -> Optional[str]:
        name = getattr(fn, "name", None)
        if name is None:
            return None
        p = self.parents.get(fn)
        while p is not None:
            if isinstance(p, ast.ClassDef):
                return f"{self.mod.path}::{p.name}.{name}"
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                outer = self._enclosing_fid(p)
                return f"{outer}.{name}" if outer else None
            p = self.parents.get(p)
        return f"{self.mod.path}::{name}"

    def _classify(self, call: ast.Call) -> JitSite:
        fn = self._enclosing_function(call)
        if fn is None:
            return JitSite(path=self.mod.path, line=call.lineno,
                           func=None, kind="module")
        fid = self._enclosing_fid(fn)
        # What does the jit value land in?
        assign = self.parents.get(call)
        targets: list[ast.AST] = []
        if isinstance(assign, ast.Assign) and assign.value is call:
            targets = list(assign.targets)
        elif (isinstance(assign, ast.AnnAssign)
              and assign.value is call and assign.target is not None):
            targets = [assign.target]
        family_t = next((t for t in targets
                         if isinstance(t, ast.Subscript)), None)
        attr_t = next((t for t in targets
                       if isinstance(t, ast.Attribute)
                       and isinstance(t.value, ast.Name)
                       and t.value.id == "self"), None)
        name_t = next((t for t in targets if isinstance(t, ast.Name)), None)
        if family_t is None and name_t is not None:
            family_t = self._later_store(fn, name_t.id, call.lineno)
        if family_t is not None:
            fam = _dotted(family_t.value) or "?"
            bounded, via = self._family_bounded(fn, family_t)
            return JitSite(path=self.mod.path, line=call.lineno, func=fid,
                           kind="family", family=fam, bounded=bounded,
                           bound_via=via)
        if attr_t is not None:
            return JitSite(path=self.mod.path, line=call.lineno, func=fid,
                           kind="attr_cache",
                           family=f"self.{attr_t.attr}")
        return JitSite(path=self.mod.path, line=call.lineno, func=fid,
                       kind="per_call")

    def _later_store(self, fn: ast.AST, name: str,
                     after_line: int) -> Optional[ast.Subscript]:
        """`f = jax.jit(...); CACHE[key] = f` — find the memo store of a
        locally-bound jit so the site classifies as a family."""
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign)
                    and node.lineno >= after_line
                    and isinstance(node.value, ast.Name)
                    and node.value.id == name):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        return t
        return None

    def _family_bounded(self, fn: ast.AST,
                        sub: ast.Subscript) -> tuple[bool, str]:
        """A memo-dict jit family is bounded when its key provably comes
        from a bounding helper (pow2/bucket/cap/clamp — directly, via a
        local, or one call-hop up through the enclosing function's
        parameter), or the memo carries an explicit size-cap guard
        (a len(<memo>) comparison in its module)."""
        key = sub.slice
        if self._bounding_expr(fn, key):
            return True, "bounding key"
        # one-hop: key is a parameter; every program caller passes a
        # bounding expression.
        pname = key.id if isinstance(key, ast.Name) else None
        if pname is not None and self._param_bounded(fn, pname):
            return True, "bounded by callers"
        fam = _dotted(sub.value)
        if fam is not None and self._len_capped(fam):
            return True, "len-capped memo"
        return False, ""

    def _bounding_expr(self, fn: ast.AST, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Call):
            t = _terminal(expr.func)
            return bool(t and _BOUNDING_NAME.search(t))
        if isinstance(expr, ast.Name):
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id == expr.id
                        and isinstance(node.value, ast.Call)):
                    t = _terminal(node.value.func)
                    if t and _BOUNDING_NAME.search(t):
                        return True
        return False

    def _param_bounded(self, fn: ast.AST, pname: str) -> bool:
        args = getattr(fn, "args", None)
        if args is None:
            return False
        names = [a.arg for a in args.args if a.arg not in ("self", "cls")]
        if pname not in names:
            return False
        idx = names.index(pname)
        fname = getattr(fn, "name", "")
        callers = 0
        for other in self.program.functions.values():
            for cs in other.calls:
                if cs.raw.split(".")[-1] != fname:
                    continue
                callers += 1
                call = self._find_call(other, cs.line, fname)
                if call is None or len(call.args) <= idx:
                    return False
                caller_fn = self._find_function_node(other)
                if caller_fn is None or not self._bounding_expr(
                        caller_fn, call.args[idx]):
                    return False
        return callers > 0

    def _find_function_node(self, info: FunctionInfo) -> Optional[ast.AST]:
        mod = self.program.modules.get(info.path)
        if mod is None:
            return None
        for node in ast.walk(mod.tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name == info.name
                    and node.lineno == info.line):
                return node
        return None

    def _find_call(self, info: FunctionInfo, line: int,
                   fname: str) -> Optional[ast.Call]:
        fn = self._find_function_node(info)
        if fn is None:
            return None
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call) and node.lineno == line
                    and _terminal(node.func) == fname):
                return node
        return None

    def _len_capped(self, fam: str) -> bool:
        """`if len(<memo>) >= N: <evict>` anywhere in the module — the
        crude-but-honest bound for repr/mesh-keyed caches."""
        tail = fam.split(".")[-1]
        for node in ast.walk(self.mod.tree):
            if not isinstance(node, ast.Compare):
                continue
            exprs = [node.left] + list(node.comparators)
            for e in exprs:
                if (isinstance(e, ast.Call) and isinstance(e.func, ast.Name)
                        and e.func.id == "len" and e.args):
                    d = _dotted(e.args[0])
                    if d is not None and d.split(".")[-1] == tail:
                        return True
        return False


# ---------------------------------------------------------------------------
# Tarjan SCC (iterative: product files can nest call chains deeply).
# ---------------------------------------------------------------------------

def _tarjan(adj: dict[str, set[str]]) -> list[list[str]]:
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = 0
    nodes = sorted(set(adj) | {v for vs in adj.values() for v in vs})

    for root in nodes:
        if root in index:
            continue
        work: list[tuple[str, Iterator[str]]] = [
            (root, iter(sorted(adj.get(root, ()))))
        ]
        index[root] = low[root] = counter
        counter += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter
                    counter += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                sccs.append(comp)
    return sccs


def iter_jit_calls(tree: ast.AST,
                   aliases: dict[str, str]) -> Iterator[
                       tuple[ast.Call, int]]:
    """(call node, index of the traced-function argument) for every
    jax.jit / _traced_jit CALL in `tree` — jax.jit(fn, ...) carries fn
    at 0, Engine._traced_jit(name, fn) at 1. Rules use this for the
    per-file jit checks (TPL105) without building a Program."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d is None:
            continue
        head = d.split(".")[0]
        norm = (aliases[head] + d[len(head):]) if head in aliases else d
        if norm == "jax.jit":
            yield node, 0
        elif norm.endswith("._traced_jit") or d.endswith("._traced_jit"):
            yield node, 1


def write_hierarchy(path: Path, program: Program) -> None:
    path.write_text(
        json.dumps(program.hierarchy_doc(), indent=2, sort_keys=True) + "\n"
    )


def load_hierarchy(path: Path) -> Optional[dict[str, Any]]:
    p = Path(path)
    if not p.exists():
        return None
    doc = json.loads(p.read_text())
    return doc if isinstance(doc, dict) else None
