"""Kernel dataflow analysis (round 20, ISSUE 15): tpuschedlint v3.

An AST-level abstract interpreter over the array programs in
``tpusched/kernels/`` (plus ``ring.py``, ``mesh.py`` and the
``device_state.py`` scatter entry points) that answers, per reduction
site, the three questions ROADMAP item 1 (shard the serving path over
the (p, n) mesh) needs answered BEFORE any reduction crosses a device
boundary:

  1. EXACTNESS — is the reduction invariant under the reduction tree's
     shape? XLA reductions are tree-shaped and the tree changes with
     width, layout, and sharding (the PR 12 finding), so only
     bool/integer reductions, int32 fixed-point sums, and
     integer-valued-f32 sums whose magnitude bound keeps every partial
     sum below 2**24 are exact-in-any-tree. Everything else is
     f32-order-sensitive: bitwise-stable only at a fixed width on a
     fixed backend, and NOT stable under psum/partial-reduce
     re-ordering.
  2. PADDING — can the result change when the reduced axis is
     zero/NEG_INF-padded? sum/cumsum of order-sensitive f32 can (tree
     reshape); mean always can (the denominator is the width);
     integer-class accumulations cannot; min/max/any/all cannot change
     from tree shape, but a min/max whose mask fill is NOT the op's
     identity (``where(valid, x, 0.0)`` under ``min``) changes when
     padding adds masked rows — the pad value must flow from a
     recognized identity constant to be proven safe. The recognized
     safe construction for f32 prefix sums is PR 12's width padding:
     cumsum over an array concatenated/scattered out to an explicit
     fixed width, byte-identical at any view width.
  3. SCATTER UNIQUENESS — ``.at[idx].add(v)`` with duplicate indices
     applies the duplicates in unspecified order; for non-integer f32
     values that makes the result layout-dependent. Recognized safe
     patterns: integer-valued adds (any order is exact), idx provably
     unique (the rank/perm idiom: argsort/lexsort permutations,
     arange), scalar indices (argmax/argmin picks), and the
     masked-segment idiom of ``_node_add`` (duplicates are masked rows
     adding exact 0.0; see kernels/assign.py:536's "duplicate scatters
     write identical content" note for the ``.set`` analogue).

The lattice (per array value)::

    BOOL < INT < INTF(bound) < F32        (+ FIXED flavor of INT)

``INTF`` is an f32 array holding integer values with a tracked
magnitude bound; a sum of INTF is exact while bound * WIDTH_CAP stays
below 2**24, where WIDTH_CAP = 2**17 is the documented member-axis cap
(100k pods/nodes per ROADMAP item 1's target shape). ``FIXED`` is the
PR 12 int32 fixed-point idiom ``clip(round(x * S), -B, B).astype(int32)``;
its sums are associativity-exact, and provably in-range iff
B * WIDTH_CAP <= 2**31 - 1 (the "P * 2**15 fits int32" cap).

Four rules ride the standard Finding/suppression/baseline machinery:

    TPL201  f32 order-sensitive reduction feeding a commit/compare
            decision (taint from the site to a Compare/argmax/argmin/
            searchsorted/top_k/where-condition in the same function)
    TPL202  padding-hazardous f32 accumulation reachable from a
            compacted-view path (_pods_view/_top_by_rank frontier
            gathers) that TPL201 does not already cover
    TPL203  non-unique scatter-add of non-integer values
    TPL204  int32 fixed-point accumulation whose overflow bound is not
            provable from a clip on the quantized operand

plus the checked-in artifact ``tools/reduction_ledger.json`` (the
lock_hierarchy.json analog: every cross-pod/cross-node reduction site
with its exactness class, padding verdict, and sharding-safety note;
regenerate with ``python tools/lint.py --write-ledger``, staleness
fails the check.py kernelflow stage) and the runtime refuter
``tools/padcheck.py`` (differential execution of the ledger sites'
enclosing kernels at two bucket widths; an exact-marked site that
diverges bitwise fails the run).

Heuristics, like the rest of tpuschedlint, are deliberate: parameter
kinds seed from the repo's naming conventions (mask/valid/ok -> bool,
rank/perm/idx -> int, counts/anti -> integer-valued f32, everything
else f32), attribute kinds from the snapshot schema, and local call
returns from a two-pass summary. Anything unprovable lands at the
top of the lattice and must be fixed or suppressed with a reason.
"""

from __future__ import annotations

import ast
import dataclasses
import json
from pathlib import Path
from typing import Any, Dict, FrozenSet, Iterable, List, Optional, Tuple

__all__ = [
    "KERNEL_SCOPE_DIRS",
    "KERNEL_SCOPE_FILES",
    "KernelProgram",
    "Site",
    "in_kernel_scope",
    "kernel_sources",
    "ledger_doc",
    "load_ledger",
    "write_ledger",
]

# ---------------------------------------------------------------------------
# Scope.
# ---------------------------------------------------------------------------

KERNEL_SCOPE_DIRS: Tuple[str, ...] = ("tpusched/kernels/",)
KERNEL_SCOPE_FILES: Tuple[str, ...] = (
    "tpusched/ring.py",
    "tpusched/mesh.py",
    "tpusched/device_state.py",
)

#: Documented width cap of the member/pod/node axes (ROADMAP item 1
#: targets 100k x 50k; 2**17 covers both with headroom). INTF sums are
#: exact while bound * WIDTH_CAP < 2**24.
WIDTH_CAP = 2 ** 17
#: The int32 fixed-point width cap is the PR 12 documented claim
#: ("P * (2**15 - 1) fits int32, exact for P <= 64k" at the
#: _deal_commit quantization): bound * 2**16 <= 2**31 - 1 is the
#: provable envelope — note the -1: a sum reaching exactly 2**31 wraps.
INT32_WIDTH_CAP = 2 ** 16
F32_EXACT_INT = 2.0 ** 24
INT32_MAX = 2.0 ** 31 - 1


def in_kernel_scope(relpath: str) -> bool:
    return (
        any(relpath.startswith(d) for d in KERNEL_SCOPE_DIRS)
        or relpath in KERNEL_SCOPE_FILES
    )


def kernel_sources(sources: Dict[str, str]) -> Dict[str, str]:
    """The kernel-scope subset of a product-source map."""
    return {p: s for p, s in sources.items() if in_kernel_scope(p)}


# ---------------------------------------------------------------------------
# The exactness lattice.
# ---------------------------------------------------------------------------

BOOL, INT, INTF, F32 = "bool", "int", "intf", "f32"
_LEVEL = {BOOL: 0, INT: 1, INTF: 2, F32: 3}


@dataclasses.dataclass(frozen=True)
class AVal:
    """Abstract array value."""

    kind: str = F32
    #: INTF magnitude bound (max |integer value| the array can hold).
    bound: float = float("inf")
    #: int32 fixed-point (the clip(round(x*S)).astype(int32) idiom).
    fixed: bool = False
    #: clip bound of the quantized operand, when provable.
    fixed_bound: Optional[float] = None
    #: built by the PR 12 width-pad idiom (concatenate-with-zeros /
    #: scatter-into-zeros(width)) — f32 prefix sums over it are
    #: byte-identical at any view width.
    width_padded: bool = False
    #: provably duplicate-free integer indices (argsort/lexsort/arange).
    unique_idx: bool = False
    #: a scalar (argmax/argmin pick, int() cast) — trivially unique as
    #: a scatter index.
    scalar: bool = False
    #: where(mask, x, +-inf): which signed infinity fills the masked
    #: rows ("pos_inf" | "neg_inf" | None). Whether that is the
    #: reduction's IDENTITY depends on the op's direction — +inf is
    #: min's identity but DOMINATES a max — so the fill is recorded
    #: signed and matched against the op at the reduction site.
    inf_fill: Optional[str] = None
    #: where(mask, x, c) for a non-identity constant c (pad rows are
    #: masked, but the fill is not the reduction identity).
    masked: bool = False
    #: bound on the SUM of all entries (count tables: counts/anti sum
    #: to at most the member count, so any partial sum stays exact
    #: even though per-entry bound * width would not).
    sum_bound: Optional[float] = None
    #: accumulation sites whose result flows into this value.
    taints: FrozenSet[int] = frozenset()


def _join(a: AVal, b: AVal) -> AVal:
    kind = a.kind if _LEVEL[a.kind] >= _LEVEL[b.kind] else b.kind
    bound = float("inf")
    if kind == INTF:
        ba = a.bound if a.kind in (INTF,) else (
            1.0 if a.kind == BOOL else a.bound)
        bb = b.bound if b.kind in (INTF,) else (
            1.0 if b.kind == BOOL else b.bound)
        bound = max(ba if ba == ba else 1.0, bb if bb == bb else 1.0)
    def _zeroish(v: AVal) -> bool:
        return v.kind in (INT, INTF) and v.bound == 0.0

    sb = None
    if a.sum_bound is not None and b.sum_bound is not None:
        sb = a.sum_bound + b.sum_bound
    elif a.sum_bound is not None and _zeroish(b):
        sb = a.sum_bound
    elif b.sum_bound is not None and _zeroish(a):
        sb = b.sum_bound
    return AVal(
        kind=kind, bound=bound,
        fixed=a.fixed or b.fixed,
        fixed_bound=a.fixed_bound if a.fixed_bound is not None
        else b.fixed_bound,
        width_padded=a.width_padded and b.width_padded,
        unique_idx=False, scalar=a.scalar and b.scalar,
        inf_fill=a.inf_fill if a.inf_fill == b.inf_fill else None,
        masked=a.masked and b.masked,
        sum_bound=sb,
        taints=a.taints | b.taints,
    )


def _intf(bound: float, **kw: Any) -> AVal:
    return AVal(kind=INTF, bound=bound, **kw)


# ---------------------------------------------------------------------------
# Name/attribute kind seeds (the repo's conventions; heuristic on
# purpose — see module docstring).
# ---------------------------------------------------------------------------

_BOOL_TOKENS = frozenset({
    "mask", "valid", "ok", "feasible", "feas", "elig", "eligible",
    "fits", "keep", "kept", "pend", "pending", "active", "evicted",
    "commit", "committed", "member", "real", "allowed", "relaxed",
    "bad", "dns", "hk", "exists", "match", "tried", "drained", "taken",
    "claimed", "want", "roll", "rolled", "placed", "hold", "can",
    "has", "spent", "progress", "boundary", "on", "explain", "covered",
    "carried", "viol", "stuck", "frontier0", "matched", "soft",
    "intol", "excl", "evict", "ev", "hit", "use", "winner", "avail",
    "released", "conservative", "cons",
})
_INTF_TOKENS = frozenset({
    "cnt", "count", "counts", "tot", "anti", "usage", "consumed",
    "contrib", "remaining0", "chosen?", "skew", "quorum",
})
_INT_TOKENS = frozenset({
    "idx", "rank", "order", "perm", "pos", "ptr", "sel", "ids", "sig",
    "dom", "node", "choice", "cand", "target", "slot", "key", "group",
    "pdb", "vidx", "bk", "assigned", "carry", "p", "n", "r", "t", "c",
    "s", "b", "i", "j", "tn", "tv", "gid", "round", "rounds", "esn",
    "assignment", "pod", "lineage",
})
#: Full-name seeds that beat the token tables.
_NAME_SEEDS: Dict[str, AVal] = {
    "requests": AVal(F32), "req": AVal(F32), "used": AVal(F32),
    "alloc": AVal(F32), "allocatable": AVal(F32), "req_s": AVal(F32),
    "counts": _intf(WIDTH_CAP, sum_bound=WIDTH_CAP),
    "anti": _intf(WIDTH_CAP, sum_bound=WIDTH_CAP),
    "match_tot": _intf(WIDTH_CAP, sum_bound=WIDTH_CAP),
    "pdb_allowed": _intf(WIDTH_CAP, sum_bound=WIDTH_CAP),
    "resource_weights": _intf(128, sum_bound=1024),
    "rw": _intf(128, sum_bound=1024),
    "pref_weight": _intf(128),
    "sign": _intf(1.0, scalar=True),
    # `masked` is the convention name for NEG_INF-filled score rows
    # (feasibility holes sink below every real score).
    "masked": AVal(F32, inf_fill="neg_inf", masked=True),
    "score": AVal(F32), "chosen": AVal(F32), "cost": AVal(F32),
    "prio": AVal(F32), "freed": AVal(F32), "need": AVal(F32),
    "rank": AVal(INT, unique_idx=True),
}
#: Snapshot-schema attribute kinds (terminal attribute name).
_ATTR_SEEDS: Dict[str, AVal] = {
    "valid": AVal(BOOL), "schedulable": AVal(BOOL),
    "tolerates_unsched": AVal(BOOL), "tolerated": AVal(BOOL),
    "ts_valid": AVal(BOOL), "ia_valid": AVal(BOOL),
    "ia_anti": AVal(BOOL), "ia_required": AVal(BOOL),
    "ns_all": AVal(BOOL), "vvalid": AVal(BOOL),
    "sig_match": AVal(BOOL), "mask": AVal(BOOL), "aff_ok": AVal(BOOL),
    "node_idx": AVal(INT), "group": AVal(INT), "domain": AVal(INT),
    "taint_ids": AVal(INT), "ts_sig": AVal(INT), "ia_sig": AVal(INT),
    "anti_sig": AVal(INT), "ts_when": AVal(INT), "ts_key": AVal(INT),
    "ia_key": AVal(INT), "key": AVal(INT), "atoms": AVal(INT),
    "ns": AVal(INT), "namespace": AVal(INT), "pdb_group": AVal(INT),
    "op": AVal(INT), "pairs": AVal(INT), "label_pairs": AVal(INT),
    "label_keys": AVal(INT), "pod_group": AVal(INT),
    "perm": AVal(INT, unique_idx=True), "vidx": AVal(INT),
    "vpdb": AVal(INT), "seg_start": AVal(INT), "node_s": AVal(INT),
    "pdb_s": AVal(INT), "taint_effect": AVal(INT),
    "counts": _intf(WIDTH_CAP, sum_bound=WIDTH_CAP),
    "anti_counts": _intf(WIDTH_CAP, sum_bound=WIDTH_CAP),
    "match_tot": _intf(WIDTH_CAP, sum_bound=WIDTH_CAP),
    "pdb_allowed": _intf(WIDTH_CAP, sum_bound=WIDTH_CAP),
    "group_min_member": _intf(WIDTH_CAP),
    "ts_max_skew": _intf(WIDTH_CAP), "tt_count": _intf(64),
    "req_term_valid": AVal(BOOL), "pref_term_valid": AVal(BOOL),
    "req_term_atoms": AVal(INT), "pref_term_atoms": AVal(INT),
}


def _seed_name(name: str) -> AVal:
    if name in _NAME_SEEDS:
        return _NAME_SEEDS[name]
    # Single-letter tokens only match single-letter NAMES (else
    # `req_s` would read as an int through its "s").
    toks = {t for t in name.split("_") if len(t) > 1 or len(name) == 1}
    if toks & _BOOL_TOKENS:
        return AVal(BOOL)
    if toks & _INTF_TOKENS:
        return _intf(WIDTH_CAP)
    if toks & _INT_TOKENS:
        return AVal(INT)
    return AVal(F32)


# ---------------------------------------------------------------------------
# Sites.
# ---------------------------------------------------------------------------

#: Accumulation ops: result mixes many rows via +; exactness is the
#: lattice question and padding/tree-shape matters.
_ACCUM_OPS = frozenset({
    "sum", "cumsum", "mean", "prod", "matmul", "einsum", "dot",
    "tensordot", "associative_scan", "at_add",
})
#: Select-combine ops: order-free (min/max are associative and exact in
#: any tree) — padding safety is about the mask fill, not the tree.
_SELECT_OPS = frozenset({
    "max", "min", "amax", "amin", "cummax", "cummin", "at_max",
    "at_min", "nanquantile",
})
#: Ordering/selection ops: included in the ledger for the sharding
#: inventory (cross-'n' top-k combine is ROADMAP item 1's own example)
#: but never rule-bearing here.
_ORDER_OPS = frozenset({
    "argsort", "lexsort", "sort", "top_k", "argmax", "argmin",
    "searchsorted",
})
_REDUCE_CALL_HEADS = frozenset({
    "jnp", "np", "numpy", "lax", "jax",
})
#: Ops that mark their operands' taints as decision-feeding.
_DECISION_OPS = frozenset({
    "argmax", "argmin", "searchsorted", "top_k", "sort", "argsort",
    "lexsort", "nanquantile",
})


@dataclasses.dataclass
class Site:
    path: str
    line: int
    col: int
    func: str          # dotted def chain inside the module
    root: str          # top-level enclosing function
    op: str            # "sum", "cumsum", "at_add", "matmul", ...
    cls: str           # "accum" | "select" | "order" | "scatter"
    operand: str       # lattice kind of the reduced/added operand
    axis: str          # "0", "1", "none", "-1", "(1, 3)", ...
    exactness: str = ""
    padding: str = ""
    unique: Optional[str] = None   # scatter-index verdict
    decision: bool = False         # taints a compare/argmax/...
    compact: bool = False          # reachable from a compacted view
    rule: Optional[str] = None
    sharding: str = ""
    suppressed: bool = False

    def record(self) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "path": self.path, "line": self.line, "func": self.func,
            "root": self.root, "op": self.op, "class": self.cls,
            "operand": self.operand, "axis": self.axis,
            "exactness": self.exactness, "padding": self.padding,
            "decision": self.decision, "compact_reachable": self.compact,
            "sharding": self.sharding,
        }
        if self.cls == "scatter":
            rec["unique"] = self.unique
        if self.rule:
            rec["rule"] = self.rule
            rec["suppressed"] = self.suppressed
        return rec


def _is_identity_const(node: ast.AST) -> Optional[str]:
    """'pos_inf' | 'neg_inf' | 'zero' | 'other' for a mask fill."""
    neg = False
    while isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        neg = not neg
        node = node.operand
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)):
        v = float(node.value)
        if v == float("inf"):
            return "neg_inf" if neg else "pos_inf"
        if v == 0.0:
            return "zero"
        return "other"
    name = None
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    if name == "inf":
        return "neg_inf" if neg else "pos_inf"
    if name == "NEG_INF":
        return "neg_inf"
    if name in ("LARGE", "BIG"):
        return "other"
    return None


def _const_float(node: ast.AST) -> Optional[float]:
    neg = False
    while isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        neg = not neg
        node = node.operand
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)) and not isinstance(node.value, bool):
        return -float(node.value) if neg else float(node.value)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Pow):
        a, b = _const_float(node.left), _const_float(node.right)
        if a is not None and b is not None:
            try:
                v = a ** b
            except OverflowError:
                return None
            return -v if neg else v
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _axis_str(call: ast.Call) -> str:
    for kw in call.keywords:
        if kw.arg == "axis":
            v = kw.value
            if isinstance(v, ast.Constant):
                return str(v.value)
            if isinstance(v, ast.UnaryOp) and isinstance(v.op, ast.USub) \
                    and isinstance(v.operand, ast.Constant):
                return str(-v.operand.value)
            if isinstance(v, ast.Tuple):
                return "(" + ", ".join(
                    str(e.value) for e in v.elts
                    if isinstance(e, ast.Constant)) + ")"
            return "?"
    return "none"


def _axis_cell_local(axis: str) -> bool:
    """Negative axes are the repo's within-cell convention (resource,
    term, and normalization axes); batch axes are written positive."""
    return axis.startswith("-") or axis.startswith("(-")


# ---------------------------------------------------------------------------
# Per-function abstract interpretation.
# ---------------------------------------------------------------------------


class _FnAnalyzer:
    """Walks one function body in statement order, maintaining a
    name -> AVal environment, recording Sites, and marking the taints
    of values that reach decisions (compares, arg-selections, where
    conditions)."""

    def __init__(self, prog: "KernelProgram", path: str, func: str,
                 root: str, env: Dict[str, AVal],
                 aliases: Dict[str, str]):
        self.prog = prog
        self.path = path
        self.func = func
        self.root = root
        self.env = env
        self.aliases = aliases
        self.calls: List[str] = []
        self.returns: List[Any] = []   # AVal or tuple of AVal

    # -- entry ------------------------------------------------------------

    def run(self, node: ast.AST) -> None:
        body = getattr(node, "body", [])
        for stmt in body:
            self.stmt(stmt)

    # -- statements -------------------------------------------------------

    def stmt(self, node: ast.stmt) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.prog._analyze_function(
                self.path, node, f"{self.func}.{node.name}", self.root,
                dict(self.env), self.aliases, collector=self,
            )
            return
        if isinstance(node, ast.Assign):
            val = self.expr(node.value)
            for tgt in node.targets:
                self._bind(tgt, val)
            return
        if isinstance(node, ast.AugAssign):
            cur = self._lookup_target(node.target)
            val = self.expr(node.value)
            if not isinstance(val, AVal):
                val = AVal(F32)
            joined = _join(cur, val)
            if isinstance(node.op, (ast.Div,)):
                joined = dataclasses.replace(joined, kind=F32)
            self._bind(node.target, joined)
            return
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            self._bind(node.target, self.expr(node.value))
            return
        if isinstance(node, ast.Return):
            if node.value is not None:
                if isinstance(node.value, ast.Tuple):
                    self.returns.append(
                        tuple(self.expr(e) for e in node.value.elts))
                else:
                    self.returns.append(self.expr(node.value))
            return
        if isinstance(node, ast.If):
            self._mark_decision(self.expr(node.test))
            before = dict(self.env)
            for s in node.body:
                self.stmt(s)
            after_body = self.env
            self.env = before
            for s in node.orelse:
                self.stmt(s)
            # Join the branch environments so a value assigned in both
            # arms carries both kinds AND both taint sets (the
            # cum_width-vs-legacy cumsum branches of _deal_commit).
            merged = dict(self.env)
            for k, v in after_body.items():
                if k in merged and isinstance(v, AVal) \
                        and isinstance(merged[k], AVal) \
                        and merged[k] is not v:
                    merged[k] = _join(merged[k], v)
                else:
                    merged[k] = v
            self.env = merged
            return
        if isinstance(node, (ast.For, ast.While)):
            if isinstance(node, ast.For):
                self._bind(node.target, AVal(INT))
                self.expr(node.iter)
            else:
                self._mark_decision(self.expr(node.test))
            for s in node.body:
                self.stmt(s)
            for s in node.orelse:
                self.stmt(s)
            return
        if isinstance(node, ast.Expr):
            self.expr(node.value)
            return
        if isinstance(node, (ast.With,)):
            for s in node.body:
                self.stmt(s)
            return
        if isinstance(node, ast.Assert):
            self.expr(node.test)
            return
        # Pass/Raise/Import/...: nothing array-shaped to track.

    def _bind(self, tgt: ast.AST, val: Any) -> None:
        if isinstance(tgt, ast.Name):
            self.env[tgt.id] = val if isinstance(val, AVal) \
                else _seed_name(tgt.id)
            return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            elts = tgt.elts
            if isinstance(val, tuple) and len(val) == len(elts):
                for e, v in zip(elts, val):
                    self._bind(e, v)
            else:
                for e in elts:
                    # Unknown tuple: fall back to name heuristics so
                    # `feasible, score, allowed = pod_cycle(...)` still
                    # lands bool/f32/bool.
                    if isinstance(e, ast.Name):
                        self.env[e.id] = _seed_name(e.id)
                    elif isinstance(e, ast.Starred) \
                            and isinstance(e.value, ast.Name):
                        self.env[e.value.id] = _seed_name(e.value.id)
            return
        # Attribute/subscript targets: ignore (no env entry).

    def _lookup_target(self, tgt: ast.AST) -> AVal:
        if isinstance(tgt, ast.Name):
            return self.env.get(tgt.id, _seed_name(tgt.id))
        return AVal(F32)

    # -- expressions ------------------------------------------------------

    def expr(self, node: ast.AST) -> Any:
        if isinstance(node, ast.Constant):
            return self._const(node)
        if isinstance(node, ast.Name):
            return self.env.get(node.id, _seed_name(node.id))
        if isinstance(node, ast.Attribute):
            return self._attr(node)
        if isinstance(node, ast.Tuple):
            return tuple(self.expr(e) for e in node.elts)
        if isinstance(node, ast.List):
            vals = [self.expr(e) for e in node.elts]
            out = AVal(BOOL)
            for v in vals:
                if isinstance(v, AVal):
                    out = _join(out, v)
            return out
        if isinstance(node, ast.BinOp):
            return self._binop(node)
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                self.expr(v)
            return AVal(BOOL)
        if isinstance(node, ast.Compare):
            ops = [self.expr(node.left)] + [
                self.expr(c) for c in node.comparators]
            for v in ops:
                self._mark_decision(v)
            return AVal(BOOL)
        if isinstance(node, ast.UnaryOp):
            v = self.expr(node.operand)
            if isinstance(node.op, (ast.Not, ast.Invert)):
                if isinstance(v, AVal) and v.kind == BOOL:
                    return v
                return AVal(BOOL) if isinstance(node.op, ast.Not) else v
            return v
        if isinstance(node, ast.Subscript):
            base = self.expr(node.value)
            sl = self.expr(node.slice)
            if isinstance(base, AVal):
                # A gather preserves the element kind but loses the
                # positional guarantees (uniqueness, width padding,
                # sum bounds); a scalar index yields a scalar pick.
                scalar = isinstance(sl, AVal) and sl.scalar \
                    and not isinstance(node.slice, ast.Slice)
                return dataclasses.replace(
                    base, unique_idx=False, width_padded=False,
                    sum_bound=None, scalar=base.scalar or scalar)
            return AVal(F32)
        if isinstance(node, ast.IfExp):
            self._mark_decision(self.expr(node.test))
            a, b = self.expr(node.body), self.expr(node.orelse)
            if isinstance(a, AVal) and isinstance(b, AVal):
                return _join(a, b)
            return a if isinstance(a, AVal) else b
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            self.expr(node.elt)
            return AVal(F32)
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        if isinstance(node, ast.Lambda):
            # Walk the body (lax.cond branches are lambdas calling the
            # real kernels — the call graph must see through them).
            saved = dict(self.env)
            for a in node.args.args:
                self.env.setdefault(a.arg, _seed_name(a.arg))
            self.expr(node.body)
            self.env = saved
            return AVal(F32)
        if isinstance(node, ast.JoinedStr):
            return AVal(F32)
        if isinstance(node, ast.Slice):
            return AVal(INT)
        return AVal(F32)

    def _const(self, node: ast.Constant) -> AVal:
        v = node.value
        if isinstance(v, bool):
            return AVal(BOOL, scalar=True)
        if isinstance(v, int):
            return AVal(INT, bound=abs(float(v)), scalar=True)
        if isinstance(v, float):
            if v != v or v in (float("inf"), float("-inf")):
                return AVal(F32, scalar=True)
            if float(v).is_integer():
                return _intf(abs(v), scalar=True)
            return AVal(F32, scalar=True)
        # Strings (einsum specs, mode flags) are lattice-neutral.
        return AVal(BOOL, scalar=True)

    def _attr(self, node: ast.Attribute) -> AVal:
        d = _dotted(node)
        if d in ("jnp.inf", "np.inf", "math.inf"):
            return AVal(F32, scalar=True)
        term = node.attr
        if term in _ATTR_SEEDS:
            return _ATTR_SEEDS[term]
        base = None
        if not isinstance(node.value, ast.Name) or \
                node.value.id not in _REDUCE_CALL_HEADS:
            base = self.expr(node.value) if not isinstance(
                node.value, ast.Name) else self.env.get(node.value.id)
        if term == "T" and isinstance(base, AVal):
            return base
        if term in ("shape", "ndim", "size", "dtype"):
            return AVal(INT, scalar=True)
        return _seed_name(term)

    def _binop(self, node: ast.BinOp) -> AVal:
        a, b = self.expr(node.left), self.expr(node.right)
        if not isinstance(a, AVal):
            a = AVal(F32)
        if not isinstance(b, AVal):
            b = AVal(F32)
        if isinstance(node.op, ast.MatMult):
            return self._accum_site(
                node, "matmul", _join(a, b), axis="contract",
                operands=(a, b))
        out = _join(a, b)
        if isinstance(node.op, ast.Div):
            out = dataclasses.replace(out, kind=F32)
        elif isinstance(node.op, (ast.Add, ast.Sub)) and out.kind == INTF:
            out = dataclasses.replace(out, bound=a.bound + b.bound)
        elif isinstance(node.op, ast.Mult) and out.kind == INTF:
            out = dataclasses.replace(
                out, bound=max(a.bound, 1.0) * max(b.bound, 1.0))
        elif isinstance(node.op, (ast.FloorDiv, ast.Mod, ast.LShift,
                                  ast.RShift, ast.BitAnd, ast.BitOr,
                                  ast.BitXor)):
            pass
        elif isinstance(node.op, ast.Pow):
            out = dataclasses.replace(out, kind=F32)
        return out

    # -- calls ------------------------------------------------------------

    def _call(self, node: ast.Call) -> Any:
        fn = node.func
        # `.at[idx].<op>(v)` scatter chain.
        if (isinstance(fn, ast.Attribute)
                and fn.attr in ("add", "set", "max", "min", "mul", "get")
                and isinstance(fn.value, ast.Subscript)
                and isinstance(fn.value.value, ast.Attribute)
                and fn.value.value.attr == "at"):
            return self._scatter(node, fn)

        name = _dotted(fn)
        term = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if term in ("concatenate", "stack", "hstack", "vstack"):
            # Handled before the generic arg sweep: _concat evaluates
            # the element list itself (a second walk would double-
            # record any reduction site inside it).
            return self._concat(node, [])
        args = [self.expr(a) for a in node.args]
        for kw in node.keywords:
            self.expr(kw.value)
        avals = [a for a in args if isinstance(a, AVal)]
        arg0 = avals[0] if avals else AVal(F32)

        head = name.split(".", 1)[0] if name else None
        is_module_call = head in _REDUCE_CALL_HEADS or (
            head is not None and self.aliases.get(head, "").split(".")[0]
            in ("jax", "numpy"))
        is_method = isinstance(fn, ast.Attribute) and not is_module_call

        if term in ("astype",) and is_method:
            return self._astype(node, fn)
        if is_method and term in ("sum", "cumsum", "mean", "prod",
                                  "max", "min", "any", "all"):
            base = self.expr(fn.value)
            if not isinstance(base, AVal):
                base = AVal(F32)
            if term in ("any", "all"):
                return AVal(BOOL)
            if term in ("max", "min"):
                return self._select_site(node, term, base)
            return self._accum_site(node, term, base, operands=(base,))
        if not is_module_call:
            # Local/cross-module kernel call: use the summarized return
            # kind when the callee is in scope.
            resolved = self._resolve_call(name, term)
            if resolved is not None:
                self.calls.append(resolved)
                ret = self.prog._returns.get(resolved)
                if ret is not None:
                    return ret
            if term in ("int", "float", "len", "round", "bool", "abs",
                        "range", "enumerate", "zip"):
                if term == "float":
                    return AVal(F32, scalar=True)
                if term == "bool":
                    return AVal(BOOL, scalar=True)
                return AVal(INT, scalar=True)
            # Unknown local call (nested def, helper without a
            # summary): None makes _bind fall back to the target's
            # NAME heuristic instead of poisoning it with F32.
            return None

        # jnp/lax/np builders and reductions.
        if term in ("zeros", "ones", "empty", "zeros_like", "ones_like",
                    "full", "full_like", "asarray", "array", "arange",
                    "linspace"):
            return self._builder(node, term, args)
        if term == "where":
            return self._where(node, args)
        if term == "clip":
            return arg0
        if term in ("maximum", "minimum", "mod", "abs", "round",
                    "floor", "ceil", "sign"):
            out = arg0
            for v in avals[1:]:
                out = _join(out, v)
            if term == "round" and out.kind == F32:
                # round() makes the VALUES integral; the bound is the
                # enclosing clip's job (see _astype / TPL204).
                out = dataclasses.replace(out, kind=INTF,
                                          bound=float("inf"))
            return out
        if term in ("sqrt", "exp", "log", "power", "divide",
                    "true_divide", "reciprocal", "nan_to_num"):
            return AVal(F32)
        if term in ("isfinite", "isnan", "isinf", "logical_and",
                    "logical_or", "logical_not", "isin"):
            return AVal(BOOL)
        if term in ("any", "all"):
            return AVal(BOOL)
        if term == "pad":
            return dataclasses.replace(arg0, width_padded=True)
        if term in ("broadcast_to", "reshape", "transpose", "squeeze",
                    "expand_dims", "tile", "flip", "take_along_axis",
                    "take", "select", "roll"):
            if term == "select":
                out = AVal(BOOL)
                got = False
                for v in avals[1:]:
                    out = _join(out, v)
                    got = True
                return out if got else arg0
            return dataclasses.replace(
                arg0, unique_idx=False) if avals else AVal(F32)
        if term in ("sum", "cumsum", "mean", "prod", "einsum", "dot",
                    "tensordot", "matmul", "associative_scan"):
            if term == "associative_scan":
                operand = args[1] if len(args) > 1 else AVal(F32)
                if isinstance(operand, tuple):
                    o = AVal(BOOL)
                    for v in operand:
                        if isinstance(v, AVal):
                            o = _join(o, v)
                    operand = o
                if not isinstance(operand, AVal):
                    operand = AVal(F32)
                return self._accum_site(node, term, operand,
                                        operands=(operand,))
            if term == "einsum":
                op = AVal(BOOL)
                for v in avals:
                    op = _join(op, v)
                return self._accum_site(node, term, op, operands=tuple(avals))
            if term in ("dot", "tensordot", "matmul"):
                op = arg0
                for v in avals[1:]:
                    op = _join(op, v)
                return self._accum_site(node, term, op,
                                        operands=tuple(avals))
            return self._accum_site(node, term, arg0, operands=(arg0,))
        if term in ("max", "min", "amax", "amin", "nanquantile"):
            return self._select_site(node, term, arg0)
        if term in ("cummax", "cummin"):
            return self._select_site(node, term, arg0)
        if term in ("argsort", "lexsort", "argmax", "argmin",
                    "searchsorted", "top_k", "sort"):
            for v in avals:
                self._mark_decision(v)
            key = arg0 if term != "lexsort" else (
                avals[-1] if avals else AVal(F32))
            self._order_site(node, term, key)
            if term == "sort":
                return arg0
            if term == "top_k":
                return (arg0, AVal(INT))
            if term in ("argsort", "lexsort"):
                return AVal(INT, unique_idx=True)
            if term == "searchsorted":
                return AVal(INT)
            # argmax/argmin without axis give a scalar pick.
            has_axis = any(kw.arg == "axis" for kw in node.keywords)
            return AVal(INT, scalar=not has_axis)
        if term in ("ppermute", "psum", "pmax", "pmin", "all_gather"):
            # Cross-device collectives (ring.py): psum of f32 is the
            # sharding hazard itself; record as accumulation.
            if term == "psum":
                return self._accum_site(node, term, arg0, operands=(arg0,))
            return arg0
        if term in ("int32", "int64", "float32", "float64", "uint32",
                    "bool_", "int8"):
            if term.startswith("int") or term.startswith("uint"):
                return dataclasses.replace(arg0, kind=INT)
            if term.startswith("float"):
                if arg0.kind in (BOOL, INT):
                    return dataclasses.replace(arg0, kind=INTF,
                                               bound=arg0.bound)
                return arg0
            return AVal(BOOL)
        if term in ("scan", "while_loop", "cond", "fori_loop", "map",
                    "vmap", "jit", "tree", "tree_map", "debug", "print",
                    "stop_gradient", "device_put"):
            return AVal(F32)
        return AVal(F32)

    def _resolve_call(self, name: Optional[str],
                      term: Optional[str]) -> Optional[str]:
        if name is None and term is None:
            return None
        if name and "." in name:
            head, rest = name.split(".", 1)
            mod = self.aliases.get(head)
            if mod:
                cand = f"{mod}.{rest}"
                if cand in self.prog._fn_index:
                    return cand
        if name and name in self.aliases:
            cand = self.aliases[name]
            if cand in self.prog._fn_index:
                return cand
        if term:
            mod = self.path_module()
            cand = f"{mod}.{term}"
            if cand in self.prog._fn_index:
                return cand
        return None

    def path_module(self) -> str:
        return self.path[:-3].replace("/", ".")

    def _builder(self, node: ast.Call, term: str,
                 args: List[Any]) -> AVal:
        dtype = None
        for kw in node.keywords:
            if kw.arg == "dtype":
                dtype = _dotted(kw.value) or (
                    kw.value.id if isinstance(kw.value, ast.Name) else None)
        for a in node.args:
            d = _dotted(a)
            if d and d.split(".")[-1] in ("int32", "int64", "bool_",
                                          "float32", "bool"):
                dtype = d
        if isinstance(node.args[-1] if node.args else None, ast.Name) \
                and node.args[-1].id == "bool":
            dtype = "bool"
        kind = None
        if dtype:
            t = dtype.split(".")[-1]
            if t in ("bool", "bool_"):
                kind = BOOL
            elif t.startswith("int") or t.startswith("uint"):
                kind = INT
            elif t.startswith("float"):
                kind = F32
        if term in ("zeros", "zeros_like", "empty"):
            if kind in (BOOL, INT):
                return AVal(kind)
            return _intf(0.0)
        if term in ("ones", "ones_like"):
            if kind in (BOOL, INT):
                return AVal(kind)
            return _intf(1.0)
        if term in ("full", "full_like"):
            fill = self.expr(node.args[1]) if len(node.args) > 1 else \
                AVal(F32)
            if kind in (BOOL, INT):
                return AVal(kind)
            return fill if isinstance(fill, AVal) else AVal(F32)
        if term == "arange":
            if kind == F32:
                return _intf(WIDTH_CAP, unique_idx=True)
            return AVal(INT, unique_idx=True)
        if term in ("asarray", "array"):
            base = self.expr(node.args[0]) if node.args else AVal(F32)
            if not isinstance(base, AVal):
                base = AVal(F32)
            if kind == INT:
                return dataclasses.replace(base, kind=INT)
            if kind == BOOL:
                return AVal(BOOL)
            if kind == F32 and base.kind in (BOOL, INT):
                return _intf(max(base.bound, 1.0))
            return base
        if term == "linspace":
            return AVal(F32)
        return AVal(F32)

    def _where(self, node: ast.Call, args: List[Any]) -> AVal:
        if len(node.args) != 3:
            return args[0] if args and isinstance(args[0], AVal) \
                else AVal(F32)
        cond = args[0] if isinstance(args[0], AVal) else AVal(BOOL)
        self._mark_decision(cond)
        a = args[1] if isinstance(args[1], AVal) else AVal(F32)
        b = args[2] if isinstance(args[2], AVal) else AVal(F32)
        out = _join(a, b)
        out = dataclasses.replace(out, taints=out.taints | cond.taints)
        fill = _is_identity_const(node.args[2])
        if fill in ("pos_inf", "neg_inf"):
            return dataclasses.replace(out, inf_fill=fill, masked=True)
        if fill is not None:
            return dataclasses.replace(out, masked=True, inf_fill=None)
        return out

    def _astype(self, node: ast.Call, fn: ast.Attribute) -> AVal:
        base = self.expr(fn.value)
        if not isinstance(base, AVal):
            base = AVal(F32)
        dt = None
        if node.args:
            dt = _dotted(node.args[0])
            if dt is None and isinstance(node.args[0], ast.Name):
                dt = node.args[0].id
        t = (dt or "").split(".")[-1]
        if t in ("bool", "bool_"):
            return dataclasses.replace(base, kind=BOOL)
        if t.startswith("int") or t.startswith("uint"):
            if base.kind == F32 or (base.kind == INTF
                                    and base.bound == float("inf")):
                # The fixed-point idiom: clip(round(x*S), -B, B)
                # .astype(int32). Provable bound only through the clip.
                bound = self._clip_bound(fn.value)
                return AVal(INT, fixed=True, fixed_bound=bound,
                            taints=base.taints)
            return dataclasses.replace(base, kind=INT)
        if t.startswith("float"):
            if base.kind in (BOOL,):
                return dataclasses.replace(base, kind=INTF, bound=1.0,
                                           sum_bound=base.sum_bound)
            if base.kind == INT:
                return dataclasses.replace(
                    base, kind=INTF,
                    bound=base.bound if base.bound == base.bound
                    else WIDTH_CAP)
            return base
        return base

    @staticmethod
    def _clip_bound(node: ast.AST) -> Optional[float]:
        """|bound| of a jnp.clip(..., -B, B) wrapping the quantized
        operand; None when no clip (or unbounded) — the TPL204 case."""
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, (ast.Attribute, ast.Name))):
            return None
        term = node.func.attr if isinstance(node.func, ast.Attribute) \
            else node.func.id
        if term != "clip":
            return None
        if len(node.args) < 3:
            return None
        lo = _const_float(node.args[1])
        hi = _const_float(node.args[2])
        if lo is None or hi is None:
            return None
        return max(abs(lo), abs(hi))

    def _concat(self, node: ast.Call, args: List[Any]) -> AVal:
        parts: List[AVal] = []
        pad_zero = False
        if node.args and isinstance(node.args[0], (ast.List, ast.Tuple)):
            for e in node.args[0].elts:
                v = self.expr(e)
                if isinstance(v, AVal):
                    parts.append(v)
                if isinstance(e, ast.Call):
                    d = _dotted(e.func)
                    if d and d.split(".")[-1] in ("zeros", "zeros_like",
                                                  "ones"):
                        pad_zero = True
        out = AVal(BOOL)
        for v in parts:
            out = _join(out, v)
        if pad_zero:
            # The PR 12 width-pad idiom: concatenate real rows with an
            # explicit zero block out to a fixed width.
            out = dataclasses.replace(out, width_padded=True)
        return out

    # -- site recording ---------------------------------------------------

    def _new_site(self, node: ast.AST, op: str, cls: str,
                  operand: AVal, axis: str) -> Site:
        site = Site(
            path=self.path, line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0), func=self.func,
            root=self.root, op=op, cls=cls, operand=operand.kind,
            axis=axis,
        )
        self.prog.sites.append(site)
        return site

    def _accum_site(self, node: ast.AST, op: str, operand: AVal,
                    axis: Optional[str] = None,
                    operands: Tuple[AVal, ...] = ()) -> AVal:
        if axis is None:
            axis = _axis_str(node) if isinstance(node, ast.Call) else "none"
        if _axis_cell_local(axis):
            # Within-cell accumulation (resource/term axes): excluded
            # from the cross-pod/cross-node inventory.
            out_kind = INTF if operand.kind in (BOOL, INT, INTF) \
                else F32
            if op == "mean":
                out_kind = F32
            return AVal(out_kind,
                        bound=operand.bound * 8 if out_kind == INTF
                        else float("inf"),
                        taints=operand.taints)
        site = self._new_site(node, op, "accum", operand, axis)
        idx = len(self.prog.sites) - 1
        self._classify_accum(site, operand, op)
        out_taints = operand.taints | {idx}
        if op == "mean":
            return AVal(F32, taints=out_taints)
        if operand.kind == BOOL:
            return AVal(INT, bound=WIDTH_CAP, taints=out_taints)
        if operand.kind == INT:
            return AVal(INT, bound=operand.bound * WIDTH_CAP,
                        fixed=operand.fixed,
                        fixed_bound=operand.fixed_bound,
                        taints=out_taints)
        if operand.kind == INTF:
            return AVal(INTF, bound=operand.bound * WIDTH_CAP,
                        taints=out_taints)
        return AVal(F32, taints=out_taints)

    def _classify_accum(self, site: Site, operand: AVal, op: str) -> None:
        if op == "mean":
            site.exactness = ("integer-exact"
                              if operand.kind in (BOOL, INT)
                              or (operand.kind == INTF
                                  and operand.bound * WIDTH_CAP
                                  < F32_EXACT_INT)
                              else "f32-order-sensitive")
            site.padding = "hazard"
            site.sharding = ("denominator is the axis width — recompute "
                             "from a mask count, never from shape, "
                             "before sharding")
            return
        if operand.fixed:
            site.exactness = "int32-fixed-point"
            ok = (operand.fixed_bound is not None
                  and operand.fixed_bound * INT32_WIDTH_CAP <= INT32_MAX)
            site.padding = "exact" if ok else "overflow-unproven"
            site.sharding = (
                f"safe-any-tree (int32 adds; |q| <= "
                f"{operand.fixed_bound:g}, the documented P*2^15 cap)"
                if ok else
                "int32 sum bound unproven — clip the quantized operand")
            return
        if operand.kind in (BOOL, INT):
            site.exactness = "integer-exact"
            site.padding = "exact"
            site.sharding = "safe-any-tree (integer adds)"
            return
        if operand.kind == INTF:
            if operand.bound * WIDTH_CAP < F32_EXACT_INT or (
                    operand.sum_bound is not None
                    and operand.sum_bound < F32_EXACT_INT):
                site.exactness = "integer-exact"
                site.padding = "exact"
                site.sharding = (
                    "safe-any-tree (integer-valued f32; "
                    + (f"table sums to <= {operand.sum_bound:g}"
                       if operand.bound * WIDTH_CAP >= F32_EXACT_INT
                       else f"bound {operand.bound:g} * 2^17 < 2^24")
                    + ")")
                return
            site.exactness = "f32-order-sensitive"
            site.padding = "hazard"
            site.sharding = ("integer-valued but bound exceeds f32 "
                             "exact range — convert to int32 before "
                             "sharding")
            return
        if operand.width_padded:
            site.exactness = "f32-order-sensitive"
            site.padding = "safe-width-padded"
            site.sharding = ("byte-stable at the padded width; pad to "
                             "the GLOBAL width before sharding this "
                             "axis")
            return
        site.exactness = "f32-order-sensitive"
        site.padding = "hazard"
        site.sharding = ("tree/layout-sensitive — needs int32 "
                        "conversion, width padding, or an ordered "
                        "segmented reduce before sharding")

    def _select_site(self, node: ast.AST, op: str, operand: AVal) -> AVal:
        axis = _axis_str(node) if isinstance(node, ast.Call) else "none"
        site = self._new_site(node, op, "select", operand, axis)
        idx = len(self.prog.sites) - 1
        site.exactness = ("integer-exact"
                          if operand.kind in (BOOL, INT)
                          or (operand.kind == INTF
                              and operand.bound < F32_EXACT_INT)
                          else "order-free-select")
        # The identity must match the op's DIRECTION: +inf is min's
        # identity but DOMINATES a max (and vice versa) — a
        # wrong-signed infinity fill makes every padded row the
        # reduction's winner, the worst possible pad value.
        identity = {"min": "pos_inf", "amin": "pos_inf",
                    "cummin": "pos_inf", "at_min": "pos_inf",
                    "max": "neg_inf", "amax": "neg_inf",
                    "cummax": "neg_inf", "at_max": "neg_inf",
                    "nanquantile": None}.get(op)
        if operand.inf_fill is not None and operand.inf_fill == identity:
            site.padding = "identity-masked"
            site.sharding = "safe-any-tree (min/max, identity mask)"
        elif operand.inf_fill is not None:
            site.padding = "dominating-fill"
            site.sharding = (f"{operand.inf_fill} fill WINS a {op} — "
                             "padded/sharded rows dominate the result; "
                             "flip the fill to the op's identity")
        elif operand.masked:
            site.padding = "masked-select"
            site.sharding = ("min/max over a non-identity mask fill — "
                             "mask must cover every padded row on "
                             "every shard")
        else:
            site.padding = "unmasked-select"
            site.sharding = ("min/max with no mask — padded rows "
                             "participate; mask with the op identity "
                             "before sharding")
        return dataclasses.replace(operand, taints=operand.taints | {idx},
                                   inf_fill=None, masked=False,
                                   width_padded=False, unique_idx=False)

    def _order_site(self, node: ast.AST, op: str, key: AVal) -> None:
        site = self._new_site(node, op, "order", key, "key")
        site.exactness = ("integer-exact"
                          if key.kind in (BOOL, INT)
                          or (key.kind == INTF
                              and key.bound < F32_EXACT_INT)
                          else "f32-keyed-select")
        site.padding = "key-order"
        site.sharding = ("stable for integer keys; f32 keys need a "
                         "globally-unique tiebreak before a cross-"
                         "shard merge" if site.exactness != "integer-exact"
                         else "safe with a cross-shard merge by key")

    def _scatter(self, node: ast.Call, fn: ast.Attribute) -> AVal:
        base = self.expr(fn.value.value.value)
        if not isinstance(base, AVal):
            base = AVal(F32)
        idx_node = fn.value.slice
        idx = self.expr(idx_node)
        idxs: List[AVal] = []
        idx_nodes: List[ast.AST] = []
        if isinstance(idx_node, ast.Tuple):
            idx_nodes = list(idx_node.elts)
            idxs = [v if isinstance(v, AVal) else AVal(INT)
                    for v in (idx if isinstance(idx, tuple) else [idx])]
        else:
            idx_nodes = [idx_node]
            idxs = [idx if isinstance(idx, AVal) else AVal(INT)]
        val = self.expr(node.args[0]) if node.args else AVal(F32)
        if not isinstance(val, AVal):
            val = AVal(F32)
        out = _join(base, val)
        # Scatter into an explicitly-built zeros buffer is the PR 12
        # rank-major width-pad idiom (absent rows stay exact zero at a
        # declared width): prefix sums over it are width-invariant.
        base_node = fn.value.value.value
        zeros_base = (isinstance(base_node, ast.Call)
                      and isinstance(base_node.func, (ast.Attribute,
                                                      ast.Name))
                      and (_dotted(base_node.func) or "").split(".")[-1]
                      in ("zeros", "zeros_like"))
        out = dataclasses.replace(
            out, width_padded=base.width_padded or zeros_base)
        if fn.attr in ("set", "get", "mul"):
            # .set duplicates ride the documented identical-content
            # idiom (kernels/assign.py:536); not a reduction.
            return out
        op = f"at_{fn.attr}"
        if fn.attr in ("max", "min"):
            site = self._new_site(node, op, "select", val, "scatter")
            site.exactness = ("integer-exact"
                              if val.kind in (BOOL, INT)
                              or (val.kind == INTF
                                  and val.bound < F32_EXACT_INT)
                              else "order-free-select")
            site.padding = "exact"
            site.sharding = "safe-any-tree (scatter-combine by min/max)"
            return out
        # at_add: the duplicate-index question.
        site = self._new_site(node, op, "scatter", val, "scatter")
        sidx = len(self.prog.sites) - 1
        unique, why = self._scatter_unique(idx_nodes, idxs, node)
        site.unique = why
        if val.fixed:
            site.exactness = "int32-fixed-point"
        elif val.kind in (BOOL, INT) or (
                val.kind == INTF and val.bound * WIDTH_CAP < F32_EXACT_INT):
            site.exactness = "integer-exact"
        else:
            site.exactness = "f32-order-sensitive"
        if site.exactness != "f32-order-sensitive":
            site.padding = "exact"
            site.sharding = ("safe-any-order (integer-valued adds "
                             "commute exactly)")
        elif unique:
            site.padding = "exact"
            site.sharding = ("duplicate-free indices (" + why + ") — "
                             "one add per slot in any order")
        else:
            site.padding = "hazard"
            site.sharding = ("duplicate f32 adds apply in unspecified "
                             "order — convert to unique-per-segment "
                             "totals (_node_add) before sharding")
        return dataclasses.replace(out, taints=out.taints | {sidx})

    def _scatter_unique(self, idx_nodes: List[ast.AST],
                        idxs: List[AVal],
                        call: ast.Call) -> Tuple[bool, str]:
        if any(v.unique_idx for v in idxs):
            return True, "unique-by-perm"
        if all(v.scalar for v in idxs):
            return True, "scalar-index"
        # The _node_add masked-segment idiom: idx = where(mask, x, c)
        # and the added value = where(mask', y, 0) — duplicates add
        # exact 0.0 at a parked slot; real rows are the caller-proven
        # unique segment ends.
        def _where_mask(n: ast.AST) -> Optional[str]:
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "where" and len(n.args) == 3):
                for sub in ast.walk(n.args[0]):
                    if isinstance(sub, ast.Name):
                        return sub.id
                return "?"
            return None

        idx_mask = None
        for n in idx_nodes:
            m = _where_mask(n)
            if m is not None:
                idx_mask = m
        val_mask = _where_mask(call.args[0]) if call.args else None
        if idx_mask is not None and val_mask is not None:
            return True, "masked-segment"
        return False, "unproven"

    def _mark_decision(self, val: Any) -> None:
        if isinstance(val, AVal):
            for s in val.taints:
                if s < len(self.prog.sites):
                    self.prog.sites[s].decision = True
        elif isinstance(val, tuple):
            for v in val:
                self._mark_decision(v)


# ---------------------------------------------------------------------------
# Whole-kernel-scope program.
# ---------------------------------------------------------------------------


def _file_aliases(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module \
                and not node.level:
            for a in node.names:
                if a.name != "*":
                    aliases[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
    return aliases


class KernelProgram:
    """The kernel-scope dataflow index: every reduction/scatter Site,
    the call graph over the scope, compacted-view reachability, and the
    ledger/report/rule surfaces."""

    #: Functions that GATHER a compacted pod-axis view; everything they
    #: reach runs (also) on view-width arrays.
    COMPACT_GATHERS = ("_pods_view", "_top_by_rank")

    def __init__(self, sources: Dict[str, str]):
        self.sources = {p: s for p, s in sources.items()
                        if in_kernel_scope(p)}
        self.sites: List[Site] = []
        #: qualname ("tpusched.kernels.assign.fn") -> relpath
        self._fn_index: Dict[str, str] = {}
        self._fn_nodes: Dict[str, ast.AST] = {}
        self._fn_aliases: Dict[str, Dict[str, str]] = {}
        self._returns: Dict[str, Any] = {}
        self.calls: Dict[str, List[str]] = {}
        self._trees: Dict[str, ast.Module] = {}
        for path in sorted(self.sources):
            try:
                tree = ast.parse(self.sources[path], filename=path)
            except SyntaxError:
                continue
            self._trees[path] = tree
            mod = path[:-3].replace("/", ".")
            aliases = _file_aliases(tree)
            for node in tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    q = f"{mod}.{node.name}"
                    self._fn_index[q] = path
                    self._fn_nodes[q] = node
                    self._fn_aliases[q] = aliases
                elif isinstance(node, ast.ClassDef):
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            q = f"{mod}.{node.name}.{item.name}"
                            self._fn_index[q] = path
                            self._fn_nodes[q] = item
                            self._fn_aliases[q] = aliases
        # Two passes: pass 1 summarizes return kinds, pass 2 re-runs
        # with cross-function returns resolved (and keeps its sites).
        for _ in range(2):
            self.sites = []
            self.calls = {}
            for q in sorted(self._fn_nodes):
                self._analyze_top(q)
        self._mark_compact_reachable()

    # -- analysis ---------------------------------------------------------

    def _param_env(self, node: ast.AST) -> Dict[str, AVal]:
        env: Dict[str, AVal] = {}
        args = node.args
        for a in list(args.posonlyargs) + list(args.args) \
                + list(args.kwonlyargs):
            env[a.arg] = _seed_name(a.arg)
        return env

    def _analyze_top(self, qualname: str) -> None:
        node = self._fn_nodes[qualname]
        path = self._fn_index[qualname]
        root = qualname[len(path[:-3].replace("/", ".")) + 1:]
        self._analyze_function(
            path, node, root, root.split(".")[0] if "." in root else root,
            self._param_env(node), self._fn_aliases[qualname],
            collector=None, qualname=qualname,
        )

    def _analyze_function(self, path: str, node: ast.AST, func: str,
                          root: str, env: Dict[str, AVal],
                          aliases: Dict[str, str],
                          collector: Optional[_FnAnalyzer],
                          qualname: Optional[str] = None) -> None:
        for a in list(node.args.posonlyargs) + list(node.args.args) \
                + list(node.args.kwonlyargs):
            env.setdefault(a.arg, _seed_name(a.arg))
        an = _FnAnalyzer(self, path, func, root, env, aliases)
        an.run(node)
        mod = path[:-3].replace("/", ".")
        key = qualname or f"{mod}.{root}"
        self.calls.setdefault(key, []).extend(an.calls)
        if collector is not None:
            collector.calls.extend(an.calls)
        if qualname is not None:
            ret: Any = None
            for r in an.returns:
                if ret is None:
                    ret = r
                elif isinstance(ret, AVal) and isinstance(r, AVal):
                    ret = _join(ret, r)
            if ret is not None:
                # Taints are site indices of the CURRENT pass; a
                # summarized return must not leak them across passes.
                if isinstance(ret, AVal):
                    ret = dataclasses.replace(ret, taints=frozenset())
                elif isinstance(ret, tuple):
                    ret = tuple(
                        dataclasses.replace(v, taints=frozenset())
                        if isinstance(v, AVal) else v for v in ret)
                self._returns[qualname] = ret

    def _mark_compact_reachable(self) -> None:
        roots = set()
        for q, callees in self.calls.items():
            names = {c.rsplit(".", 1)[-1] for c in callees}
            if names & set(self.COMPACT_GATHERS):
                roots.add(q)
        reached = set(roots)
        frontier = list(roots)
        while frontier:
            q = frontier.pop()
            for c in self.calls.get(q, ()):
                if c in self._fn_index and c not in reached:
                    reached.add(c)
                    frontier.append(c)
        reach_roots = {q.rsplit(".", 1)[-1] for q in reached}
        for s in self.sites:
            if s.root in reach_roots and in_kernel_scope(s.path):
                s.compact = True

    # -- reachability for padcheck ---------------------------------------

    def reachable_from(self, entry_names: Iterable[str]) -> "set[str]":
        """Top-level function ROOT names (module-unqualified) reachable
        from the given entry function names, used by tools/padcheck.py
        to map harnesses to covered ledger sites."""
        wanted = set(entry_names)
        starts = [q for q in self._fn_index
                  if q.rsplit(".", 1)[-1] in wanted]
        seen = set(starts)
        frontier = list(starts)
        while frontier:
            q = frontier.pop()
            for c in self.calls.get(q, ()):
                if c in self._fn_index and c not in seen:
                    seen.add(c)
                    frontier.append(c)
        return {q.rsplit(".", 1)[-1] for q in seen} | wanted

    # -- rule surfaces ----------------------------------------------------

    def classify_rules(self) -> None:
        """Assign rule ids to the hazardous sites (idempotent)."""
        for s in self.sites:
            s.rule = None
            if s.cls == "accum" and s.exactness == "int32-fixed-point" \
                    and s.padding == "overflow-unproven":
                s.rule = "TPL204"
            elif s.cls == "scatter" and s.padding == "hazard":
                s.rule = "TPL203"
            elif s.cls == "accum" \
                    and s.exactness == "f32-order-sensitive" \
                    and s.padding == "hazard":
                if s.decision:
                    s.rule = "TPL201"
                elif s.compact:
                    s.rule = "TPL202"

    def sites_for(self, relpath: str) -> List[Site]:
        return [s for s in self.sites if s.path == relpath]

    # -- artifacts --------------------------------------------------------

    def ledger_doc(self,
                   suppressed: Optional[Dict[str, Dict[int, "set[str]"]]]
                   = None) -> Dict[str, Any]:
        self.classify_rules()
        if suppressed:
            for s in self.sites:
                if s.rule:
                    s.suppressed = s.rule in suppressed.get(
                        s.path, {}).get(s.line, set())
        recs = sorted(
            (s.record() for s in self.sites),
            key=lambda r: (r["path"], r["line"], r["op"], r["axis"]),
        )
        counts: Dict[str, int] = {}
        for r in recs:
            counts[r["exactness"]] = counts.get(r["exactness"], 0) + 1
        findings = [r for r in recs if r.get("rule")]
        return {
            "version": 1,
            "scope": sorted(self.sources),
            "sites": recs,
            "totals": {
                "sites": len(recs),
                "by_exactness": dict(sorted(counts.items())),
                "findings": len(findings),
                "unsuppressed": len(
                    [r for r in findings if not r.get("suppressed")]),
            },
        }

    def report_lines(self) -> List[str]:
        self.classify_rules()
        out = []
        for s in sorted(self.sites,
                        key=lambda s: (s.path, s.line, s.op)):
            tag = f" {s.rule}" + ("(suppressed)" if s.suppressed else "") \
                if s.rule else ""
            flags = []
            if s.decision:
                flags.append("decision")
            if s.compact:
                flags.append("compact")
            fl = f" [{','.join(flags)}]" if flags else ""
            out.append(
                f"{s.path}:{s.line}: {s.op}({s.operand}, axis={s.axis}) "
                f"in {s.func} — {s.exactness} / {s.padding}{fl}{tag}\n"
                f"    sharding: {s.sharding}"
            )
        return out


# ---------------------------------------------------------------------------
# Artifact I/O (the lock_hierarchy.json pattern).
# ---------------------------------------------------------------------------


def ledger_doc(program: KernelProgram,
               suppressed: Optional[Dict[str, Dict[int, "set[str]"]]]
               = None) -> Dict[str, Any]:
    return program.ledger_doc(suppressed)


def write_ledger(path: Path, doc: Dict[str, Any]) -> None:
    Path(path).write_text(
        json.dumps(doc, indent=2, sort_keys=True) + "\n")


def load_ledger(path: Path) -> Optional[Dict[str, Any]]:
    p = Path(path)
    if not p.exists():
        return None
    return json.loads(p.read_text())


# ---------------------------------------------------------------------------
# The TPL201-204 rules (duck-typed against lint.rules.Rule so this
# module never imports rules.py — rules.py imports KERNEL_RULES from
# here and appends them to RULES).
# ---------------------------------------------------------------------------

from tpusched.lint.engine import Finding  # noqa: E402  (bottom import: Finding only — engine never imports kernelflow at module top, so no cycle)


class _KernelRule:
    rule_id = "TPL2xx"
    title = ""
    incident = ""

    def applies(self, relpath: str) -> bool:
        return in_kernel_scope(relpath)

    def check(self, tree: ast.Module, src: str, relpath: str,
              ctx: Any, parents: Dict[ast.AST, ast.AST]) -> List[Finding]:
        prog = ctx.kernel_view(relpath, src)
        prog.classify_rules()
        return [
            Finding(relpath, s.line, self.rule_id, self.message(s))
            for s in prog.sites_for(relpath) if s.rule == self.rule_id
        ]

    def message(self, site: Site) -> str:
        raise NotImplementedError


class OrderSensitiveDecisionReduction(_KernelRule):
    """An f32 sum/cumsum/contraction whose result flows into a
    commit/compare decision (Compare, argmax/argmin, searchsorted,
    top_k, a where condition) is bitwise-stable only at a fixed width
    on a fixed backend: XLA reductions are tree-shaped, the tree
    changes with width/layout/sharding, and a flipped last-ulp compare
    moves a placement. PR 12 hit exactly this converting the commit
    rounds to compacted views; ROADMAP item 1's psum boundaries re-open
    it for every site left unconverted.
    """

    rule_id = "TPL201"
    title = "f32 order-sensitive reduction feeds a commit/compare decision"
    incident = ("PR 12 construction notes: XLA f32 tree reductions are "
                "not invariant under zero-padding or layout changes — "
                "desirability sums had to become int32 fixed point")

    def message(self, site: Site) -> str:
        return (f"f32 order-sensitive {site.op} feeds a commit/compare "
                "decision — the result depends on the reduction tree "
                "(width/layout/sharding); convert to int32 fixed point, "
                "an integer-valued form, or a width-padded layout "
                "(ledger: tools/reduction_ledger.json)")


class PaddingHazardOnCompactedPath(_KernelRule):
    """A padding-hazardous f32 accumulation in a function reachable
    from a compacted-view gather (_pods_view/_top_by_rank) runs on
    view-width arrays: zero-padding or a view-width change can move
    its result bitwise, silently violating the frontier-compaction
    contract. TPL201 covers the decision-feeding subset; this rule
    covers the rest of the compacted surface.
    """

    rule_id = "TPL202"
    title = "padding-hazardous reduction reachable from a compacted view"
    incident = ("ISSUE 12 bitwise contract: compacted [cap, N] rounds "
                "must equal full-width rounds byte-for-byte; the "
                "width-padded cumsum idiom exists because plain f32 "
                "cumsums do not")

    def message(self, site: Site) -> str:
        return (f"padding-hazardous f32 {site.op} on a compacted-view "
                "path — pad the operand to an explicit fixed width "
                "(the _node_add/_deal_commit cum_width idiom) or move "
                "it to an exact class")


class NonUniqueScatterAdd(_KernelRule):
    """``.at[idx].add(v)`` with duplicate-capable indices and
    non-integer f32 values applies the duplicates in UNSPECIFIED order,
    so the result depends on the pod-axis layout. Recognized safe
    forms: integer-valued adds (commute exactly), provably unique
    indices (argsort/lexsort perms, arange, scalar picks), and the
    masked-segment idiom (_node_add: duplicates add exact 0.0).
    """

    rule_id = "TPL203"
    title = "scatter-add with non-unique indices and f32 values"
    incident = ("PR 12: _node_add replaced the order-unspecified "
                "duplicate f32 scatter-add that made `used` depend on "
                "the pod-axis layout")

    def message(self, site: Site) -> str:
        return ("duplicate-capable f32 scatter-add applies in "
                "unspecified order (layout-dependent result) — use "
                "unique-per-segment totals (_node_add), a perm/arange "
                "index, or integer-valued adds")


class FixedPointOverflowUnproven(_KernelRule):
    """An int32 fixed-point accumulation whose quantized operand is not
    clipped to a bound B with B * 2**16 <= 2**31 (the documented
    "P * 2**15 fits int32" cap) can silently wrap at scale; wrapping is
    deterministic nonsense, which is worse than noise.
    """

    rule_id = "TPL204"
    title = "int32 fixed-point sum without a provable overflow bound"
    incident = ("PR 12 _deal_commit quantization: clip(round(x*16), "
                "-2^15, 2^15) is the pattern that makes the bound "
                "provable")

    def message(self, site: Site) -> str:
        return ("int32 fixed-point accumulation without a provable "
                "bound — clip the quantized operand to +-B with "
                "B * 2^16 <= 2^31 before astype(int32)")


KERNEL_RULES: Tuple[type, ...] = (
    OrderSensitiveDecisionReduction,
    PaddingHazardOnCompactedPath,
    NonUniqueScatterAdd,
    FixedPointOverflowUnproven,
)
