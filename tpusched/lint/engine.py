"""Lint engine: file walking, suppressions, baseline, rule dispatch.

Design constraints (round 15, ISSUE 10):

* Pure stdlib ``ast`` — this image must not grow dependencies.
* Findings are (path, line, rule, message) and deterministic: the
  tier-1 gate diffs them against an (empty) checked-in baseline, so
  ordering and paths must be stable across machines — paths are
  repo-root-relative POSIX strings.
* Suppressions are per-line and must carry a reason:
  ``# tpl: disable=TPL003(scrape is O(1) here)``. A reasonless
  suppression is itself a finding (TPL000) — the escape hatch is part
  of the documented invariant surface, not a way around it.
* The baseline exists for grandfathering a rule in; the repo keeps it
  empty (acceptance: ``tools/lint.py tpusched tools bench.py`` exits 0
  with ``tools/lint_baseline.json == []``).
"""

from __future__ import annotations

import ast
import dataclasses
import importlib.util
import io
import json
import re
import tokenize
from pathlib import Path, PurePosixPath
from typing import TYPE_CHECKING, Any, Iterable, Sequence

if TYPE_CHECKING:
    from tpusched.lint.interproc import Program
    from tpusched.lint.rules import Rule

#: Engine-level pseudo-rule for malformed suppression comments.
BAD_SUPPRESSION = "TPL000"

_SUPPRESS_RE = re.compile(r"#\s*tpl:\s*disable=(?P<entries>.+)$")
_ENTRY_RE = re.compile(r"(TPL\d{3})\s*(?:\(([^)]*)\))?")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    path: str   # repo-relative POSIX path
    line: int   # 1-indexed
    rule: str   # "TPL001"
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def key(self) -> "tuple[str, int, str]":
        """Baseline identity (message excluded: wording may evolve
        without re-grandfathering a finding)."""
        return (self.path, self.line, self.rule)


class LintContext:
    """Cross-file project knowledge shared by all rules over one run.

    ``root`` anchors relative paths; ``closeable_classes`` (TPL010) and
    ``benchdiff`` (TPL006) are computed lazily so linting a single
    fixture snippet never scans the tree, and both are injectable for
    rule unit tests.
    """

    def __init__(
        self,
        root: "Path | None" = None,
        closeable_classes: "set[str] | None" = None,
        benchdiff: Any = None,
        program_sources: "dict[str, str] | None" = None,
    ) -> None:
        self.root = Path(root) if root is not None else _default_root()
        self._closeable = closeable_classes
        self._benchdiff = benchdiff
        self._benchdiff_loaded = benchdiff is not None
        # Whole-program index (round 19, ISSUE 14): `program_sources`
        # injects an explicit {relpath: src} universe for multi-file
        # rule tests; None scans the real product tree lazily.
        self._program_sources = program_sources
        self._base_sources: "dict[str, str] | None" = None
        self._program: "Program | None" = None
        self._kernel_program: Any = None

    @property
    def closeable_classes(self) -> "set[str]":
        """Public tpusched classes defining close(): the TPL010 set."""
        if self._closeable is None:
            self._closeable = scan_closeable_classes(self.root / "tpusched")
        return self._closeable

    def program_view(self, relpath: str, src: str) -> "Program":
        """The interprocedural Program the TPL1xx rules run against
        when linting (relpath, src).

        Real-tree runs (the file on disk matches `src`) share ONE
        cached whole-program index, so the gate builds the call graph
        once. A fixture snippet (no such file, or content differs)
        gets an ISOLATED program over the injected `program_sources`
        plus the snippet — per-rule fixture twins stay hermetic instead
        of resolving against the live tree."""
        from tpusched.lint import interproc  # tpl: disable=TPL001(lazy: keeps engine.py importable standalone without the analysis layer — rules.py does load interproc at module top for the shared COSTLY sets, but engine alone must not)

        if self._program_sources is not None:
            base = self._program_sources
        else:
            if self._base_sources is None:
                self._base_sources = interproc.scan_product_sources(self.root)
            base = self._base_sources
        if base.get(relpath) == src:
            if self._program is None:
                self._program = interproc.Program(base)
            return self._program
        srcs = dict(self._program_sources or {})
        srcs[relpath] = src
        return interproc.Program(srcs)

    def kernel_view(self, relpath: str, src: str) -> Any:
        """The kernelflow KernelProgram the TPL2xx rules run against
        when linting (relpath, src) — same caching/isolation contract
        as program_view: real-tree runs share ONE cached index built
        over the kernel-scope sources; a fixture snippet gets an
        isolated program over the injected sources plus itself."""
        from tpusched.lint import kernelflow  # tpl: disable=TPL001(lazy: keeps engine.py importable standalone without the analysis layer — same contract as the interproc import above)

        if self._program_sources is not None:
            base = self._program_sources
        else:
            if self._base_sources is None:
                from tpusched.lint import interproc  # tpl: disable=TPL001(lazy: same engine-standalone contract as program_view)

                self._base_sources = interproc.scan_product_sources(
                    self.root)
            base = self._base_sources
        if base.get(relpath) == src:
            if self._kernel_program is None:
                self._kernel_program = kernelflow.KernelProgram(
                    kernelflow.kernel_sources(base))
            return self._kernel_program
        srcs = kernelflow.kernel_sources(dict(self._program_sources or {}))
        srcs[relpath] = src
        return kernelflow.KernelProgram(srcs)

    @property
    def benchdiff(self) -> Any:
        """tools/benchdiff.py as a module (direction-inference source
        of truth for TPL006), or None when the repo doesn't carry it."""
        if not self._benchdiff_loaded:
            self._benchdiff_loaded = True
            self._benchdiff = _load_benchdiff(self.root)
        return self._benchdiff


def _default_root() -> Path:
    # tpusched/lint/engine.py -> tpusched/lint -> tpusched -> repo root
    return Path(__file__).resolve().parents[2]


def _load_benchdiff(root: Path) -> Any:
    path = root / "tools" / "benchdiff.py"
    if not path.exists():
        return None
    spec = importlib.util.spec_from_file_location(
        "tpusched_lint_benchdiff", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def scan_closeable_classes(pkg_dir: Path) -> "set[str]":
    """Names of PUBLIC classes under ``pkg_dir`` that define close():
    the classes a test may construct but must not leak (TPL010)."""
    out: set[str] = set()
    if not pkg_dir.is_dir():
        return out
    for path in sorted(pkg_dir.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef) or node.name.startswith("_"):
                continue
            for item in node.body:
                if (isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and item.name == "close"):
                    out.add(node.name)
                    break
    return out


def parse_suppressions(src: str) -> "tuple[dict[int, set[str]], list[tuple[int, str]]]":
    """``(line -> suppressed rule ids, [(line, error)])``.

    Grammar (one comment suppresses one PHYSICAL line — put it on the
    line the finding reports, i.e. the statement's first line):

        # tpl: disable=TPL001(reason),TPL009(another reason)

    The reason is mandatory; ``TPL001`` or ``TPL001()`` yields a
    TPL000 error instead of a suppression.
    """
    by_line: dict[int, set[str]] = {}
    errors: list[tuple[int, str]] = []
    # Real COMMENT tokens only: the suppression marker inside a string
    # literal (e.g. lint's own error messages) must not suppress.
    try:
        comments = [
            (tok.start[0], tok.string)
            for tok in tokenize.generate_tokens(io.StringIO(src).readline)
            if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        comments = []
    for lineno, line in comments:
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        entries = m.group("entries").strip()
        matched_any = False
        for em in _ENTRY_RE.finditer(entries):
            matched_any = True
            rule, reason = em.group(1), em.group(2)
            if not reason or not reason.strip():
                errors.append((
                    lineno,
                    f"suppression of {rule} without a reason — write "
                    f"`# tpl: disable={rule}(why this line is exempt)`",
                ))
                continue
            by_line.setdefault(lineno, set()).add(rule)
        if not matched_any:
            errors.append((
                lineno,
                f"unparseable tpl suppression {entries!r} — expected "
                "`TPLnnn(reason)` entries",
            ))
    return by_line, errors


def load_baseline(path: "Path | str") -> "set[tuple[str, int, str]]":
    """Baseline file: JSON list of {path, line, rule}. Missing file ==
    empty baseline."""
    if not Path(path).exists():
        return set()
    doc = json.loads(Path(path).read_text())
    if not isinstance(doc, list):
        raise ValueError(f"{path}: baseline must be a JSON list")
    out: "set[tuple[str, int, str]]" = set()
    for rec in doc:
        out.add((str(rec["path"]), int(rec["line"]), str(rec["rule"])))
    return out


def write_baseline(path: "Path | str",
                   findings: "Sequence[Finding]") -> None:
    recs = [
        {"path": f.path, "line": f.line, "rule": f.rule}
        for f in sorted(findings)
    ]
    Path(path).write_text(json.dumps(recs, indent=2) + "\n")


def build_parent_map(tree: ast.AST) -> "dict[ast.AST, ast.AST]":
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


class LintEngine:
    def __init__(self, rules: "Iterable[Rule] | None" = None,
                 ctx: "LintContext | None" = None) -> None:
        if rules is None:
            from tpusched.lint.rules import default_rules  # tpl: disable=TPL001(rules imports Finding from engine; importing rules at module top would be a cycle)

            rules = default_rules()
        self.rules = list(rules)
        self.ctx = ctx if ctx is not None else LintContext()

    # -- single-source entry (also the fixture-test entry) -----------

    def lint_text(self, src: str, relpath: str) -> "list[Finding]":
        """Lint one source blob as if it lived at ``relpath`` (POSIX,
        repo-relative — applicability predicates key off it)."""
        relpath = str(PurePosixPath(relpath))
        try:
            tree = ast.parse(src, filename=relpath)
        except SyntaxError as e:
            return [Finding(relpath, int(e.lineno or 1), BAD_SUPPRESSION,
                            f"file does not parse: {e.msg}")]
        suppressed, sup_errors = parse_suppressions(src)
        parents = build_parent_map(tree)
        findings = [
            Finding(relpath, line, BAD_SUPPRESSION, msg)
            for line, msg in sup_errors
        ]
        for rule in self.rules:
            if not rule.applies(relpath):
                continue
            for f in rule.check(tree, src, relpath, self.ctx, parents):
                if rule.rule_id in suppressed.get(f.line, ()):
                    continue
                findings.append(f)
        return sorted(findings)

    # -- filesystem entries ------------------------------------------

    def lint_file(self, path: "Path | str") -> "list[Finding]":
        path = Path(path).resolve()
        try:
            rel = path.relative_to(self.ctx.root).as_posix()
        except ValueError:
            # A basename fallback would fail every path-scoped
            # applies() predicate and report the file CLEAN — a
            # false-green gate for sibling checkouts / CI mounts.
            raise ValueError(
                f"{path} is outside the lint root {self.ctx.root}; "
                "pass a LintContext(root=...) covering it"
            ) from None
        return self.lint_text(path.read_text(), rel)

    def lint_paths(self, paths: "Iterable[Path | str]") -> "list[Finding]":
        findings: list[Finding] = []
        for path in paths:
            path = Path(path)
            if path.is_dir():
                for f in sorted(path.rglob("*.py")):
                    findings.extend(self.lint_file(f))
            else:
                findings.extend(self.lint_file(path))
        return sorted(findings)


def apply_baseline(
    findings: "Sequence[Finding]",
    baseline: "set[tuple[str, int, str]]",
) -> "list[Finding]":
    return [f for f in findings if f.key() not in baseline]
