"""Runtime lock-order witness (round 19, ISSUE 14 tentpole, part 3).

The static lock hierarchy (tools/lock_hierarchy.json, produced by
tpusched/lint/interproc.py) is a MODEL: call-graph resolution is
heuristic, so the model must be validated against reality rather than
trusted. This module records the acquisition orders the process
ACTUALLY exhibits and cross-checks them:

  * a `violation` is an observed order (A held while B acquired) whose
    INVERSE is reachable in the static order graph — the two disagree
    about which lock comes first, which is exactly the state a
    deadlock needs (tier-1 asserts zero via tests/conftest.py);
  * an `unmodeled` edge is an observed order the static graph has no
    opinion on — reported (it names a call path the analysis failed to
    resolve) but not fatal: overapproximation gaps and third-party
    callback paths land here.

Design constraints, in the trace.py lineage (disabled by default, safe
to ship in every path):

  * installation REPLACES threading.Lock with a factory; locks whose
    creation site (filename:lineno) matches a hierarchy LockDecl get a
    recording wrapper, EVERYTHING else — stdlib, grpc, jax, test
    helpers — gets a raw `_thread.allocate_lock()` exactly as before.
    Zero overhead for foreign locks; one frame peek per construction.
  * a wrapped acquire is: inner acquire, thread-local list append, and
    (only while another witnessed lock is held) a set-membership probe
    per held lock with a tiny critical section on first sight of a new
    edge. Release is a reverse scan of the (nearly always 1-element)
    held list. Measured at noise level next to the dispatch costs the
    serving paths pay (bench note in tools/README.md).
  * Condition/RLock creation is NOT wrapped: the repo's only Condition
    (`_DispatchGate._cv`) stays static-only — the witness never has to
    emulate the `_release_save`/`_is_owned` protocol.
  * no threads, no ambient entropy; uninstall() restores threading.Lock
    and keeps the observations for the report.
"""

from __future__ import annotations

import json
import sys
import threading
from pathlib import Path
from typing import Any, Optional

__all__ = ["LockWitness", "install", "uninstall", "active"]

_REAL_LOCK = threading.Lock  # the builtin factory, captured at import


class _WitnessLock:
    """A recording wrapper around one hierarchy lock. Supports the
    subset of the lock protocol the repo uses (`with`, acquire/release,
    locked); deliberately NOT the Condition integration protocol."""

    __slots__ = ("_inner", "name", "_witness")

    def __init__(self, witness: "LockWitness", name: str):
        self._inner = _REAL_LOCK()
        self._witness = witness
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._witness._note_acquire(self)
        return ok

    def release(self) -> None:
        self._witness._note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: object) -> bool:
        self.release()
        return False


class LockWitness:
    """Observed-acquisition-order recorder (module docstring)."""

    def __init__(self, hierarchy: "dict[str, Any] | None",
                 root: "Path | None" = None):
        self._tls = threading.local()
        self._edges_mu = _REAL_LOCK()
        #: (src lock_id, dst lock_id) -> first-seen count marker
        self.observed: "dict[tuple[str, str], int]" = {}
        self._seen: "set[tuple[str, str]]" = set()
        #: (abs filename suffix, lineno) -> lock_id, for plain Locks only
        self._by_site: dict[tuple[str, int], str] = {}
        #: static forward reachability: lock_id -> set of lock_ids that
        #: may be acquired while it is held (transitive closure)
        self._after: "dict[str, set[str]]" = {}
        self.installed = False
        self.root = str(root) if root is not None else None
        if hierarchy:
            self._load(hierarchy)

    def _load(self, doc: "dict[str, Any]") -> None:
        for lk in doc.get("locks", ()):
            if lk.get("kind") == "Lock":
                self._by_site[(lk["path"], int(lk["line"]))] = lk["lock_id"]
        adj: "dict[str, set[str]]" = {}
        for e in doc.get("edges", ()):
            adj.setdefault(e["src"], set()).add(e["dst"])
        # Forward transitive closure (the graph is tiny: tens of locks).
        for src in adj:
            seen: "set[str]" = set()
            stack = list(adj[src])
            while stack:
                n = stack.pop()
                if n in seen:
                    continue
                seen.add(n)
                stack.extend(adj.get(n, ()))
            self._after[src] = seen

    # -- construction-time site lookup -----------------------------------

    def name_for(self, filename: str, lineno: int) -> Optional[str]:
        """lock_id for a creation site, matching on repo-relative path
        suffix (the hierarchy stores POSIX relpaths; the frame gives an
        absolute path)."""
        fn = filename.replace("\\", "/")
        for (rel, line), lock_id in self._by_site.items():
            if line == lineno and fn.endswith("/" + rel):
                return lock_id
        return None

    # -- recording -------------------------------------------------------

    def _held(self) -> "list[_WitnessLock]":
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _note_acquire(self, lock: _WitnessLock) -> None:
        held = self._held()
        if held:
            seen = self._seen
            for h in held:
                key = (h.name, lock.name)
                if key not in seen:
                    with self._edges_mu:
                        if key not in self._seen:
                            self._seen.add(key)
                            self.observed[key] = len(self.observed)
        held.append(lock)

    def _note_release(self, lock: _WitnessLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    # -- reporting -------------------------------------------------------

    def report(self) -> "dict[str, Any]":
        """{observed, violations, unmodeled}: violations are observed
        orders whose inverse the static hierarchy derives (a deadlock-
        shaped disagreement) AND pairs observed in BOTH orders at
        runtime with the static graph endorsing NEITHER — the
        strongest deadlock evidence there is, on exactly the edges the
        heuristic call graph failed to model. A direction the static
        hierarchy endorses is never flagged (when its inverse is also
        observed, the INVERSE carries the violation — the diagnostic
        must point at the wrong call site, not the right one).
        unmodeled are one-direction orders the static graph does not
        contain (self-edges between two INSTANCES of one static lock
        are reported as unmodeled, not violations — same lock_id,
        different runtime locks)."""
        with self._edges_mu:
            observed = sorted(self.observed, key=self.observed.get)
        pairs = set(observed)
        violations = []
        unmodeled = []
        for a, b in observed:
            if a == b:
                unmodeled.append((a, b))
                continue
            if a in self._after.get(b, ()):    # static says b before a
                violations.append((a, b))
            elif b in self._after.get(a, ()):  # static endorses a -> b
                pass
            elif (b, a) in pairs:   # unmodeled pair seen BOTH ways
                violations.append((a, b))
            else:
                unmodeled.append((a, b))
        return {
            "observed": [list(e) for e in observed],
            "violations": [list(e) for e in violations],
            "unmodeled": [list(e) for e in unmodeled],
        }


_ACTIVE: "LockWitness | None" = None


def active() -> "LockWitness | None":
    return _ACTIVE


def install(hierarchy_path: "Path | str | None" = None,
            hierarchy: "dict[str, Any] | None" = None) -> LockWitness:
    """Patch threading.Lock with the witness factory. Idempotent per
    process (a second install returns the active witness). Locks
    created BEFORE install stay raw and unobserved — install early
    (tests/conftest.py does it at import, before product modules load)."""
    global _ACTIVE
    if _ACTIVE is not None and _ACTIVE.installed:
        return _ACTIVE
    if hierarchy is None and hierarchy_path is not None:
        p = Path(hierarchy_path)
        if p.exists():
            hierarchy = json.loads(p.read_text())
    witness = LockWitness(hierarchy)
    if not witness._by_site:
        # No hierarchy to key on: do not patch at all — an unkeyed
        # witness would wrap nothing and observe nothing.
        return witness

    def _factory() -> Any:
        frame = sys._getframe(1)
        name = witness.name_for(frame.f_code.co_filename, frame.f_lineno)
        if name is None:
            return _REAL_LOCK()
        return _WitnessLock(witness, name)

    threading.Lock = _factory  # type: ignore[assignment]
    witness.installed = True
    _ACTIVE = witness
    return witness


def uninstall() -> None:
    """Restore threading.Lock; the active witness keeps its
    observations so a session-end report can still read them."""
    global _ACTIVE
    threading.Lock = _REAL_LOCK  # type: ignore[assignment]
    if _ACTIVE is not None:
        _ACTIVE.installed = False
